#!/usr/bin/env python3
"""Distributed data-parallel ImageNet training on TPU — the ``imagenet_ddp.py``
entry point (reference: /root/reference/imagenet_ddp.py), CLI-compatible.

Same flags, same defaults, same run book commands (reference README.md:74-99)
— but the engine is dptpu's SPMD path: one process per host drives every
local chip through a ``jax.sharding.Mesh``; gradient all-reduce is an XLA
collective compiled into the train step (no NCCL, no mp.spawn, no DDP
wrapper). ``--dist-backend``/``--world-size``/``--rank``/``--dist-url`` keep
their reference semantics, mapped onto ``jax.distributed.initialize``.
"""

from dptpu.cli import main_ddp

if __name__ == "__main__":
    main_ddp()
