#!/usr/bin/env python3
"""Single-device / fallback-everything ImageNet training on TPU — the
``nd_imagenet.py`` entry point (reference: /root/reference/nd_imagenet.py),
CLI-compatible.

The reference's 5-way device-placement ladder (CPU → pinned GPU → DDP →
DataParallel, nd_imagenet.py:140-169) collapses on TPU: ``--gpu N`` pins one
local chip, otherwise all visible devices join a mesh; a CPU-only machine
just runs the same program on the CPU backend. ``--seed`` gives end-to-end
reproducibility (XLA is deterministic by default — no cudnn.deterministic
trade-off, nd_imagenet.py:84-92).
"""

from dptpu.cli import main_nd

if __name__ == "__main__":
    main_nd()
