#!/usr/bin/env python3
"""Headline benchmark: ResNet-50 train-step throughput, images/sec/chip.

Runs the full compiled training step (uint8 batch → on-device normalize →
forward → backward → SGD update, bf16 compute like the Apex path) on
synthetic data on every visible chip and reports images/sec/chip — the
reference's own throughput definition, world·batch/time ÷ chips
(imagenet_ddp_apex.py:411-412).

Baseline for ``vs_baseline``: ~2800 images/sec/chip, the public ballpark for
A100 + AMP + NCCL-DDP ResNet-50/224 training — the "≥ A100x32 NCCL-DDP
images/sec/chip" bar from BASELINE.json's north star (no reference-published
number exists; SURVEY.md §6).

Self-defending methodology (added after the round-3 capture collapse, where
one contended run became the official 0.05× record): wall-clock rates are
cross-checked IN-PROCESS against the device-time op sum from the XLA trace
(`dptpu.utils.profiling`), which is contention-immune — op durations come
from the hardware's own profile. Any two-point-differenced wall rate
disagreeing with the device-derived rate by >1.5× is rejected and retried;
if no wall window is ever plausible (a persistently contended relay), the
device-derived steady-state rate is reported instead. A one-line JSON
diagnostic (op sum, per-trial rates, rejections, which source won) goes to
stderr so a bad capture is attributable rather than silently becoming the
headline. Prints ONE JSON line on stdout: {"metric","value","unit",
"vs_baseline"}.
"""

import json
import sys
import time

import numpy as np

BASELINE_IMG_PER_SEC_PER_CHIP = 2800.0

# Wall-clock drift on the relayed chip is up to ±8% (PERF.md); 1.5× is far
# outside any honest window and only trips on real capture failures
# (contention stalls, relay backpressure, a mis-provisioned chip).
PLAUSIBILITY_RATIO = 1.5
TRIALS_NEEDED = 4
TRIALS_MAX = 10
# On a contended relay every window stretches; without a budget the
# trial schedule can outlive the driver's timeout and the round records
# NOTHING (worse than a diagnosed bad number). Past this many seconds of
# measurement the bench reports what it has — accepted trials or the
# device-time fallback — with the shortfall in the diagnostics.
TIME_BUDGET_S = 360.0


def plausible(rate: float, device_rate, ratio: float = PLAUSIBILITY_RATIO):
    """A wall-clock rate is plausible iff it agrees with the
    device-time-derived rate within ``ratio`` (always true when no
    device profile exists to check against)."""
    if device_rate is None:
        return True
    return device_rate / ratio <= rate <= device_rate * ratio


def finalize(accepted, device_rate, rejected):
    """Pick the reported rate and its source — the decision the r03
    capture collapse motivated, kept pure so tests can lock it.

    Accepted wall trials win (median); with none, the contention-immune
    device-derived rate stands in; with neither, the benchmark must
    fail loudly rather than print a junk number."""
    if accepted:
        return float(np.median(accepted)), "wall_clock_two_point_diff"
    if device_rate is not None:
        return float(device_rate), "device_time_op_sum_fallback"
    raise RuntimeError(
        "benchmark unusable: no plausible wall-clock window and no "
        f"device profile; rejected={rejected}"
    )


def main():
    import jax
    import jax.numpy as jnp

    from dptpu.models import create_model
    from dptpu.ops.schedules import make_step_decay_schedule
    from dptpu.parallel import make_mesh, shard_host_batch
    from dptpu.train import create_train_state, make_optimizer, make_train_step

    n_chips = jax.device_count()
    per_chip_batch = 128
    global_batch = per_chip_batch * n_chips

    mesh = make_mesh() if n_chips > 1 else None
    # standard 7x7/2 stem: the space-to-depth variant measured ~1.3% slower
    # on v5e-1 (see PERF.md); it remains available via stem_space_to_depth
    model = create_model("resnet50", dtype=jnp.bfloat16)
    tx = make_optimizer(0.9, 1e-4)
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, input_shape=(1, 224, 224, 3)
    )
    step = make_train_step(
        mesh, jnp.bfloat16, lr_schedule=make_step_decay_schedule(0.1, 100)
    )

    rng = np.random.RandomState(0)
    host_batch = {
        "images": rng.randint(0, 256, (global_batch, 224, 224, 3)).astype(
            np.uint8
        ),
        "labels": rng.randint(0, 1000, (global_batch,)).astype(np.int32),
    }
    batch = (
        shard_host_batch(host_batch, mesh)
        if mesh is not None
        else jax.device_put(host_batch)
    )

    # warmup: compile + 3 steps; end on a VALUE fetch — on relayed/remote
    # PJRT backends block_until_ready can return before execution finishes,
    # so only a device→host scalar read is a trustworthy timing fence
    for _ in range(3):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    # Contention-immune reference: sum of device-side op durations from the
    # XLA trace (the state is donated, so the profiled callable carries it).
    device_ms = None
    try:
        from dptpu.utils.profiling import profile_device_time

        def traced_step():
            nonlocal state
            state, m = step(state, batch)
            return m

        device_ms, _ = profile_device_time(traced_step, iters=6)
        if device_ms is not None and device_ms <= 0:
            device_ms = None
    except Exception as exc:  # no device tracks (CPU backend) / profiler off
        print(
            json.dumps({"bench_diag": "device_profile_unavailable",
                        "error": repr(exc)[:200]}),
            file=sys.stderr,
        )
    device_rate = global_batch / device_ms * 1000.0 if device_ms else None

    def window(iters):
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(iters):
            state, metrics = step(state, batch)
        float(metrics["loss"])  # fence: depends on every queued step
        return time.perf_counter() - t0

    # Two-point differencing: each fenced window carries a fixed ~100ms
    # cost (relay round-trip + pipeline refill) that a single window would
    # book against throughput. t(long) - t(short) cancels it exactly and
    # yields the steady-state step time — which matches the per-op device
    # time sum from the XLA trace (PERF.md). The short/long order alternates
    # between trials (the first window after idle runs 2-3% off steady
    # state, so a fixed order would bias the difference one way).
    short_iters, long_iters = 20, 120
    accepted, rejected = [], []
    budget_exhausted = False
    t_bench_start = time.perf_counter()
    for trial in range(TRIALS_MAX):
        if time.perf_counter() - t_bench_start > TIME_BUDGET_S:
            budget_exhausted = True
            break
        if trial % 2 == 0:
            t_short = window(short_iters)
            t_long = window(long_iters)
        else:
            t_long = window(long_iters)
            t_short = window(short_iters)
        if t_long <= t_short:  # contention spike inverted the difference
            rejected.append({"trial": trial, "rate": None,
                             "why": "inverted_windows"})
            continue
        r = global_batch * (long_iters - short_iters) / (t_long - t_short)
        if not plausible(r, device_rate):
            rejected.append({"trial": trial, "rate": round(r, 1),
                             "why": "implausible_vs_device_time"})
            continue
        accepted.append(round(r, 1))
        if len(accepted) >= TRIALS_NEEDED:
            break

    rate, source = finalize(accepted, device_rate, rejected)

    print(
        json.dumps(
            {
                "bench_diag": "ok",
                "source": source,
                "device_ms_per_step": (
                    round(device_ms, 2) if device_ms else None
                ),
                "device_rate_per_chip": (
                    round(device_rate / n_chips, 1) if device_rate else None
                ),
                "accepted_rates": accepted,
                "rejected": rejected,
                "time_budget_exhausted": budget_exhausted,
            }
        ),
        file=sys.stderr,
    )
    per_chip = rate / n_chips
    print(
        json.dumps(
            {
                "metric": "resnet50_bf16_train_images_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
            }
        )
    )


if __name__ == "__main__":
    main()


