#!/usr/bin/env python3
"""Headline benchmark: ResNet-50 train-step throughput, images/sec/chip.

Runs the full compiled training step (uint8 batch → on-device normalize →
forward → backward → SGD update, bf16 compute like the Apex path) on
synthetic data on every visible chip and reports images/sec/chip — the
reference's own throughput definition, world·batch/time ÷ chips
(imagenet_ddp_apex.py:411-412).

Baseline for ``vs_baseline``: ~2800 images/sec/chip, the public ballpark for
A100 + AMP + NCCL-DDP ResNet-50/224 training — the "≥ A100x32 NCCL-DDP
images/sec/chip" bar from BASELINE.json's north star (no reference-published
number exists; SURVEY.md §6).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

BASELINE_IMG_PER_SEC_PER_CHIP = 2800.0


def main():
    import jax
    import jax.numpy as jnp

    from dptpu.models import create_model
    from dptpu.ops.schedules import make_step_decay_schedule
    from dptpu.parallel import make_mesh, shard_host_batch
    from dptpu.train import create_train_state, make_optimizer, make_train_step

    n_chips = jax.device_count()
    per_chip_batch = 128
    global_batch = per_chip_batch * n_chips

    mesh = make_mesh() if n_chips > 1 else None
    # standard 7x7/2 stem: the space-to-depth variant measured ~1.3% slower
    # on v5e-1 (see PERF.md); it remains available via stem_space_to_depth
    model = create_model("resnet50", dtype=jnp.bfloat16)
    tx = make_optimizer(0.9, 1e-4)
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, input_shape=(1, 224, 224, 3)
    )
    step = make_train_step(
        mesh, jnp.bfloat16, lr_schedule=make_step_decay_schedule(0.1, 100)
    )

    rng = np.random.RandomState(0)
    host_batch = {
        "images": rng.randint(0, 256, (global_batch, 224, 224, 3)).astype(
            np.uint8
        ),
        "labels": rng.randint(0, 1000, (global_batch,)).astype(np.int32),
    }
    batch = (
        shard_host_batch(host_batch, mesh)
        if mesh is not None
        else jax.device_put(host_batch)
    )

    # warmup: compile + 3 steps; end on a VALUE fetch — on relayed/remote
    # PJRT backends block_until_ready can return before execution finishes,
    # so only a device→host scalar read is a trustworthy timing fence
    for _ in range(3):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    def window(iters):
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(iters):
            state, metrics = step(state, batch)
        float(metrics["loss"])  # fence: depends on every queued step
        return time.perf_counter() - t0

    # Two-point differencing: each fenced window carries a fixed ~100ms
    # cost (relay round-trip + pipeline refill) that a single window would
    # book against throughput. t(long) - t(short) cancels it exactly and
    # yields the steady-state step time — which matches the per-op device
    # time sum from the XLA trace (PERF.md). The short/long order alternates
    # between trials (the first window after idle runs 2-3% off steady
    # state, so a fixed order would bias the difference one way) and the
    # reported rate is the median of per-trial rates, so one contention
    # spike in either window cannot be cherry-picked.
    short_iters, long_iters = 20, 120
    rates = []
    for trial in range(2):
        if trial % 2 == 0:
            t_short = window(short_iters)
            t_long = window(long_iters)
        else:
            t_long = window(long_iters)
            t_short = window(short_iters)
        if t_long > t_short:  # a contention spike in the short window can
            rates.append(      # invert the difference; skip such trials
                global_batch * (long_iters - short_iters) / (t_long - t_short)
            )
    if not rates:
        raise RuntimeError("benchmark windows unusable (contention?)")
    rate = float(np.median(rates))

    per_chip = rate / n_chips
    print(
        json.dumps(
            {
                "metric": "resnet50_bf16_train_images_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
