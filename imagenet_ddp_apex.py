#!/usr/bin/env python3
"""Mixed-precision distributed ImageNet training on TPU — the
``imagenet_ddp_apex.py`` entry point (reference:
/root/reference/imagenet_ddp_apex.py), CLI-compatible.

Apex AMP becomes the native bf16 compute policy: any ``--opt-level`` ≥ O1
runs the model in bf16 with fp32 BatchNorm and fp32 master params —
``--loss-scale`` is accepted and unused because bf16 keeps fp32's exponent
range (no underflow to scale away). ``--sync-bn`` turns on cross-replica
BatchNorm statistics via a pmean inside the compiled step. The linear LR
scaling rule (lr·global_batch/256), 5-epoch warmup, and the extra ×0.1 decay
at epoch ≥ 80 match the reference schedule exactly
(imagenet_ddp_apex.py:161-162,527-543). Batch size is per-device, as in the
reference (:63-67). Launch: one process per host with WORLD_SIZE/RANK/
MASTER_ADDR env vars (env:// rendezvous), not one per chip.
"""

from dptpu.cli import main_apex

if __name__ == "__main__":
    main_apex()
