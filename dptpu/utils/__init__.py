from dptpu.utils.meters import AverageMeter, ProgressMeter, Summary

__all__ = ["AverageMeter", "ProgressMeter", "Summary"]
