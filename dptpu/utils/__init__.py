from dptpu.utils.meters import AverageMeter, ProgressMeter, Summary
from dptpu.utils.profiling import parse_perfetto_trace, profile_device_time

__all__ = ["AverageMeter", "ProgressMeter", "Summary",
           "parse_perfetto_trace", "profile_device_time"]
