"""Device-time profiling: the trustworthy timing primitive on TPU.

Wall-clock timing through a relayed/remote PJRT backend carries ~1.4 ms
of per-dispatch overhead and drifts up to +-8% with chip contention
(PERF.md round 3), so dptpu's performance methodology is built on XLA
device traces instead: op durations come from the hardware's own
profile, are contention-immune, and sum to the true step time.

``profile_device_time(fn, *args)`` runs ``fn`` a few times under
``jax.profiler.trace``, parses the perfetto export, and returns per-op
device milliseconds. This is the tool behind PERF.md's attribution
tables and the recommended first step for any "why is my step slow"
investigation — before believing any wall-clock number.

The reference's observability story is wall-clock meters plus explicit
``torch.cuda.synchronize()`` before reads (imagenet_ddp_apex.py:406,
SURVEY.md §5); meters remain the console surface here
(dptpu/utils/meters.py), this module is the layer beneath them.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import tempfile
from typing import Callable, Dict, Tuple


def parse_perfetto_trace(trace: dict, iters: int = 1) -> Tuple[float, Dict[str, float]]:
    """Sum device-side op durations from a loaded perfetto trace.

    Returns ``(total_ms_per_iter, {op_name: ms_per_iter})``. Host-side
    tracks are excluded; the per-core duplicate tracks TPU traces carry
    are collapsed by taking the maximum-duration track per op name.

    A trace with NO device-side events raises ``RuntimeError`` instead
    of silently reporting ``(0, {})`` — a zero would read as "the device
    did no work" when the real cause is almost always that no device
    tracks matched: a host-only trace (backend whose PJRT plugin exports
    no device timeline), a traced region that dispatched nothing, or a
    track-naming scheme this parser doesn't know.

    CPU-PJRT fallback: the CPU backend has no ``/device:*`` track — its
    XLA ops execute on the ``tf_XLAEigen`` threadpool of the
    ``/host:CPU`` process track, interleaved with Python tracemes and
    compiler passes on OTHER threads of the same pid. When (and only
    when) no real device track matched, op events from those Eigen
    threads are used instead, so CPU-only runs still get a per-op table
    (approximate: thread-parallel op time max-collapses to the busiest
    thread, like the multi-replica rule).
    """
    events = trace.get("traceEvents", [])
    pid_names, thread_names = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pid_names[e["pid"]] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", "")
            )
    dev_pids = {
        p for p, n in pid_names.items()
        if ("TPU" in n or "/device" in n or "Device" in n) and "Host" not in n
    }

    def _collect(want):
        tracks: dict = collections.defaultdict(lambda: collections.Counter())
        for e in events:
            if e.get("ph") == "X" and want(e):
                tracks[(e["pid"], e.get("tid"))][e.get("name", "")] += (
                    e.get("dur", 0) / 1000.0
                )
        return tracks

    per_track = _collect(lambda e: e.get("pid") in dev_pids)
    if not per_track:
        xla_cpu = {
            (p, t) for (p, t), n in thread_names.items()
            if str(pid_names.get(p, "")).startswith("/host:")
            and str(n).startswith("tf_XLAEigen")
        }
        per_track = _collect(
            lambda e: (e.get("pid"), e.get("tid")) in xla_cpu
        )
    if not per_track:
        tracks = sorted(set(pid_names.values())) or ["<no process_name metadata>"]
        raise RuntimeError(
            "no device tracks matched in this trace — likely a host-only "
            "trace (the backend exports no device timeline, e.g. an "
            "un-relayed CPU run) or a traced region that dispatched no "
            "device work. Process tracks seen: " + ", ".join(
                repr(t) for t in tracks[:8]
            )
        )
    by_op: collections.Counter = collections.Counter()
    for track in per_track.values():
        for name, ms in track.items():
            by_op[name] = max(by_op[name], ms)
    per_iter = {k: v / iters for k, v in by_op.items()}
    # XLA module-level spans (named "jit_<fn>(...)") CONTAIN the op events:
    # they are the authoritative totals (one per jitted module — summed, in
    # case the profiled fn dispatches several distinct modules), and they
    # are filtered out of the per-op table so op shares don't double-count
    # against it. NOTE the max-collapse above makes multi-replica semantics
    # "the slowest replica's time" per op: SPMD workers run the same
    # program, so the max is the critical-path one.
    modules = {k: v for k, v in per_iter.items() if k.startswith("jit_")}
    ops = {k: v for k, v in per_iter.items() if k not in modules}
    if modules:
        return sum(modules.values()), ops
    return sum(ops.values()), ops


def load_trace_dir(path: str) -> dict:
    """Load + merge every perfetto export under ``path`` into one trace.

    One ``*.trace.json.gz`` per host on multi-process runs. Perfetto
    pids are only unique within a file, so namespace them per source
    file before merging — otherwise host tracks from one file can
    masquerade as device tracks of another. The parser's max-collapse
    then yields the slowest replica's per-op time (the SPMD critical
    path). Raises ``RuntimeError`` when no trace file exists under
    ``path``.
    """
    paths = sorted(
        glob.glob(os.path.join(path, "**", "*.trace.json.gz"),
                  recursive=True)
    )
    if not paths:
        raise RuntimeError(f"no trace written under {path}")
    merged = {"traceEvents": []}
    for i, p in enumerate(paths):
        with gzip.open(p, "rt") as f:
            for e in json.load(f).get("traceEvents", []):
                if "pid" in e:
                    e = dict(e, pid=(i, e["pid"]))
                merged["traceEvents"].append(e)
    return merged


def profile_device_time(fn: Callable, *args, iters: int = 6,
                        fence: Callable = None):
    """Trace ``iters`` calls of ``fn(*args)`` and return per-op device time.

    ``fn`` should be a compiled callable whose outputs carry at least one
    array; ``fence`` (default: fetch the first output leaf) forces
    completion — on relayed backends only a device->host value read is a
    trustworthy fence (PERF.md).
    """
    import jax

    def default_fence(out):
        leaf = jax.tree_util.tree_leaves(out)[0]
        float(leaf.ravel()[0])

    fence = fence or default_fence
    out = fn(*args)
    fence(out)  # warm / compile outside the trace
    tmp = tempfile.mkdtemp(prefix="dptpu_prof_")
    try:
        with jax.profiler.trace(tmp):
            for _ in range(iters):
                out = fn(*args)
            fence(out)
        return parse_perfetto_trace(load_trace_dir(tmp), iters=iters)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
