"""Device-time profiling: the trustworthy timing primitive on TPU.

Wall-clock timing through a relayed/remote PJRT backend carries ~1.4 ms
of per-dispatch overhead and drifts up to +-8% with chip contention
(PERF.md round 3), so dptpu's performance methodology is built on XLA
device traces instead: op durations come from the hardware's own
profile, are contention-immune, and sum to the true step time.

``profile_device_time(fn, *args)`` runs ``fn`` a few times under
``jax.profiler.trace``, parses the perfetto export, and returns per-op
device milliseconds. This is the tool behind PERF.md's attribution
tables and the recommended first step for any "why is my step slow"
investigation — before believing any wall-clock number.

The reference's observability story is wall-clock meters plus explicit
``torch.cuda.synchronize()`` before reads (imagenet_ddp_apex.py:406,
SURVEY.md §5); meters remain the console surface here
(dptpu/utils/meters.py), this module is the layer beneath them.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import tempfile
from typing import Callable, Dict, Tuple


def parse_perfetto_trace(trace: dict, iters: int = 1) -> Tuple[float, Dict[str, float]]:
    """Sum device-side op durations from a loaded perfetto trace.

    Returns ``(total_ms_per_iter, {op_name: ms_per_iter})``. Host-side
    tracks are excluded; the per-core duplicate tracks TPU traces carry
    are collapsed by taking the maximum-duration track per op name.
    """
    events = trace.get("traceEvents", [])
    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e["pid"]] = e.get("args", {}).get("name", "")
    dev_pids = {
        p for p, n in pid_names.items()
        if ("TPU" in n or "/device" in n or "Device" in n) and "Host" not in n
    }
    per_track: dict = collections.defaultdict(lambda: collections.Counter())
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        per_track[(e["pid"], e.get("tid"))][e.get("name", "")] += (
            e.get("dur", 0) / 1000.0
        )
    by_op: collections.Counter = collections.Counter()
    for track in per_track.values():
        for name, ms in track.items():
            by_op[name] = max(by_op[name], ms)
    per_iter = {k: v / iters for k, v in by_op.items()}
    # XLA module-level spans (named "jit_<fn>(...)") CONTAIN the op events:
    # they are the authoritative totals (one per jitted module — summed, in
    # case the profiled fn dispatches several distinct modules), and they
    # are filtered out of the per-op table so op shares don't double-count
    # against it. NOTE the max-collapse above makes multi-replica semantics
    # "the slowest replica's time" per op: SPMD workers run the same
    # program, so the max is the critical-path one.
    modules = {k: v for k, v in per_iter.items() if k.startswith("jit_")}
    ops = {k: v for k, v in per_iter.items() if k not in modules}
    if modules:
        return sum(modules.values()), ops
    return sum(ops.values()), ops


def profile_device_time(fn: Callable, *args, iters: int = 6,
                        fence: Callable = None):
    """Trace ``iters`` calls of ``fn(*args)`` and return per-op device time.

    ``fn`` should be a compiled callable whose outputs carry at least one
    array; ``fence`` (default: fetch the first output leaf) forces
    completion — on relayed backends only a device->host value read is a
    trustworthy fence (PERF.md).
    """
    import jax

    def default_fence(out):
        leaf = jax.tree_util.tree_leaves(out)[0]
        float(leaf.ravel()[0])

    fence = fence or default_fence
    out = fn(*args)
    fence(out)  # warm / compile outside the trace
    tmp = tempfile.mkdtemp(prefix="dptpu_prof_")
    try:
        with jax.profiler.trace(tmp):
            for _ in range(iters):
                out = fn(*args)
            fence(out)
        paths = sorted(
            glob.glob(os.path.join(tmp, "**", "*.trace.json.gz"),
                      recursive=True)
        )
        if not paths:
            raise RuntimeError(f"no trace written under {tmp}")
        # one file per host on multi-process runs. Perfetto pids are only
        # unique within a file, so namespace them per source file before
        # merging — otherwise host tracks from one file can masquerade as
        # device tracks of another. The parser's max-collapse then yields
        # the slowest replica's per-op time (the SPMD critical path).
        merged = {"traceEvents": []}
        for i, path in enumerate(paths):
            with gzip.open(path, "rt") as f:
                for e in json.load(f).get("traceEvents", []):
                    if "pid" in e:
                        e = dict(e, pid=(i, e["pid"]))
                    merged["traceEvents"].append(e)
        return parse_perfetto_trace(merged, iters=iters)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
