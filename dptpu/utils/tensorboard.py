"""Zero-dependency TensorBoard event writer.

The reference logs 11 scalars per epoch from rank 0 into a run-config-named
directory via tensorboardX (imagenet_ddp_apex.py:152-159,280-290). dptpu
writes the same wire format — TFRecord-framed Event protobufs with masked
CRC32C — by hand, so metrics open in stock TensorBoard with no tensorflow /
tensorboardX / torch dependency anywhere in the framework.

Format references (public): TFRecord framing = {uint64 len, uint32
masked_crc32c(len), bytes, uint32 masked_crc32c(bytes)}; Event proto fields
{1: wall_time double, 2: step int64, 3: file_version string, 5: Summary};
Summary.Value fields {1: tag string, 2: simple_value float}.
"""

from __future__ import annotations

import atexit
import os
import socket
import struct
import time
from typing import Optional

# ---------------------------------------------------------------- crc32c ----

_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78  # Castagnoli, reflected
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC_TABLE = table
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------- protobuf -----


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint(num << 3 | 2) + _varint(len(payload)) + payload


def _field_double(num: int, value: float) -> bytes:
    return _varint(num << 3 | 1) + struct.pack("<d", value)


def _field_float(num: int, value: float) -> bytes:
    return _varint(num << 3 | 5) + struct.pack("<f", value)


def _field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3 | 0) + _varint(value & 0xFFFFFFFFFFFFFFFF)


def _event(wall_time: float, step: Optional[int] = None,
           file_version: Optional[str] = None,
           summary: Optional[bytes] = None) -> bytes:
    msg = _field_double(1, wall_time)
    if step is not None:
        msg += _field_varint(2, step)
    if file_version is not None:
        msg += _field_bytes(3, file_version.encode())
    if summary is not None:
        msg += _field_bytes(5, summary)
    return msg


def _scalar_summary(tag: str, value: float) -> bytes:
    val = _field_bytes(1, tag.encode()) + _field_float(2, float(value))
    return _field_bytes(1, val)


# --------------------------------------------------------------- writer -----


class SummaryWriter:
    """tensorboardX-compatible surface: ``add_scalar``, ``log_dir``, ``close``.

    ``comment`` builds the run directory exactly like the reference's
    ``runs/<datetime>_<host><comment>`` naming (imagenet_ddp_apex.py:155-159).

    Durability contract (dptpu/resilience): every ``add_scalar`` flushes
    the record to the OS, so the event file is parseable after a crash
    at ANY record boundary — even SIGKILL mid-run loses nothing already
    written. ``close`` is additionally registered with ``atexit`` so the
    preemption path (SIGTERM guard → cooperative return, or an exception
    that unwinds past the trainer) still closes the file even when no
    caller reaches ``close()`` explicitly.
    """

    def __init__(self, log_dir: Optional[str] = None, comment: str = ""):
        if log_dir is None:
            stamp = time.strftime("%b%d_%H-%M-%S")
            log_dir = os.path.join(
                "runs", f"{stamp}_{socket.gethostname()}{comment}"
            )
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        self._file = open(os.path.join(log_dir, fname), "ab")
        self._write_record(_event(time.time(), file_version="brain.Event:2"))
        atexit.register(self.close)

    def _write_record(self, data: bytes):
        header = struct.pack("<Q", len(data))
        self._file.write(header)
        self._file.write(struct.pack("<I", _masked_crc(header)))
        self._file.write(data)
        self._file.write(struct.pack("<I", _masked_crc(data)))

    def add_scalar(self, tag: str, value, global_step: int = 0):
        self._write_record(
            _event(time.time(), step=int(global_step),
                   summary=_scalar_summary(tag, float(value)))
        )
        self._file.flush()

    def flush(self):
        self._file.flush()

    def close(self):
        if not self._file.closed:
            self._file.flush()
            self._file.close()
        # bound methods compare equal, so this unregisters the handler
        # installed in __init__ (idempotent close: later calls no-op)
        atexit.unregister(self.close)
