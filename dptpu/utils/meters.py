"""Running-statistics meters with the reference's exact display surface.

The *console bytes* are contractual — the reference prints
``"{name} {val:fmt} ({avg:fmt})"`` meter strings (imagenet_ddp.py:333-354),
``"<prefix>[i/N]\\t<meter>\\t..."`` progress lines (imagenet_ddp.py:357-371),
``" * <summaries>"`` epilogue lines with a ``Summary`` enum selecting
avg/sum/count (nd_imagenet.py:361-421), and the Apex variant's nameless
meters (imagenet_ddp_apex.py:509-524, covered by the ``name=""`` default).
That surface is locked byte-for-byte by the golden test in
``tests/test_meters.py::test_golden_console_surface``.

The *internals* are dptpu's own: a meter is a weighted accumulator pair
``(total, weight)`` plus the last observed value, and ``val/avg/sum/count``
are derived read-only properties rather than four mutable fields updated in
lockstep — there is no state that can drift out of sync, and ``avg`` is
well-defined (0) even before the first update. Formatting goes through
:func:`format` with the spec string directly instead of building and
re-parsing a ``str.format`` template per call.
"""

from enum import Enum


class Summary(Enum):
    NONE = 0
    AVERAGE = 1
    SUM = 2
    COUNT = 3


# Summary variant -> which derived statistic it reports (None = silent).
_SUMMARY_STAT = {
    Summary.NONE: None,
    Summary.AVERAGE: "avg",
    Summary.SUM: "sum",
    Summary.COUNT: "count",
}


class AverageMeter:
    """Weighted running average with the reference meter's display surface.

    ``update(v, n)`` folds in ``n`` observations of value ``v``;
    ``val``/``avg``/``sum``/``count`` are derived properties over the
    ``(total, weight, last)`` accumulator state.
    """

    def __init__(self, name="", fmt=":f", summary_type=Summary.AVERAGE):
        self.name = name
        self.fmt = fmt
        self.summary_type = summary_type
        self.reset()

    def reset(self):
        self._last = 0
        self._total = 0
        self._weight = 0

    def update(self, val, n=1):
        self._last = val
        self._total += val * n
        self._weight += n

    @property
    def val(self):
        """Most recently observed value (0 before any update)."""
        return self._last

    @property
    def sum(self):
        """Weighted sum of observed values."""
        return self._total

    @property
    def count(self):
        """Total observation weight."""
        return self._weight

    @property
    def avg(self):
        """Weighted mean; 0 for an empty meter (matching a fresh reset)."""
        return self._total / self._weight if self._weight else 0

    def _format(self, value):
        # fmt is a ":"-prefixed format spec (e.g. ":6.2f"); apply it directly
        return format(value, self.fmt[1:] if self.fmt.startswith(":") else self.fmt)

    def __str__(self):
        # "{name} {val:fmt} ({avg:fmt})" — imagenet_ddp.py:352-354
        return f"{self.name} {self._format(self.val)} ({self._format(self.avg)})"

    def summary(self):
        # " {name} {stat:.3f}" per Summary variant — nd_imagenet.py:389-404
        try:
            stat = _SUMMARY_STAT[self.summary_type]
        except (KeyError, TypeError):
            raise ValueError(f"invalid summary type {self.summary_type!r}")
        if stat is None:
            return ""
        return f"{self.name} {getattr(self, stat):.3f}"


class ProgressMeter:
    """Prints ``<prefix>[i/N]`` progress lines over a set of meters.

    The batch counter is right-aligned to the width of ``N`` so columns stay
    stable across an epoch (``[  7/391]``), exactly the reference's line
    shape (imagenet_ddp.py:357-371; summary epilogue nd_imagenet.py:418-421).
    """

    def __init__(self, num_batches, meters, prefix=""):
        self.num_batches = num_batches
        self.meters = meters
        self.prefix = prefix

    def _counter(self, batch):
        width = len(str(self.num_batches))
        return f"[{batch:{width}d}/{self.num_batches}]"

    def display(self, batch):
        print("\t".join([self.prefix + self._counter(batch),
                         *(str(m) for m in self.meters)]))

    def display_summary(self):
        print(" ".join([" *", *(m.summary() for m in self.meters)]))
