"""Running-statistics meters with the reference's exact display surface.

The reference ships three meter variants with one shared core:

* ``AverageMeter(name, fmt)`` with ``val/sum/count/avg`` running stats and a
  ``"{name} {val:fmt} ({avg:fmt})"`` string form (imagenet_ddp.py:333-354).
* The Apex variant drops ``name``/``fmt`` (imagenet_ddp_apex.py:509-524) —
  covered here by the defaults.
* The nd variant adds a ``Summary`` enum {NONE, AVERAGE, SUM, COUNT} and a
  ``summary()`` formatter (nd_imagenet.py:361-404).

``ProgressMeter`` prints ``"<prefix>[i/N]\\t<meter>\\t<meter>..."`` lines
(imagenet_ddp.py:357-371) plus the nd variant's ``display_summary()``
(nd_imagenet.py:418-421). This single implementation is a superset of all
three, so every entry point shares one meter surface.
"""

from enum import Enum


class Summary(Enum):
    NONE = 0
    AVERAGE = 1
    SUM = 2
    COUNT = 3


class AverageMeter:
    """Computes and stores the average and current value."""

    def __init__(self, name="", fmt=":f", summary_type=Summary.AVERAGE):
        self.name = name
        self.fmt = fmt
        self.summary_type = summary_type
        self.reset()

    def reset(self):
        self.val = 0
        self.avg = 0
        self.sum = 0
        self.count = 0

    def update(self, val, n=1):
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count

    def __str__(self):
        fmtstr = "{name} {val" + self.fmt + "} ({avg" + self.fmt + "})"
        return fmtstr.format(**self.__dict__)

    def summary(self):
        if self.summary_type is Summary.NONE:
            fmtstr = ""
        elif self.summary_type is Summary.AVERAGE:
            fmtstr = "{name} {avg:.3f}"
        elif self.summary_type is Summary.SUM:
            fmtstr = "{name} {sum:.3f}"
        elif self.summary_type is Summary.COUNT:
            fmtstr = "{name} {count:.3f}"
        else:
            raise ValueError("invalid summary type %r" % self.summary_type)
        return fmtstr.format(**self.__dict__)


class ProgressMeter:
    def __init__(self, num_batches, meters, prefix=""):
        self.batch_fmtstr = self._get_batch_fmtstr(num_batches)
        self.meters = meters
        self.prefix = prefix

    def display(self, batch):
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(meter) for meter in self.meters]
        print("\t".join(entries))

    def display_summary(self):
        entries = [" *"]
        entries += [meter.summary() for meter in self.meters]
        print(" ".join(entries))

    @staticmethod
    def _get_batch_fmtstr(num_batches):
        num_digits = len(str(num_batches // 1))
        fmt = "{:" + str(num_digits) + "d}"
        return "[" + fmt + "/" + fmt.format(num_batches) + "]"
