"""Runtime half of the concurrency contract: ordered locks + stop tokens.

The static half (``dptpu check``'s ``guarded-by`` / ``lock-order`` /
``thread-hygiene`` rules, dptpu/analysis/concurrency.py) derives a
repo-wide lock acquisition order from the AST; this module is where that
order is DECLARED (:data:`LOCK_RANKS`) and asserted at runtime.

* :func:`OrderedLock` / :func:`OrderedRLock` / :func:`ordered_mp_lock` —
  factories for the repo's named locks. ZERO-COST unless
  ``DPTPU_SYNC_CHECK=1``: disabled they return the raw primitive
  (``threading.Lock()`` etc.) with no wrapping at all, so production hot
  paths pay nothing. Enabled, every lock records per-thread acquisition
  stacks and an UNBOUNDED acquire while already holding a lock of equal
  or higher rank raises :class:`LockOrderError` naming both locks and
  both acquisition stacks — the ABBA deadlock surfaces as a loud,
  attributable failure on the FIRST inverted acquisition, not as a
  wedged pod an hour later. Deadline-bounded acquisitions
  (``timeout=``/``blocking=False`` — the shm slab's whole protocol) are
  exempt from the order assert: a bounded try-acquire cannot deadlock,
  it can only time out.

* :func:`held_locks` — the per-thread held-lock registry the
  ``# guarded-by:`` annotations conceptually name; a debugging aid and
  the sanitizer's own bookkeeping.

* :class:`StopToken` — the one blessed thread-teardown idiom: loops
  block in ``token.wait(interval)`` instead of ``time.sleep`` +
  flag-polling, so ``stop()`` wakes them IMMEDIATELY and teardown is
  prompt (the quorum heartbeat and the shard-extent prefetcher ride it;
  the ``thread-hygiene`` lint polices new threads toward it).

Stdlib-only — imported by the data layer (spawned decode workers, never
JAX) and by the lint rules themselves (which cross-check every
``OrderedLock("name")`` literal against :data:`LOCK_RANKS`).
"""

from __future__ import annotations

import sys
import threading
from typing import List, Optional, Tuple

from dptpu.envknob import env_bool

SYNC_CHECK_KNOB = "DPTPU_SYNC_CHECK"


def sync_check_enabled(environ=None) -> bool:
    """The ``DPTPU_SYNC_CHECK`` knob under the locked fail-fast
    contract. Read at LOCK CONSTRUCTION time (not per acquire), so the
    disabled mode's zero-wrapping guarantee holds."""
    return bool(env_bool(SYNC_CHECK_KNOB, False, environ))


# The global lock order, low rank = acquired first (outermost). A thread
# may only take an UNBOUNDED acquisition of a lock whose rank is
# STRICTLY greater than every lock it already holds. Derived from the
# static lock-order graph (``dptpu check``) and documented with the
# thread inventory in CONCURRENCY.md; the lock-order lint rejects an
# ``OrderedLock(name)`` whose name is not declared here, and rejects
# nested ``with`` scopes that invert these ranks.
LOCK_RANKS = {
    # serve: the batcher's dispatcher/submitter seam is outermost (it
    # calls into the engine, the histogram and the tracer while running).
    # Admission sits ABOVE the batcher because occupancy releases run in
    # future done-callbacks fired under the batcher's condition; the
    # canary controller sits between admission and the engine because
    # pick/rollback/promote pin generations while holding its lock.
    "serve.batcher": 10,
    # the fleet router's route table: acquired only from the fleet
    # front's poll/pick/release paths, never while holding (or under)
    # any member-side serve lock — forwarding happens entirely off-lock.
    "serve.fleet": 12,
    "serve.admission": 15,
    "serve.canary": 18,
    "serve.engine": 20,
    # train: the async checkpoint writer's error seam
    "train.ckpt_writer": 30,
    # data plane: store telemetry > shard engine > per-file reader >
    # in-process decode cache
    "data.store": 40,
    "data.shard_engine": 50,
    "data.shard_reader": 60,
    "data.decode_cache": 70,
    # observability: the trace ring is innermost — record() may be
    # called from any thread, under anyone's lock
    "obs.trace_ring": 80,
    # cross-process pooled slab (dptpu/data/shm_cache.py). Every
    # acquisition in that protocol is deadline-bounded (try-acquire +
    # orphan recovery), so the runtime order assert never applies; the
    # ranks document the designed arena -> recovery -> stripe order.
    "shm.alloc": 100,
    "shm.recovery": 110,
    "shm.stripe": 120,
}


class LockOrderError(RuntimeError):
    """An unbounded acquisition inverted the declared LOCK_RANKS order."""


# -- per-thread held-lock registry -------------------------------------------

_tls = threading.local()


def _stack() -> list:
    s = getattr(_tls, "held", None)
    if s is None:
        s = _tls.held = []
    return s


def held_locks() -> List[Tuple[str, int]]:
    """``[(name, rank), ...]`` of checked locks THIS thread holds,
    oldest first. Empty when ``DPTPU_SYNC_CHECK`` is off (raw locks do
    no bookkeeping — that is the zero-cost contract)."""
    return [(e[1], e[2]) for e in _stack()]


def _capture_frames(skip: int = 2, limit: int = 12) -> List[str]:
    """A cheap acquisition stack: ``file:line in func`` frames walked via
    sys._getframe — no linecache I/O, ~µs, affordable per acquire."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return []
    out: List[str] = []
    while f is not None and len(out) < limit:
        out.append(
            f"{f.f_code.co_filename}:{f.f_lineno} in {f.f_code.co_name}"
        )
        f = f.f_back
    return out


def _check_order(lock, name: str, rank: int, reentrant: bool):
    """The order assert for an UNBOUNDED acquire: every lock this thread
    already holds must rank strictly below the one being taken."""
    for entry in _stack():
        held_obj, held_name, held_rank, held_frames = entry
        if held_obj is lock:
            if reentrant:
                continue  # RLock re-entry is legal by definition
            raise LockOrderError(
                f"dptpu sync: re-acquiring non-reentrant lock "
                f"'{name}' already held by this thread (self-deadlock)."
                f"\n  first acquired at:\n    "
                + "\n    ".join(held_frames)
                + "\n  re-acquired at:\n    "
                + "\n    ".join(_capture_frames(skip=3))
            )
        if held_rank >= rank:
            raise LockOrderError(
                f"dptpu sync: lock order violation — acquiring "
                f"'{name}' (rank {rank}) while holding "
                f"'{held_name}' (rank {held_rank}); the declared order "
                f"(dptpu/utils/sync.py LOCK_RANKS, CONCURRENCY.md) "
                f"requires '{name}' first."
                f"\n  '{held_name}' acquired at:\n    "
                + "\n    ".join(held_frames)
                + f"\n  '{name}' acquisition at:\n    "
                + "\n    ".join(_capture_frames(skip=3))
            )


def _push(lock, name: str, rank: int):
    _stack().append((lock, name, rank, _capture_frames(skip=3)))


def _pop(lock):
    s = _stack()
    # search from the top: releases are LIFO in practice, and a release
    # of a lock this thread never recorded (the shm orphan-recovery
    # path releasing a DEAD owner's semaphore) must stay a no-op here
    for i in range(len(s) - 1, -1, -1):
        if s[i][0] is lock:
            del s[i]
            return


class _CheckedLock:
    """threading.Lock with rank checking + held bookkeeping (the
    DPTPU_SYNC_CHECK=1 arm; disabled mode never builds one)."""

    _reentrant = False

    def __init__(self, name: str, rank: int, inner=None):
        self.name = name
        self.rank = rank
        self._inner = inner if inner is not None else self._make_inner()

    @staticmethod
    def _make_inner():
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        bounded = (not blocking) or (timeout is not None and timeout >= 0)
        if not bounded:
            _check_order(self, self.name, self.rank, self._reentrant)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _push(self, self.name, self.rank)
        return got

    def release(self):
        _pop(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:
        # accurate ownership for threading.Condition (the raw Lock
        # fallback probe would call acquire(False) and say "owned"
        # whenever ANYONE holds it)
        return any(e[0] is self for e in _stack())

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return (f"<{type(self).__name__} {self.name!r} rank={self.rank} "
                f"inner={self._inner!r}>")


class _CheckedRLock(_CheckedLock):
    _reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def _is_owned(self):
        return self._inner._is_owned()


class _CheckedMpLock:
    """A ``multiprocessing`` Lock under the same bookkeeping. The shm
    slab acquires ONLY with deadlines (bounded — no order assert ever
    fires), so this wrapper's value is the held registry and the shared
    naming. Pickles across the spawn boundary exactly like the raw mp
    lock it wraps (the attach spec in ShmDecodeCache.__getstate__)."""

    def __init__(self, inner, name: str, rank: int):
        self._inner = inner
        self.name = name
        self.rank = rank

    def acquire(self, block: bool = True, timeout: Optional[float] = None
                ) -> bool:
        if block and timeout is None:
            _check_order(self, self.name, self.rank, reentrant=False)
        got = self._inner.acquire(block, timeout)
        if got:
            _push(self, self.name, self.rank)
        return got

    def release(self):
        _pop(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getstate__(self):
        # rides the same spawn-only boundary as the raw mp lock
        return {"inner": self._inner, "name": self.name, "rank": self.rank}

    def __setstate__(self, state):
        self._inner = state["inner"]
        self.name = state["name"]
        self.rank = state["rank"]


def _resolve_rank(name: str) -> int:
    if name not in LOCK_RANKS:
        raise ValueError(
            f"OrderedLock name {name!r} is not declared in "
            f"dptpu/utils/sync.py LOCK_RANKS — declare it (and its place "
            f"in the CONCURRENCY.md order table); known: "
            f"{', '.join(sorted(LOCK_RANKS))}"
        )
    return LOCK_RANKS[name]


def OrderedLock(name: str):
    """A named, rank-ordered mutex. ``DPTPU_SYNC_CHECK`` off (the
    default): returns a RAW ``threading.Lock`` — zero wrapping, zero
    cost. On: a checked lock that asserts :data:`LOCK_RANKS` on every
    unbounded acquire. The name must be declared in LOCK_RANKS (the
    lock-order lint enforces this statically too)."""
    rank = _resolve_rank(name)
    if not sync_check_enabled():
        return threading.Lock()
    return _CheckedLock(name, rank)


def OrderedRLock(name: str):
    """Reentrant variant of :func:`OrderedLock` (same-lock re-entry is
    exempt from the order assert)."""
    rank = _resolve_rank(name)
    if not sync_check_enabled():
        return threading.RLock()
    return _CheckedRLock(name, rank)


def ordered_mp_lock(name: str, ctx):
    """A ``multiprocessing`` lock (from ``ctx``) under the shared naming/
    bookkeeping; raw ``ctx.Lock()`` when the check is off."""
    rank = _resolve_rank(name)
    inner = ctx.Lock()
    if not sync_check_enabled():
        return inner
    return _CheckedMpLock(inner, name, rank)


# -- the stop-token teardown idiom -------------------------------------------


class StopToken:
    """The one blessed way a dptpu background thread idles and stops.

    A loop that would otherwise ``time.sleep(interval)`` and poll a
    ``self._stop`` flag blocks in ``token.wait(interval)`` instead:
    ``stop()`` sets the underlying Event and the waiter wakes
    IMMEDIATELY — teardown latency is the cost of the in-flight work
    item, never the residue of a sleep. The canonical loop::

        while not stop.wait(interval_s):
            do_periodic_work()        # heartbeat, poll, flush...

    and for queue-draining threads, pair ``stop()`` with a sentinel
    enqueue so a blocking ``get()`` wakes too.
    """

    __slots__ = ("_event",)

    def __init__(self):
        self._event = threading.Event()

    def stop(self):
        self._event.set()

    @property
    def stopped(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block up to ``timeout`` (None = forever); True when stopped."""
        return self._event.wait(timeout)
