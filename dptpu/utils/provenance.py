"""Host provenance — the fingerprint every committed artifact carries.

Moved here from scripts/bench_util.py (which re-exports it) so the
static-analysis report (ANALYSIS.json, dptpu/analysis/report.py) can
stamp itself the way every bench artifact does without importing the
scripts tree: ROADMAP's standing caveat — "every number since r6 is
from a throttled 2-core host" — stays a machine-readable field, and
automated comparisons can refuse to diff artifacts from different host
classes.
"""

from __future__ import annotations

import os
import platform
import sys


def host_provenance() -> dict:
    """The host fingerprint every committed artifact carries: CPU
    budget, platform triple, interpreter and jax/XLA versions. Cheap,
    pure, and safe to call before OR after jax initializes a backend.
    The jax version is read from ``sys.modules`` WITHOUT importing jax:
    a lint-only ``dptpu check --no-hlo`` run (or a spawned data worker)
    must stay genuinely jax-free — every caller that benches jax code
    has already imported it, so the field is still populated wherever
    it is meaningful (``None`` = the stamping process never loaded
    jax)."""
    jax_version = getattr(sys.modules.get("jax"), "__version__", None)
    affinity = None
    if hasattr(os, "sched_getaffinity"):
        try:
            affinity = len(os.sched_getaffinity(0))
        except OSError:
            affinity = None
    return {
        "cpu_count": os.cpu_count(),
        "cpu_affinity": affinity,
        "platform": platform.platform(),
        "machine": platform.machine(),
        "python": sys.version.split()[0],
        "jax": jax_version,
    }
