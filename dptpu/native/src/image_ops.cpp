// dptpu native image ops: JPEG decode + fused bilinear crop-resize (+flip).
//
// The hot path of the host input pipeline. The reference leans on
// torchvision's PIL loader + C image ops inside torch DataLoader worker
// processes (reference imagenet_ddp.py:166-194); this is the dptpu-native
// equivalent: a small C core driven from Python threads via ctypes (the
// call releases the GIL, so a thread pool scales across cores without
// process forking).
//
// Two tricks make it faster than the PIL path:
//  1. libjpeg scaled decode (scale_num/8): when the sampled crop will be
//     downscaled to out_size anyway, decode directly at 1/2, 3/8, ... of
//     full resolution — typically 3-6x less IDCT + color-convert work for
//     ImageNet-sized JPEGs cropped to 224.
//  2. crop+resize+flip fused into one bilinear gather straight into the
//     caller's batch slot — no intermediate full-size RGB copy beyond the
//     decode buffer, no per-item allocation in steady state.
//
// C ABI (ctypes): all functions return 0 on success, negative on failure
// (caller falls back to the PIL path — e.g. PNGs land there).

#include <cstddef>
#include <cstdio>  // jpeglib.h uses FILE/size_t without including them

#include <jpeglib.h>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// Bilinear sample of src (h x w x 3) region [left,top,cw,ch] to out
// (out_size x out_size x 3), optional horizontal flip. Matches PIL's
// box-resize semantics: source pixel centers at integer+0.5 coordinates.
// Horizontal coordinates/weights are identical for every output row, so
// they are computed once into LUTs; the inner loop is fixed-point (15-bit
// weights) with two horizontal lerps + one vertical lerp per channel.
void crop_resize_bilinear(const uint8_t* src, int src_w, int src_h,
                          double left, double top, double cw, double ch,
                          int out_size, bool flip, uint8_t* out) {
  constexpr int kBits = 15;
  constexpr int kOne = 1 << kBits;
  const double sx = cw / out_size;
  const double sy = ch / out_size;

  std::vector<int> x0s(out_size), x1s(out_size), wxs(out_size);
  for (int ox = 0; ox < out_size; ++ox) {
    const int tx = flip ? (out_size - 1 - ox) : ox;
    const double fx = left + (tx + 0.5) * sx - 0.5;
    int x0 = static_cast<int>(std::floor(fx));
    const double wx = fx - x0;
    int x1 = x0 + 1;
    x0s[ox] = std::clamp(x0, 0, src_w - 1) * 3;
    x1s[ox] = std::clamp(x1, 0, src_w - 1) * 3;
    wxs[ox] = static_cast<int>(wx * kOne + 0.5);
  }

  for (int oy = 0; oy < out_size; ++oy) {
    const double fy = top + (oy + 0.5) * sy - 0.5;
    int y0 = static_cast<int>(std::floor(fy));
    const double wyd = fy - y0;
    int y1 = y0 + 1;
    y0 = std::clamp(y0, 0, src_h - 1);
    y1 = std::clamp(y1, 0, src_h - 1);
    const int wy = static_cast<int>(wyd * kOne + 0.5);
    const uint8_t* row0 = src + static_cast<size_t>(y0) * src_w * 3;
    const uint8_t* row1 = src + static_cast<size_t>(y1) * src_w * 3;
    uint8_t* orow = out + static_cast<size_t>(oy) * out_size * 3;
    for (int ox = 0; ox < out_size; ++ox) {
      const int x0 = x0s[ox], x1 = x1s[ox], wx = wxs[ox];
      for (int c = 0; c < 3; ++c) {
        const int t0 = (row0[x0 + c] << kBits) +
                       (row0[x1 + c] - row0[x0 + c]) * wx;
        const int t1 = (row1[x0 + c] << kBits) +
                       (row1[x1 + c] - row1[x0 + c]) * wx;
        const int64_t v =
            (static_cast<int64_t>(t0) << kBits) +
            static_cast<int64_t>(t1 - t0) * wy;
        orow[ox * 3 + c] =
            static_cast<uint8_t>((v + (1ll << (2 * kBits - 1))) >> (2 * kBits));
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Pillow-exact bilinear box resample (the serve-ingest path).
//
// The serving contract (dptpu/serve/preprocess.py) is BIT-identity with the
// PIL val pipeline — the pixels published accuracies were measured on. The
// augmentation-grade kernel above (scaled decode + 2-tap lerp) trades that
// for speed; this one replicates Pillow's ImagingResample for the BILINEAR
// filter exactly: per-output-pixel normalized coefficient windows computed
// in double, quantized to PRECISION_BITS fixed point with round-half-away,
// a horizontal pass into a uint8 intermediate restricted to the vertical
// window, then a vertical pass — including both uint8 quantization steps,
// Pillow's clip8 saturation, and its pass-skip conditions, so the output
// byte-matches img.resize((s, s), BILINEAR, box=...) on the same decode.
namespace pillow_exact {

constexpr int kPrecisionBits = 32 - 8 - 2;  // Pillow's PRECISION_BITS

inline uint8_t clip8(int in) {
  if (in >= (1 << (kPrecisionBits + 8))) return 255;
  if (in <= 0) return 0;
  return static_cast<uint8_t>(in >> kPrecisionBits);
}

inline double bilinear_filter(double x) {
  if (x < 0.0) x = -x;
  if (x < 1.0) return 1.0 - x;
  return 0.0;
}

// Pillow's precompute_coeffs, verbatim semantics (support = 1.0 bilinear):
// returns ksize, fills per-pixel [xmin, xmax) bounds and normalized double
// weights (outSize x ksize). The box endpoints are SINGLE-precision and
// their difference is subtracted in float before the double divide —
// exactly Pillow's `(double)(in1 - in0) / outSize` with float args; doing
// either step in double shifts coefficient windows by ~1e-7 px and flips
// ±1 output LSBs (measured on the probe set).
int precompute_coeffs(int in_size, float in0, float in1, int out_size,
                      std::vector<int>* bounds, std::vector<double>* kk) {
  double scale = static_cast<double>(in1 - in0) / out_size;
  double filterscale = scale < 1.0 ? 1.0 : scale;
  const double support = 1.0 * filterscale;
  const int ksize = static_cast<int>(std::ceil(support)) * 2 + 1;
  kk->assign(static_cast<size_t>(out_size) * ksize, 0.0);
  bounds->assign(static_cast<size_t>(out_size) * 2, 0);
  for (int xx = 0; xx < out_size; ++xx) {
    const double center = in0 + (xx + 0.5) * scale;
    const double ss = 1.0 / filterscale;
    int xmin = static_cast<int>(center - support + 0.5);
    if (xmin < 0) xmin = 0;
    int xmax = static_cast<int>(center + support + 0.5);
    if (xmax > in_size) xmax = in_size;
    xmax -= xmin;
    double* k = &(*kk)[static_cast<size_t>(xx) * ksize];
    double ww = 0.0;
    int x = 0;
    for (; x < xmax; ++x) {
      const double w = bilinear_filter((x + xmin - center + 0.5) * ss);
      k[x] = w;
      ww += w;
    }
    for (x = 0; x < xmax; ++x) {
      if (ww != 0.0) k[x] /= ww;
    }
    for (; x < ksize; ++x) k[x] = 0.0;
    (*bounds)[xx * 2 + 0] = xmin;
    (*bounds)[xx * 2 + 1] = xmax;
  }
  return ksize;
}

// Pillow's normalize_coeffs_8bpc: round-half-away-from-zero into fixed point.
void normalize_coeffs_8bpc(const std::vector<double>& prekk,
                           std::vector<int>* kk) {
  kk->resize(prekk.size());
  for (size_t i = 0; i < prekk.size(); ++i) {
    (*kk)[i] = prekk[i] < 0
                   ? static_cast<int>(-0.5 + prekk[i] * (1 << kPrecisionBits))
                   : static_cast<int>(0.5 + prekk[i] * (1 << kPrecisionBits));
  }
}

// Horizontal pass: src rows [offset, offset + dst_h) -> dst (dst_w wide).
void resample_horizontal(uint8_t* dst, int dst_w, int dst_h,
                         const uint8_t* src, int src_w, int offset,
                         int ksize, const std::vector<int>& bounds,
                         const std::vector<int>& kk) {
  for (int yy = 0; yy < dst_h; ++yy) {
    const uint8_t* srow =
        src + static_cast<size_t>(yy + offset) * src_w * 3;
    uint8_t* drow = dst + static_cast<size_t>(yy) * dst_w * 3;
    for (int xx = 0; xx < dst_w; ++xx) {
      const int xmin = bounds[xx * 2], xmax = bounds[xx * 2 + 1];
      const int* k = &kk[static_cast<size_t>(xx) * ksize];
      int s0 = 1 << (kPrecisionBits - 1), s1 = s0, s2 = s0;
      for (int x = 0; x < xmax; ++x) {
        const uint8_t* p = srow + static_cast<size_t>(xmin + x) * 3;
        s0 += p[0] * k[x];
        s1 += p[1] * k[x];
        s2 += p[2] * k[x];
      }
      drow[xx * 3 + 0] = clip8(s0);
      drow[xx * 3 + 1] = clip8(s1);
      drow[xx * 3 + 2] = clip8(s2);
    }
  }
}

// Vertical pass over the (already-horizontal) intermediate (width == dst_w).
void resample_vertical(uint8_t* dst, int dst_w, int dst_h,
                       const uint8_t* src, int ksize,
                       const std::vector<int>& bounds,
                       const std::vector<int>& kk) {
  for (int yy = 0; yy < dst_h; ++yy) {
    const int ymin = bounds[yy * 2], ymax = bounds[yy * 2 + 1];
    const int* k = &kk[static_cast<size_t>(yy) * ksize];
    uint8_t* drow = dst + static_cast<size_t>(yy) * dst_w * 3;
    for (int xx = 0; xx < dst_w; ++xx) {
      int s0 = 1 << (kPrecisionBits - 1), s1 = s0, s2 = s0;
      for (int y = 0; y < ymax; ++y) {
        const uint8_t* p =
            src + (static_cast<size_t>(ymin + y) * dst_w + xx) * 3;
        s0 += p[0] * k[y];
        s1 += p[1] * k[y];
        s2 += p[2] * k[y];
      }
      drow[xx * 3 + 0] = clip8(s0);
      drow[xx * 3 + 1] = clip8(s1);
      drow[xx * 3 + 2] = clip8(s2);
    }
  }
}

// ImagingResample for one fractional box -> out_size x out_size x 3,
// including the pass-skip conditions (an identity axis is NOT resampled —
// and therefore not re-quantized — exactly as in Pillow).
int resample_box(const uint8_t* src, int src_w, int src_h, float bx0,
                 float by0, float bx1, float by1, int out_size,
                 uint8_t* out) {
  const bool need_h = out_size != src_w || bx0 != 0.0f ||
                      bx1 != static_cast<float>(out_size);
  const bool need_v = out_size != src_h || by0 != 0.0f ||
                      by1 != static_cast<float>(out_size);
  std::vector<int> bounds_h, bounds_v, kkh, kkv;
  std::vector<double> pre_h, pre_v;
  const int ksize_h =
      precompute_coeffs(src_w, bx0, bx1, out_size, &bounds_h, &pre_h);
  const int ksize_v =
      precompute_coeffs(src_h, by0, by1, out_size, &bounds_v, &pre_v);
  normalize_coeffs_8bpc(pre_h, &kkh);
  normalize_coeffs_8bpc(pre_v, &kkv);
  // source rows the vertical filter will touch: the horizontal pass only
  // materializes those.
  const int ybox_first = bounds_v[0];
  const int ybox_last =
      bounds_v[out_size * 2 - 2] + bounds_v[out_size * 2 - 1];
  if (need_h && need_v) {
    for (int i = 0; i < out_size; ++i) bounds_v[i * 2] -= ybox_first;
    std::vector<uint8_t> temp(static_cast<size_t>(out_size) *
                              (ybox_last - ybox_first) * 3);
    resample_horizontal(temp.data(), out_size, ybox_last - ybox_first, src,
                        src_w, ybox_first, ksize_h, bounds_h, kkh);
    resample_vertical(out, out_size, out_size, temp.data(), ksize_v,
                      bounds_v, kkv);
  } else if (need_h) {
    resample_horizontal(out, out_size, out_size, src, src_w, 0, ksize_h,
                        bounds_h, kkh);
  } else if (need_v) {
    resample_vertical(out, out_size, out_size, src, ksize_v, bounds_v, kkv);
  } else {
    std::memcpy(out, src, static_cast<size_t>(out_size) * out_size * 3);
  }
  return 0;
}

}  // namespace pillow_exact

}  // namespace

extern "C" {

// Advise the kernel to pull a file's bytes into the page cache
// asynchronously (posix_fadvise WILLNEED) — the cold-epoch JPEG
// readahead path. The parent calls this at span PRE-ISSUE time, so by
// the time a worker opens the file (decode_ahead batches later) the
// read services from memory instead of stalling a decode core on disk
// latency. Returns the file size on success (telemetry-friendly),
// negative on failure. The GIL is released for the open/advise/close
// (ctypes does this for every call here), so the parent's submit path
// pays microseconds, not I/O.
long long dptpu_file_readahead(const char* path) {
  const int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  long long size = 0;
  if (fstat(fd, &st) == 0) size = static_cast<long long>(st.st_size);
#if defined(POSIX_FADV_WILLNEED)
  const int rc = posix_fadvise(fd, 0, 0, POSIX_FADV_WILLNEED);
#else
  const int rc = 0;  // no fadvise on this platform: open itself primed
                     // the dentry/inode caches, which is all we can do
#endif
  close(fd);
  return rc == 0 ? size : -2;
}

// Parse JPEG header only; writes full-resolution dimensions.
int dptpu_jpeg_dims(const uint8_t* data, size_t size, int* width,
                    int* height) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  *width = static_cast<int>(cinfo.image_width);
  *height = static_cast<int>(cinfo.image_height);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Full-resolution decode into a caller buffer of expected_w x expected_h x 3
// (dimensions from dptpu_jpeg_dims) — the decode-cache fill path. Identical
// libjpeg settings to dptpu_jpeg_decode_crop_resize at scale 8/8 (JCS_RGB,
// IFAST DCT), so a crop-resize from this buffer is BIT-IDENTICAL to the
// fused path whenever the fused path's scale picker stays at full
// resolution (it always does when no crop axis reaches out_size*8/7).
int dptpu_jpeg_decode_rgb(const uint8_t* data, size_t size, int expected_w,
                          int expected_h, uint8_t* out) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  cinfo.scale_num = 8;
  cinfo.scale_denom = 8;
  cinfo.out_color_space = JCS_RGB;
  cinfo.dct_method = JDCT_IFAST;
  jpeg_start_decompress(&cinfo);
  if (static_cast<int>(cinfo.output_width) != expected_w ||
      static_cast<int>(cinfo.output_height) != expected_h) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -4;  // caller's buffer was sized from stale/foreign dims
  }
  const int dw = static_cast<int>(cinfo.output_width);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out + static_cast<size_t>(cinfo.output_scanline) * dw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Crop + bilinear resize + flip from a raw RGB buffer (src_w x src_h x 3) —
// the decode-cache HIT path: same kernel the fused decode path uses, so
// hit and miss produce the same pixels from the same decoded buffer.
int dptpu_crop_resize_rgb(const uint8_t* src, int src_w, int src_h,
                          double crop_left, double crop_top, double crop_w,
                          double crop_h, int out_size, int flip,
                          uint8_t* out) {
  if (src_w <= 0 || src_h <= 0 || crop_w <= 0.0 || crop_h <= 0.0 ||
      out_size <= 0) {
    return -3;
  }
  crop_resize_bilinear(src, src_w, src_h, crop_left, crop_top, crop_w,
                       crop_h, out_size, flip != 0, out);
  return 0;
}

// Decode + crop box (full-resolution coords; FRACTIONAL boxes allowed —
// the exact-val-pipeline path expresses Resize(256)+CenterCrop(224) as
// one fractional box) + bilinear resize to out_size x out_size RGB +
// optional horizontal flip, into `out` (out_size*out_size*3 bytes,
// caller-allocated).
int dptpu_jpeg_decode_crop_resize(const uint8_t* data, size_t size,
                                  double crop_left, double crop_top,
                                  double crop_w, double crop_h,
                                  int out_size, int flip,
                                  uint8_t* out) {
  if (crop_w <= 0.0 || crop_h <= 0.0 || out_size <= 0) return -3;
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  std::vector<uint8_t> pixels;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  // Scaled decode: largest downscale such that the decoded crop still has
  // >= out_size pixels on each axis (never upsample a crop we'd then
  // shrink; keep full quality when the crop must be enlarged).
  int num = 8;
  while (num > 1) {
    const int cand = num - 1;
    if (crop_w * cand >= out_size * 8.0 && crop_h * cand >= out_size * 8.0) {
      num = cand;
    } else {
      break;
    }
  }
  cinfo.scale_num = static_cast<unsigned>(num);
  cinfo.scale_denom = 8;
  cinfo.out_color_space = JCS_RGB;
  cinfo.dct_method = JDCT_IFAST;  // augmentation path: speed over the last
                                  // fraction of a bit of DCT precision
  jpeg_start_decompress(&cinfo);
  const int dw = static_cast<int>(cinfo.output_width);
  const int dh = static_cast<int>(cinfo.output_height);
  pixels.resize(static_cast<size_t>(dw) * dh * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = pixels.data() +
                   static_cast<size_t>(cinfo.output_scanline) * dw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  const double full_w = static_cast<double>(cinfo.image_width);
  const double full_h = static_cast<double>(cinfo.image_height);
  jpeg_destroy_decompress(&cinfo);

  // Map the full-resolution crop box into decoded coordinates.
  const double rx = dw / full_w;
  const double ry = dh / full_h;
  crop_resize_bilinear(pixels.data(), dw, dh, crop_left * rx, crop_top * ry,
                       crop_w * rx, crop_h * ry, out_size, flip != 0, out);
  return 0;
}

// The fused serve-ingest kernel: request JPEG bytes -> the val pipeline's
// uint8 pixels, straight into the caller's staging-ring row. One native
// call replaces the PIL round trip (bytes -> PIL Image -> convert ->
// box-resize -> np.asarray -> copyto), with no intermediate fp32 HWC
// buffer anywhere: the resample runs in Pillow's own fixed-point integer
// arithmetic and the output stays uint8 (normalization remains fused into
// the compiled forward on device, exactly as on the PIL path).
//
// BIT-IDENTITY is the contract, not a goal: the decode uses PIL's own
// libjpeg settings (full resolution, ISLOW DCT, fancy upsampling — the
// library defaults PIL never overrides) and the resample replicates
// ImagingResample exactly (pillow_exact above); Resize(resize) +
// CenterCrop(out_size) is folded to the same fractional box the Python
// side computes (center_fit_box, dptpu/data/transforms.py — integer math
// reproduced here 1:1). The Python wrapper PROVES the identity at first
// use with a probe against the PIL path and falls back loudly on any
// mismatch, so a foreign libjpeg can never silently change served pixels.
//
// Returns 0 on success; negative = caller must take the PIL path
// (non-JPEG container, CMYK/YCCK color — PIL's CMYK->RGB convert is not
// a libjpeg conversion — or corrupt bytes).
int dptpu_serve_ingest(const uint8_t* data, size_t size, int out_size,
                       int resize, uint8_t* out) {
  if (out_size <= 0 || resize <= 0) return -3;
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  std::vector<uint8_t> pixels;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  if (cinfo.jpeg_color_space == JCS_CMYK ||
      cinfo.jpeg_color_space == JCS_YCCK) {
    jpeg_destroy_decompress(&cinfo);
    return -5;  // PIL's CMYK handling is its own convert; don't imitate
  }
  // PIL's decode settings exactly: no scaling, ISLOW, fancy upsampling
  // (the last two are the libjpeg defaults PIL leaves untouched);
  // grayscale -> RGB replication matches PIL's L -> RGB convert.
  cinfo.out_color_space = JCS_RGB;
  cinfo.dct_method = JDCT_ISLOW;
  jpeg_start_decompress(&cinfo);
  const int w = static_cast<int>(cinfo.output_width);
  const int h = static_cast<int>(cinfo.output_height);
  if (w <= 0 || h <= 0) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -6;
  }
  pixels.resize(static_cast<size_t>(w) * h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row =
        pixels.data() + static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);

  // center_fit_box(w, h, out_size, resize), integer-for-integer: Python's
  // int() on a true division is C's (int) on the same double; // 2 on a
  // possibly-negative margin is floor, not truncation.
  int nw, nh;
  if (w <= h) {
    nw = resize;
    nh = static_cast<int>(static_cast<double>(resize) * h / w);
  } else {
    nh = resize;
    nw = static_cast<int>(static_cast<double>(resize) * w / h);
  }
  const double sx = w / static_cast<double>(nw);
  const double sy = h / static_cast<double>(nh);
  const int left =
      static_cast<int>(std::floor((nw - out_size) / 2.0));
  const int top =
      static_cast<int>(std::floor((nh - out_size) / 2.0));
  // PIL parses the resize box as C float (32-bit) — "(ffff)" in
  // _imaging.c — so the box coordinates are float-quantized BEFORE the
  // coefficient windows are computed. Bit-identity requires the same
  // quantization here; keeping doubles shifts windows by ~1e-7 px and
  // flips ±1 LSBs (measured: 0.2% of pixels on the probe set).
  const float bx0 = static_cast<float>(left * sx);
  const float by0 = static_cast<float>(top * sy);
  const float bx1 = static_cast<float>(left * sx + out_size * sx);
  const float by1 = static_cast<float>(top * sy + out_size * sy);
  return pillow_exact::resample_box(pixels.data(), w, h, bx0, by0, bx1,
                                    by1, out_size, out);
}

}  // extern "C"
