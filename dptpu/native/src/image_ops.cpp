// dptpu native image ops: JPEG decode + fused bilinear crop-resize (+flip).
//
// The hot path of the host input pipeline. The reference leans on
// torchvision's PIL loader + C image ops inside torch DataLoader worker
// processes (reference imagenet_ddp.py:166-194); this is the dptpu-native
// equivalent: a small C core driven from Python threads via ctypes (the
// call releases the GIL, so a thread pool scales across cores without
// process forking).
//
// Two tricks make it faster than the PIL path:
//  1. libjpeg scaled decode (scale_num/8): when the sampled crop will be
//     downscaled to out_size anyway, decode directly at 1/2, 3/8, ... of
//     full resolution — typically 3-6x less IDCT + color-convert work for
//     ImageNet-sized JPEGs cropped to 224.
//  2. crop+resize+flip fused into one bilinear gather straight into the
//     caller's batch slot — no intermediate full-size RGB copy beyond the
//     decode buffer, no per-item allocation in steady state.
//
// C ABI (ctypes): all functions return 0 on success, negative on failure
// (caller falls back to the PIL path — e.g. PNGs land there).

#include <cstddef>
#include <cstdio>  // jpeglib.h uses FILE/size_t without including them

#include <jpeglib.h>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// Bilinear sample of src (h x w x 3) region [left,top,cw,ch] to out
// (out_size x out_size x 3), optional horizontal flip. Matches PIL's
// box-resize semantics: source pixel centers at integer+0.5 coordinates.
// Horizontal coordinates/weights are identical for every output row, so
// they are computed once into LUTs; the inner loop is fixed-point (15-bit
// weights) with two horizontal lerps + one vertical lerp per channel.
void crop_resize_bilinear(const uint8_t* src, int src_w, int src_h,
                          double left, double top, double cw, double ch,
                          int out_size, bool flip, uint8_t* out) {
  constexpr int kBits = 15;
  constexpr int kOne = 1 << kBits;
  const double sx = cw / out_size;
  const double sy = ch / out_size;

  std::vector<int> x0s(out_size), x1s(out_size), wxs(out_size);
  for (int ox = 0; ox < out_size; ++ox) {
    const int tx = flip ? (out_size - 1 - ox) : ox;
    const double fx = left + (tx + 0.5) * sx - 0.5;
    int x0 = static_cast<int>(std::floor(fx));
    const double wx = fx - x0;
    int x1 = x0 + 1;
    x0s[ox] = std::clamp(x0, 0, src_w - 1) * 3;
    x1s[ox] = std::clamp(x1, 0, src_w - 1) * 3;
    wxs[ox] = static_cast<int>(wx * kOne + 0.5);
  }

  for (int oy = 0; oy < out_size; ++oy) {
    const double fy = top + (oy + 0.5) * sy - 0.5;
    int y0 = static_cast<int>(std::floor(fy));
    const double wyd = fy - y0;
    int y1 = y0 + 1;
    y0 = std::clamp(y0, 0, src_h - 1);
    y1 = std::clamp(y1, 0, src_h - 1);
    const int wy = static_cast<int>(wyd * kOne + 0.5);
    const uint8_t* row0 = src + static_cast<size_t>(y0) * src_w * 3;
    const uint8_t* row1 = src + static_cast<size_t>(y1) * src_w * 3;
    uint8_t* orow = out + static_cast<size_t>(oy) * out_size * 3;
    for (int ox = 0; ox < out_size; ++ox) {
      const int x0 = x0s[ox], x1 = x1s[ox], wx = wxs[ox];
      for (int c = 0; c < 3; ++c) {
        const int t0 = (row0[x0 + c] << kBits) +
                       (row0[x1 + c] - row0[x0 + c]) * wx;
        const int t1 = (row1[x0 + c] << kBits) +
                       (row1[x1 + c] - row1[x0 + c]) * wx;
        const int64_t v =
            (static_cast<int64_t>(t0) << kBits) +
            static_cast<int64_t>(t1 - t0) * wy;
        orow[ox * 3 + c] =
            static_cast<uint8_t>((v + (1ll << (2 * kBits - 1))) >> (2 * kBits));
      }
    }
  }
}

}  // namespace

extern "C" {

// Advise the kernel to pull a file's bytes into the page cache
// asynchronously (posix_fadvise WILLNEED) — the cold-epoch JPEG
// readahead path. The parent calls this at span PRE-ISSUE time, so by
// the time a worker opens the file (decode_ahead batches later) the
// read services from memory instead of stalling a decode core on disk
// latency. Returns the file size on success (telemetry-friendly),
// negative on failure. The GIL is released for the open/advise/close
// (ctypes does this for every call here), so the parent's submit path
// pays microseconds, not I/O.
long long dptpu_file_readahead(const char* path) {
  const int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  long long size = 0;
  if (fstat(fd, &st) == 0) size = static_cast<long long>(st.st_size);
#if defined(POSIX_FADV_WILLNEED)
  const int rc = posix_fadvise(fd, 0, 0, POSIX_FADV_WILLNEED);
#else
  const int rc = 0;  // no fadvise on this platform: open itself primed
                     // the dentry/inode caches, which is all we can do
#endif
  close(fd);
  return rc == 0 ? size : -2;
}

// Parse JPEG header only; writes full-resolution dimensions.
int dptpu_jpeg_dims(const uint8_t* data, size_t size, int* width,
                    int* height) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  *width = static_cast<int>(cinfo.image_width);
  *height = static_cast<int>(cinfo.image_height);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Full-resolution decode into a caller buffer of expected_w x expected_h x 3
// (dimensions from dptpu_jpeg_dims) — the decode-cache fill path. Identical
// libjpeg settings to dptpu_jpeg_decode_crop_resize at scale 8/8 (JCS_RGB,
// IFAST DCT), so a crop-resize from this buffer is BIT-IDENTICAL to the
// fused path whenever the fused path's scale picker stays at full
// resolution (it always does when no crop axis reaches out_size*8/7).
int dptpu_jpeg_decode_rgb(const uint8_t* data, size_t size, int expected_w,
                          int expected_h, uint8_t* out) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  cinfo.scale_num = 8;
  cinfo.scale_denom = 8;
  cinfo.out_color_space = JCS_RGB;
  cinfo.dct_method = JDCT_IFAST;
  jpeg_start_decompress(&cinfo);
  if (static_cast<int>(cinfo.output_width) != expected_w ||
      static_cast<int>(cinfo.output_height) != expected_h) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -4;  // caller's buffer was sized from stale/foreign dims
  }
  const int dw = static_cast<int>(cinfo.output_width);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out + static_cast<size_t>(cinfo.output_scanline) * dw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Crop + bilinear resize + flip from a raw RGB buffer (src_w x src_h x 3) —
// the decode-cache HIT path: same kernel the fused decode path uses, so
// hit and miss produce the same pixels from the same decoded buffer.
int dptpu_crop_resize_rgb(const uint8_t* src, int src_w, int src_h,
                          double crop_left, double crop_top, double crop_w,
                          double crop_h, int out_size, int flip,
                          uint8_t* out) {
  if (src_w <= 0 || src_h <= 0 || crop_w <= 0.0 || crop_h <= 0.0 ||
      out_size <= 0) {
    return -3;
  }
  crop_resize_bilinear(src, src_w, src_h, crop_left, crop_top, crop_w,
                       crop_h, out_size, flip != 0, out);
  return 0;
}

// Decode + crop box (full-resolution coords; FRACTIONAL boxes allowed —
// the exact-val-pipeline path expresses Resize(256)+CenterCrop(224) as
// one fractional box) + bilinear resize to out_size x out_size RGB +
// optional horizontal flip, into `out` (out_size*out_size*3 bytes,
// caller-allocated).
int dptpu_jpeg_decode_crop_resize(const uint8_t* data, size_t size,
                                  double crop_left, double crop_top,
                                  double crop_w, double crop_h,
                                  int out_size, int flip,
                                  uint8_t* out) {
  if (crop_w <= 0.0 || crop_h <= 0.0 || out_size <= 0) return -3;
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  std::vector<uint8_t> pixels;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  // Scaled decode: largest downscale such that the decoded crop still has
  // >= out_size pixels on each axis (never upsample a crop we'd then
  // shrink; keep full quality when the crop must be enlarged).
  int num = 8;
  while (num > 1) {
    const int cand = num - 1;
    if (crop_w * cand >= out_size * 8.0 && crop_h * cand >= out_size * 8.0) {
      num = cand;
    } else {
      break;
    }
  }
  cinfo.scale_num = static_cast<unsigned>(num);
  cinfo.scale_denom = 8;
  cinfo.out_color_space = JCS_RGB;
  cinfo.dct_method = JDCT_IFAST;  // augmentation path: speed over the last
                                  // fraction of a bit of DCT precision
  jpeg_start_decompress(&cinfo);
  const int dw = static_cast<int>(cinfo.output_width);
  const int dh = static_cast<int>(cinfo.output_height);
  pixels.resize(static_cast<size_t>(dw) * dh * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = pixels.data() +
                   static_cast<size_t>(cinfo.output_scanline) * dw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  const double full_w = static_cast<double>(cinfo.image_width);
  const double full_h = static_cast<double>(cinfo.image_height);
  jpeg_destroy_decompress(&cinfo);

  // Map the full-resolution crop box into decoded coordinates.
  const double rx = dw / full_w;
  const double ry = dh / full_h;
  crop_resize_bilinear(pixels.data(), dw, dh, crop_left * rx, crop_top * ry,
                       crop_w * rx, crop_h * ry, out_size, flip != 0, out);
  return 0;
}

}  // extern "C"
