"""Native (C++) runtime components.

The reference's capabilities rest on out-of-repo native code (torch
DataLoader C++ workers, PIL's C decoders — SURVEY.md §2b); dptpu carries its
native pieces in-tree. Currently: libjpeg-backed image ops
(``src/image_ops.cpp``) — header-only dims probe and a fused
decode+crop+resize+flip used by the data pipeline's hot path.

``load_library()`` compiles the shared object on first use (g++, cached by
source mtime under ``_build/``) and returns the ctypes handle, or None when
the toolchain/libjpeg is unavailable — callers fall back to PIL.
"""

from dptpu.native.build import load_library

__all__ = ["load_library"]
