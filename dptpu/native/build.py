"""Build + load the native image-ops shared library.

Compiled lazily with g++ (no pybind11 — plain C ABI via ctypes), cached
under ``_build/`` keyed by source mtime. Thread-safe; failure is cached so a
missing toolchain costs one attempt per process and the pipeline silently
stays on PIL.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "src", "image_ops.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB = os.path.join(_BUILD_DIR, "libdptpu_image.so")

_lock = threading.Lock()
_cached: Optional[ctypes.CDLL] = None
_attempted = False


def _compile() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return True
    # pid-unique temp: loader worker PROCESSES may race to rebuild after a
    # source change; each compiles to its own file and the replace is atomic
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, _SRC, "-ljpeg",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return False
    os.replace(tmp, _LIB)
    return True


def load_library() -> Optional[ctypes.CDLL]:
    """Return the ctypes handle to the native lib, building if needed."""
    global _cached, _attempted
    with _lock:
        if _cached is not None or _attempted:
            return _cached
        _attempted = True
        if not _compile():
            return None
        lib = ctypes.CDLL(_LIB)
        lib.dptpu_jpeg_dims.restype = ctypes.c_int
        lib.dptpu_jpeg_dims.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ]
        lib.dptpu_jpeg_decode_crop_resize.restype = ctypes.c_int
        lib.dptpu_jpeg_decode_crop_resize.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            # fractional crop box (exact-val-pipeline boxes are floats)
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_double,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
        ]
        # decode-cache entry points: full-res decode into a caller buffer
        # (cache fill) and crop-resize from a raw RGB buffer (cache hit)
        lib.dptpu_jpeg_decode_rgb.restype = ctypes.c_int
        lib.dptpu_jpeg_decode_rgb.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
        ]
        lib.dptpu_crop_resize_rgb.restype = ctypes.c_int
        lib.dptpu_crop_resize_rgb.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_double,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
        ]
        # fused serve-ingest: JPEG bytes -> val-pipeline pixels into a
        # staging row, BIT-identical to the PIL path (probe-verified at
        # first use by dptpu/serve/preprocess.py)
        lib.dptpu_serve_ingest.restype = ctypes.c_int
        lib.dptpu_serve_ingest.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
        ]
        # cold-epoch byte readahead: posix_fadvise(WILLNEED) the JPEG
        # files of pre-issued spans (parent-side, GIL released)
        lib.dptpu_file_readahead.restype = ctypes.c_longlong
        lib.dptpu_file_readahead.argtypes = [ctypes.c_char_p]
        _cached = lib
        return _cached
