"""Config layer: the reference's three argparse surfaces, made immutable.

The reference carries three near-identical CLI schemas (imagenet_ddp.py:23-67;
imagenet_ddp_apex.py:42-98; nd_imagenet.py:26-76) and then *mutates* the
parsed ``args`` at runtime (per-GPU batch/worker rescaling
imagenet_ddp.py:125-126, linear LR scaling imagenet_ddp_apex.py:161-162,
world-size rescaling imagenet_ddp.py:76-81). Here the same flags parse into a
frozen :class:`Config` and every derived quantity is computed once, purely, in
:class:`DerivedConfig` — nothing downstream ever rewrites configuration.

CUDA-specific flags (``--dist-backend nccl``, ``--opt-level O2``,
``--loss-scale``, ``--channels-last``, ``--gpu``) are **accepted and mapped,
never a crash** (SURVEY.md §7 hard part (e)): on TPU, NCCL becomes XLA ICI/DCN
collectives, any Apex opt-level ≥ O1 becomes the bf16 compute policy (loss
scaling is unnecessary in bf16 — same exponent range as fp32), channels_last
is a no-op because the zoo is already NHWC, and ``--gpu`` pins
``jax.local_devices()[gpu]``.
"""

from __future__ import annotations

import argparse
import dataclasses
import math
import os
from typing import Optional

# Flag spec table: (args, kwargs) per flag, keyed by which CLI variants carry
# it. Variants: "ddp" = imagenet_ddp.py, "apex" = imagenet_ddp_apex.py,
# "nd" = nd_imagenet.py. Defaults that differ per variant are resolved in
# build_parser.
_VARIANTS = ("ddp", "apex", "nd")

# Per-variant default overrides (reference: arch resnet18 + batch 256 in nd,
# nd_imagenet.py:29,40; batch 224 *per GPU* in apex, imagenet_ddp_apex.py:63-67).
_DEFAULTS = {
    "ddp": {"arch": "resnet50", "batch_size": 1024},
    "apex": {"arch": "resnet50", "batch_size": 224},
    "nd": {"arch": "resnet18", "batch_size": 256},
}


@dataclasses.dataclass(frozen=True)
class Config:
    """Union of the three reference CLI schemas, immutable.

    Field names follow the reference's ``dest`` names exactly so downstream
    code reads like the reference's ``args.*`` accesses.
    """

    data: str
    arch: str = "resnet50"
    workers: int = 4
    epochs: int = 90
    start_epoch: int = 0
    batch_size: int = 1024
    lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4
    print_freq: int = 10
    resume: str = ""
    evaluate: bool = False
    pretrained: bool = False
    # resilience (dptpu extension, all variants): mid-epoch checkpoint
    # cadence + rotation depth (dptpu/resilience). 0 = epoch-boundary
    # saves only, the reference's behavior (imagenet_ddp.py:216-222).
    ckpt_steps: int = 0
    ckpt_keep: int = 3
    # checkpoint destination: a directory OR a store URL (file:// /
    # http(s)://) routed through dptpu.data.store — object-store
    # checkpointing with the same CRC-footer + fallback-scan contract.
    # Empty keeps the legacy default (CWD; apex: the TB run dir).
    ckpt_dir: str = ""
    # large-batch training engine (dptpu extension, all variants):
    # optimizer recipe, gradient-accumulation microbatching, warmup
    # schedule and label smoothing (dptpu/ops/optimizers.py,
    # dptpu/train/step.py). Defaults reproduce the reference exactly.
    optimizer: str = "sgd"
    accum_steps: int = 1
    warmup_epochs: int = 0
    label_smoothing: float = 0.0
    # hierarchical data parallelism (dptpu extension, all variants):
    # factor the data axis into {slice: S, dp_in_slice} so gradient
    # reduction runs reduce-scatter on ICI and only a shard-sized
    # all-reduce on DCN (dptpu/parallel/hierarchy.py). 1 = flat mesh,
    # the reference topology. Env twin DPTPU_SLICES wins when set;
    # DPTPU_DCN_DTYPE=bf16 additionally compresses the DCN hop.
    slices: int = 1
    # distributed (ddp/nd; apex uses env:// exclusively)
    world_size: int = -1
    rank: int = -1
    dist_url: str = "tcp://224.66.41.62:23456"
    dist_backend: str = "nccl"
    desired_acc: Optional[float] = None
    # nd extras (nd_imagenet.py:68-76)
    seed: Optional[int] = None
    gpu: Optional[int] = None
    multiprocessing_distributed: bool = False
    # apex extras (imagenet_ddp_apex.py:88-95). local_rank's REFERENCE
    # default is 0; None here just distinguishes "not passed" so the
    # accepted-and-mapped notice can fire even for an explicit 0 (the
    # launcher's first worker) — behavior is identical either way.
    local_rank: Optional[int] = None
    sync_bn: bool = False
    opt_level: Optional[str] = None
    keep_batchnorm_fp32: Optional[str] = None
    loss_scale: Optional[str] = None
    channels_last: bool = False
    # which CLI variant parsed this config (drives batch semantics + schedule)
    variant: str = "ddp"

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def build_parser(variant: str = "ddp", model_names=None) -> argparse.ArgumentParser:
    """Build the argparse surface for one reference CLI variant.

    Flag names, aliases, types, and defaults match the reference schema for
    that variant (SURVEY.md §2 #1/#12/#20) so published run commands
    (README.md:64-99) parse unchanged.
    """
    if variant not in _VARIANTS:
        raise ValueError(f"variant must be one of {_VARIANTS}, got {variant!r}")
    if model_names is None:
        from dptpu.models import model_names as _mn

        model_names = _mn()
    d = _DEFAULTS[variant]

    p = argparse.ArgumentParser(description="TPU-native ImageNet Training (dptpu)")
    p.add_argument("data", metavar="DIR", help="path to dataset")
    p.add_argument(
        "-a",
        "--arch",
        metavar="ARCH",
        default=d["arch"],
        choices=model_names,
        help="model architecture: " + " | ".join(model_names),
    )
    p.add_argument("-j", "--workers", default=4, type=int, metavar="N",
                   help="number of data loading workers")
    p.add_argument("--epochs", default=90, type=int, metavar="N")
    p.add_argument("--start-epoch", default=0, type=int, metavar="N",
                   help="manual epoch number (useful on restarts)")
    batch_help = (
        "per-device mini-batch size"
        if variant == "apex"
        else "total batch size across all local devices"
    )
    p.add_argument("-b", "--batch-size", default=d["batch_size"], type=int,
                   metavar="N", help=batch_help)
    p.add_argument("--lr", "--learning-rate", default=0.1, type=float,
                   metavar="LR", dest="lr", help="initial learning rate")
    p.add_argument("--momentum", default=0.9, type=float, metavar="M")
    p.add_argument("--wd", "--weight-decay", default=1e-4, type=float,
                   metavar="W", dest="weight_decay")
    p.add_argument("-p", "--print-freq", default=10, type=int, metavar="N")
    p.add_argument("--resume", default="", type=str, metavar="PATH",
                   help="path to latest checkpoint — a FILE (used if it "
                        "verifies; corrupt files fall back to the newest "
                        "verifiable sibling) or a DIRECTORY to scan")
    # dptpu resilience extension (not a reference flag): preemption-safe
    # mid-epoch checkpoints; resume replays the deterministic sampler to
    # the saved (epoch, step) so the trajectory stays bit-identical
    p.add_argument("--ckpt-steps", default=0, type=int, metavar="N",
                   help="also save a rotated mid-epoch checkpoint every N "
                        "steps (0 disables; SIGTERM/SIGINT always trigger "
                        "one final mid-epoch save)")
    p.add_argument("--ckpt-keep", default=3, type=int, metavar="K",
                   help="how many rotated mid-epoch checkpoints to keep")
    p.add_argument("--ckpt-dir", default="", type=str, metavar="DIR_OR_URL",
                   help="where checkpoints go: a directory or a store "
                        "URL (file:// or http(s)://, dptpu.data.store) — "
                        "writes keep the CRC footer and --resume keeps "
                        "the corrupt-fallback scan either way")
    # dptpu large-batch extension (not reference flags): the
    # ImageNet-in-minutes recipe — LARS/LAMB trust-ratio optimizers,
    # emulated large batches via gradient accumulation, linear-warmup +
    # cosine LR, label smoothing. Env twins: DPTPU_OPT / DPTPU_ACCUM /
    # DPTPU_WARMUP_EPOCHS / DPTPU_LABEL_SMOOTH (env wins when set).
    p.add_argument("--optimizer", default="sgd",
                   choices=("sgd", "lars", "lamb"),
                   help="update rule: reference SGD (default), or the "
                        "large-batch layer-wise trust-ratio optimizers "
                        "LARS/LAMB")
    p.add_argument("--accum-steps", default=1, type=int, metavar="K",
                   help="gradient-accumulation microbatches per step: "
                        "each replica's batch splits into K fp32-"
                        "accumulated microbatches before one optimizer "
                        "update, so -b can exceed per-chip activation "
                        "memory (the global batch is unchanged; K "
                        "emulates a K x wider pod at microbatch b/K)")
    p.add_argument("--warmup-epochs", default=0, type=int, metavar="N",
                   help="N > 0 selects the large-batch schedule: linear "
                        "LR warmup over N epochs then cosine decay "
                        "(0 keeps the variant's reference schedule)")
    p.add_argument("--label-smoothing", default=0.0, type=float,
                   metavar="S",
                   help="label-smoothing mass in [0, 1) for the training "
                        "loss (0 = reference hard-target CE)")
    # dptpu hierarchical-comms extension (not a reference flag): on a
    # multi-slice pod the DCN hop between slices is ~10x slower than
    # ICI; --slices S rewrites the gradient all-reduce as
    # reduce-scatter(ICI) -> shard-sized all-reduce(DCN) ->
    # all-gather(ICI), cutting per-chip DCN bytes to ~1/(N/S). Env twin:
    # DPTPU_SLICES (wins when set); DPTPU_DCN_DTYPE=bf16 halves the DCN
    # bytes again (fp32 accumulation).
    p.add_argument("--slices", default=1, type=int, metavar="S",
                   help="factor the data-parallel mesh into S "
                        "DCN-connected slices for two-level gradient "
                        "reduction (1 = flat mesh; S must divide the "
                        "device count)")
    p.add_argument("-e", "--evaluate", dest="evaluate", action="store_true",
                   help="evaluate model on validation set")
    p.add_argument("--pretrained", dest="pretrained", action="store_true")

    if variant in ("ddp", "nd"):
        p.add_argument("--world-size", default=-1, type=int,
                       help="number of nodes for distributed training")
        p.add_argument("--rank", default=-1, type=int,
                       help="node rank for distributed training")
        p.add_argument("--dist-url", default="tcp://224.66.41.62:23456",
                       type=str, help="rendezvous url (host:port of node 0)")
        p.add_argument("--dist-backend", default="nccl", type=str,
                       help="accepted for CLI parity; TPU always uses XLA "
                            "collectives over ICI/DCN")
    if variant == "ddp":
        p.add_argument("--desired-acc", default=None, type=float,
                       help="stop training once val top-1 reaches this "
                            "FRACTION (e.g. 0.75 = 75%% top-1, the README's "
                            "canonical bar); values > 1 are read as percent")
    if variant == "nd":
        p.add_argument("--seed", default=None, type=int,
                       help="seed for initializing training")
        p.add_argument("--gpu", default=None, type=int,
                       help="device id to pin (single-device mode)")
        p.add_argument("--multiprocessing-distributed", action="store_true")
    if variant == "apex":
        p.add_argument("--local_rank", default=None, type=int)
        p.add_argument("--sync-bn", action="store_true",
                       help="cross-replica BatchNorm statistics")
        p.add_argument("--opt-level", type=str, default=None,
                       help="Apex O0-O3; O1+ maps to the bf16 compute policy")
        p.add_argument("--keep-batchnorm-fp32", type=str, default=None)
        p.add_argument("--loss-scale", type=str, default=None,
                       help="accepted for parity; bf16 needs no loss scaling")
        # type=bool quirk preserved: any non-empty value parses truthy,
        # matching the reference flag exactly (imagenet_ddp_apex.py:95)
        p.add_argument("--channels-last", type=bool, default=False,
                       help="no-op: dptpu models are NHWC already")
    return p


def parse_config(argv=None, variant: str = "ddp") -> Config:
    """Parse argv through the variant's reference-parity schema into a Config."""
    ns = build_parser(variant).parse_args(argv)
    fields = {f.name for f in dataclasses.fields(Config)}
    kw = {k: v for k, v in vars(ns).items() if k in fields}
    return Config(variant=variant, **kw)


@dataclasses.dataclass(frozen=True)
class DerivedConfig:
    """Every runtime-derived quantity, computed once and immutably.

    Replaces the reference's in-place args mutation:

    * ``world_size = ngpus_per_node * nnodes``  (imagenet_ddp.py:76-81)
    * ``rank = node_rank * ngpus + gpu``        (imagenet_ddp.py:103)
    * ``batch_size //= ngpus``                  (imagenet_ddp.py:125)
    * ``workers = ceil(workers / ngpus)``       (imagenet_ddp.py:126)
    * ``lr *= global_batch/256`` (apex only)    (imagenet_ddp_apex.py:161-162)
    """

    num_processes: int  # hosts (JAX processes), = reference's nnodes
    process_index: int  # this host's index, = node rank
    local_device_count: int  # chips on this host, = ngpus_per_node
    global_device_count: int  # total chips, = reference world_size after rescale
    per_device_batch_size: int
    global_batch_size: int
    per_host_batch_size: int
    # ceil(workers / local devices), imagenet_ddp.py:126 — one host
    # process drives all local chips, so its loader runs
    # workers_per_device * local_device_count decode threads (the sum of
    # what the reference's per-GPU DataLoaders would spawn)
    workers_per_device: int
    scaled_lr: float
    use_bf16: bool
    sync_bn: bool

    @property
    def is_chief(self) -> bool:
        """Single-writer guard, the ``rank % ngpus_per_node == 0`` /
        rank-0 analog (imagenet_ddp.py:215; imagenet_ddp_apex.py:268)."""
        return self.process_index == 0


def derive(cfg: Config, *, local_device_count: int,
           num_processes: int = 1, process_index: int = 0) -> DerivedConfig:
    """Compute the DerivedConfig for this host.

    Batch semantics per variant (the reference's own split):
      * ddp/nd: ``-b`` is the total batch for all local devices
        (imagenet_ddp.py:37-41) → per-device = b // local_devices.
      * apex: ``-b`` is already per-device (imagenet_ddp_apex.py:63-67).
    """
    n_local = local_device_count
    global_devices = n_local * num_processes
    if cfg.variant == "apex":
        per_device = cfg.batch_size
    else:
        per_device = max(1, cfg.batch_size // n_local)
    global_batch = per_device * global_devices

    use_bf16 = cfg.variant == "apex" and (cfg.opt_level or "O2") != "O0"
    scaled_lr = cfg.lr
    if cfg.variant == "apex":
        scaled_lr = cfg.lr * float(global_batch) / 256.0

    # NOTE: the reference's ``args.distributed`` switch (DDP vs
    # DataParallel vs single device, nd_imagenet.py:101,140-169) has no
    # derived field here BY DESIGN: the rendezvous decision lives in
    # ``initialize_distributed`` (which reads the config directly, before
    # jax process info exists) and the placement ladder collapses into
    # "mesh over however many devices there are".
    return DerivedConfig(
        num_processes=num_processes,
        process_index=process_index,
        local_device_count=n_local,
        global_device_count=global_devices,
        per_device_batch_size=per_device,
        global_batch_size=global_batch,
        per_host_batch_size=per_device * n_local,
        workers_per_device=int(math.ceil(cfg.workers / n_local)),
        scaled_lr=scaled_lr,
        use_bf16=use_bf16,
        sync_bn=cfg.sync_bn,
    )
