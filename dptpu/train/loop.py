"""Epoch orchestration: train → validate → checkpoint-best → early stop.

The reference's L6 (imagenet_ddp.py:200-324) with its exact console surface
(``Epoch: [e][i/N]  Time … Loss … Acc@1 …`` lines every ``--print-freq``,
``* Acc@1 … Acc@5 …`` validation summaries) and its control contract
(checkpoint-best each epoch, ``--desired-acc`` early stop recording
``training_time``, imagenet_ddp.py:224-236).

One deliberate performance change: metric scalars are NOT pulled from device
every step — device values are buffered and fetched once per print interval,
so the hot loop never blocks on a D2H sync (the reference's own optimization,
imagenet_ddp_apex.py:385-388, applied to all paths; its non-Apex path paid a
``.item()`` sync per batch, imagenet_ddp.py:267).
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import numpy as np

from dptpu import obs
from dptpu.utils.meters import AverageMeter, ProgressMeter, Summary


def train_one_epoch(
    state,
    train_step: Callable,
    batches,
    *,
    epoch: int,
    num_batches: int,
    print_freq: int = 10,
    verbose: bool = True,
    feed_stats: Callable = None,
    start_step: int = 0,
    should_stop: Callable = None,
    on_step: Callable = None,
    ckpt_every: int = 0,
    ckpt_cb: Callable = None,
    emergency_cb: Callable = None,
):
    """One training epoch. ``batches`` yields device-ready batch dicts.

    Returns ``(state, stats)`` with host-float averages for the epoch.
    ``feed_stats`` (optional, e.g. ``DataLoader.feed_stats``) is called
    once at epoch end and its entries (workers_mode, cache hit rate, …)
    are merged into the stats — the input-pipeline half of the feed-rate
    telemetry, alongside the loop's own ``data_time``/``starvation``.

    Resilience hooks (all optional, dptpu/resilience):

    * ``start_step`` — batches of this epoch already consumed before a
      mid-epoch resume (display offset + step accounting; the caller
      feeds a correspondingly-skipped batch iterator);
    * ``should_stop()`` — checked after every completed step; True means
      a preemption signal arrived: stop cleanly NOW (the in-flight step
      is already finished) and return ``stats["preempted"] = True`` so
      the caller saves a mid-epoch checkpoint and exits 0;
    * ``on_step()`` — fault-injection tick, called after each step;
    * ``ckpt_cb(state, steps_done)`` — called every ``ckpt_every`` steps
      with the post-step state (the ``--ckpt-steps`` writer);
    * ``emergency_cb(state, steps_done)`` — called (best-effort, errors
      swallowed) when the loop dies on an unexpected exception, with the
      last CONSISTENT ``(state, position)`` pair, so even a crash between
      epoch boundaries loses at most the in-flight step.
    """
    batch_time = AverageMeter("Time", ":6.3f")
    data_time = AverageMeter("Data", ":6.3f")
    losses = AverageMeter("Loss", ":.4e")
    top1 = AverageMeter("Acc@1", ":6.2f")
    top5 = AverageMeter("Acc@5", ":6.2f")
    progress = ProgressMeter(
        num_batches,
        [batch_time, data_time, losses, top1, top5],
        prefix=f"Epoch: [{epoch}]",
    )

    pending = []  # (device_metrics, n) buffered until the next display
    last_lr = 0.0
    # trust-ratio telemetry (LARS/LAMB steps only): last fetched values,
    # reported like lr — absent keys mean a plain-SGD step
    opt_last = {}
    _TRUST_KEYS = ("trust_min", "trust_mean", "trust_max")
    steps_done = start_step  # batches of THIS epoch consumed so far
    preempted = False
    # step-phase spans (dptpu/obs): data_wait / step / fetch / ckpt plus
    # a per-step "iter" envelope — the host half of the epoch
    # attribution report. A NullTracer makes every record a no-op.
    tracer = obs.get_tracer()
    pc = time.perf_counter
    end = time.time()
    it = iter(batches)
    i = -1
    try:
        while True:
            t_iter0 = pc()
            try:
                batch = next(it)
            except StopIteration:
                break
            i += 1
            t_data = pc()
            tracer.record("data_wait", t_iter0, t_data - t_iter0,
                          step=steps_done)
            data_time.update(time.time() - end)
            n = int(np.prod(batch["labels"].shape))
            state, metrics = train_step(state, batch)
            tracer.record("step", t_data, pc() - t_data, step=steps_done)
            steps_done += 1
            pending.append((metrics, n))
            if i % print_freq == 0:
                # one sync per interval — but lag it: blocking on the newest
                # (still in-flight) step would drain the dispatch queue and pay
                # the ~100ms refill documented in PERF.md, so keep the last two
                # steps un-fetched and in flight. The first display (i == 0)
                # fetches everything so the epoch's opening line shows real
                # values (the queue is cold there anyway).
                # (capped below print_freq so short intervals still advance the
                # display every interval instead of repeating stale values)
                lag = 0 if i == 0 else min(2, max(print_freq - 1, 0))
                cut = max(len(pending) - lag, 0)
                ready, pending = pending[:cut], pending[cut:]
                t_fetch = pc()
                for m, nb in jax.device_get(  # dptpu: allow-host-sync(the ONE lagged sync per print interval — the documented buffered-fetch design; the newest 2 steps stay in flight)
                        [(p[0], p[1]) for p in ready]):
                    losses.update(float(m["loss"]), nb)
                    top1.update(float(m["top1"]), nb)
                    top5.update(float(m["top5"]), nb)
                    last_lr = float(m.get("lr", last_lr))
                    for tk in _TRUST_KEYS:
                        if tk in m:
                            opt_last[tk] = float(m[tk])
                tracer.record("fetch", t_fetch, pc() - t_fetch,
                              step=steps_done - 1)
                batch_time.update(time.time() - end)
                if verbose:
                    progress.display(i + start_step)
            else:
                batch_time.update(time.time() - end)
            if ckpt_every and ckpt_cb is not None \
                    and steps_done % ckpt_every == 0:
                t_ckpt = pc()
                ckpt_cb(state, steps_done)
                # steps_done already advanced: label the save with the
                # 0-based index of the step whose completion triggered
                # it, matching this iteration's data_wait/step/iter
                # spans (the anomaly report joins phases by this label)
                tracer.record("ckpt", t_ckpt, pc() - t_ckpt,
                              step=steps_done - 1)
            # the iter envelope closes BEFORE the on_step hook: a
            # profile-trigger window that ends on this tick must see
            # this step's iter span (the hook itself is microseconds)
            tracer.record("iter", t_iter0, pc() - t_iter0,
                          step=steps_done - 1)
            if on_step is not None:
                on_step()
            if should_stop is not None and should_stop():
                preempted = True
                break
            # re-stamp AFTER the hooks: a checkpoint save (gather +
            # device_get + fsync) must not be billed to the next step's
            # data_time / starvation feed telemetry
            end = time.time()
    except BaseException:
        if emergency_cb is not None:
            # the last fully-applied step is (state, steps_done) — a
            # consistent resume point even when the exception hit mid-step
            try:
                emergency_cb(state, steps_done)
            except Exception:
                pass
        raise
    t_fetch = pc()
    for m, nb in jax.device_get(pending):  # dptpu: allow-host-sync(epoch-tail drain: the last un-fetched steps sync once, after the loop)
        losses.update(float(m["loss"]), nb)
        top1.update(float(m["top1"]), nb)
        top5.update(float(m["top5"]), nb)
        last_lr = float(m.get("lr", last_lr))
        for tk in _TRUST_KEYS:
            if tk in m:
                opt_last[tk] = float(m[tk])
    if pending:
        # the epoch-tail sync: the last un-fetched steps drain here
        tracer.record("fetch", t_fetch, pc() - t_fetch,
                      step=steps_done - 1)
    stats = {
        "loss": losses.avg,
        "top1": top1.avg,
        "top5": top5.avg,
        "lr": last_lr,
        "batch_time": batch_time.avg,
        "data_time": data_time.avg,
        # fraction of epoch wall time spent WAITING on host data — the
        # feed-rate health number (≈0 when the loader keeps up; → 1 when
        # the chip starves; the reference watches the same ratio through
        # its Data meter, imagenet_ddp_apex.py:304-351)
        "starvation": data_time.sum / max(batch_time.sum, 1e-9),
        "num_batches": i + 1,
        "steps_done": steps_done,
        "preempted": preempted,
        **opt_last,
    }
    if feed_stats is not None:
        for k, v in feed_stats().items():
            stats.setdefault(k, v)
    return state, stats


def validate(
    state,
    eval_step: Callable,
    batches,
    *,
    num_batches: int,
    print_freq: int = 10,
    verbose: bool = True,
    count_divisor: int = 1,
):
    """Full validation pass; returns ``{top1, top5, loss, count}`` with exact
    global aggregation (sharded val + psum — the Apex behavior,
    imagenet_ddp_apex.py:232-234,457-460 — with a single final sync).

    ``count_divisor``: in full-val-on-every-rank mode (ddp/nd,
    imagenet_ddp.py:186-194) every host feeds the full val set, so the
    psum counts each sample once per host; the averages are unaffected
    (numerator and denominator scale together) and the divisor restores
    the true sample count in the report."""
    batch_time = AverageMeter("Time", ":6.3f", Summary.NONE)
    progress = ProgressMeter(num_batches, [batch_time], prefix="Test: ")

    tracer = obs.get_tracer()
    pc = time.perf_counter
    device_sums = []
    end = time.time()
    it = iter(batches)
    i = -1
    while True:
        t0 = pc()
        try:
            batch = next(it)
        except StopIteration:
            break
        i += 1
        t_data = pc()
        tracer.record("data_wait", t0, t_data - t0, step=i)
        device_sums.append(eval_step(state, batch))
        tracer.record("eval_step", t_data, pc() - t_data, step=i)
        batch_time.update(time.time() - end)
        end = time.time()
        if verbose and i % print_freq == 0:
            progress.display(i)
    totals = {"loss_sum": 0.0, "correct1": 0.0, "correct5": 0.0, "count": 0.0}
    t_fetch = pc()
    for sums in jax.device_get(device_sums):  # dptpu: allow-host-sync(validation's single final sync — the Apex sharded-val behavior without its per-step stall)
        for k in totals:
            totals[k] += float(sums[k])
    if device_sums:
        tracer.record("fetch", t_fetch, pc() - t_fetch, step=i)
    count = max(totals["count"], 1.0)
    stats = {
        "top1": 100.0 * totals["correct1"] / count,
        "top5": 100.0 * totals["correct5"] / count,
        "loss": totals["loss_sum"] / count,
        "count": totals["count"] / count_divisor,
        "batch_time": batch_time.avg,
    }
    if verbose:
        # reference summary line (imagenet_ddp.py:321-322)
        print(" * Acc@1 {top1:.3f} Acc@5 {top5:.3f}".format(**stats))
    return stats
