"""End-to-end training driver: the ``main_worker`` analog for every CLI.

One function covers the reference's three worker paths
(imagenet_ddp.py:89-236, imagenet_ddp_apex.py:101-301,
nd_imagenet.py:116-263): rendezvous → mesh → model/optimizer → resume →
loaders → epoch loop with checkpoint-best, ``--evaluate`` short-circuit, and
``--desired-acc`` early stop recording ``training_time``.

Differences by design (TPU-first):
* one process per host drives all local chips through a mesh — there is no
  mp.spawn ladder; single-device is just a 1-device mesh-less jit.
* the number of classes is inferred from the dataset (ImageFolder classes),
  so tiny fixtures train tiny heads; ImageNet layouts get the usual 1000.
* ``data`` may be ``synthetic[:N]`` for a decode-free pipeline (benchmarks,
  integration tests) — N samples of 224×224×3 across 1000 classes.
"""

from __future__ import annotations

import sys
import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from dptpu import obs
from dptpu.config import Config, derive
from dptpu.data import (
    DataLoader,
    DevicePrefetcher,
    ImageFolderDataset,
    ShardedSampler,
    SyntheticDataset,
    train_transform,
    val_transform,
)
from dptpu.models import create_model
from dptpu.ops.schedules import (
    make_step_decay_schedule,
    make_warmup_cosine_schedule,
    make_warmup_step_decay_schedule,
)
from dptpu.parallel import (
    gather_state,
    initialize_distributed,
    make_mesh,
    make_zero1_train_step,
    shard_host_batch,
    shard_zero1_state,
)
from dptpu.resilience import (
    CheckpointManager,
    FaultPlan,
    PreemptionGuard,
    find_resumable,
)
from dptpu.train.checkpoint import load_checkpoint, save_checkpoint
from dptpu.train.loop import train_one_epoch, validate
from dptpu.train.state import create_train_state, make_optimizer
from dptpu.train.step import make_eval_step, make_train_step


def _os_environ_flag(name: str) -> bool:
    """Boolean env knob under the fail-fast contract (dptpu/envknob.py):
    unset/empty → False, junk raises actionably — DPTPU_ZERO1=flase must
    never silently mean 'off' (the knob-contract lint, dptpu/analysis,
    polices that no raw os.environ read can reintroduce the fallback)."""
    from dptpu.envknob import env_bool

    return bool(env_bool(name, False))


def _os_environ_int(name: str):
    """Integer env knob; unset/empty → None (so callers can tell an
    explicit 0 from absence — the fail-fast knob contract), junk →
    actionable error. One shared implementation: dptpu/envknob.py."""
    from dptpu.envknob import env_int

    return env_int(name, None)


def _axis_env_knob(name: str, what: str) -> int:
    """Parallelism-axis env knob: unset → 0 (off); any explicit value
    ≤ 0 raises — 0 gets the same fail-fast treatment as negatives (the
    locked knob contract: every explicit value produces feedback, =1
    additionally prints a no-op notice at the call site)."""
    n = _os_environ_int(name)
    if n is not None and n <= 0:
        raise ValueError(
            f"{name}={n} must be a positive {what} (e.g. {name}=2)"
        )
    return n or 0


def _shard_source(data: str):
    """``(train_loc, val_loc)`` when ``data`` names a PACKED-shard tree
    (``dptpu pack`` layout: train/ + val/ each holding a manifest) —
    either a store URL (http(s)://, file://) or a local directory with
    manifests — else None (plain ImageFolder)."""
    import os

    from dptpu.data.shards import MANIFEST_NAME
    from dptpu.data.store import is_store_url

    if is_store_url(data):
        base = data.rstrip("/")
        return f"{base}/train", f"{base}/val"
    if os.path.exists(os.path.join(data, "train", MANIFEST_NAME)):
        return os.path.join(data, "train"), os.path.join(data, "val")
    return None


def _build_datasets(cfg: Config, image_size: int, cache_bytes: int = 0,
                    cache_scope: str = "sharded"):
    import os

    if cfg.data.startswith("synthetic"):
        n = int(cfg.data.split(":", 1)[1]) if ":" in cfg.data else 2048
        train_ds = SyntheticDataset(n, image_size, 1000)
        val_ds = SyntheticDataset(max(n // 10, 1), image_size, 1000)
        return train_ds, val_ds, 1000
    # DPTPU_CACHE_BYTES is a PER-DATASET budget: train and val each keep
    # their own decoded-pixel cache (val redecodes the same files every
    # epoch, so it benefits at least as much per byte)
    shards = _shard_source(cfg.data)
    if shards is not None:
        # packed-shard streaming data plane (dptpu/data/stream.py):
        # pixels are bit-identical to the ImageFolder path by
        # construction, so --data may point at either form of the same
        # dataset and a seeded run cannot tell the difference
        from dptpu.data import ShardStreamDataset

        train_ds = ShardStreamDataset(
            shards[0], train_transform(image_size),
            cache_bytes=cache_bytes, cache_scope=cache_scope,
        )
        val_ds = ShardStreamDataset(
            shards[1],
            val_transform(image_size, resize=int(image_size * 256 / 224)),
            cache_bytes=cache_bytes, cache_scope=cache_scope,
        )
        return train_ds, val_ds, len(train_ds.classes)
    traindir = os.path.join(cfg.data, "train")
    valdir = os.path.join(cfg.data, "val")
    train_ds = ImageFolderDataset(
        traindir, train_transform(image_size), cache_bytes=cache_bytes,
        cache_scope=cache_scope,
    )
    val_ds = ImageFolderDataset(
        valdir, val_transform(image_size, resize=int(image_size * 256 / 224)),
        cache_bytes=cache_bytes, cache_scope=cache_scope,
    )
    return train_ds, val_ds, len(train_ds.classes)


def _feed_knobs() -> tuple:
    """The input-pipeline env knobs, under the locked fail-fast contract:
    every explicit-but-invalid value raises with the accepted values.

    Returns ``(workers_mode, cache_bytes, cache_scope, leased)``:

    * ``DPTPU_CACHE_SCOPE`` — ``pooled`` (one cross-process /dev/shm
      slab, the process-mode default) or ``sharded`` (in-process
      ``DecodeCache``, split N ways by a worker pool; the thread-mode
      default, where in-process already means pooled);
    * ``DPTPU_LEASE`` — zero-copy consumer-leased batch slots in process
      mode (default on; the copy-out path remains for ``=0``).
    """
    from dptpu.envknob import env_bool, env_choice

    workers_mode = env_choice(
        "DPTPU_WORKERS_MODE", ("thread", "process"), default="thread"
    )
    cache_bytes = _os_environ_int("DPTPU_CACHE_BYTES")
    if cache_bytes is not None and cache_bytes < 0:
        raise ValueError(
            f"DPTPU_CACHE_BYTES={cache_bytes} must be >= 0 bytes "
            f"(0/unset disables the decode cache)"
        )
    cache_scope = env_choice(
        "DPTPU_CACHE_SCOPE", ("pooled", "sharded"),
        default="pooled" if workers_mode == "process" else "sharded",
    )
    leased = env_bool("DPTPU_LEASE", True)
    return workers_mode, cache_bytes or 0, cache_scope, leased


def _opt_knobs(cfg: Config) -> tuple:
    """The large-batch training-engine knobs, under the locked fail-fast
    contract (every explicit-but-invalid value raises, pre-compile).

    Returns ``(optimizer, accum_steps, warmup_epochs, label_smoothing)``.
    Each ``DPTPU_*`` env twin OVERRIDES its CLI/config field when set —
    same precedence as the feed knobs — and config values passed
    programmatically get the identical validation as env values:

    * ``DPTPU_OPT`` / ``--optimizer`` — ``sgd`` (reference), ``lars``,
      ``lamb`` (dptpu/ops/optimizers.py);
    * ``DPTPU_ACCUM`` / ``--accum-steps`` — microbatches per update,
      >= 1 (1 = the exact unaccumulated step);
    * ``DPTPU_WARMUP_EPOCHS`` / ``--warmup-epochs`` — > 0 selects the
      linear-warmup + cosine schedule;
    * ``DPTPU_LABEL_SMOOTH`` / ``--label-smoothing`` — in [0, 1).
    """
    from dptpu.envknob import env_choice, env_float, env_int

    name = env_choice("DPTPU_OPT", ("sgd", "lars", "lamb"))
    if name is None:
        name = cfg.optimizer
        if name not in ("sgd", "lars", "lamb"):
            raise ValueError(
                f"--optimizer {name!r} must be one of 'sgd'/'lars'/'lamb'"
            )
    accum = env_int("DPTPU_ACCUM", None)
    if accum is None:
        accum = cfg.accum_steps
    if accum < 1:
        raise ValueError(
            f"DPTPU_ACCUM/--accum-steps {accum} must be >= 1 (1 disables "
            f"gradient accumulation)"
        )
    warmup = env_int("DPTPU_WARMUP_EPOCHS", None)
    if warmup is None:
        warmup = cfg.warmup_epochs
    if warmup < 0:
        raise ValueError(
            f"DPTPU_WARMUP_EPOCHS/--warmup-epochs {warmup} must be >= 0 "
            f"(0 keeps the variant's reference schedule)"
        )
    if 0 < cfg.epochs <= warmup:
        # make_warmup_cosine_schedule would clamp the cosine phase away
        # and the whole run would sit below peak LR — silently-worse
        # training, so it fails fast like every other invalid knob
        raise ValueError(
            f"DPTPU_WARMUP_EPOCHS/--warmup-epochs {warmup} must be < "
            f"--epochs {cfg.epochs}: the run would end mid-warmup and "
            f"never reach peak LR or the cosine decay"
        )
    smooth = env_float("DPTPU_LABEL_SMOOTH", None)
    if smooth is None:
        smooth = float(cfg.label_smoothing)
    if not 0.0 <= smooth < 1.0:
        raise ValueError(
            f"DPTPU_LABEL_SMOOTH/--label-smoothing {smooth} must be in "
            f"[0, 1) (0 disables smoothing)"
        )
    return name, int(accum), int(warmup), float(smooth)


def fit(cfg: Config, *, image_size: int = 224, verbose: Optional[bool] = None):
    """Train (or evaluate) per the config; returns a result dict."""
    # self-tuning control plane (ISSUE 19): the offline artifact applies
    # FIRST — it env-injects ONLY knobs nothing else set, so every
    # fail-fast parse below sees the tuned values while explicit
    # env/CLI knobs always win; the banner names every applied value
    from dptpu.tune.artifact import apply_tuning, tune_knobs

    tune_conf = tune_knobs()
    tuning = None
    if tune_conf["artifact"]:
        cli_set = set()
        if cfg.accum_steps != 1:
            cli_set.add("DPTPU_ACCUM")  # explicit --accum-steps wins
        tuning = apply_tuning(tune_conf["artifact"], cli_set=cli_set)
    # resilience knobs fail fast, before any compile (the locked contract)
    if cfg.ckpt_steps < 0:
        raise ValueError(
            f"--ckpt-steps {cfg.ckpt_steps} must be >= 0 (0 disables "
            f"mid-epoch checkpoints)"
        )
    if cfg.ckpt_keep < 1:
        raise ValueError(f"--ckpt-keep {cfg.ckpt_keep} must be >= 1")
    fault_plan = FaultPlan.from_env()  # raises on a typo'd DPTPU_FAULT
    obs_conf = obs.obs_knobs()  # DPTPU_OBS_* knobs fail fast too
    # elastic-lifecycle knobs (DPTPU_ELASTIC / DPTPU_QUORUM_DEADLINE_S /
    # DPTPU_STRAGGLER_*) fail fast pre-compile under the same contract
    from dptpu.resilience.elastic import elastic_knobs

    el_conf = elastic_knobs()
    # large-batch engine knobs (optimizer / accumulation / warmup /
    # smoothing) fail fast pre-compile under the same locked contract
    opt_name, accum_steps, warmup_epochs, label_smooth = _opt_knobs(cfg)
    # hierarchical-comms knobs (--slices/DPTPU_SLICES, DPTPU_DCN_DTYPE)
    # fail fast pre-compile too; divisibility is checked against the
    # device count once the mesh is factored below
    from dptpu.parallel.hierarchy import hierarchy_knobs

    slices, dcn_dtype = hierarchy_knobs(cfg)
    # overlapped gradient comms (DPTPU_OVERLAP / DPTPU_BUCKET_MB,
    # dptpu/parallel/overlap.py) — validated here even when off
    from dptpu.envknob import env_float as _env_float
    from dptpu.envknob import env_str as _ramp_env_str
    from dptpu.parallel.overlap import overlap_knobs

    want_overlap, bucket_bytes, _bucket_explicit = overlap_knobs()
    # extreme-scale recipe knobs (ISSUE 13): the batch-size ramp and
    # the polynomial warmup exponent (arXiv:1811.05233), both under
    # the locked fail-fast contract, both pre-compile
    from dptpu.ops.schedules import (
        parse_batch_ramp,
        ramp_multiplier,
        ramp_phase_start,
    )

    _ramp_spec = _ramp_env_str("DPTPU_BATCH_RAMP")
    batch_ramp = parse_batch_ramp(_ramp_spec) if _ramp_spec else None
    warmup_poly = _env_float("DPTPU_WARMUP_POLY", None)
    if warmup_poly is not None and warmup_poly <= 0:
        raise ValueError(
            f"DPTPU_WARMUP_POLY={warmup_poly} must be > 0 (the warmup "
            f"exponent; 1 is the linear ramp, 2 the 1811.05233 "
            f"polynomial)"
        )
    if warmup_poly is not None and warmup_epochs == 0 \
            and not cfg.evaluate:
        # composition check only where a schedule is built: --evaluate
        # trains nothing, so a training env's exported knob must not
        # block a pure evaluation (the DPTPU_BATCH_RAMP treatment)
        raise ValueError(
            f"DPTPU_WARMUP_POLY={warmup_poly} needs a warmup phase to "
            f"shape — set --warmup-epochs/DPTPU_WARMUP_EPOCHS > 0"
        )
    if batch_ramp is not None and not cfg.evaluate:
        if warmup_epochs == 0:
            raise ValueError(
                "DPTPU_BATCH_RAMP is the large-batch recipe's ramp and "
                "needs the warmup->cosine schedule — set "
                "--warmup-epochs/DPTPU_WARMUP_EPOCHS > 0"
            )
        if cfg.epochs > 0 and batch_ramp[-1][0] >= cfg.epochs:
            raise ValueError(
                f"DPTPU_BATCH_RAMP names epoch {batch_ramp[-1][0]} but "
                f"the run ends at --epochs {cfg.epochs} — that phase "
                f"would never train"
            )
    initialize_distributed(cfg)
    derived = derive(
        cfg,
        local_device_count=jax.local_device_count(),
        num_processes=jax.process_count(),
        process_index=jax.process_index(),
    )
    if verbose is None:
        verbose = derived.is_chief
    if not cfg.evaluate and derived.per_device_batch_size % accum_steps:
        raise ValueError(
            f"--accum-steps/DPTPU_ACCUM {accum_steps} does not divide the "
            f"per-device batch of {derived.per_device_batch_size} — the "
            f"microbatch is per-device-batch/K, so pick a divisor (or "
            f"raise the batch size)"
        )

    single_device = cfg.gpu is not None or jax.device_count() == 1
    # THE run geometry tuple, built once: stamped into every checkpoint
    # (CheckpointManager / boundary saves) AND compared by the
    # mid-epoch resume cross-check — one construction site, so the
    # saved tuple and the checked tuple cannot desynchronize
    run_geom = (derived.global_device_count, derived.global_batch_size,
                accum_steps)
    # DPTPU_TP=N opens a model axis of size N on the mesh and routes
    # training through the GSPMD tensor-parallel step (specs picked by
    # arch below). The model axis is INNER: on multi-host pods the
    # hierarchical mesh keeps its collectives on ICI (make_mesh guards
    # the DCN crossing). This is the trainer-level entry for the
    # vit/swin TP sharding rules in dptpu/parallel/gspmd.py.
    tp_n = _axis_env_knob("DPTPU_TP", "model-axis size")
    if tp_n == 1 and verbose:
        print("=> DPTPU_TP=1 is a no-op: a one-way model axis is just "
              "data parallelism")
    use_tp = tp_n > 1 and not single_device and not cfg.evaluate
    if tp_n > 1 and not use_tp and verbose:
        why = (
            "--evaluate does not train"
            if cfg.evaluate and not single_device
            else "single-device run (no mesh to open a model axis on)"
        )
        print(f"=> DPTPU_TP ignored: {why}")
    # Arch rule decided BEFORE mesh construction: an arch with no TP rule
    # (CNNs, MaxViT) gets the flat full-width data mesh — factoring a
    # model axis it cannot use would make those devices compute 100%
    # redundantly instead of joining the data axis.
    tp_fallback = False
    if use_tp:
        from dptpu.parallel.gspmd import tp_rule_for_arch

        tp_fallback = tp_rule_for_arch(cfg.arch) == "dp_specs"
    if tp_fallback:
        # demote the TP request entirely: with no rule for this arch
        # there is nothing for a model axis to do, so later precedence
        # checks (DPTPU_ZERO1 etc.) must not see an inert TP claim
        if verbose:
            print(
                f"=> DPTPU_TP={tp_n}: no tensor-parallel rule for "
                f"'{cfg.arch}' (TP ships for vit_*/swin*/convnext_*; classic "
                f"CNNs and MaxViT keep the data axis — see dp_specs "
                f"docstring) — "
                f"running data parallelism over all "
                f"{jax.device_count()} devices instead"
            )
        use_tp = False
    if use_tp and jax.device_count() % tp_n != 0:
        raise ValueError(
            f"DPTPU_TP={tp_n} does not divide the {jax.device_count()} "
            f"available devices — pick a divisor so the "
            f"{{data, model}} mesh factors"
        )
    # DPTPU_SP=N: sequence/context parallelism — a {data, seq: N} mesh,
    # the ViT token axis sharded over the inner seq axis with Ulysses or
    # ring attention (DPTPU_SP_MODE, default ulysses). ViT-only: Swin's
    # windowed attention is already local and parallelizes spatially via
    # the data axis (README); CNNs have no token axis at all.
    from dptpu.envknob import env_choice

    sp_n = _axis_env_knob("DPTPU_SP", "seq-axis size")
    # fail-fast even when SP is off: a typo'd mode must not sit silently
    # in the environment waiting for the day DPTPU_SP is turned on
    sp_mode = env_choice("DPTPU_SP_MODE", ("ulysses", "ring"), "ulysses")
    if sp_n == 1 and verbose:
        print("=> DPTPU_SP=1 is a no-op: a one-way seq axis is just "
              "data parallelism")
    use_sp = (
        sp_n > 1 and not single_device and not cfg.evaluate and not use_tp
    )
    if sp_n > 1 and not use_sp and verbose:
        why = (
            "DPTPU_TP takes precedence (TP x SP composition is not "
            "implemented)"
            if use_tp
            else "--evaluate does not train"
            if cfg.evaluate and not single_device
            else "single-device run (no mesh to open a seq axis on)"
        )
        print(f"=> DPTPU_SP ignored: {why}")
    if use_sp and not cfg.arch.startswith("vit_"):
        if verbose:
            print(
                f"=> DPTPU_SP={sp_n}: no sequence-parallel path for "
                f"'{cfg.arch}' (global-attention ViTs only; Swin windows "
                f"are spatially local, CNNs have no token axis) — "
                f"running plain data parallelism over all "
                f"{jax.device_count()} devices instead"
            )
        use_sp = False
    if use_sp and jax.device_count() % sp_n != 0:
        raise ValueError(
            f"DPTPU_SP={sp_n} does not divide the {jax.device_count()} "
            f"available devices — pick a divisor so the "
            f"{{data, seq}} mesh factors"
        )
    if use_sp and accum_steps > 1:
        # fail fast rather than silently changing the effective batch:
        # the sequence-parallel step has no microbatch scan (its token
        # axis already divides the work another way). Name the offending
        # knob AND the supported alternatives (message locked by
        # tests/test_opt_knobs.py::test_sp_accum_error_names_knob_and_alternative)
        raise ValueError(
            f"--accum-steps/DPTPU_ACCUM={accum_steps} has no "
            f"sequence-parallel implementation (DPTPU_SP={sp_n} replaces "
            f"the microbatch scan with a token-axis split); supported "
            f"alternatives: set DPTPU_ACCUM=1 and keep DPTPU_SP={sp_n}, "
            f"or unset DPTPU_SP to get data-parallel gradient "
            f"accumulation"
        )
    # DPTPU_SLICES/--slices > 1: two-level hierarchical data
    # parallelism (dptpu/parallel/hierarchy.py) — the gradient
    # all-reduce decomposes into reduce-scatter(ICI) + shard-sized
    # all-reduce(DCN) + all-gather(ICI). Composes with the default DDP
    # step, with DPTPU_ZERO1/DPTPU_ZERO=3 (state shards over the
    # intra-slice axis, so the weight all-gather stays on ICI), AND
    # with DPTPU_GSPMD (the {slice, data}-factored mesh + rules-table
    # FSDP placement make the partitioner derive its own DCN-aware
    # decomposition); TP/SP keep their own single-level topologies
    # (explicit requests win, with a notice — the repo-wide precedence
    # discipline).
    want_hier = slices > 1
    want_gspmd_early = _os_environ_flag("DPTPU_GSPMD")
    use_hier = (
        want_hier and not single_device and not cfg.evaluate
        and not use_tp and not use_sp
    )
    if slices == 1 and _os_environ_int("DPTPU_SLICES") == 1 and verbose:
        print("=> DPTPU_SLICES=1 is a no-op: one slice is the flat "
              "single-level data mesh")
    if want_hier and not use_hier and verbose:
        why = (
            "DPTPU_TP drives the GSPMD tensor-parallel step"
            if use_tp
            else "DPTPU_SP drives the sequence-parallel step"
            if use_sp
            else "--evaluate does not train"
            if cfg.evaluate and not single_device
            else "single-device run (no DCN hop to factor)"
        )
        print(f"=> DPTPU_SLICES={slices} ignored: {why}")
    if dcn_dtype != "fp32" and not use_hier and verbose:
        print(f"=> DPTPU_DCN_DTYPE={dcn_dtype} ignored: no hierarchical "
              f"mesh (set DPTPU_SLICES >= 2), so there is no DCN-only "
              f"hop to compress")
    if single_device:
        mesh = None
    elif use_tp:
        from dptpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        mesh = make_mesh(mesh_shape={DATA_AXIS: -1, MODEL_AXIS: tp_n})
    elif use_sp:
        from dptpu.parallel.mesh import DATA_AXIS
        from dptpu.parallel.sequence import SEQ_AXIS

        mesh = make_mesh(mesh_shape={DATA_AXIS: -1, SEQ_AXIS: sp_n})
    elif use_hier:
        from dptpu.parallel import make_hierarchical_mesh

        if el_conf["elastic"] and cfg.resume:
            # elastic composition first: a shrunk world that no longer
            # divides --slices gets the message naming the knob AND
            # both fallbacks (drop slices / pick a dividing S) instead
            # of the generic mesh-factoring error. Gated on --resume:
            # a FRESH run with DPTPU_ELASTIC exported (a job env knob
            # that must survive restarts) is a plain slices
            # misconfiguration and deserves the generic message, not a
            # phantom elastic-restart diagnosis.
            from dptpu.parallel.hierarchy import elastic_slices_check

            elastic_slices_check(jax.device_count(), slices)
        # raises when slices does not divide the device count (or the
        # host count, multi-process) — the locked fail-fast contract
        mesh = make_hierarchical_mesh(slices)
        if verbose:
            import jax as _jax

            if want_gspmd_early or tp_fallback:
                print(
                    f"=> hierarchical data parallelism: {slices} slices "
                    f"x {_jax.device_count() // slices} chips/slice — "
                    f"the SPMD partitioner derives the per-link "
                    f"decomposition from the {{slice, data}}-factored "
                    f"mesh + rules-table FSDP placement"
                )
            else:
                print(
                    f"=> hierarchical data parallelism: {slices} slices x "
                    f"{_jax.device_count() // slices} chips/slice — "
                    f"gradient reduction is reduce-scatter(ICI) + "
                    f"shard-sized all-reduce(DCN, {dcn_dtype}) + "
                    f"all-gather(ICI)"
                )
    else:
        mesh = make_mesh()
    if cfg.multiprocessing_distributed and verbose:
        # accepted-and-mapped, never silent: the reference forks one
        # process per GPU (nd_imagenet.py:72-76); dptpu is one process
        # per HOST driving every local chip through the mesh, so the
        # flag's intent (use all local accelerators) is already the
        # default and spawning would only duplicate work.
        print(
            "=> --multiprocessing-distributed noted: dptpu always drives "
            "all local chips from one process per host (SPMD mesh); no "
            "worker processes are spawned"
        )
    if cfg.variant == "apex" and cfg.local_rank is not None and verbose:
        # accepted-and-mapped, never silent (imagenet_ddp_apex.py:88,
        # 120-123): the launcher's per-GPU pinning flag has no per-chip
        # process here — one process per HOST drives every local chip
        print(
            f"=> --local_rank {cfg.local_rank} noted: dptpu is one "
            "process per host (SPMD mesh), so per-device process "
            "pinning is not needed; all local chips are driven together"
        )
    put = (
        partial(jax.device_put, device=jax.local_devices()[cfg.gpu or 0])
        if single_device
        else partial(shard_host_batch, mesh=mesh)
    )

    if cfg.variant == "apex" and cfg.arch == "inception_v3":
        # reference parity: the Apex script rejects inception_v3 by name
        # (imagenet_ddp_apex.py:209-210); ddp/nd train its main head here
        raise RuntimeError(
            "Currently, inception_v3 is not supported by this example."
        )

    # DPTPU_WORKERS_MODE=process routes decode through the shared-memory
    # worker-process ring (dptpu/data/shm.py) — same batches bit-for-bit,
    # but decode scales with host cores instead of the GIL; DPTPU_CACHE_BYTES
    # budgets a decoded-pixel cache so epoch 1+ skips JPEG Huffman decode
    # (DPTPU_CACHE_SCOPE picks pooled-slab vs per-worker-sharded), and
    # DPTPU_LEASE keeps process-mode batches zero-copy end to end.
    workers_mode, cache_bytes, cache_scope, leased = _feed_knobs()
    if verbose and (workers_mode != "thread" or cache_bytes):
        print(
            f"=> input pipeline: workers_mode={workers_mode}, "
            f"decode cache "
            + (f"{cache_bytes / 1e6:.0f} MB per dataset ({cache_scope})"
               if cache_bytes else "off")
            + (", leased slots" if leased and workers_mode == "process"
               else "")
        )
    train_ds, val_ds, num_classes = _build_datasets(
        cfg, image_size, cache_bytes=cache_bytes, cache_scope=cache_scope
    )

    # per-host loaders over disjoint shards (DistributedSampler contract);
    # batches are per-HOST (global batch = per_host × hosts).
    # DPTPU_SHARD_LOCALITY=1 (packed-shard data only; opt-in — it
    # REORDERS the epoch visit, so the trajectory diverges from the
    # ImageFolder-identical default) swaps the global permutation for
    # the seeded shard-level shuffle + in-shard shuffle: sequential
    # extent I/O, one shard resident at a time, still pure in
    # (seed, epoch) so mid-epoch --resume replays exactly.
    from dptpu.envknob import env_bool as _sl_bool

    want_locality = _sl_bool("DPTPU_SHARD_LOCALITY", False)
    use_locality = want_locality and hasattr(train_ds, "shard_set")
    if want_locality and not use_locality and verbose:
        print("=> DPTPU_SHARD_LOCALITY ignored: --data is not a "
              "packed-shard tree (dptpu pack)")
    if use_locality and verbose:
        print("=> shard-locality sampling: seeded shard-level shuffle "
              "+ in-shard shuffle (sequential extent I/O; trajectory "
              "differs from the global-permutation default)")
    host_batch = derived.per_host_batch_size
    if use_locality:
        from dptpu.data import ShardLocalitySampler

        train_sampler = ShardLocalitySampler(
            train_ds.shard_set,
            num_shards=derived.num_processes,
            shard_index=derived.process_index,
            shuffle=True,
            seed=cfg.seed if cfg.seed is not None else 0,
        )
    else:
        train_sampler = ShardedSampler(
            len(train_ds),
            num_shards=derived.num_processes,
            shard_index=derived.process_index,
            shuffle=True,
            seed=cfg.seed if cfg.seed is not None else 0,
        )
    if batch_ramp is not None and cfg.evaluate:
        if verbose:
            print("=> DPTPU_BATCH_RAMP ignored: --evaluate does not train")
        batch_ramp = None

    def _ramp_mult(epoch: int) -> int:
        return (ramp_multiplier(batch_ramp, epoch)
                if batch_ramp is not None else 1)

    def _spe(mult: int) -> int:
        # mirrors DataLoader.__len__ under drop_last=True — the phase
        # table must be computable WITHOUT building a loader per phase
        return max(len(train_sampler) // (host_batch * mult), 1)

    def _cum_steps(epoch: int) -> int:
        # optimizer steps completed before `epoch` starts — the phase
        # schedule's step anchor and the ramped --start-epoch offset
        return sum(_spe(_ramp_mult(e)) for e in range(epoch))

    def _make_train_loader(batch: int) -> DataLoader:
        return DataLoader(
            train_ds,
            batch,
            sampler=train_sampler,
            # the sum of the reference's per-GPU worker pools: each of
            # the n_local device-slots gets ceil(workers / n_local)
            # decode threads (imagenet_ddp.py:126), pooled per host
            num_workers=(derived.workers_per_device
                         * derived.local_device_count),
            drop_last=True,
            pad_final=False,
            seed=cfg.seed if cfg.seed is not None else 0,
            workers_mode=workers_mode,
            leased=leased,
        )

    ramp_mult = _ramp_mult(cfg.start_epoch)
    train_loader = _make_train_loader(host_batch * ramp_mult)
    # Validation sharding follows the reference's split behavior:
    # * ddp/nd validate the FULL val set on every rank with no cross-rank
    #   reduction (imagenet_ddp.py:186-194, nd_imagenet.py) — here every
    #   HOST loads the full set; the in-step psum then counts each sample
    #   once per host, so the reported count is divided back down and the
    #   averages are bit-identical on every host by construction;
    # * apex shards val and all-reduces the sums — exact aggregation
    #   (imagenet_ddp_apex.py:232-234,457-460).
    # DPTPU_DIST_EVAL=1 (ISSUE 13 satellite): shard validation over the
    # hosts for EVERY variant — the ddp/nd default feeds the FULL val
    # set to every host (replicated work: N hosts decode N copies), the
    # apex variant already shards. The in-step psum'd
    # correct/count sums make the sharded aggregate EXACT, and on one
    # host the shard IS the full set, so top1 is bit-identical to the
    # single-stream pass by construction (locked in
    # tests/test_overlap.py).
    dist_eval = _os_environ_flag("DPTPU_DIST_EVAL")
    full_val = cfg.variant in ("ddp", "nd") and not dist_eval
    if dist_eval and verbose:
        if cfg.variant in ("ddp", "nd") and derived.num_processes > 1:
            print(
                f"=> distributed eval: val set sharded over "
                f"{derived.num_processes} hosts (exact psum-aggregated "
                f"top1; each host decodes 1/{derived.num_processes} of "
                f"the set instead of all of it)"
            )
        elif cfg.variant == "apex":
            print("=> DPTPU_DIST_EVAL noted: the apex variant already "
                  "shards validation (imagenet_ddp_apex.py:232-234)")
    val_loader = DataLoader(
        val_ds,
        host_batch,
        sampler=(
            ShardedSampler(len(val_ds), num_shards=1, shard_index=0,
                           shuffle=False)
            if full_val
            else ShardedSampler(
                len(val_ds),
                num_shards=derived.num_processes,
                shard_index=derived.process_index,
                shuffle=False,
            )
        ),
        num_workers=derived.workers_per_device * derived.local_device_count,
        workers_mode=workers_mode,
        leased=leased,
    )
    val_count_divisor = derived.num_processes if full_val else 1
    steps_per_epoch = max(len(train_loader), 1)

    compute_dtype = jnp.bfloat16 if derived.use_bf16 else jnp.float32
    # BN activations follow the compute dtype (statistics always accumulate
    # in fp32 inside flax) unless --keep-batchnorm-fp32 True pins BN I/O to
    # fp32 — the Apex flag's strictest reading (imagenet_ddp_apex.py:93).
    keep_bn_fp32 = str(cfg.keep_batchnorm_fp32).lower() in ("true", "1")
    want_s2d = _os_environ_flag("DPTPU_S2D")
    _resnet_family = cfg.arch.startswith(("resnet", "wide_resnet", "resnext"))
    use_s2d = want_s2d and _resnet_family and image_size % 2 == 0
    if want_s2d and not use_s2d and verbose:
        print(
            f"=> DPTPU_S2D ignored: requires a resnet arch and even input "
            f"size (got arch={cfg.arch}, image_size={image_size})"
        )
    # DPTPU_GSPMD=1: run the single-program GSPMD/pjit data-parallel step
    # (dp_specs) instead of the shard_map DDP step. Read before model
    # build because BN semantics differ: under GSPMD the global batch is
    # one logical program, so BN statistics are ALWAYS global (SyncBN
    # behavior) and the model must not carry a shard-local axis name.
    want_gspmd = _os_environ_flag("DPTPU_GSPMD")
    # DPTPU_ZERO selects the ZeRO stage by number: 1 is the shipped
    # weight-update sharding (same as DPTPU_ZERO1=1), 3 the full
    # param+grad+optimizer sharding driven by the arch's partition
    # rules table (dptpu/parallel/rules.py); DPTPU_FSDP=1 is the
    # synonym the FSDP literature spells stage 3 with. Read once; the
    # step-selection blocks below reuse these so the precedence rule
    # has one source.
    _zero_stage = _os_environ_int("DPTPU_ZERO")
    if _zero_stage not in (None, 0, 1, 3):
        raise ValueError(
            f"DPTPU_ZERO={_zero_stage} is not a supported stage — use 1 "
            f"(weight-update sharding, the DPTPU_ZERO1=1 alias), 3 "
            f"(param+grad+optimizer sharding, the DPTPU_FSDP=1 alias), "
            f"or 0/unset for replicated data parallelism"
        )
    want_zero3 = _zero_stage == 3 or _os_environ_flag("DPTPU_FSDP")
    want_zero1 = _os_environ_flag("DPTPU_ZERO1") or _zero_stage == 1
    # Precedence: DPTPU_TP (an explicit topology request — the mesh was
    # already factored for it) > DPTPU_SP > DPTPU_ZERO=3 > DPTPU_ZERO1
    # > DPTPU_GSPMD.
    use_zero3 = (
        want_zero3 and mesh is not None and not cfg.evaluate
        and not use_tp and not use_sp
    )
    use_zero1 = (
        want_zero1 and mesh is not None and not cfg.evaluate and not use_tp
        and not use_sp and not use_zero3
    )
    if want_zero3 and use_tp and verbose:
        print("=> DPTPU_ZERO=3/DPTPU_FSDP ignored: DPTPU_TP drives the "
              "GSPMD tensor-parallel step (params shard over the model "
              "axis per the same rules table)")
    elif want_zero3 and use_sp and verbose:
        print("=> DPTPU_ZERO=3/DPTPU_FSDP ignored: DPTPU_SP drives the "
              "sequence-parallel step")
    if want_zero1 and use_zero3 and verbose:
        print("=> DPTPU_ZERO1 noted: DPTPU_ZERO=3 supersedes it (stage "
              "3 shards everything stage 1 shards, plus the params)")
    elif want_zero1 and use_tp and verbose:
        print("=> DPTPU_ZERO1 ignored: DPTPU_TP drives the GSPMD "
              "tensor-parallel step (params shard over the model axis, "
              "not the optimizer state over data)")
    elif want_zero1 and use_sp and verbose:
        print("=> DPTPU_ZERO1 ignored: DPTPU_SP drives the "
              "sequence-parallel step")
    use_gspmd = (
        (want_gspmd or use_tp or tp_fallback)
        and mesh is not None and not cfg.evaluate
        and not use_zero3 and not use_zero1 and not use_sp
    )
    if want_gspmd and use_sp and verbose:
        print("=> DPTPU_GSPMD ignored: DPTPU_SP drives the "
              "sequence-parallel step")
    if want_gspmd and not use_gspmd and not use_sp and verbose:
        # name a ZeRO stage as the reason only when it will actually run
        why = (
            "DPTPU_ZERO=3 takes precedence"
            if use_zero3
            else "DPTPU_ZERO1 takes precedence"
            if use_zero1
            else "--evaluate does not train"
            if cfg.evaluate
            else "single-device run (no mesh)"
        )
        print(f"=> DPTPU_GSPMD ignored: {why}")
    if use_gspmd and derived.sync_bn and verbose:
        print("=> --sync-bn is implicit under DPTPU_GSPMD: BatchNorm "
              "always sees the global batch in the single-program step")
    # Bucketed backward-overlapped gradient comms (DPTPU_OVERLAP=1,
    # dptpu/parallel/overlap.py): composes with the shard_map step
    # families (DDP, ZeRO-1/3, --slices, --accum-steps) AND the plain
    # GSPMD path (per-bucket sharding-constraint boundaries — the
    # partitioner already interleaves per-leaf reductions, so the
    # buckets bound its regrouping freedom rather than create overlap
    # from nothing); TP/SP place their own collectives, and a mesh-less
    # single-device step has none to overlap.
    use_overlap = (
        want_overlap and mesh is not None and not cfg.evaluate
        and not use_tp and not use_sp
    )
    if want_overlap and not use_overlap and verbose:
        why = (
            "DPTPU_TP drives the GSPMD tensor-parallel step"
            if use_tp
            else "DPTPU_SP drives the sequence-parallel step"
            if use_sp
            else "--evaluate does not train"
            if cfg.evaluate and mesh is not None
            else "single-device run (no gradient collective to overlap)"
        )
        print(f"=> DPTPU_OVERLAP ignored: {why}")
    if _bucket_explicit and not want_overlap and verbose:
        print(f"=> DPTPU_BUCKET_MB={bucket_bytes / 1e6:g} noted: the "
              f"bucket bound only applies with DPTPU_OVERLAP=1")
    if use_overlap and verbose:
        print(
            f"=> overlapped gradient comms: reverse-layer buckets of "
            f"<= {bucket_bytes / 1e6:g} MB, each reduced as one fused "
            f"collective issued inside backward (bit-identical to the "
            f"unbucketed step)"
        )
    # The sharding fingerprint this run stamps into checkpoints:
    # "<rules-table-hash>:<placement>" for the sharded placements (the
    # hash pins the TABLE the placement came from, so editing a
    # family's rules reads as a sharding change on resume), plain
    # "replicated" for the replicated-param steps. The mid-epoch
    # --resume cross-check below fail-fasts on a mismatch naming both
    # fingerprints unless DPTPU_ELASTIC opts into re-sharding.
    from dptpu.models.registry import (
        GENERIC_RULES,
        partition_rules_for_arch,
    )
    from dptpu.parallel.rules import rules_fingerprint

    _arch_fp = rules_fingerprint(partition_rules_for_arch(cfg.arch))
    sharding_tag = (
        f"{_arch_fp}:zero3" if use_zero3
        # ZeRO-1 places per-leaf over data via the GENERIC table's
        # AUTO_FSDP row — its fingerprint must not move when a
        # family's TP rules are edited
        else f"{rules_fingerprint(GENERIC_RULES)}:zero1" if use_zero1
        else f"{_arch_fp}:tp{tp_n}" if use_tp
        else f"{_arch_fp}:fsdp" if (use_gspmd and use_hier)
        else "replicated"
    )
    # ramp x parallel-topology composition: the ramp rebuilds the
    # loader + step per phase, which only the shard_map families
    # support — fail fast naming the knobs and both alternatives
    if batch_ramp is not None and (use_tp or use_sp or use_gspmd):
        who = ("DPTPU_TP" if use_tp else
               "DPTPU_SP" if use_sp else "DPTPU_GSPMD")
        raise ValueError(
            f"DPTPU_BATCH_RAMP has no {who} composition (the ramp "
            f"rebuilds the loader and step per phase; only the "
            f"shard_map DDP/ZeRO-1/--slices families support that); "
            f"supported alternatives: unset DPTPU_BATCH_RAMP and keep "
            f"{who}, or unset {who} to run the ramped data-parallel "
            f"recipe"
        )
    # SyncBN spans EVERY replica: on a hierarchical mesh the BatchNorm
    # statistics pmean over both data axes (slice × dp_in_slice) — the
    # flax axis_name accepts the tuple like any jax collective
    _bn_axis = None
    if derived.sync_bn and mesh is not None and not use_gspmd:
        from dptpu.parallel.mesh import data_axis_names, squeeze_axes

        _bn_axis = squeeze_axes(data_axis_names(mesh))
    model = create_model(
        cfg.arch,
        pretrained=cfg.pretrained,
        num_classes=num_classes,
        dtype=compute_dtype,
        bn_axis_name=_bn_axis,
        bn_dtype=jnp.float32 if keep_bn_fp32 else None,
        # space-to-depth stem: identical math + identical params (checkpoints
        # interchange freely; parity locked in tests/test_models.py). Opt-in
        # via DPTPU_S2D=1: measured ~1.3% SLOWER than the 7x7/2 stem on
        # v5e-1 (order-balanced interleaved A/B, 6 reps) — XLA's native
        # small-channel conv handling already covers this chip.
        **({"stem_space_to_depth": True} if use_s2d else {}),
        # fused Pallas stem (bn1+relu+maxpool custom-VJP region): opt-in,
        # parity-tested; slower than XLA's stem on v5e Mosaic (PERF.md)
        **({"fused_stem": True}
           if _os_environ_flag("DPTPU_FUSED_STEM") and _resnet_family
           else {}),
    )
    # LR schedule: --warmup-epochs > 0 selects the large-batch recipe's
    # linear-warmup + cosine decay (every ImageNet-in-minutes paper's
    # shape); otherwise each variant keeps its reference schedule.
    # Accumulation does NOT rescale the LR: --accum-steps splits the
    # global batch the user already chose into K microbatches (the
    # optimizer still steps on exactly global_batch samples), so the
    # apex linear-scaling rule's global_batch/256 factor already
    # carries the full batch scale.
    sched_lr = derived.scaled_lr

    def _phase_schedule(mult: int, epoch: int):
        # ONE ramp phase's warmup->cosine in fractional epochs: the
        # anchor (phase-start epoch, cumulative step count) is derived
        # from the ramp table alone, so a resumed run reconstructs the
        # identical schedule; the peak scales x mult per the
        # linear-scaling rule (the batch grew x mult)
        from dptpu.ops.schedules import make_ramp_phase_schedule

        e0 = ramp_phase_start(batch_ramp, epoch)
        return make_ramp_phase_schedule(
            sched_lr * mult, _spe(mult), cfg.epochs, warmup_epochs,
            epoch0=e0, step0=_cum_steps(e0),
            power=warmup_poly if warmup_poly is not None else 1.0,
        )

    if batch_ramp is not None:
        schedule = _phase_schedule(ramp_mult, cfg.start_epoch)
    elif warmup_epochs > 0:
        schedule = make_warmup_cosine_schedule(
            sched_lr, steps_per_epoch, cfg.epochs, warmup_epochs,
            power=warmup_poly if warmup_poly is not None else 1.0,
        )
    elif cfg.variant == "apex":
        schedule = make_warmup_step_decay_schedule(sched_lr, steps_per_epoch)
    else:
        schedule = make_step_decay_schedule(sched_lr, steps_per_epoch)
    tx = make_optimizer(cfg.momentum, cfg.weight_decay, name=opt_name)
    if verbose and (opt_name != "sgd" or accum_steps > 1 or warmup_epochs
                    or label_smooth):
        print(
            f"=> large-batch engine: optimizer={opt_name}, "
            f"accum={accum_steps} (global batch "
            f"{derived.global_batch_size} in microbatches of "
            f"{derived.per_device_batch_size // accum_steps}/chip — "
            f"emulates {accum_steps}x the DP width), "
            f"warmup={warmup_epochs} epochs"
            + (" (linear->cosine)" if warmup_epochs else "")
            + f", label smoothing {label_smooth}"
        )
    rng = jax.random.PRNGKey(cfg.seed if cfg.seed is not None else 0)
    pretrained_vars = None
    if cfg.pretrained:
        # converted-torchvision weights (imagenet_ddp.py:109-111); see
        # dptpu/models/pretrained.py for the offline conversion workflow
        from dptpu.models.pretrained import load_pretrained_variables

        pretrained_vars = load_pretrained_variables(
            cfg.arch, model, input_shape=(1, image_size, image_size, 3)
        )
        if verbose:
            print(f"=> using pre-trained model '{cfg.arch}'")
    state = create_train_state(
        rng,
        model,
        tx,
        input_shape=(1, image_size, image_size, 3),
        # --start-epoch without --resume still lands on the reference's
        # epoch-N learning rate (the schedule reads the global step);
        # under a batch ramp the offset is the cumulative step count
        # over the earlier (differently-sized) phases
        initial_step=(_cum_steps(cfg.start_epoch)
                      if batch_ramp is not None
                      else cfg.start_epoch * steps_per_epoch),
        variables=pretrained_vars,
    )

    import os

    best_acc1, start_epoch, resume_step = 0.0, cfg.start_epoch, 0
    elastic_resume = None  # set when DPTPU_ELASTIC re-maps a geometry
    if cfg.resume:
        # --resume accepts a file OR a directory; corrupt/truncated files
        # fall back to the newest VERIFIABLE checkpoint (CRC footer /
        # structural check — dptpu/resilience/checkpoint.py)
        resolved = find_resumable(cfg.resume, verbose=verbose)
        if resolved is not None:
            # arch + steps_per_epoch let a reference-produced torch
            # checkpoint resume too (key-mapped params/momentum, step
            # rebuilt on the epoch boundary — see train/checkpoint.py)
            state, meta = load_checkpoint(
                resolved, state, arch=cfg.arch,
                steps_per_epoch=steps_per_epoch,
            )
            if cfg.start_epoch == 0:
                start_epoch = meta["epoch"]
                resume_step = max(int(meta.get("step_in_epoch", 0)), 0)
                # geometry cross-check: a mid-epoch replay is only
                # exact when the run that resumes has the SAME batch
                # geometry as the run that saved — UNLESS DPTPU_ELASTIC
                # opts into re-mapping the position onto this run's
                # geometry (dptpu/resilience/elastic.py): the sampler's
                # interleaved shard assignment makes the visited-index
                # prefix geometry-independent, so the remainder replays
                # exactly on the new world. Without the opt-in the
                # fail-fast names BOTH tuples. Pre-geometry files fall
                # back to the data_position cross-check below.
                saved_geom = tuple(meta.get("geometry", (-1, -1, -1)))
                # under a batch ramp the geometry THIS run trains
                # epoch N at is the ramped one — the stamp a mid-phase
                # (or phase-boundary) save carries, so the cross-check
                # compares ramped-to-ramped and a ramp boundary
                # resumes exactly (ISSUE 13 satellite)
                expect_geom = (
                    (run_geom[0],
                     run_geom[1] * _ramp_mult(meta["epoch"]),
                     run_geom[2])
                    if batch_ramp is not None else run_geom
                )
                if resume_step and saved_geom[0] >= 0 \
                        and saved_geom != expect_geom \
                        and batch_ramp is not None:
                    raise ValueError(
                        f"'{resolved}' was saved mid-epoch (step "
                        f"{resume_step}) at geometry {saved_geom}, but "
                        f"this run's DPTPU_BATCH_RAMP puts epoch "
                        f"{meta['epoch']} at {expect_geom} — resume "
                        f"with the ramp spec the save was made under "
                        f"(DPTPU_ELASTIC does not compose with "
                        f"DPTPU_BATCH_RAMP), or pass --start-epoch to "
                        f"restart from an epoch boundary."
                    )
                if resume_step and saved_geom[0] >= 0 \
                        and saved_geom != expect_geom \
                        and not el_conf["elastic"]:
                    raise ValueError(
                        f"'{resolved}' was saved mid-epoch (step "
                        f"{resume_step}) by a run with (world_size, "
                        f"global_batch, accum) = {saved_geom}, but this "
                        f"run is {run_geom} — the batch geometry "
                        f"changed, so the exact mid-epoch replay is "
                        f"impossible. Resume on the saved geometry, "
                        f"pass --start-epoch to restart from an epoch "
                        f"boundary, or set DPTPU_ELASTIC=1 to re-map "
                        f"the saved position onto this geometry "
                        f"(shrink/grow resume — the remainder of the "
                        f"epoch replays exactly; the LR is rescaled "
                        f"per the linear-scaling rule)."
                    )
                # sharding fingerprint cross-check (ISSUE 16): the
                # checkpoint always holds gathered full leaves, so ANY
                # placement can load it — but a mid-epoch replay under
                # a silently-changed sharding config (a different ZeRO
                # stage, an edited rules table) is a config drift the
                # operator should confirm, not discover post-hoc in a
                # diverged curve. DPTPU_ELASTIC is the confirmation:
                # the full-leaf state simply re-shards onto the new
                # placement (shard_zero3_state et al. device_put). ""
                # means a pre-rules file — no stamp, no check.
                saved_sharding = str(meta.get("sharding", ""))
                if resume_step and saved_sharding \
                        and saved_sharding != sharding_tag \
                        and not el_conf["elastic"]:
                    raise ValueError(
                        f"'{resolved}' was saved mid-epoch (step "
                        f"{resume_step}) under sharding "
                        f"'{saved_sharding}' but this run places as "
                        f"'{sharding_tag}' — the sharding config (ZeRO "
                        f"stage, TP rule, or the partition-rules table "
                        f"itself) changed. Resume with the saved "
                        f"config, pass --start-epoch to restart from "
                        f"an epoch boundary, or set DPTPU_ELASTIC=1 to "
                        f"re-shard the full-leaf checkpoint onto the "
                        f"new placement."
                    )
                if saved_sharding and saved_sharding != sharding_tag \
                        and el_conf["elastic"] and verbose:
                    print(f"=> elastic re-shard: checkpoint sharding "
                          f"'{saved_sharding}' -> '{sharding_tag}' "
                          f"(full-leaf state re-places on load)")
                if resume_step and saved_geom[0] >= 0 \
                        and saved_geom != expect_geom:
                    # the elastic shrink/grow remap (ROADMAP item 3a)
                    from dptpu.resilience.elastic import (
                        remap_resume_position,
                    )

                    remap = remap_resume_position(
                        saved_geom, run_geom, resume_step,
                        # the slices constraint binds only when the
                        # hierarchical mesh is actually in play: a
                        # single-device / TP / SP / GSPMD resume just
                        # declared DPTPU_SLICES a no-op above, and the
                        # remap must not fail over an ignored knob
                        slices=slices if use_hier else 1,
                        num_examples=len(train_ds),
                    )
                    # what the SAVED run trained at under the linear-
                    # scaling rule — reconstructed from THIS run's base
                    # --lr, since checkpoints do not stamp it: accurate
                    # when the base LR is unchanged between attempts
                    # (the normal elastic restart), labeled as such
                    old_lr = (
                        cfg.lr * saved_geom[1] / 256.0
                        if cfg.variant == "apex" else cfg.lr
                    )
                    elastic_resume = {
                        "saved_geometry": list(saved_geom),
                        "new_geometry": list(run_geom),
                        "consumed": remap.consumed,
                        "resume_step_saved": resume_step,
                        "resume_step": remap.new_step,
                        "lr_saved": old_lr,  # assumes an unchanged base --lr
                        "lr": derived.scaled_lr,
                        "accum_changed": remap.accum_changed,
                    }
                    resume_step = remap.new_step
                    # LOUD by contract, not verbose-gated: an elastic
                    # restart changes the optimization trajectory (the
                    # batch, and with it the linear-scaled LR) and that
                    # must never scroll by silently
                    print(
                        f"=> ELASTIC RESUME: geometry {saved_geom} -> "
                        f"{run_geom}; {remap.consumed} samples of the "
                        f"epoch already trained, replaying the "
                        f"remainder from step {remap.new_step} (was "
                        f"step {elastic_resume['resume_step_saved']}); "
                        f"LR {old_lr:g} -> {derived.scaled_lr:g} per "
                        f"the linear-scaling rule (saved-run LR "
                        f"reconstructed from this run's base --lr)"
                        + (" ; accumulation depth changed — microbatch "
                           "virtual-replica streams differ from the "
                           "saved run" if remap.accum_changed else ""),
                        file=sys.stderr,
                    )
                # legacy (pre-geometry) files: the checkpoint's
                # data_position (samples consumed per host) must agree
                # with step x THIS run's host batch, or the replay
                # contract is void — resuming would re-train (or skip)
                # part of the epoch silently. (An elastic remap above
                # already re-expressed the position in THIS geometry.)
                meta_dp = int(meta.get("data_position", -1))
                resume_host_batch = host_batch * _ramp_mult(meta["epoch"])
                if elastic_resume is None and resume_step \
                        and meta_dp >= 0 \
                        and meta_dp != resume_step * resume_host_batch:
                    raise ValueError(
                        f"'{resolved}' was saved at step {resume_step} "
                        f"with {meta_dp} samples consumed per host, but "
                        f"this run's per-host batch is "
                        f"{resume_host_batch} "
                        f"({resume_step} x {resume_host_batch} = "
                        f"{resume_step * resume_host_batch}) — the batch "
                        f"geometry changed, so the exact mid-epoch "
                        f"replay is impossible. Resume with the "
                        f"original batch size, or pass --start-epoch "
                        f"to restart from an epoch boundary."
                    )
                if resume_step >= (_spe(_ramp_mult(start_epoch))
                                   if batch_ramp is not None
                                   else steps_per_epoch):
                    # a mid-epoch save from a run with MORE steps/epoch
                    # (different batch size/dataset): the exact replay
                    # contract is void, so land on the next boundary
                    start_epoch += 1
                    resume_step = 0
            else:
                start_epoch = cfg.start_epoch
            best_acc1 = meta["best_acc1"]
            if verbose:
                pos = (f", step {resume_step}" if resume_step else "")
                print(f"=> loaded checkpoint '{resolved}' "
                      f"(epoch {meta['epoch']}{pos})")
        else:
            # warn-and-continue, reference behavior (imagenet_ddp.py:152-153)
            if verbose:
                print(f"=> no checkpoint found at '{cfg.resume}'")

    if batch_ramp is not None and _ramp_mult(start_epoch) != ramp_mult:
        # the resume landed in a different ramp phase than the loaders
        # were provisionally built for: re-enter the resumed phase
        # BEFORE any step compiles (the loop-top switcher handles
        # later boundaries; this handles the entry point)
        ramp_mult = _ramp_mult(start_epoch)
        train_loader.close()
        train_loader = _make_train_loader(host_batch * ramp_mult)
        steps_per_epoch = max(len(train_loader), 1)
        schedule = _phase_schedule(ramp_mult, start_epoch)

    # want_zero*/use_zero* were computed once, before model build (the
    # GSPMD-precedence block) — reused here so the rule cannot desync.
    # --evaluate never trains: sharding the state only to re-gather it
    # for validation would be two pointless full-state device_put rounds.
    if want_zero3 and mesh is None and verbose:
        print("=> DPTPU_ZERO=3/DPTPU_FSDP ignored: single-device run "
              "(no mesh to shard the params over)")
    elif want_zero3 and cfg.evaluate and verbose:
        print("=> DPTPU_ZERO=3/DPTPU_FSDP ignored: --evaluate does not "
              "train")
    if want_zero1 and mesh is None and verbose:
        print("=> DPTPU_ZERO1 ignored: single-device run (no mesh to "
              "shard the optimizer state over)")
    elif want_zero1 and cfg.evaluate and not want_zero3 and verbose:
        print("=> DPTPU_ZERO1 ignored: --evaluate does not train")
    opt_shard_bytes = None
    if use_zero3:
        # ZeRO-3/FSDP: params, gradients AND optimizer state live
        # sharded over the (intra-slice) data axis — placement comes
        # from the arch's partition-rules table projected onto the
        # data axis (dptpu/parallel/rules.py), the forward/backward
        # all-gather-on-use boundary is the _zero3_gather custom VJP
        # (its backward IS the reduce-scatter), and the entire update
        # runs on the local shard exactly like ZeRO-1. Same collective
        # volume as DDP (gather + scatter = the all-reduce bytes), so
        # the win is memory: ~1/N persistent bytes per chip for the
        # whole params+opt-state footprint (tests/test_zero1.py locks
        # parity and the byte ratio; SCALEBENCH reports it).
        from dptpu.parallel import (
            make_zero3_train_step,
            shard_zero3_state,
            state_shard_bytes,
            zero3_param_specs,
            zero3_state_specs,
        )

        z3_param_specs = zero3_param_specs(cfg.arch, state.params, mesh)

        def _build_train_step(sched):
            # `state` binds late: a ramp-phase rebuild mid-run passes
            # the LIVE sharded state as the template (same structure)
            return make_zero3_train_step(
                mesh, state, z3_param_specs, compute_dtype,
                lr_schedule=sched,
                seed=cfg.seed if cfg.seed is not None else 0,
                accum_steps=accum_steps, label_smoothing=label_smooth,
                tx_factory=partial(
                    make_optimizer, cfg.momentum, cfg.weight_decay,
                    opt_name
                ),
                dcn_dtype=dcn_dtype if use_hier else "fp32",
                overlap=use_overlap, bucket_bytes=bucket_bytes,
            )

        train_step = _build_train_step(schedule)
        opt_shard_bytes = state_shard_bytes(
            state, mesh, zero3_state_specs(state, mesh, z3_param_specs)
        )
        state = shard_zero3_state(state, mesh, z3_param_specs)
        # one all-gather per validation pass / checkpoint write (the
        # ZeRO-1 discipline) — sharded leaves are global jax.Arrays,
        # so the gather is transparent to eval and the writer
        eval_view = lambda s: gather_state(s, mesh)  # noqa: E731
        eval_view_gathers = True  # collective: every host must join
        if verbose:
            print("=> ZeRO-3 param+grad+optimizer sharding over the "
                  f"data axis (rules table; persistent state "
                  f"{opt_shard_bytes / 1e6:.1f} MB/chip)")
    elif use_zero1:
        # ZeRO-1 weight-update sharding: params + optimizer state live
        # sharded over the data axis (~1/N persistent memory per chip),
        # gradients arrive reduce-scattered through the all-gather VJP,
        # and the ENTIRE update — including LARS/LAMB trust-ratio norms,
        # completed shard-locally with one small psum via the injected
        # tx_factory — runs on the local shard (arXiv:2004.13336;
        # tests/test_zero1.py). Checkpoints and eval read the state
        # transparently (sharded leaves are global jax.Arrays);
        # eval/checkpoint gathers are per-epoch, not per-step.
        def _build_train_step(sched):
            # `state` binds late: a ramp-phase rebuild mid-run passes
            # the LIVE sharded state as the template (same structure)
            return make_zero1_train_step(
                mesh, state, compute_dtype, lr_schedule=sched,
                seed=cfg.seed if cfg.seed is not None else 0,
                accum_steps=accum_steps, label_smoothing=label_smooth,
                tx_factory=partial(
                    make_optimizer, cfg.momentum, cfg.weight_decay,
                    opt_name
                ),
                dcn_dtype=dcn_dtype if use_hier else "fp32",
                overlap=use_overlap, bucket_bytes=bucket_bytes,
            )

        train_step = _build_train_step(schedule)
        from dptpu.parallel import zero1_update_shard_bytes

        opt_shard_bytes = zero1_update_shard_bytes(state, mesh)
        state = shard_zero1_state(state, mesh)
        # one all-gather per validation pass / checkpoint write (instead
        # of per eval step), and multi-host save stays fully addressable
        eval_view = lambda s: gather_state(s, mesh)  # noqa: E731
        eval_view_gathers = True  # collective: every host must join
        if verbose:
            print("=> ZeRO-1 optimizer-state sharding over the data axis"
                  f" (update touches {opt_shard_bytes / 1e6:.1f} MB/chip)")
    elif use_gspmd:
        # single-program GSPMD/pjit path: shardings annotated on jit, the
        # partitioner derives every collective (gradient all-reduce over
        # data; under TP, one all-reduce per MLP/attention block over
        # model). Batch stays batch-dim-sharded over the data axes — the
        # layout shard_host_batch already produces — so loaders are
        # unchanged. On a hierarchical mesh (--slices > 1) params take
        # the rules-table FSDP placement over the intra-slice axis, so
        # the partitioner's decomposition is DCN-aware (the per-link
        # budget gspmd_hier in HLO_BUDGETS.json locks the shape).
        from dptpu.parallel.gspmd import (
            dp_specs,
            gspmd_specs_for_arch,
            make_gspmd_train_step,
            shard_gspmd_state,
            tp_specs_for_arch,
        )

        if use_tp:
            # a demoted (no-rule) TP request never reaches here — the
            # fallback cleared use_tp at mesh time, so the rule is real
            rule, specs = tp_specs_for_arch(cfg.arch, state.params)
            if verbose:
                print(
                    f"=> tensor parallelism: {rule} over model axis of "
                    f"{tp_n} × data axis of {int(mesh.shape['data'])}"
                )
        elif use_hier:
            rule = "gspmd_fsdp"
            specs = gspmd_specs_for_arch(
                cfg.arch, state.params, mesh, fsdp=True
            )
            if verbose:
                print("=> GSPMD hierarchical data parallelism: "
                      "rules-table FSDP placement over the intra-slice "
                      "axis; the partitioner derives the per-link "
                      "collective decomposition")
            if dcn_dtype != "fp32":
                print(f"=> DPTPU_DCN_DTYPE={dcn_dtype} ignored: the "
                      f"GSPMD partitioner schedules its own DCN "
                      f"collectives (the compressed hop is "
                      f"shard_map-only)")
        else:
            rule, specs = "dp_specs", dp_specs(state.params)
            if verbose:
                print("=> GSPMD single-program data parallelism (dp_specs)")
        train_step = make_gspmd_train_step(
            mesh, state, specs, compute_dtype, lr_schedule=schedule,
            seed=cfg.seed if cfg.seed is not None else 0,
            accum_steps=accum_steps, label_smoothing=label_smooth,
            overlap=use_overlap, bucket_bytes=bucket_bytes,
        )
        state = shard_gspmd_state(state, mesh, specs)
        if rule == "dp_specs":
            eval_view = lambda s: s  # noqa: E731
            eval_view_gathers = False
        else:
            # TP-sharded params: one all-gather per validation pass /
            # checkpoint write (the ZeRO-1 discipline) so the replicated-
            # spec eval step and the checkpoint writer see full leaves
            eval_view = lambda s: gather_state(s, mesh)  # noqa: E731
            eval_view_gathers = True
    elif use_sp:
        # sequence-parallel step: token axis over the inner seq axis,
        # batch over data. Params stay replicated (no sharded state, no
        # gather needed) — the SAME TrainState trains here and evals
        # through the standard replicated eval step below. The step's
        # model is a second ViT instance with the seq flags on; its
        # param tree is identical (the flags add no params).
        from dptpu.parallel.sequence import SEQ_AXIS, make_seq_train_step

        seq_model = create_model(
            cfg.arch,
            num_classes=num_classes,
            dtype=compute_dtype,
            seq_axis_name=SEQ_AXIS,
            seq_mode=sp_mode,
            seq_shard_tokens=True,
        )
        train_step = make_seq_train_step(
            mesh, seq_model, compute_dtype, lr_schedule=schedule,
            label_smoothing=label_smooth,
        )
        eval_view = lambda s: s  # noqa: E731
        eval_view_gathers = False
        if verbose:
            print(
                f"=> sequence parallelism: {sp_mode} attention over seq "
                f"axis of {sp_n} × data axis of {int(mesh.shape['data'])} "
                f"(tokens pad to multiples of {sp_n}; cls psum-recovered)"
            )
    else:
        def _build_train_step(sched):
            return make_train_step(
                mesh, compute_dtype, lr_schedule=sched,
                seed=cfg.seed if cfg.seed is not None else 0,
                accum_steps=accum_steps, label_smoothing=label_smooth,
                dcn_dtype=dcn_dtype if use_hier else "fp32",
                overlap=use_overlap, bucket_bytes=bucket_bytes,
            )

        train_step = _build_train_step(schedule)
        eval_view = lambda s: s  # noqa: E731
        eval_view_gathers = False
    eval_step = make_eval_step(mesh, compute_dtype)

    if cfg.evaluate:
        stats = validate(
            eval_view(state),
            eval_step,
            DevicePrefetcher(val_loader.epoch(0), put),
            num_batches=len(val_loader),
            print_freq=cfg.print_freq,
            verbose=verbose,
            count_divisor=val_count_divisor,
        )
        train_loader.close()
        val_loader.close()
        for ds in (train_ds, val_ds):
            if hasattr(ds, "close"):
                ds.close()
        return {"val": stats, "state": state, "epochs_run": 0}

    # rank-0-only TensorBoard with the reference's run-config comment tag
    # (imagenet_ddp_apex.py:152-159); apex variant only, like the reference
    writer = None
    ckpt_dir = "."
    if cfg.variant == "apex" and derived.is_chief:
        from dptpu.utils.tensorboard import SummaryWriter

        writer = SummaryWriter(
            comment="_{}_chipx{}_b{}_cpu{}_opt{}".format(
                cfg.arch,
                derived.global_device_count,
                cfg.batch_size,
                cfg.workers,
                cfg.opt_level or "bf16",
            )
        )
        ckpt_dir = writer.log_dir  # apex checkpoints into the run dir (:271-277)
    if cfg.ckpt_dir:
        # explicit --ckpt-dir wins over both defaults; may be a plain
        # directory OR a store URL (file:// / http(s)://) — every save,
        # the rotation scan and --resume route through dptpu.data.store
        # with the CRC-footer + fallback-scan contract unchanged
        ckpt_dir = cfg.ckpt_dir

    # structured tracing (SURVEY.md §5: the reference has only wall-clock
    # meters; dptpu adds an opt-in XLA profile): DPTPU_PROFILE=<dir> traces
    # the first training epoch into a TensorBoard-viewable profile.
    from dptpu.envknob import env_str

    profile_dir = env_str("DPTPU_PROFILE")
    if profile_dir and derived.is_chief:
        jax.profiler.start_trace(profile_dir)

    # --- observability (dptpu/obs): one tracer, one metrics registry,
    # one sink fan-out. Step phases (data_wait/h2d/step/ckpt) record
    # into the span ring; every per-epoch scalar publishes into the
    # registry and flushes once to console + TB + JSONL; SIGUSR2 (or
    # the DPTPU_OBS_TRIGGER sentinel) arms an in-flight device trace of
    # the next DPTPU_OBS_TRACE_STEPS steps — no restart required.
    tracer = obs.set_tracer(
        obs.Tracer(capacity=obs_conf["ring"])
        if obs_conf["enabled"] else obs.NullTracer()
    )
    registry = obs.set_registry(obs.Registry())
    trace_sink = None
    if obs_conf["dir"]:
        # deliberately PER-HOST, not chief-only: the files are named
        # obs-<host>.* and pod-wide straggler analysis needs every
        # host's timeline (ROADMAP observability follow-on (a))
        trace_sink = obs.TraceSink(obs_conf["dir"])
        registry.add_sink(obs.JsonlSink(trace_sink.jsonl_file))
    if writer is not None:
        registry.add_sink(obs.TensorBoardSink(writer))
    if verbose:
        registry.add_sink(obs.ConsoleSink())
    trigger = None
    if obs_conf["enabled"]:
        trigger = obs.ProfileTrigger(
            obs_conf["dir"] or ckpt_dir,
            trace_steps=obs_conf["trace_steps"],
            tracer=tracer,
            sentinel=obs_conf["trigger"],
            verbose=verbose,
        ).install()

    start_time = time.time()
    # resilience wiring (dptpu/resilience): a preemption guard turns
    # SIGTERM/SIGINT into a cooperative stop (finish the in-flight step,
    # save a mid-epoch checkpoint, return cleanly → exit 0), and the
    # checkpoint manager rotates --ckpt-steps step saves so losing a
    # host costs at most ckpt_steps steps, not an epoch.
    # --ckpt-steps cadence saves run on a background writer thread
    # (device_get + serialize + fsync + rename all off the step loop —
    # ROADMAP resilience follow-on (b)); emergency/preemption saves stay
    # synchronous, draining the writer first so "newest file" == "latest
    # position". DPTPU_ASYNC_CKPT=0 restores fully synchronous saves.
    from dptpu.envknob import env_bool as _env_bool
    from dptpu.train.checkpoint import AsyncCheckpointWriter

    ckpt_writer = (
        AsyncCheckpointWriter()
        if cfg.ckpt_steps and _env_bool("DPTPU_ASYNC_CKPT", True)
        else None
    )
    manager = CheckpointManager(
        directory=ckpt_dir,
        keep=cfg.ckpt_keep,
        is_chief=derived.is_chief,
        arch=cfg.arch,
        # data_position stamps samples-consumed-per-host: under a ramp
        # that is the PHASE batch (kept current by the phase switcher)
        batch_size=host_batch * ramp_mult,
        fault_plan=fault_plan,
        async_writer=ckpt_writer,
        # under a batch ramp every save stamps the PHASE geometry (the
        # global batch actually trained at that epoch), so a resume
        # cross-checks ramped-to-ramped and a ramp boundary resumes
        # exactly; the loop-top phase switcher keeps this current
        geometry=(run_geom[0], run_geom[1] * ramp_mult, run_geom[2])
        if batch_ramp is not None else run_geom,
        sharding=sharding_tag,
    )
    guard = PreemptionGuard()
    # quorum coordination (dptpu/resilience/quorum.py): when a
    # transport exists — DPTPU_QUORUM_DIR (tests/benches/single-machine
    # pods) or the live jax.distributed KV service — a preemption that
    # reaches only ONE host propagates through the store, the pod
    # agrees on a common stop step, and the gathered mid-epoch save
    # happens behind a barrier-with-deadline. No transport = the PR-2
    # single-signal rules, unchanged; a single host degenerates to the
    # plain PreemptionGuard path at the identical save position.
    from dptpu.resilience.quorum import QuorumSession, make_coordinator

    from dptpu.envknob import env_str as _env_str

    _quorum_dir = _env_str("DPTPU_QUORUM_DIR")
    _coord = make_coordinator(
        derived.num_processes, derived.process_index,
        el_conf["quorum_deadline_s"], directory=_quorum_dir,
        # protocol keys scoped to this run ATTEMPT: the resume position
        # is the one value every host derives identically, and it moves
        # with each preemption — a restart pointed at the same store
        # must not re-read the previous attempt's stop request
        namespace=f"e{start_epoch:04d}s{resume_step:06d}-",
    )
    qs = QuorumSession(_coord, guard) if _coord is not None else None
    if qs is not None and verbose:
        print(
            f"=> quorum save armed: {derived.num_processes} host(s), "
            f"deadline {el_conf['quorum_deadline_s']:g}s"
            + (f", store dir {_quorum_dir}" if _quorum_dir else
               " over the jax.distributed KV service")
        )
    # host-lost verdict (the "gone for good" trigger for elastic
    # resume): the fault harness — or, on a real pod, the chief's
    # heartbeat monitor — flips this flag; the loop then stops cleanly,
    # saves synchronously at the exact position, and the run reports
    # host_lost so the operator restarts shrunk with DPTPU_ELASTIC=1.
    lost = {"flag": False}

    def _host_lost():
        lost["flag"] = True
        print(
            "WARNING: host marked LOST (gone for good) — stopping with "
            "a sync save at the current position; restart on the "
            "shrunk world with DPTPU_ELASTIC=1 to replay the remainder",
            file=sys.stderr,
        )

    if fault_plan is not None:
        fault_plan.bind_worker_kill(train_loader.kill_one_worker)
        fault_plan.bind_host_lost(_host_lost)
        if qs is not None:
            fault_plan.bind_quorum_request(qs.request_remote)
        if verbose:
            print(f"=> fault injection armed: DPTPU_FAULT={fault_plan.spec}")
    # Emergency (single-host-initiated) saves must not enter a cross-host
    # gather: on a divergent failure only the raising host reaches the
    # handler, and a collective it enters alone hangs the job instead of
    # surfacing the error. Graceful preemption is different — cluster
    # SIGTERM reaches every host, so hosts converge on the same save
    # (full consensus is ROADMAP open item (a)).
    emergency_ok = derived.num_processes == 1 or not eval_view_gathers

    def _preempt_save_ok() -> bool:
        # Graceful-preemption saves may gather when the signal plausibly
        # reached every host: cluster preemption broadcasts SIGTERM, so
        # all hosts converge on the same save. A SIGINT (operator Ctrl-C
        # on ONE host) must not enter a collective alone — UNLESS the
        # quorum barrier proves the whole pod checked in within the
        # deadline (dptpu/resilience/quorum.py): then every host enters
        # the gather together and the save is pod-consistent even for a
        # single-host signal. No quorum / barrier timeout = skip the
        # gathered save (the boundary checkpoint stands) instead of
        # hanging the pod.
        import signal as _signal

        if emergency_ok:
            # no collective in this save (single host, or state never
            # gathers): nothing to coordinate
            return True
        if qs is not None:
            # EVERY host goes through the barrier — including the one
            # that caught the SIGTERM. If the signal host skipped it
            # (the pre-quorum rule below), its peers would wait for a
            # check-in that never comes, time out, skip the save, and
            # the signal host would enter the gather alone: the exact
            # hang this module exists to prevent. All hosts stopped at
            # the same agreed step, so the barrier tag matches.
            return qs.save_barrier()
        return guard.signum == _signal.SIGTERM

    def _drain_spans():
        # every drain of the shared tracer flows through here so an
        # on-demand profile window straddling the drain point keeps its
        # early spans (ProfileTrigger.absorb)
        spans = tracer.drain()
        if trigger is not None:
            trigger.absorb(spans)
        return spans

    # straggler-driven control (dptpu/resilience/elastic.py): armed by
    # DPTPU_STRAGGLER_FACTOR on a process-mode feed — per-worker span
    # latencies stream into P² quantiles and a persistently-slow worker
    # escalates re-split → eviction through the loader seam. Thread
    # mode has no worker pool to steer: the explicit knob gets a
    # notice, never silence (the locked contract).
    straggler = None
    if el_conf["straggler_factor"] is not None and not cfg.evaluate:
        if workers_mode == "process":
            from dptpu.resilience.elastic import StragglerController

            straggler = StragglerController(
                train_loader,
                el_conf["straggler_factor"],
                persist=el_conf["straggler_persist"],
                on_event=(trace_sink.log_event if trace_sink is not None
                          else None),
            )
            if verbose:
                print(
                    f"=> straggler control armed: re-split at "
                    f"{el_conf['straggler_factor']:g}x the healthiest "
                    f"worker's span p50 for "
                    f"{el_conf['straggler_persist']} consecutive "
                    f"verdicts, eviction at 2x that"
                )
        elif verbose:
            print("=> DPTPU_STRAGGLER_FACTOR ignored: thread-mode feed "
                  "(set DPTPU_WORKERS_MODE=process to get a worker "
                  "pool the controller can re-split/evict)")

    # online tune control (dptpu/tune/controller.py, ISSUE 19): armed
    # by DPTPU_TUNE_CONTROL, each actuator bounded, rate-limited, and
    # individually disarmable. No new thread: they tick on the host
    # thread in the same post-step hook as the straggler controller.
    tune_ctl = None
    if tune_conf["control"] and not cfg.evaluate:
        from dptpu.tune.controller import (
            Controller,
            decode_ahead_actuator,
            host_lost_actuator,
        )

        _tune_evt = (trace_sink.log_event if trace_sink is not None
                     else None)
        tune_ctl = Controller()
        if "host_lost" in tune_conf["control"] and qs is not None \
                and derived.is_chief:
            # chief-only, like the manual missing_hosts verdict it
            # automates: one declaration, then the elastic restart
            tune_ctl.add(host_lost_actuator(
                qs.coord, lambda missing: _host_lost(),
                deadline_s=el_conf["quorum_deadline_s"],
                interval_s=tune_conf["interval_s"], on_event=_tune_evt,
            ))
        if "decode_ahead" in tune_conf["control"]:
            if workers_mode == "process":
                # callable indirection: the ramp phase switch rebuilds
                # the loader and the actuator must follow it, not a
                # closed one
                tune_ctl.add(decode_ahead_actuator(
                    lambda: train_loader,
                    interval_s=tune_conf["interval_s"],
                    on_event=_tune_evt,
                ))
            elif verbose:
                print("=> tune control: decode_ahead ignored on a "
                      "thread-mode feed (no ring to deepen)")
        if not tune_ctl.actuators:
            tune_ctl = None
        elif verbose:
            print(
                f"=> tune control armed: "
                f"{', '.join(a.name for a in tune_ctl.actuators)} "
                f"(interval {tune_conf['interval_s']:g}s; disarm with "
                f"DPTPU_TUNE_CONTROL=off)"
            )

    # per-step tick: the profiling trigger, fault injection, the quorum
    # protocol and the straggler/tune controllers all ride ONE post-step
    # hook (order matters: faults fire before quorum reads the guard, so
    # a same-step signal reaches agreement on the step it landed)
    _ticks = [t for t in (
        trigger.tick if trigger is not None else None,
        fault_plan.on_step if fault_plan is not None else None,
        qs.tick if qs is not None else None,
        straggler.tick if straggler is not None else None,
        tune_ctl.tick if tune_ctl is not None else None,
    ) if t is not None]
    if not _ticks:
        obs_tick = None
    elif len(_ticks) == 1:
        obs_tick = _ticks[0]
    else:
        def obs_tick():
            for t in _ticks:
                t()

    def _stop_requested() -> bool:
        # quorum runs defer the stop to the AGREED step so the pod
        # stays consistent; without a coordinator the local guard (or
        # the host-lost verdict) decides alone, as before
        if lost["flag"]:
            return True
        if qs is not None:
            return qs.should_stop()
        return guard.requested

    def _stop_reason() -> str:
        if guard.signum is not None:
            return guard.signal_name
        if lost["flag"]:
            return "host_lost"
        if qs is not None and qs.stats()["reason"]:
            return f"quorum:{qs.stats()['reason']}"
        return "stop"

    result = {"history": [], "early_stopped": False, "training_time": None,
              "preempted": False}
    ramp_record = []
    if batch_ramp is not None:
        ramp_record.append({
            "epoch": start_epoch, "mult": ramp_mult,
            "global_batch": run_geom[1] * ramp_mult,
            "steps_per_epoch": steps_per_epoch,
            "peak_lr": sched_lr * ramp_mult,
        })

    def _enter_ramp_phase(m: int, epoch: int):
        # the batch-size ramp's phase switch (arXiv:1811.05233): bigger
        # per-host batch, fewer steps/epoch, peak LR x m per the
        # linear-scaling rule, geometry stamp updated so checkpoints
        # carry the phase they were trained at. LOUD by contract — a
        # changed batch/LR must never scroll by silently.
        nonlocal train_loader, train_step, schedule, steps_per_epoch
        nonlocal ramp_mult
        old_batch = host_batch * ramp_mult
        old_ahead = train_loader.decode_ahead
        ramp_mult = m
        train_loader.close()
        train_loader = _make_train_loader(host_batch * m)
        if old_ahead is not None and (
                train_loader.decode_ahead is None
                or train_loader.decode_ahead < old_ahead):
            # a controller-deepened issue window survives the rebuild
            # (the ctor already re-applied any explicit env value; only
            # carry forward what grew beyond it)
            train_loader.decode_ahead = old_ahead
        steps_per_epoch = max(len(train_loader), 1)
        schedule = _phase_schedule(m, epoch)
        train_step = _build_train_step(schedule)
        manager.geometry = (run_geom[0], run_geom[1] * m, run_geom[2])
        manager.batch_size = host_batch * m
        if fault_plan is not None:
            fault_plan.bind_worker_kill(train_loader.kill_one_worker)
        if straggler is not None:
            # fresh estimator windows over the REBUILT pool — a stale
            # verdict must never convict a fresh worker
            straggler.rebind(train_loader)
        ramp_record.append({
            "epoch": epoch, "mult": m,
            "global_batch": run_geom[1] * m,
            "steps_per_epoch": steps_per_epoch,
            "peak_lr": sched_lr * m,
        })
        print(
            f"=> BATCH RAMP at epoch {epoch}: per-host batch "
            f"{old_batch} -> {host_batch * m} (global "
            f"{run_geom[1] * m}), {steps_per_epoch} steps/epoch, peak "
            f"LR -> {sched_lr * m:g} per the linear-scaling rule",
            file=sys.stderr,
        )
    # last position at which `state` is known consistent — the boundary
    # fallback for the best-effort save below (mid-epoch exceptions save
    # their exact position through train_one_epoch's emergency_cb)
    current_pos = {"epoch": start_epoch, "step": resume_step}
    emergency = {"saved": False}
    try:
      # liveness beats ride a dedicated thread (StopToken teardown), so
      # a host parked inside a blocking device fetch keeps beating and
      # the chief's missing_hosts verdict stays meaningful (ROADMAP
      # item 3 residual (d)). Started INSIDE the try: every exit path
      # — including a setup failure below — reaches the finally that
      # closes it, so a dead host can never keep beating.
      if qs is not None:
          qs.start_heartbeat()
      with guard:
        for epoch in range(start_epoch, cfg.epochs):
            if batch_ramp is not None \
                    and _ramp_mult(epoch) != ramp_mult:
                _enter_ramp_phase(_ramp_mult(epoch), epoch)
            start_step = resume_step if epoch == start_epoch else 0
            current_pos = {"epoch": epoch, "step": start_step}
            if qs is not None:
                qs.epoch_start(epoch, start_step)
            if guard.requested or lost["flag"] \
                    or (qs is not None and qs.stop_signaled()):
                # the signal landed OUTSIDE the training loop (during the
                # previous epoch's validation/boundary save): act on it
                # before paying for another epoch's first step — the
                # grace window may not cover it
                path = None
                if _preempt_save_ok():
                    path = manager.save_step(
                        eval_view(state), epoch=epoch,
                        step_in_epoch=start_step, best_acc1=best_acc1,
                        sync=True,
                    )
                result["preempted"] = True
                if verbose:
                    print(
                        f"=> preempted ({_stop_reason()}) between "
                        f"epochs: "
                        + (f"saved '{path}' at epoch {epoch} step "
                           f"{start_step}" if path else
                           "skipped the gathered save (single-host "
                           "signal on a sharded multi-host run); the "
                           "epoch-boundary checkpoint stands")
                    )
                break

            def _save_step(s, steps, _e=epoch, sync=False):
                return manager.save_step(
                    eval_view(s), epoch=_e, step_in_epoch=steps,
                    best_acc1=best_acc1, sync=sync,
                )

            def _emergency(s, steps, _e=epoch):
                path = _save_step(s, steps, _e, sync=True)
                # flag only AFTER the save succeeded: if it raised (disk
                # full, transient I/O), the outer boundary fallback below
                # still gets its own attempt
                emergency["saved"] = True
                return path

            ep_t0 = time.time()
            state, train_stats = train_one_epoch(
                state,
                train_step,
                DevicePrefetcher(
                    train_loader.epoch(epoch, start_batch=start_step), put
                ),
                epoch=epoch,
                num_batches=steps_per_epoch,
                print_freq=cfg.print_freq,
                verbose=verbose,
                feed_stats=train_loader.feed_stats,
                start_step=start_step,
                should_stop=_stop_requested,
                on_step=obs_tick,
                ckpt_every=cfg.ckpt_steps,
                ckpt_cb=_save_step if cfg.ckpt_steps else None,
                emergency_cb=_emergency if emergency_ok else None,
            )
            ep_wall = time.time() - ep_t0
            # epoch attribution: drain this epoch's spans, account the
            # wall time (data_wait / h2d / device / ckpt / other), and
            # persist the timeline — the answer to "where did this
            # epoch's time go" without a profiler session
            ep_spans = _drain_spans()
            obs_report = None
            if tracer.enabled:
                obs_report = obs.attribute_epoch(
                    ep_spans, ep_wall, anomaly_x=obs_conf["anomaly"]
                )
                if verbose:
                    print(obs.format_report(obs_report, epoch))
            if trace_sink is not None:
                trace_sink.add_spans(ep_spans)
                if obs_report is not None:
                    # the attribution block, machine-readable, in the
                    # same per-host log as the spans it summarizes
                    trace_sink.log_event(
                        "epoch_report", {"epoch": epoch, **obs_report}
                    )
            # update the fallback position the moment the state advances:
            # if anything below (the preemption save itself, a profiler
            # stop, validate) raises, the outer best-effort save must
            # label `state` with the steps it actually contains — a stale
            # start-of-epoch label would make resume re-train k batches
            # already baked into the weights
            current_pos = {"epoch": epoch,
                           "step": train_stats["steps_done"]}
            if profile_dir and derived.is_chief and epoch == start_epoch:
                jax.profiler.stop_trace()
                profile_dir = None
            if train_stats.get("preempted"):
                path = None
                if _preempt_save_ok():
                    path = manager.save_step(
                        eval_view(state), epoch=epoch,
                        step_in_epoch=train_stats["steps_done"],
                        best_acc1=best_acc1, sync=True,
                    )
                result["preempted"] = True
                if verbose:
                    print(
                        f"=> preempted ({_stop_reason()}): "
                        + (f"saved '{path}' at epoch {epoch} step "
                           f"{train_stats['steps_done']}; --resume "
                           f"replays the sampler to this exact position"
                           if path else
                           "skipped the gathered mid-epoch save "
                           "(single-host signal on a sharded multi-host "
                           "run); the last boundary checkpoint stands")
                    )
                break
            current_pos = {"epoch": epoch + 1, "step": 0}
            gathered = eval_view(state)  # one ZeRO-1 all-gather per epoch
            val_stats = validate(
                gathered,
                eval_step,
                DevicePrefetcher(val_loader.epoch(0), put),
                num_batches=len(val_loader),
                print_freq=cfg.print_freq,
                verbose=verbose,
                count_divisor=val_count_divisor,
            )
            acc1 = val_stats["top1"]
            is_best = acc1 > best_acc1
            best_acc1 = max(acc1, best_acc1)
            result["history"].append({
                "epoch": epoch,
                **{f"train_{k}": v for k, v in train_stats.items()},
                **{f"val_{k}": v for k, v in val_stats.items()},
                **({"obs": obs_report} if obs_report is not None else {}),
            })
            with tracer.span("ckpt"):
                boundary_path = save_checkpoint(
                    gathered,
                    epoch=epoch + 1,
                    arch=cfg.arch,
                    best_acc1=best_acc1,
                    is_best=is_best,
                    is_chief=derived.is_chief,
                    directory=ckpt_dir,
                    geometry=manager.geometry,
                    sharding=sharding_tag,
                )
            if fault_plan is not None and boundary_path:
                # boundary saves count toward ckpt_truncate@save=N too —
                # the fault targets "the N-th checkpoint written", not
                # only the rotated step files. Store-URL saves have no
                # local file to tear, so the hook stands down there
                # (the CheckpointManager applies the same guard)
                from dptpu.data.store import is_store_url as _is_url

                if not _is_url(boundary_path):
                    fault_plan.on_checkpoint_saved(boundary_path)
            # one registry, one fan-out (dptpu/obs): the reference's 11
            # scalars/epoch (imagenet_ddp_apex.py:280-290), the feed
            # telemetry, and the step-phase attribution all publish into
            # the metrics registry and flush ONCE per epoch to every
            # attached sink — TB writer (chief, apex), the per-host
            # JSONL log (DPTPU_OBS_DIR), and the console Obs line —
            # replacing the three parallel plumbing paths that used to
            # carry them. Tags are unchanged: dashboards keep working.
            bt = max(train_stats["batch_time"], 1e-9)
            val_bt = max(val_stats.get("batch_time", bt), 1e-9)
            # under a batch ramp a step consumes the PHASE batch —
            # ramp_mult follows the switcher, so throughput stays
            # honest across phases (val keeps the base batch)
            scalars = {
                "Throughput/train":
                    derived.global_batch_size * ramp_mult / bt,
                "Throughput/val": derived.global_batch_size / val_bt,
                "Time/train": train_stats["batch_time"],
                "Time/val": val_bt,
                # feed-rate accounting: loader wait per step + the
                # fraction of the epoch the chip spent starved for data
                "Time/data": train_stats["data_time"],
                "Starvation/train": train_stats["starvation"],
                "Loss/train": train_stats["loss"],
                "Loss/val": val_stats["loss"],
                "Top1/train": train_stats["top1"],
                "Top1/val": val_stats["top1"],
                "Top5/train": train_stats["top5"],
                "Top5/val": val_stats["top5"],
                "Lr": train_stats["lr"],
            }
            # decode-cache + zero-copy + decode-ahead ring telemetry
            # (bytes_copied_per_batch = 0 is the zero-copy contract on
            # a dashboard)
            for tag, key in (
                ("Cache/hit_rate", "cache_hit_rate"),
                ("Feed/bytes_copied_per_batch", "bytes_copied_per_batch"),
                ("Feed/ring_occupancy", "ring_occupancy"),
                ("Feed/issue_ahead_depth", "issue_ahead_depth"),
                ("Feed/straggler_reissues", "straggler_reissues"),
                ("Feed/io_wait_s", "io_wait_s"),
                # packed-shard streaming plane (dptpu/data/stream.py):
                # byte-ring vs fadvise ownership, store fetch health
                ("Feed/odirect_active", "odirect_active"),
                ("Feed/shard_bytes_read", "shard_bytes_read"),
                ("Feed/shard_extents_read", "shard_extents_read"),
                ("Feed/store_wait_s", "store_wait_s"),
                ("Feed/store_retries", "store_retries"),
            ):
                if key in train_stats:
                    scalars[tag] = float(train_stats[key])
            # large-batch engine telemetry (Opt/*): accumulation depth,
            # the layer-wise trust-ratio spread (min/mean/max over
            # layers, from the optimizer's own norms), and — under the
            # sharded weight update — the bytes of optimizer state one
            # chip actually touches per update (the 1/N claim on a
            # dashboard)
            scalars["Opt/accum_steps"] = accum_steps
            for tag, key in (
                ("Opt/trust_ratio_min", "trust_min"),
                ("Opt/trust_ratio_mean", "trust_mean"),
                ("Opt/trust_ratio_max", "trust_max"),
            ):
                if key in train_stats:
                    scalars[tag] = train_stats[key]
            if opt_shard_bytes is not None:
                scalars["Opt/update_shard_bytes"] = opt_shard_bytes
            if obs_report is not None:
                scalars.update({
                    "Obs/data_wait_s": obs_report["data_wait_s"],
                    "Obs/h2d_s": obs_report["h2d_s"],
                    "Obs/device_s": obs_report["device_s"],
                    "Obs/ckpt_s": obs_report["ckpt_s"],
                    "Obs/other_s": obs_report["other_s"],
                    "Obs/coverage": obs_report["coverage"],
                    "Obs/step_p50_s": obs_report["step_p50_s"],
                    "Obs/step_p90_s": obs_report["step_p90_s"],
                    "Obs/step_max_s": obs_report["step_max_s"],
                    "Obs/anomalous_steps":
                        len(obs_report["anomalous_steps"]),
                    "Obs/tracer_dropped": tracer.dropped,
                })
            registry.set_scalars(scalars)
            registry.flush(epoch + 1)
            # validation + boundary-save spans: persisted to the
            # timeline, but never billed to the NEXT epoch's report
            val_spans = _drain_spans()
            if trace_sink is not None:
                trace_sink.add_spans(val_spans)
            # --desired-acc early stop, fractional like the reference
            # (README --desired-acc 0.75 vs top1 in percent, imagenet_ddp.py:224-236);
            # values > 1 are read as percent directly (documented in --help)
            target_pct = (
                None
                if cfg.desired_acc is None
                else cfg.desired_acc * 100.0
                if cfg.desired_acc <= 1.0
                else cfg.desired_acc
            )
            if target_pct is not None and best_acc1 >= target_pct:
                training_time = time.time() - start_time
                early_path = save_checkpoint(
                    gathered,
                    epoch=epoch + 1,
                    arch=cfg.arch,
                    best_acc1=best_acc1,
                    is_best=False,
                    is_chief=derived.is_chief,
                    training_time=training_time,
                    directory=ckpt_dir,
                    geometry=manager.geometry,
                    sharding=sharding_tag,
                )
                if fault_plan is not None and early_path:
                    from dptpu.data.store import is_store_url as _is_url

                    if not _is_url(early_path):
                        fault_plan.on_checkpoint_saved(early_path)
                if verbose:
                    print(
                        f"top-1 accuracy {best_acc1:.3f} reached desired "
                        f"{target_pct:.3f} after {training_time:.1f}s"
                    )
                result["early_stopped"] = True
                result["training_time"] = training_time
                break
    except BaseException:
        # best-effort safety net (never masks the original error): an
        # unexpected exception or KeyboardInterrupt between epoch-boundary
        # saves used to lose everything since the last boundary. Mid-epoch
        # failures already saved their exact position via emergency_cb;
        # anything else (validate, TB, checkpoint-best) saves the last
        # consistent boundary position here.
        if not emergency["saved"] and emergency_ok:
            try:
                manager.save_step(
                    eval_view(state),
                    epoch=current_pos["epoch"],
                    step_in_epoch=current_pos["step"],
                    best_acc1=best_acc1,
                    sync=True,
                )
            except Exception:
                pass
        raise
    finally:
        # Teardown is loud on the NORMAL path and silent only while
        # another error propagates (probe for an in-flight exception
        # BEFORE any close attempt: inside an except clause
        # sys.exc_info() would report the close error itself, never
        # None). Order: profiler trigger (may need to stop a live jax
        # trace), span/metric sinks, the TB writer — closing it HERE
        # covers the exception/preemption paths too, so a preempted
        # run's last-epoch scalars are never lost in a buffer — then
        # the checkpoint writer thread (exception paths already saved
        # synchronously, which drains the queue; a failed cadence write
        # must fail the run, not vanish).
        propagating = sys.exc_info()[0] is not None
        teardown_errors = []
        if qs is not None:
            # stop the heartbeat thread on EVERY exit path: a dead
            # host whose beat thread keeps posting would mask the
            # chief's missing_hosts verdict — the exact signal the
            # off-thread heartbeat exists to make meaningful
            try:
                qs.close()
            except Exception as e:
                teardown_errors.append(e)
        if trigger is not None:
            try:
                trigger.uninstall()
            except Exception:
                pass
        try:
            if trace_sink is not None:
                trace_sink.add_spans(tracer.drain())
                trace_sink.close()
        except Exception as e:
            teardown_errors.append(e)
        if trace_sink is not None and derived.is_chief:
            # the chief-side collector (ROADMAP item 3c): merge every
            # host's obs-<host>.jsonl under the obs dir into ONE pod
            # timeline — per-host streaming quantiles, windowed step
            # p50s ("what changed at 14:07"), straggler verdicts —
            # written atomically next to the logs it summarizes
            try:
                obs.merge_pod_timeline(
                    trace_sink.directory,
                    os.path.join(trace_sink.directory,
                                 "pod-timeline.json"),
                )
            except Exception as e:
                teardown_errors.append(e)
        obs.reset()
        if writer is not None:
            try:
                writer.close()
            except Exception as e:
                teardown_errors.append(e)
        if ckpt_writer is not None:
            # ALWAYS attempted, whatever the sinks above did: close() is
            # the one place a failed async cadence write surfaces — an
            # obs I/O error must never swallow a lost checkpoint
            try:
                ckpt_writer.close()
            except Exception as e:
                teardown_errors.append(e)
        if teardown_errors:
            # every failure gets at least a stderr line — raising can
            # only surface one, and under a propagating exception none
            for e in teardown_errors:
                print(f"WARNING: teardown close failed: {e!r}",
                      file=sys.stderr)
            if not propagating:
                # the LAST error is the checkpoint writer's when it
                # failed — the one that must win the raise
                raise teardown_errors[-1]
    if writer is not None:
        # final wall-clock report (imagenet_ddp_apex.py:292-300)
        elapsed = time.time() - start_time
        mins, secs = divmod(elapsed, 60)
        hrs, mins = divmod(mins, 60)
        print(
            "### Training Time: {:.2f} hrs {:.2f} mins {:.2f} secs "
            "| {:.2f} secs".format(hrs, mins, secs, elapsed)
        )
    train_loader.close()
    val_loader.close()
    for ds in (train_ds, val_ds):
        # streaming datasets own fds + /dev/shm staging slabs; release
        # them at the end of the run (ImageFolder/Synthetic have no
        # close — their caches are reclaimed by the atexit sweeps)
        if hasattr(ds, "close"):
            ds.close()
    result.update({"state": state, "best_acc1": best_acc1,
                   "epochs_run": len(result["history"])})
    # elastic-lifecycle report: what the remap did, what the quorum
    # agreed, what the straggler controller escalated — the benches'
    # (and an operator's post-mortem's) machine-readable record
    if elastic_resume is not None:
        result["elastic"] = elastic_resume
    if batch_ramp is not None:
        result["batch_ramp"] = ramp_record
    if lost["flag"]:
        result["host_lost"] = True
    if qs is not None:
        result["quorum"] = qs.stats()
    if straggler is not None:
        result["straggler"] = straggler.stats()
    if tuning is not None:
        result["tuning"] = tuning
    if tune_ctl is not None:
        result["tune_control"] = tune_ctl.stats()
    return result
