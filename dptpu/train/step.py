"""Compiled train/eval steps: the reference's hot loop as one XLA program.

One ``train_step`` fuses what the reference does in five eager stages
(H2D copy → forward → backward with bucketed NCCL all-reduce → SGD step →
metric ``.item()`` syncs, imagenet_ddp.py:254-281): normalization, forward,
backward, a single ``lax.pmean`` gradient all-reduce that XLA overlaps with
the backward computation (replacing c10d's bucketing engine, SURVEY.md §2b),
the optimizer update, and metric reduction. Parallelism is ``shard_map`` over
the mesh ``data`` axis with replicated params — the DDP topology. BatchNorm
runs on the *local* shard (per-replica statistics, DDP's default non-synced
BN) unless the model was built with ``bn_axis_name="data"`` (the SyncBN
analog); running stats are pmean'd every step so state stays replicated,
which matches what every replica would checkpoint/eval after DDP broadcast.

Normalization is fused into the step: batches arrive as raw **uint8** NHWC
and are converted + normalized on-device with mean/std ×255 — the
DataPrefetcher's GPU-side normalize (imagenet_ddp_apex.py:329-340), done the
XLA way (fused into the first conv's input, zero extra HBM round-trips, and
4× less host→device bandwidth than shipping f32).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:  # jax ≥ 0.8 top-level name; experimental path kept as fallback
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from dptpu.ops.loss import cross_entropy_loss
from dptpu.ops.metrics import topk_correct_fraction
from dptpu.parallel.mesh import DATA_AXIS

# torchvision Normalize constants (imagenet_ddp.py:163-165)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def tpu_compiler_options() -> Optional[dict]:
    """XLA:TPU compile options for the train/eval steps.

    The latency-hiding scheduler reorders the compiled program so DMA
    (parameter/operand prefetch, and ICI collectives on multi-chip meshes)
    overlaps compute instead of serializing with it — the standard option
    for multi-chip training, where it hides the gradient all-reduce under
    backward compute. It is a scheduling pass, not a numerics change.

    Honest caveat (PERF.md round 3): on the relayed single-chip bench
    environment this option is provably inert — the relay's compile cache
    keys on the HLO hash alone, and device-time profiles of "with" and
    "without" executables are identical. Apparent +8% readings from
    option sweeps there were wall-clock drift, not the scheduler. The
    option is kept because it is correct and load-bearing for real
    (non-relayed) multi-chip deployments, and harmless where ignored.

    ``DPTPU_NO_LHS=1`` opts out (debugging/regression triage).
    """
    import os

    if jax.default_backend() != "tpu" or os.environ.get("DPTPU_NO_LHS"):
        return None
    return {"xla_tpu_enable_latency_hiding_scheduler": "true"}


def normalize_images(images, dtype=jnp.float32):
    """uint8 [0,255] NHWC → normalized float, on device.

    The ``(x - mean·255) / (std·255)`` form matches the DataPrefetcher
    (imagenet_ddp_apex.py:333-340); already-float inputs are assumed
    normalized (the non-Apex ToTensor+Normalize path) and only cast.
    """
    if images.dtype == jnp.uint8:
        mean = jnp.asarray(IMAGENET_MEAN, jnp.float32) * 255.0
        std = jnp.asarray(IMAGENET_STD, jnp.float32) * 255.0
        return ((images.astype(jnp.float32) - mean) / std).astype(dtype)
    return images.astype(dtype)


def train_step_body(state, batch, *, compute_dtype, lr_schedule, seed,
                    axis_size, on_mesh, gather_params=None):
    """The shared per-shard train-step math — ONE source of truth for the
    DDP step below and the ZeRO-1 step (dptpu/parallel/zero.py), which
    differ only in whether params pass through a ``gather_params`` hook
    (whose all-gather VJP turns the gradient all-reduce into a
    reduce-scatter) and in their shard_map specs."""
    images = normalize_images(batch["images"], compute_dtype)
    labels = batch["labels"]
    dropout_key = jax.random.fold_in(jax.random.PRNGKey(seed), state.step)
    if on_mesh:
        dropout_key = jax.random.fold_in(
            dropout_key, lax.axis_index(DATA_AXIS)
        )

    def loss_fn(params):
        full = gather_params(params) if gather_params else params
        out, mutated = state.apply_fn(
            {"params": full, "batch_stats": state.batch_stats},
            images,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": dropout_key},
        )
        local_loss = cross_entropy_loss(out, labels)
        # Divide the shard-local mean by the axis size: under shard_map,
        # replicated params enter invariant, and jax's VMA semantics make
        # the gradient transpose insert the cross-shard psum automatically
        # — that psum IS the DDP all-reduce (XLA schedules it overlapped
        # with backward); psum(local_mean/axis_size) is exactly the
        # global-batch-mean gradient. Through a gather_params hook the
        # same transpose yields psum_scatter — the reduce-scattered shard
        # of that gradient.
        return local_loss / axis_size, (local_loss, out, mutated["batch_stats"])

    (_, (loss, logits, new_stats)), grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(state.params)
    top1, top5 = topk_correct_fraction(logits, labels, (1, 5))
    if on_mesh:
        # running BN stats + reported metrics: explicit cross-replica mean
        # (the reference's reduce_tensor, imagenet_ddp_apex.py:562-566)
        new_stats, loss, top1, top5 = lax.pmean(
            (new_stats, loss, top1, top5), DATA_AXIS
        )
    # the optimizer chain is elementwise (momentum, wd), so it is equally
    # valid on full params (DDP) and on ZeRO-1 shard-local slices
    direction, new_opt = state.tx.update(grads, state.opt_state, state.params)
    lr = lr_schedule(state.step)
    updates = jax.tree_util.tree_map(lambda u: -lr * u, direction)
    params = optax.apply_updates(state.params, updates)
    new_state = state.replace(
        step=state.step + 1,
        params=params,
        batch_stats=new_stats,
        opt_state=new_opt,
    )
    metrics = {
        "loss": loss,
        "top1": top1 * 100.0,
        "top5": top5 * 100.0,
        "lr": jnp.asarray(lr, jnp.float32),
    }
    return new_state, metrics


def make_train_step(mesh: Optional[Mesh] = None, compute_dtype=jnp.float32,
                    lr_schedule=None, seed: int = 0):
    """Build the jitted train step.

    Returns ``step(state, batch) -> (state, metrics)`` where ``batch`` is a
    dict with ``images`` (uint8/float NHWC) and ``labels`` (int32), and
    ``metrics`` has scalar f32 ``loss``/``top1``/``top5``/``lr``;
    loss/top1/top5 are already cross-replica-averaged (the reference's
    reduce_tensor, imagenet_ddp_apex.py:562-566, folded into the step).

    ``lr_schedule`` maps the global step count → learning rate (see
    dptpu.ops.schedules); it is applied here, after the optimizer's
    momentum/weight-decay chain, reproducing torch SGD's ``p -= lr·buf``.
    Defaults to constant 0.1 (the reference's base LR) for schedule-less
    callers.

    ``seed`` feeds the dropout streams of the models that have them
    (alexnet/vgg classifier heads, squeezenet): the per-step key is
    ``fold_in(PRNGKey(seed), global_step)`` — resume-stable — and each
    data shard folds in its axis index so replicas draw independent masks
    (per-process torch RNG semantics, nd_imagenet.py:84-92).
    """

    if lr_schedule is None:
        lr_schedule = lambda count: 0.1  # noqa: E731
    # Gradient normalizer: the data-axis size, NOT mesh.size. Under
    # shard_map's varying-axis semantics the param cotangents only vary
    # over axes the batch varied over ({data}), so the automatic psum in
    # the VJP spans exactly the data axis even when inner axes (e.g.
    # {"data": N, "model": M}) are open — the model-axis duplicates are
    # already invariant and are not summed. Locked by
    # tests/test_train_step.py::test_axes_open_mesh_matches_single_device.
    axis_size = int(mesh.shape[DATA_AXIS]) if mesh is not None else 1

    def step(state, batch):
        return train_step_body(
            state, batch, compute_dtype=compute_dtype,
            lr_schedule=lr_schedule, seed=seed, axis_size=axis_size,
            on_mesh=mesh is not None,
        )

    opts = tpu_compiler_options()
    if mesh is None:
        return jax.jit(step, donate_argnums=0, compiler_options=opts)
    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=(P(), P()),
    )
    return jax.jit(sharded, donate_argnums=0, compiler_options=opts)


def make_eval_step(mesh: Optional[Mesh] = None, compute_dtype=jnp.float32):
    """Build the jitted eval step.

    Returns ``eval_step(state, batch) -> sums`` with ``loss_sum``,
    ``correct1``, ``correct5``, ``count`` summed over the GLOBAL batch
    (psum over the data axis) — exact aggregate accuracy, the sharded-val +
    all-reduce behavior of the Apex path (imagenet_ddp_apex.py:232-234,
    457-460), but without its per-step host sync. An optional f32 ``mask``
    in the batch (1.0 = real sample) makes padded remainder batches exact.
    """

    def step(state, batch):
        images = normalize_images(batch["images"], compute_dtype)
        labels = batch["labels"]
        mask = batch.get("mask", jnp.ones(labels.shape, jnp.float32))
        logits = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            images,
            train=False,
        ).astype(jnp.float32)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        _, pred = lax.top_k(logits, min(5, logits.shape[-1]))
        hit = pred == labels[:, None]
        sums = {
            "loss_sum": (per_ex * mask).sum(),
            "correct1": (hit[:, :1].any(axis=1) * mask).sum(),
            "correct5": (hit.any(axis=1) * mask).sum(),
            "count": mask.sum(),
        }
        if mesh is not None:
            sums = lax.psum(sums, DATA_AXIS)
        return sums

    opts = tpu_compiler_options()
    if mesh is None:
        return jax.jit(step, compiler_options=opts)
    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(),
    )
    return jax.jit(sharded, compiler_options=opts)
