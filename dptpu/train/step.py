"""Compiled train/eval steps: the reference's hot loop as one XLA program.

One ``train_step`` fuses what the reference does in five eager stages
(H2D copy → forward → backward with bucketed NCCL all-reduce → SGD step →
metric ``.item()`` syncs, imagenet_ddp.py:254-281): normalization, forward,
backward, a single ``lax.pmean`` gradient all-reduce that XLA overlaps with
the backward computation (replacing c10d's bucketing engine, SURVEY.md §2b),
the optimizer update, and metric reduction. Parallelism is ``shard_map`` over
the mesh ``data`` axis with replicated params — the DDP topology. BatchNorm
runs on the *local* shard (per-replica statistics, DDP's default non-synced
BN) unless the model was built with ``bn_axis_name="data"`` (the SyncBN
analog); running stats are pmean'd every step so state stays replicated,
which matches what every replica would checkpoint/eval after DDP broadcast.

Normalization is fused into the step: batches arrive as raw **uint8** NHWC
and are converted + normalized on-device with mean/std ×255 — the
DataPrefetcher's GPU-side normalize (imagenet_ddp_apex.py:329-340), done the
XLA way (fused into the first conv's input, zero extra HBM round-trips, and
4× less host→device bandwidth than shipping f32).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:  # jax ≥ 0.8 top-level name; experimental path kept as fallback
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from dptpu.ops.loss import cross_entropy_loss
from dptpu.ops.metrics import topk_correct_fraction
from dptpu.ops.optimizers import trust_ratio_stats
from dptpu.parallel.hierarchy import (
    flat_replica_index,
    is_hierarchical,
    make_hierarchical_reduce,
)
from dptpu.parallel.mesh import (
    DATA_AXIS,
    SLICE_AXIS,
    data_axis_names,
    data_parallel_width,
    squeeze_axes,
)


def shard_map_nocheck(f, mesh, in_specs, out_specs):
    """``shard_map`` with the replication checker OFF, across jax APIs.

    This container's jax (0.4.37) cannot statically infer that the train
    step's ``P()`` outputs are replicated (the pre-existing slow-tier
    DDP failure, ROADMAP known constraint), so every dptpu step now
    places its collectives EXPLICITLY (``lax.psum`` in the step body /
    the all-gather VJP) and disables the checker — the same design
    ``dptpu/parallel/sequence.py`` always needed. Newer jax versions
    that drop the ``check_rep`` kwarg get the plain call."""
    try:
        return shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
    except TypeError:  # pragma: no cover - future jax without check_rep
        return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

# torchvision Normalize constants (imagenet_ddp.py:163-165)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def tpu_compiler_options() -> Optional[dict]:
    """XLA:TPU compile options for the train/eval steps.

    The latency-hiding scheduler reorders the compiled program so DMA
    (parameter/operand prefetch, and ICI collectives on multi-chip meshes)
    overlaps compute instead of serializing with it — the standard option
    for multi-chip training, where it hides the gradient all-reduce under
    backward compute. It is a scheduling pass, not a numerics change.

    Honest caveat (PERF.md round 3): on the relayed single-chip bench
    environment this option is provably inert — the relay's compile cache
    keys on the HLO hash alone, and device-time profiles of "with" and
    "without" executables are identical. Apparent +8% readings from
    option sweeps there were wall-clock drift, not the scheduler. The
    option is kept because it is correct and load-bearing for real
    (non-relayed) multi-chip deployments, and harmless where ignored.

    ``DPTPU_NO_LHS=1`` opts out (debugging/regression triage).
    """
    from dptpu.envknob import env_bool

    if jax.default_backend() != "tpu" or env_bool("DPTPU_NO_LHS", False):
        return None
    return {"xla_tpu_enable_latency_hiding_scheduler": "true"}


def normalize_images(images, dtype=jnp.float32):
    """uint8 [0,255] NHWC → normalized float, on device.

    The ``(x - mean·255) / (std·255)`` form matches the DataPrefetcher
    (imagenet_ddp_apex.py:333-340); already-float inputs are assumed
    normalized (the non-Apex ToTensor+Normalize path) and only cast.
    """
    if images.dtype == jnp.uint8:
        mean = jnp.asarray(IMAGENET_MEAN, jnp.float32) * 255.0
        std = jnp.asarray(IMAGENET_STD, jnp.float32) * 255.0
        return ((images.astype(jnp.float32) - mean) / std).astype(dtype)
    return images.astype(dtype)


def train_step_body(state, batch, *, compute_dtype, lr_schedule, seed,
                    axis_size, on_mesh, gather_params=None,
                    reduce_grads=None, tx=None, accum_steps=1,
                    label_smoothing=0.0, axis_names=(DATA_AXIS,),
                    overlap_plan=None):
    """The shared per-shard train-step math — ONE source of truth for the
    DDP step below, the ZeRO-1 step (dptpu/parallel/zero.py) and the
    GSPMD step (dptpu/parallel/gspmd.py), which differ only in their
    specs and two hooks:

    * ``gather_params`` — ZeRO-1's all-gather, whose tiled-all-gather
      VJP delivers the gradient reduce-scattered per shard;
    * ``reduce_grads`` — the explicit cross-replica gradient reduction
      (the DDP all-reduce: ``lax.psum`` over the data axis; ZeRO-1's
      psum for its few replicated leaves; None under GSPMD, where the
      partitioner derives it). Collectives are EXPLICIT here — the steps
      run ``check_rep=False`` because this container's jax rep-checker
      cannot infer the step's replicated outputs (ROADMAP known
      constraint), so correctness must not depend on the checker's
      implicit-psum rewrite.

    ``accum_steps=k > 1`` turns the step into gradient-accumulation
    microbatching: the per-replica batch splits into ``k`` microbatches
    and a ``lax.scan`` accumulates gradients (and BN statistics and
    metrics) in fp32 before the ONE optimizer update. Each microbatch is
    mathematically a virtual replica — per-microbatch BatchNorm over
    ``b/k`` samples, a distinct dropout stream per ``(replica, micro)``
    — so ``k·N`` emulates a pod ``k×`` wider than the rig, and the
    gradient reduction still happens ONCE, after the scan. ``k=1`` takes
    the exact unaccumulated code path (bit-identity by construction).

    ``tx`` overrides ``state.tx`` for the update (ZeRO-1 injects a
    shard-aware trust-ratio optimizer whose state structure matches).
    ``label_smoothing`` feeds the training loss only.

    ``axis_names`` is the tuple of mesh axes the replicas span:
    ``("data",)`` on the flat mesh, ``("slice", "data")`` on the
    two-level hierarchical mesh (dptpu/parallel/hierarchy.py) — the
    dropout replica id flattens over them slice-major (so it equals the
    flat mesh's index for the same chip) and the BN-stat/metric pmeans
    span all replicas either way.

    ``overlap_plan`` (``DPTPU_OVERLAP=1``; dptpu/parallel/overlap.py)
    REPLACES ``reduce_grads`` with the bucketed engine: at
    ``accum_steps == 1`` each bucket's reduction is part of the
    backward graph (issued the moment its gradients exist); under
    accumulation the bucketed reduction runs once, after the scan —
    the one-reduction-per-update contract unchanged.  Bit-identical to
    the unbucketed path at any bucket count (the regrouping argument —
    see the overlap module docstring).
    """
    labels = batch["labels"]
    wrap_params = (
        overlap_plan.wrap
        if overlap_plan is not None and accum_steps == 1 else None
    )
    step_key = jax.random.fold_in(jax.random.PRNGKey(seed), state.step)
    tx = state.tx if tx is None else tx
    pmean_axes = squeeze_axes(axis_names)

    def loss_and_grads(images_u8, labels_mb, dropout_key, denom):
        images = normalize_images(images_u8, compute_dtype)

        def loss_fn(params):
            if wrap_params is not None:
                # the overlap engine's per-bucket custom-VJP boundary:
                # backward through this identity performs the bucket's
                # reduction in-place in the backward graph
                params = wrap_params(params)
            full = gather_params(params) if gather_params else params
            out, mutated = state.apply_fn(
                {"params": full, "batch_stats": state.batch_stats},
                images,
                train=True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_key},
            )
            local_loss = cross_entropy_loss(out, labels_mb, label_smoothing)
            # the shard-local mean over `denom`; `reduce_grads`
            # completes the cross-replica mean AFTER accumulation — the
            # DDP psum runs once per step, not once per microbatch.
            # (ZeRO-1 is different: its gather_params all-gather and
            # psum_scatter VJP live inside the scan, so THOSE run per
            # microbatch — the documented price of never materializing
            # full params, see make_zero1_train_step.)
            return local_loss / denom, (
                local_loss, out, mutated["batch_stats"]
            )

        (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        return aux, grads

    if accum_steps == 1:
        dropout_key = step_key
        if on_mesh:
            dropout_key = jax.random.fold_in(
                dropout_key, flat_replica_index(axis_names)
            )
        (loss, logits, new_stats), grads = loss_and_grads(
            batch["images"], labels, dropout_key, axis_size
        )
        top1, top5 = topk_correct_fraction(logits, labels, (1, 5))
    else:
        k = accum_steps
        b = labels.shape[0]
        if b % k != 0:
            raise ValueError(
                f"accum_steps={k} does not divide the per-replica batch "
                f"of {b} — pick a divisor (the microbatch is b/k)"
            )
        imgs = batch["images"].reshape(
            (k, b // k) + batch["images"].shape[1:]
        )
        labs = labels.reshape((k, b // k))
        # virtual-replica id: replica r, microbatch j acts like replica
        # r·k + j of a k×-wider pod — distinct dropout streams, same
        # resume-stable (seed, step) root
        ax = flat_replica_index(axis_names) if on_mesh else 0

        def micro(carry, xs):
            g_acc, s_acc, m_acc = carry
            im, lb, j = xs
            dropout_key = jax.random.fold_in(step_key, ax * k + j)
            (loss, out, stats), grads = loss_and_grads(
                im, lb, dropout_key, 1.0
            )
            t1, t5 = topk_correct_fraction(out, lb, (1, 5))
            g_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), g_acc, grads
            )
            s_acc = jax.tree_util.tree_map(
                lambda a, s: a + s.astype(jnp.float32), s_acc, stats
            )
            return (g_acc, s_acc, m_acc + jnp.stack([loss, t1, t5])), None

        carry0 = (
            jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            ),
            jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, jnp.float32), state.batch_stats
            ),
            jnp.zeros((3,), jnp.float32),
        )
        (g_acc, s_acc, m_acc), _ = lax.scan(
            micro, carry0, (imgs, labs, jnp.arange(k))
        )
        # mean over the k·axis_size virtual replicas, fp32 throughout
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / (k * axis_size)).astype(p.dtype),
            g_acc, state.params,
        )
        new_stats = jax.tree_util.tree_map(
            lambda s, ref: (s / k).astype(ref.dtype),
            s_acc, state.batch_stats,
        )
        loss, top1, top5 = m_acc[0] / k, m_acc[1] / k, m_acc[2] / k
    if overlap_plan is not None and wrap_params is None:
        # accumulation x overlap: the bucketed reduction runs ONCE per
        # update, on the post-scan accumulated gradients
        grads = overlap_plan.reduce(grads)
    elif reduce_grads is not None:
        # the ONE explicit cross-replica gradient reduction (DDP
        # all-reduce / ZeRO-1 replicated-leaf psum)
        grads = reduce_grads(grads)
    if on_mesh:
        # running BN stats + reported metrics: explicit cross-replica mean
        # (the reference's reduce_tensor, imagenet_ddp_apex.py:562-566)
        new_stats, loss, top1, top5 = lax.pmean(
            (new_stats, loss, top1, top5), pmean_axes
        )
    # SGD's chain is elementwise, so it is equally valid on full params
    # (DDP) and ZeRO-1 shard-local slices; LARS/LAMB additionally need
    # per-layer norms, which the injected `tx`'s sumsq_reduce completes
    # across shards with one small psum (dptpu/ops/optimizers.py)
    direction, new_opt = tx.update(grads, state.opt_state, state.params)
    lr = lr_schedule(state.step)
    updates = jax.tree_util.tree_map(lambda u: -lr * u, direction)
    params = optax.apply_updates(state.params, updates)
    new_state = state.replace(
        step=state.step + 1,
        params=params,
        batch_stats=new_stats,
        opt_state=new_opt,
    )
    metrics = {
        "loss": loss,
        "top1": top1 * 100.0,
        "top5": top5 * 100.0,
        "lr": jnp.asarray(lr, jnp.float32),
    }
    tstats = trust_ratio_stats(new_opt)
    if tstats is not None:
        # layer-wise trust-ratio summary (Opt/* gauges): free — the
        # transform already computed it from the update's norms
        metrics.update(
            {name: jnp.asarray(v, jnp.float32)
             for name, v in tstats.items()}
        )
    return new_state, metrics


def make_train_step(mesh: Optional[Mesh] = None, compute_dtype=jnp.float32,
                    lr_schedule=None, seed: int = 0, accum_steps: int = 1,
                    label_smoothing: float = 0.0, dcn_dtype: str = "fp32",
                    overlap: bool = False, bucket_bytes: Optional[int] = None):
    """Build the jitted train step.

    Returns ``step(state, batch) -> (state, metrics)`` where ``batch`` is a
    dict with ``images`` (uint8/float NHWC) and ``labels`` (int32), and
    ``metrics`` has scalar f32 ``loss``/``top1``/``top5``/``lr`` (plus
    ``trust_min/mean/max`` under a trust-ratio optimizer);
    loss/top1/top5 are already cross-replica-averaged (the reference's
    reduce_tensor, imagenet_ddp_apex.py:562-566, folded into the step).

    ``lr_schedule`` maps the global step count → learning rate (see
    dptpu.ops.schedules); it is applied here, after the optimizer's
    momentum/weight-decay chain, reproducing torch SGD's ``p -= lr·buf``.
    Defaults to constant 0.1 (the reference's base LR) for schedule-less
    callers.

    ``seed`` feeds the dropout streams of the models that have them
    (alexnet/vgg classifier heads, squeezenet): the per-step key is
    ``fold_in(PRNGKey(seed), global_step)`` — resume-stable — and each
    data shard folds in its axis index so replicas draw independent masks
    (per-process torch RNG semantics, nd_imagenet.py:84-92).

    ``accum_steps=k`` enables gradient-accumulation microbatching
    (``--accum-steps`` / ``DPTPU_ACCUM``): each replica's batch splits
    into ``k`` fp32-accumulated microbatches before the one optimizer
    update, emulating a pod ``k×`` wider (see ``train_step_body``).

    On a hierarchical ``{slice, data}`` mesh
    (``make_hierarchical_mesh``) the gradient reduction decomposes into
    reduce-scatter(ICI) → shard-sized all-reduce(DCN) → all-gather(ICI)
    per leaf (dptpu/parallel/hierarchy.py), with ``dcn_dtype="bf16"``
    compressing the DCN hop (fp32 accumulation). Under accumulation the
    whole three-hop reduction still runs ONCE per update, after the
    microbatch scan — never per microbatch.

    ``overlap=True`` (``DPTPU_OVERLAP=1``) swaps the per-leaf reduction
    for the bucketed backward-overlapped engine
    (dptpu/parallel/overlap.py): the gradient tree packs into
    ``bucket_bytes``-bounded buckets in reverse layer order and each
    bucket reduces as ONE fused collective, issued inside the backward
    graph the moment its gradients exist (the hierarchical ladder runs
    per bucket on the flat buffer).  Bit-identical to ``overlap=False``
    at any bucket count.  No-op on a mesh-less single-device step
    (there is no collective to overlap).
    """

    if lr_schedule is None:
        lr_schedule = lambda count: 0.1  # noqa: E731
    # Gradient normalizer: the data axes' size, NOT mesh.size. The
    # explicit psum below spans exactly the data axis (both data axes on
    # a hierarchical mesh) even when inner axes (e.g. {"data": N,
    # "model": M}) are open — the model-axis duplicates compute
    # identical grads and must NOT be summed. Locked by
    # tests/test_train_step.py::test_axes_open_mesh_matches_single_device.
    axis_names = data_axis_names(mesh) if mesh is not None else (DATA_AXIS,)
    axis_size = data_parallel_width(mesh)
    hier = is_hierarchical(mesh)
    reduce_grads = None
    overlap_plan = None
    if overlap and mesh is not None:
        from dptpu.parallel.overlap import (
            DEFAULT_BUCKET_MB,
            OverlapPlan,
            make_ddp_bucket_reduce,
        )

        inner = int(mesh.shape[DATA_AXIS]) if hier else None
        n_slices = int(mesh.shape[SLICE_AXIS]) if hier else None
        overlap_plan = OverlapPlan(
            bucket_bytes or int(DEFAULT_BUCKET_MB * 1e6),
            make_ddp_bucket_reduce(hier, dcn_dtype, inner=inner,
                                   slices=n_slices),
        )
    elif hier:
        # the two-level reduction: per-chip DCN bytes ~1/dp_in_slice of
        # the flat all-reduce (the Mikami/Yamazaki hierarchy)
        reduce_grads = make_hierarchical_reduce(mesh, dcn_dtype)
    elif mesh is not None:
        # the DDP all-reduce, placed explicitly (see shard_map_nocheck):
        # grads arrive as d(local_mean/axis_size), so the psum IS the
        # global-batch-mean gradient
        reduce_grads = lambda g: lax.psum(g, DATA_AXIS)  # noqa: E731

    def step(state, batch):
        return train_step_body(
            state, batch, compute_dtype=compute_dtype,
            lr_schedule=lr_schedule, seed=seed, axis_size=axis_size,
            on_mesh=mesh is not None, reduce_grads=reduce_grads,
            accum_steps=accum_steps, label_smoothing=label_smoothing,
            axis_names=axis_names, overlap_plan=overlap_plan,
        )

    opts = tpu_compiler_options()
    if mesh is None:
        return jax.jit(step, donate_argnums=0, compiler_options=opts)
    batch_spec = P(squeeze_axes(axis_names))
    sharded = shard_map_nocheck(
        step,
        mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=(P(), P()),
    )
    return jax.jit(sharded, donate_argnums=0, compiler_options=opts)


def make_eval_step(mesh: Optional[Mesh] = None, compute_dtype=jnp.float32):
    """Build the jitted eval step.

    Returns ``eval_step(state, batch) -> sums`` with ``loss_sum``,
    ``correct1``, ``correct5``, ``count`` summed over the GLOBAL batch
    (psum over the data axis) — exact aggregate accuracy, the sharded-val +
    all-reduce behavior of the Apex path (imagenet_ddp_apex.py:232-234,
    457-460), but without its per-step host sync. An optional f32 ``mask``
    in the batch (1.0 = real sample) makes padded remainder batches exact.
    """

    def step(state, batch):
        images = normalize_images(batch["images"], compute_dtype)
        labels = batch["labels"]
        mask = batch.get("mask", jnp.ones(labels.shape, jnp.float32))
        logits = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            images,
            train=False,
        ).astype(jnp.float32)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        _, pred = lax.top_k(logits, min(5, logits.shape[-1]))
        hit = pred == labels[:, None]
        sums = {
            "loss_sum": (per_ex * mask).sum(),
            "correct1": (hit[:, :1].any(axis=1) * mask).sum(),
            "correct5": (hit.any(axis=1) * mask).sum(),
            "count": mask.sum(),
        }
        if mesh is not None:
            sums = lax.psum(sums, squeeze_axes(data_axis_names(mesh)))
        return sums

    opts = tpu_compiler_options()
    if mesh is None:
        return jax.jit(step, compiler_options=opts)
    sharded = shard_map_nocheck(
        step,
        mesh=mesh,
        in_specs=(P(), P(squeeze_axes(data_axis_names(mesh)))),
        out_specs=P(),
    )
    return jax.jit(sharded, compiler_options=opts)
