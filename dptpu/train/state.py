"""Train state and the torch-semantics optimizer.

One pytree carries everything the reference splits across mutable objects
(model params + BN buffers, ``optimizer.param_groups`` state, epoch counter):
params, batch_stats, optimizer state, and the global step. The checkpoint
payload (SURVEY.md §3.5) serializes this tree plus bookkeeping.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)


def make_optimizer(
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    name: str = "sgd",
    sumsq_reduce=None,
) -> optax.GradientTransformation:
    """Build the lr-less optimizer direction chain.

    ``name`` selects the recipe (``--optimizer`` / ``DPTPU_OPT``):

    * ``sgd`` (default) — torch-exact SGD semantics
      (imagenet_ddp.py:133-135): weight decay folds *into the gradient
      before* the momentum accumulation (``g += wd·p``; ``buf = m·buf +
      g``; ``p -= lr·buf``), and decays **every** parameter —
      conv/dense kernels, biases, and BN scale/shift alike.
    * ``lars`` / ``lamb`` — the large-batch layer-wise trust-ratio
      optimizers (dptpu/ops/optimizers.py); these follow their papers'
      skip list instead (no decay/trust on ndim<2 leaves). ``momentum``
      feeds LARS's momentum; LAMB keeps its Adam betas.

    Every chain yields the un-scaled direction; the train step
    multiplies by ``-lr(state.step)`` itself (torch's
    apply-lr-after-momentum), so the LR schedule is a pure function of
    the checkpointed global step — restart at ``--start-epoch N`` or
    resume lands on exactly the reference's epoch-N LR instead of an
    optimizer-internal count that resets to 0.

    ``sumsq_reduce`` threads the weight-update-sharding norm completer
    into the trust-ratio stage (see dptpu/parallel/zero.py); ignored by
    sgd, whose update is purely elementwise.
    """
    if name == "sgd":
        return optax.chain(
            optax.add_decayed_weights(weight_decay),
            optax.trace(decay=momentum, nesterov=False),
        )
    if name == "lars":
        from dptpu.ops.optimizers import lars

        return lars(
            momentum=momentum,
            weight_decay=weight_decay,
            sumsq_reduce=sumsq_reduce,
        )
    if name == "lamb":
        from dptpu.ops.optimizers import lamb

        return lamb(weight_decay=weight_decay, sumsq_reduce=sumsq_reduce)
    raise ValueError(
        f"unknown optimizer {name!r}: expected 'sgd', 'lars' or 'lamb'"
    )


def map_momentum(opt_state, trace_fn, leaf_fn=None):
    """Structurally rebuild an optax chain state: each ``TraceState``'s
    momentum trace maps through ``trace_fn(trace)``; every other leaf
    maps through ``leaf_fn`` (identity when None).

    Structural — matching by tree position, never by shape — because a
    replicated param's shape can collide with a sharded one's. The ONE
    walk shared by GSPMD sharding trees (dptpu/parallel/gspmd.py),
    torch-checkpoint momentum restore (dptpu/train/checkpoint.py), and
    any future optimizer-state surgery.
    """
    import optax

    def rec(node):
        if isinstance(node, optax.TraceState):
            return optax.TraceState(trace=trace_fn(node.trace))
        if isinstance(node, (tuple, list)) and not hasattr(node, "shape"):
            children = [rec(c) for c in node]
            if hasattr(node, "_fields"):  # NamedTuple (optax states)
                return type(node)(*children)
            return children if isinstance(node, list) else tuple(children)
        if leaf_fn is None:
            return node
        return jax.tree_util.tree_map(leaf_fn, node)

    return rec(opt_state)


def create_train_state(
    rng: jax.Array,
    model,
    tx: optax.GradientTransformation,
    input_shape=(1, 224, 224, 3),
    input_dtype=jnp.float32,
    initial_step: int = 0,
    variables=None,
) -> TrainState:
    """Initialize params/BN state with a dummy batch and build the state.

    ``initial_step`` seeds the global step for fresh runs that start at a
    later epoch (``--start-epoch`` without ``--resume``,
    imagenet_ddp.py:35-36): the LR schedule reads this step.

    ``variables`` overrides the random init with an existing
    ``{"params", "batch_stats"}`` tree — the ``--pretrained`` path
    (imagenet_ddp.py:109-111), fed by
    ``dptpu.models.pretrained.load_pretrained_variables``.
    """
    if variables is None:
        variables = model.init(
            rng, jnp.zeros(input_shape, input_dtype), train=False
        )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.asarray(initial_step, jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        apply_fn=model.apply,
        tx=tx,
    )
