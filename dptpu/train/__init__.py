"""Training layer: state, compiled steps, epoch loop, checkpointing.

The TPU-native L6 (SURVEY.md §1): the reference's per-batch Python loop with
eager H2D copies, backward-hook all-reduce, and ``optimizer.step()``
(imagenet_ddp.py:239-281) becomes one jitted SPMD ``train_step`` whose
gradient all-reduce, optimizer update, and metric reduction are a single XLA
program per step.
"""

from dptpu.train.checkpoint import load_checkpoint, save_checkpoint
from dptpu.train.fit import fit
from dptpu.train.loop import train_one_epoch, validate
from dptpu.train.state import TrainState, create_train_state, make_optimizer
from dptpu.train.step import make_eval_step, make_train_step

__all__ = [
    "TrainState",
    "create_train_state",
    "fit",
    "load_checkpoint",
    "make_eval_step",
    "make_optimizer",
    "make_train_step",
    "save_checkpoint",
    "train_one_epoch",
    "validate",
]
