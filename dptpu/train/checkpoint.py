"""Checkpoint save / best-copy / resume with the reference's exact contract.

Payload mirrors the reference's dict {epoch, arch, state_dict, best_acc1,
optimizer} (imagenet_ddp.py:216-222), carried as a flax-serialized pytree:
{epoch, arch, params, batch_stats, opt_state, step, best_acc1, and
training_time when early-stop records it (imagenet_ddp.py:227-234)}.
Filenames match (``checkpoint.pth.tar`` → copy ``model_best.pth.tar`` when
best, imagenet_ddp.py:327-330); writes are single-writer (the
``rank % ngpus == 0`` guard, imagenet_ddp.py:215 — here ``process_index==0``)
and atomic (tmp + rename), which the reference is not. Unlike torch.load
there is no ``map_location`` dance: restored arrays are host numpy until the
next step's sharded ``device_put`` places them (SURVEY.md §3.5 caveat (d):
we keep a native pytree, not a ``module.``-prefixed state dict).
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

import jax
from flax import serialization

CHECKPOINT_NAME = "checkpoint.pth.tar"
BEST_NAME = "model_best.pth.tar"


def save_checkpoint(
    state,
    *,
    epoch: int,
    arch: str,
    best_acc1: float,
    is_best: bool,
    directory: str = ".",
    is_chief: bool = True,
    training_time: Optional[float] = None,
    filename: str = CHECKPOINT_NAME,
) -> Optional[str]:
    """Serialize state; copy to model_best when ``is_best``. Chief-only."""
    if not is_chief:
        return None
    payload = {
        "epoch": epoch,
        "arch": arch,
        "best_acc1": float(best_acc1),
        "step": jax.device_get(state.step),
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
        "training_time": -1.0 if training_time is None else float(training_time),
    }
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, filename)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(payload))
    os.replace(tmp, path)
    if is_best:
        shutil.copyfile(path, os.path.join(directory, BEST_NAME))
    return path


def load_checkpoint(path: str, state):
    """Resume: restore state + bookkeeping from a checkpoint file.

    The reference restores start_epoch/best_acc1/model/optimizer
    (imagenet_ddp.py:138-153). Returns ``(state, meta)`` where meta has
    ``epoch`` (resume start epoch), ``arch``, ``best_acc1``.
    """
    with open(path, "rb") as f:
        raw = f.read()
    template = {
        "epoch": 0,
        "arch": "",
        "best_acc1": 0.0,
        "step": jax.device_get(state.step),
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
        "training_time": -1.0,
    }
    payload = serialization.from_bytes(template, raw)
    new_state = state.replace(
        step=payload["step"],
        params=payload["params"],
        batch_stats=payload["batch_stats"],
        opt_state=payload["opt_state"],
    )
    meta = {
        "epoch": int(payload["epoch"]),
        "arch": payload["arch"],
        "best_acc1": float(payload["best_acc1"]),
        "training_time": float(payload["training_time"]),
    }
    return new_state, meta
