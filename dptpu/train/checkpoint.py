"""Checkpoint save / best-copy / resume with the reference's exact contract.

Payload mirrors the reference's dict {epoch, arch, state_dict, best_acc1,
optimizer} (imagenet_ddp.py:216-222), carried as a flax-serialized pytree:
{epoch, arch, params, batch_stats, opt_state, step, best_acc1, and
training_time when early-stop records it (imagenet_ddp.py:227-234)}.
Filenames match (``checkpoint.pth.tar`` → copy ``model_best.pth.tar`` when
best, imagenet_ddp.py:327-330); writes are single-writer (the
``rank % ngpus == 0`` guard, imagenet_ddp.py:215 — here ``process_index==0``)
and atomic (tmp + rename), which the reference is not. Unlike torch.load
there is no ``map_location`` dance: restored arrays are host numpy until the
next step's sharded ``device_put`` places them.

``--resume`` also accepts the REFERENCE'S OWN checkpoints
(imagenet_ddp.py:216-222: ``torch.save({epoch, arch, state_dict,
best_acc1, optimizer})`` with DDP's ``module.``-prefixed keys): a file
that is not a flax-serialized payload routes through the torchvision key
map (dptpu/models/pretrained.py) to restore params/batch_stats, and the
SGD ``momentum_buffer``s map onto the optax trace (same semantics:
both store ``buf`` with ``p -= lr·buf``), closing SURVEY §3.5 caveat
(d). The global step is rebuilt as ``epoch · steps_per_epoch`` so the
LR schedule resumes on the reference's epoch boundary.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

import jax
from flax import serialization

from dptpu.models.pretrained import QKV_LAYOUT, qkv_needs_migration
from dptpu.train.state import map_momentum

CHECKPOINT_NAME = "checkpoint.pth.tar"
BEST_NAME = "model_best.pth.tar"


def save_checkpoint(
    state,
    *,
    epoch: int,
    arch: str,
    best_acc1: float,
    is_best: bool,
    directory: str = ".",
    is_chief: bool = True,
    training_time: Optional[float] = None,
    filename: str = CHECKPOINT_NAME,
) -> Optional[str]:
    """Serialize state; copy to model_best when ``is_best``. Chief-only."""
    if not is_chief:
        return None
    payload = {
        "epoch": epoch,
        "arch": arch,
        "best_acc1": float(best_acc1),
        "step": jax.device_get(state.step),
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
        "training_time": -1.0 if training_time is None else float(training_time),
        # attention-storage layout marker: lets a future layout change
        # (like round 4's [q|k|v]-major -> head-major move) detect and
        # migrate old files instead of silently scrambling them
        "qkv_layout": QKV_LAYOUT,
    }
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, filename)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(payload))
    os.replace(tmp, path)
    if is_best:
        shutil.copyfile(path, os.path.join(directory, BEST_NAME))
    return path


def load_checkpoint(path: str, state, arch: Optional[str] = None,
                    steps_per_epoch: Optional[int] = None):
    """Resume: restore state + bookkeeping from a checkpoint file.

    The reference restores start_epoch/best_acc1/model/optimizer
    (imagenet_ddp.py:138-153). Returns ``(state, meta)`` where meta has
    ``epoch`` (resume start epoch), ``arch``, ``best_acc1``.

    Accepts dptpu's flax-serialized payload OR a reference-produced
    ``torch.save`` checkpoint (detected by failed flax deserialization;
    see module docstring). ``arch`` names the key map for the torch
    path (the checkpoint's own ``arch`` field wins when present);
    ``steps_per_epoch`` rebuilds the global step from the torch
    checkpoint's epoch, which stores no step count.
    """
    with open(path, "rb") as f:
        raw = f.read()
    # dispatch on the file's magic, not on a failed parse: a torch file is
    # a zip (PK..) or legacy pickle (protocol-2 \x80 prefix); anything
    # else goes to flax so a genuinely corrupt/mismatched flax payload
    # surfaces its own precise error instead of an unpickling one (and
    # the torch path never pays for building the flax template)
    if raw[:4] == b"PK\x03\x04" or raw[:2] == b"\x80\x02":
        return _load_torch_checkpoint(path, state, arch, steps_per_epoch)
    template = {
        "epoch": 0,
        "arch": "",
        "best_acc1": 0.0,
        "step": jax.device_get(state.step),
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
        "training_time": -1.0,
        "qkv_layout": "",
    }
    # structural legacy detection, single decode: restore the msgpack
    # tree once (raises its precise error on a corrupt file), pick the
    # template by the payload's own top-level keys, and validate with
    # from_state_dict (from_bytes is exactly restore + from_state_dict).
    # A pre-round-4 payload has no qkv_layout field — parse it with the
    # legacy template, then migrate ViT attention columns from
    # [q|k|v]-major to head-major (dptpu/models/vit.py).
    restored = serialization.msgpack_restore(raw)
    if not isinstance(restored, dict):
        raise ValueError(
            f"{path}: checkpoint payload is {type(restored).__name__}, "
            "not a dict — corrupt or not a dptpu checkpoint"
        )
    if "qkv_layout" in restored:
        payload = serialization.from_state_dict(template, restored)
    else:
        legacy = {k: v for k, v in template.items() if k != "qkv_layout"}
        payload = serialization.from_state_dict(legacy, restored)
        payload["qkv_layout"] = ""
    params = payload["params"]
    opt_state = payload["opt_state"]
    ckpt_arch = payload["arch"] or arch or ""
    if qkv_needs_migration(ckpt_arch, payload["qkv_layout"]):
        from dptpu.models.pretrained import _qkv_to_head_major

        params = _qkv_to_head_major(ckpt_arch, params)
        opt_state = map_momentum(
            opt_state, lambda t: _qkv_to_head_major(ckpt_arch, t)
        )
    new_state = state.replace(
        step=payload["step"],
        params=params,
        batch_stats=payload["batch_stats"],
        opt_state=opt_state,
    )
    meta = {
        "epoch": int(payload["epoch"]),
        "arch": payload["arch"],
        "best_acc1": float(payload["best_acc1"]),
        "training_time": float(payload["training_time"]),
    }
    return new_state, meta


def _load_torch_checkpoint(path: str, state, arch: Optional[str],
                           steps_per_epoch: Optional[int]):
    """Resume from the reference's own ``torch.save`` checkpoint
    (imagenet_ddp.py:216-222): ``module.``-prefixed state dict through
    the torchvision key map, SGD momentum buffers onto the optax trace.
    """
    import numpy as np
    import torch

    from dptpu.models.pretrained import (
        _from_torch,
        convert_state_dict,
        torch_key_map,
    )

    ckpt = torch.load(path, map_location="cpu", weights_only=False)
    arch = str(ckpt.get("arch") or arch or "")
    if not arch:
        raise ValueError(
            f"{path}: torch-format checkpoint carries no 'arch' and none "
            "was passed — cannot build the key map"
        )
    raw_sd = ckpt["state_dict"]
    template = {
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
    }
    kmap = torch_key_map(arch, template)
    sd = {}
    # torch parameters() order == state-dict key order restricted to
    # keys the map resolves into the 'params' collection — this excludes
    # EVERY registered buffer generically (BN running stats/bookkeeping,
    # Swin's relative_position_index/attn_mask, ...), not just the BN
    # suffixes, so the param-index mapping below cannot desync on archs
    # with exotic buffers
    param_keys = []
    for k, v in raw_sd.items():
        k = k[len("module."):] if k.startswith("module.") else k
        if k.endswith("num_batches_tracked"):
            continue  # torch BN bookkeeping; no dptpu equivalent
        sd[k] = v.detach().cpu().numpy()
        if k in kmap and kmap[k][0] == "params":
            param_keys.append(k)
    variables = convert_state_dict(arch, sd, template, kmap=kmap)

    # SGD momentum: torch keys state entries by global param index in
    # param_groups order — identical to parameters() order (param_keys)
    opt_sd = ckpt.get("optimizer") or {}
    indices = [
        i for g in opt_sd.get("param_groups", []) for i in g["params"]
    ]
    if indices and len(indices) != len(param_keys):
        # a silent skip here would partially restore momentum after a
        # desync; refuse loudly instead
        raise ValueError(
            f"{path}: torch optimizer tracks {len(indices)} params but "
            f"the key map resolves {len(param_keys)} trainable keys for "
            f"'{arch}' — the param-index mapping would desync, so "
            f"momentum cannot be restored safely"
        )
    torch_state = opt_sd.get("state", {})
    buffers = {}
    for pos, idx in enumerate(indices):
        buf = torch_state.get(idx, {}).get("momentum_buffer")
        if buf is None:
            continue  # torch SGD momentum starts lazily per-param
        collection, names, kind = kmap[param_keys[pos]]
        buffers[names] = _from_torch(
            buf.detach().cpu().numpy(), kind
        ).astype(np.float32)
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        variables["params"]
    )
    trace_leaves = []
    for leaf_path, leaf in flat:
        names = tuple(p.key for p in leaf_path)
        buf = buffers.get(names)
        if buf is not None and buf.shape != leaf.shape:
            raise ValueError(
                f"momentum buffer for {'.'.join(names)}: shape "
                f"{buf.shape} != param {leaf.shape}"
            )
        trace_leaves.append(
            np.zeros_like(leaf) if buf is None else buf
        )
    new_trace = jax.tree_util.tree_unflatten(treedef, trace_leaves)

    epoch = int(ckpt.get("epoch", 0))
    step = jax.device_get(state.step)
    if steps_per_epoch is not None:
        step = np.asarray(epoch * int(steps_per_epoch), dtype=step.dtype)
    new_state = state.replace(
        step=step,
        params=variables["params"],
        batch_stats=variables["batch_stats"],
        opt_state=map_momentum(
            jax.device_get(state.opt_state), lambda _: new_trace
        ),
    )
    meta = {
        "epoch": epoch,
        "arch": arch,
        "best_acc1": float(ckpt.get("best_acc1", 0.0)),
        "training_time": float(ckpt.get("training_time", -1.0)),
    }
    return new_state, meta
