"""Checkpoint save / best-copy / resume with the reference's exact contract.

Payload mirrors the reference's dict {epoch, arch, state_dict, best_acc1,
optimizer} (imagenet_ddp.py:216-222), carried as a flax-serialized pytree:
{epoch, arch, params, batch_stats, opt_state, step, best_acc1, and
training_time when early-stop records it (imagenet_ddp.py:227-234)}.
Filenames match (``checkpoint.pth.tar`` → copy ``model_best.pth.tar`` when
best, imagenet_ddp.py:327-330); writes are single-writer (the
``rank % ngpus == 0`` guard, imagenet_ddp.py:215 — here ``process_index==0``)
and atomic (tmp + rename), which the reference is not. Unlike torch.load
there is no ``map_location`` dance: restored arrays are host numpy until the
next step's sharded ``device_put`` places them.

``--resume`` also accepts the REFERENCE'S OWN checkpoints
(imagenet_ddp.py:216-222: ``torch.save({epoch, arch, state_dict,
best_acc1, optimizer})`` with DDP's ``module.``-prefixed keys): a file
that is not a flax-serialized payload routes through the torchvision key
map (dptpu/models/pretrained.py) to restore params/batch_stats, and the
SGD ``momentum_buffer``s map onto the optax trace (same semantics:
both store ``buf`` with ``p -= lr·buf``), closing SURVEY §3.5 caveat
(d). The global step is rebuilt as ``epoch · steps_per_epoch`` so the
LR schedule resumes on the reference's epoch boundary.
"""

from __future__ import annotations

import queue
import struct
import threading
import zlib
from typing import Optional

import jax
from flax import serialization

from dptpu.models.pretrained import QKV_LAYOUT, qkv_needs_migration
from dptpu.train.state import map_momentum
from dptpu.utils.sync import OrderedLock

CHECKPOINT_NAME = "checkpoint.pth.tar"
BEST_NAME = "model_best.pth.tar"

# Content-checksum footer: ``payload || CRC_MAGIC || crc32(payload)``.
# Appended (not prepended) so pre-footer files and the reference's torch
# files keep loading unchanged; a truncated write loses the footer and a
# bit-flip fails the CRC — both are detected before flax ever parses.
CRC_MAGIC = b"DPTPUCRC"
_FOOTER_LEN = len(CRC_MAGIC) + 4


class EmptyCheckpointError(FileNotFoundError):
    """A checkpoint file that exists but holds zero bytes — the signature
    of a crash between ``open`` and the first write (or a power loss with
    no fsync). Derives from FileNotFoundError so warn-and-continue resume
    paths can treat 'empty' like 'absent'."""


class CorruptCheckpointError(ValueError):
    """Checkpoint bytes fail their content checksum or parse."""


def seal_payload(payload: bytes) -> bytes:
    """Append the CRC footer to serialized checkpoint bytes."""
    return payload + CRC_MAGIC + struct.pack(
        "<I", zlib.crc32(payload) & 0xFFFFFFFF
    )


def split_payload(raw: bytes, path: str = "<bytes>") -> tuple:
    """Strip + verify the CRC footer; returns ``(payload, verified)``.

    ``verified`` is False for pre-footer (legacy) files, which pass
    through untouched; a present-but-wrong CRC raises
    :class:`CorruptCheckpointError`.
    """
    if len(raw) >= _FOOTER_LEN and raw[-_FOOTER_LEN:-4] == CRC_MAGIC:
        payload, crc = raw[:-_FOOTER_LEN], raw[-4:]
        if struct.unpack("<I", crc)[0] != (zlib.crc32(payload) & 0xFFFFFFFF):
            raise CorruptCheckpointError(
                f"{path}: checkpoint content checksum mismatch — the file "
                f"is corrupt (bit rot or a partial overwrite)"
            )
        return payload, True
    return raw, False


class AsyncCheckpointWriter:
    """One background thread that performs whole checkpoint saves —
    device_get + serialize + CRC + fsync + rename — off the step thread.

    ``--ckpt-steps`` at small N used to cost a device_get stall per save
    (the gather drains the dispatch queue and the step loop eats the
    ~100 ms refill, PERF.md); submitting the save here lets the step
    loop keep dispatching while the writer thread blocks on the gather.
    JAX arrays are immutable values, so the enqueued state is a
    consistent snapshot no matter how far the step thread races ahead.

    Guarantees:

    * FIFO — saves land in submission order (one thread, one queue);
    * bounded memory — at most ``max_pending`` snapshots queued
      (``submit`` blocks beyond that: backpressure, not OOM);
    * error surfacing — a failed write re-raises on the NEXT
      ``submit``/``flush``/``close``, never silently;
    * ``flush()`` drains the queue — emergency/preemption saves call it
      first and then write SYNCHRONOUSLY, so the newest-mtime file the
      resume scanner picks is always the true latest position.
    """

    def __init__(self, max_pending: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=max_pending)
        self._lock = OrderedLock("train.ckpt_writer")
        self._exc: Optional[BaseException] = None  # guarded-by: _lock
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dptpu-ckpt-writer"
        )
        self._thread.start()

    def _run(self):
        while True:
            fn = self._q.get()
            try:
                if fn is None:
                    return
                fn()
            except BaseException as e:  # surfaced on the next call-in
                with self._lock:
                    self._exc = e
            finally:
                self._q.task_done()

    def _raise_pending(self):
        with self._lock:
            exc, self._exc = self._exc, None
        if exc is not None:
            raise RuntimeError(
                "async checkpoint write failed (surfacing on the next "
                "checkpoint call — the failed file never replaced a "
                "good one: writes are tmp+rename)"
            ) from exc

    def submit(self, fn) -> None:
        """Enqueue one save closure; blocks when ``max_pending`` saves
        are already in flight (bounded snapshot memory)."""
        self._raise_pending()
        if not self._thread.is_alive():
            raise RuntimeError("AsyncCheckpointWriter is closed")
        self._q.put(fn)

    def pending(self) -> int:
        """Queued-but-unwritten saves (approximate; the observability
        layer publishes this as ``Obs/ckpt_queue_depth`` — a depth that
        sits at ``max_pending`` means the step loop is blocking on
        checkpoint backpressure)."""
        return self._q.qsize()

    def flush(self) -> None:
        """Block until every queued save has hit disk. The wait is
        recorded as a ``ckpt_flush`` span — this is exactly the stall a
        preemption/emergency save pays before its synchronous write."""
        from dptpu import obs

        with obs.get_tracer().span("ckpt_flush"):
            self._q.join()
        self._raise_pending()

    def close(self) -> None:
        """Drain, stop the thread, surface any pending error."""
        if self._thread.is_alive():
            self._q.put(None)
            self._thread.join()
        self._raise_pending()


def save_checkpoint(
    state,
    *,
    epoch: int,
    arch: str,
    best_acc1: float,
    is_best: bool,
    directory: str = ".",
    is_chief: bool = True,
    training_time: Optional[float] = None,
    filename: str = CHECKPOINT_NAME,
    step_in_epoch: int = 0,
    data_position: Optional[int] = None,
    geometry: Optional[tuple] = None,
    sharding: str = "",
) -> Optional[str]:
    """Serialize state; copy to model_best when ``is_best``. Chief-only.

    ``step_in_epoch``/``data_position`` are the mid-epoch resume
    coordinates (dptpu/resilience): batches already consumed from epoch
    ``epoch`` and samples consumed per shard. 0 means an epoch boundary
    (the reference's only save point, imagenet_ddp.py:216-222).

    ``geometry`` is the run's ``(world_size, global_batch, accum)``
    tuple. Saving it lets a mid-epoch ``--resume`` under a CHANGED
    batch geometry fail fast naming both the saved and current tuples
    (the groundwork for elastic resume, ROADMAP item 3b: a remapper
    needs exactly these coordinates) instead of a bare mismatch.

    ``sharding`` is the run's sharding fingerprint —
    ``"<rules-table-hash>:zero<stage>"`` for the rules-driven sharded
    families (dptpu/parallel/rules.py), ``"replicated"`` for the
    replicated steps, ``""`` for contexts with no placement to stamp.
    A mid-epoch ``--resume`` under a CHANGED sharding fails fast naming
    both fingerprints (fit.py) unless DPTPU_ELASTIC opts into
    re-sharding; epoch-boundary resumes re-shard freely (checkpoints
    always hold the gathered full-leaf state, so the stamp is
    provenance, not a storage format).
    """
    if not is_chief:
        return None
    geom = tuple(int(g) for g in geometry) if geometry is not None \
        else (-1, -1, -1)
    payload = {
        "epoch": epoch,
        "arch": arch,
        "best_acc1": float(best_acc1),
        "step": jax.device_get(state.step),
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
        "training_time": -1.0 if training_time is None else float(training_time),
        # attention-storage layout marker: lets a future layout change
        # (like round 4's [q|k|v]-major -> head-major move) detect and
        # migrate old files instead of silently scrambling them
        "qkv_layout": QKV_LAYOUT,
        "step_in_epoch": int(step_in_epoch),
        "data_position": int(
            data_position if data_position is not None else -1
        ),
        "world_size": geom[0],
        "global_batch": geom[1],
        "accum_steps": geom[2],
        "sharding": str(sharding),
    }
    # EVERY checkpoint write goes through the Store abstraction
    # (dptpu/data/store.py): a plain directory routes to LocalStore —
    # whose put_bytes is the exact tmp+flush+fsync+rename+dirent-fsync
    # discipline this function used to inline, bit-for-bit — and a
    # store URL (--ckpt-dir file:///... or http(s)://...) routes to the
    # matching backend with retry/backoff. The CRC footer is sealed
    # into the bytes BEFORE the store sees them, so the verify/fallback
    # contract is backend-independent.
    from dptpu.data.store import open_store

    store = open_store(directory or ".")
    store.put_bytes(filename, seal_payload(serialization.to_bytes(payload)))
    if is_best:
        store.copy(filename, BEST_NAME)
    return store.path_for(filename)


def load_checkpoint(path: str, state, arch: Optional[str] = None,
                    steps_per_epoch: Optional[int] = None):
    """Resume: restore state + bookkeeping from a checkpoint file.

    The reference restores start_epoch/best_acc1/model/optimizer
    (imagenet_ddp.py:138-153). Returns ``(state, meta)`` where meta has
    ``epoch`` (resume start epoch), ``arch``, ``best_acc1``.

    Accepts dptpu's flax-serialized payload OR a reference-produced
    ``torch.save`` checkpoint (detected by failed flax deserialization;
    see module docstring). ``arch`` names the key map for the torch
    path (the checkpoint's own ``arch`` field wins when present);
    ``steps_per_epoch`` rebuilds the global step from the torch
    checkpoint's epoch, which stores no step count.
    """
    from dptpu.data.store import is_store_url, open_store, split_store_url

    if is_store_url(path):
        base, name = split_store_url(path)
        raw = open_store(base).get_bytes(name)
    else:
        with open(path, "rb") as f:
            raw = f.read()
    if not raw:
        raise EmptyCheckpointError(
            f"{path}: checkpoint file is empty (0 bytes) — a crashed or "
            f"power-lost write; resume from an older checkpoint (the "
            f"resilience scanner, dptpu.resilience.find_resumable, does "
            f"this automatically)"
        )
    # dispatch on the file's magic, not on a failed parse: a torch file is
    # a zip (PK..) or legacy pickle (protocol-2 \x80 prefix); anything
    # else goes to flax so a genuinely corrupt/mismatched flax payload
    # surfaces its own precise error instead of an unpickling one (and
    # the torch path never pays for building the flax template)
    if raw[:4] == b"PK\x03\x04" or raw[:2] == b"\x80\x02":
        return _load_torch_checkpoint(path, state, arch, steps_per_epoch,
                                      raw=raw)
    raw, _verified = split_payload(raw, path)
    template = {
        "epoch": 0,
        "arch": "",
        "best_acc1": 0.0,
        "step": jax.device_get(state.step),
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
        "training_time": -1.0,
        "qkv_layout": "",
        "step_in_epoch": 0,
        "data_position": -1,
        "world_size": -1,
        "global_batch": -1,
        "accum_steps": -1,
        "sharding": "",
    }
    # Optional bookkeeping fields, defaulted when absent so every older
    # payload generation parses: pre-round-4 files lack qkv_layout (and
    # get the ViT attention-column migration below), pre-resilience files
    # lack the mid-epoch resume coordinates, pre-hierarchy files lack
    # the (world_size, global_batch, accum) geometry tuple.
    _OPTIONAL = ("qkv_layout", "step_in_epoch", "data_position",
                 "world_size", "global_batch", "accum_steps", "sharding")
    # structural legacy detection, single decode: restore the msgpack
    # tree once (raises its precise error on a corrupt file), pick the
    # template by the payload's own top-level keys, and validate with
    # from_state_dict (from_bytes is exactly restore + from_state_dict).
    restored = serialization.msgpack_restore(raw)
    if not isinstance(restored, dict):
        raise CorruptCheckpointError(
            f"{path}: checkpoint payload is {type(restored).__name__}, "
            "not a dict — corrupt or not a dptpu checkpoint"
        )
    present = {
        k: v for k, v in template.items()
        if k not in _OPTIONAL or k in restored
    }
    payload = serialization.from_state_dict(present, restored)
    for k in _OPTIONAL:
        payload.setdefault(k, template[k])
    params = payload["params"]
    opt_state = payload["opt_state"]
    ckpt_arch = payload["arch"] or arch or ""
    if qkv_needs_migration(ckpt_arch, payload["qkv_layout"]):
        from dptpu.models.pretrained import _qkv_to_head_major

        params = _qkv_to_head_major(ckpt_arch, params)
        opt_state = map_momentum(
            opt_state, lambda t: _qkv_to_head_major(ckpt_arch, t)
        )
    new_state = state.replace(
        step=payload["step"],
        params=params,
        batch_stats=payload["batch_stats"],
        opt_state=opt_state,
    )
    meta = {
        "epoch": int(payload["epoch"]),
        "arch": payload["arch"],
        "best_acc1": float(payload["best_acc1"]),
        "training_time": float(payload["training_time"]),
        "step_in_epoch": int(payload["step_in_epoch"]),
        "data_position": int(payload["data_position"]),
        # (world_size, global_batch, accum) at save time; (-1,-1,-1)
        # for pre-hierarchy files (resume then falls back to the
        # data_position cross-check)
        "geometry": (int(payload["world_size"]),
                     int(payload["global_batch"]),
                     int(payload["accum_steps"])),
        # sharding fingerprint at save time; "" for files from before
        # the rules engine (resume then skips the sharding cross-check)
        "sharding": str(payload["sharding"]),
    }
    return new_state, meta


def _load_torch_checkpoint(path: str, state, arch: Optional[str],
                           steps_per_epoch: Optional[int],
                           raw: Optional[bytes] = None):
    """Resume from the reference's own ``torch.save`` checkpoint
    (imagenet_ddp.py:216-222): ``module.``-prefixed state dict through
    the torchvision key map, SGD momentum buffers onto the optax trace.
    ``raw`` carries already-fetched bytes (store-URL resumes have no
    local file for torch to open)."""
    import io

    import numpy as np
    import torch

    from dptpu.models.pretrained import (
        _from_torch,
        convert_state_dict,
        torch_key_map,
    )

    ckpt = torch.load(
        io.BytesIO(raw) if raw is not None else path,
        map_location="cpu", weights_only=False,
    )
    arch = str(ckpt.get("arch") or arch or "")
    if not arch:
        raise ValueError(
            f"{path}: torch-format checkpoint carries no 'arch' and none "
            "was passed — cannot build the key map"
        )
    raw_sd = ckpt["state_dict"]
    template = {
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
    }
    kmap = torch_key_map(arch, template)
    sd = {}
    # torch parameters() order == state-dict key order restricted to
    # keys the map resolves into the 'params' collection — this excludes
    # EVERY registered buffer generically (BN running stats/bookkeeping,
    # Swin's relative_position_index/attn_mask, ...), not just the BN
    # suffixes, so the param-index mapping below cannot desync on archs
    # with exotic buffers
    param_keys = []
    for k, v in raw_sd.items():
        k = k[len("module."):] if k.startswith("module.") else k
        if k.endswith("num_batches_tracked"):
            continue  # torch BN bookkeeping; no dptpu equivalent
        sd[k] = v.detach().cpu().numpy()
        if k in kmap and kmap[k][0] == "params":
            param_keys.append(k)
    variables = convert_state_dict(arch, sd, template, kmap=kmap)

    # SGD momentum: torch keys state entries by global param index in
    # param_groups order — identical to parameters() order (param_keys)
    opt_sd = ckpt.get("optimizer") or {}
    indices = [
        i for g in opt_sd.get("param_groups", []) for i in g["params"]
    ]
    if indices and len(indices) != len(param_keys):
        # a silent skip here would partially restore momentum after a
        # desync; refuse loudly instead
        raise ValueError(
            f"{path}: torch optimizer tracks {len(indices)} params but "
            f"the key map resolves {len(param_keys)} trainable keys for "
            f"'{arch}' — the param-index mapping would desync, so "
            f"momentum cannot be restored safely"
        )
    torch_state = opt_sd.get("state", {})
    buffers = {}
    for pos, idx in enumerate(indices):
        buf = torch_state.get(idx, {}).get("momentum_buffer")
        if buf is None:
            continue  # torch SGD momentum starts lazily per-param
        collection, names, kind = kmap[param_keys[pos]]
        buffers[names] = _from_torch(
            buf.detach().cpu().numpy(), kind
        ).astype(np.float32)
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        variables["params"]
    )
    trace_leaves = []
    for leaf_path, leaf in flat:
        names = tuple(p.key for p in leaf_path)
        buf = buffers.get(names)
        if buf is not None and buf.shape != leaf.shape:
            raise ValueError(
                f"momentum buffer for {'.'.join(names)}: shape "
                f"{buf.shape} != param {leaf.shape}"
            )
        trace_leaves.append(
            np.zeros_like(leaf) if buf is None else buf
        )
    new_trace = jax.tree_util.tree_unflatten(treedef, trace_leaves)

    epoch = int(ckpt.get("epoch", 0))
    step = jax.device_get(state.step)
    if steps_per_epoch is not None:
        step = np.asarray(epoch * int(steps_per_epoch), dtype=step.dtype)
    new_state = state.replace(
        step=step,
        params=variables["params"],
        batch_stats=variables["batch_stats"],
        opt_state=map_momentum(
            jax.device_get(state.opt_state), lambda _: new_trace
        ),
    )
    meta = {
        "epoch": epoch,
        "arch": arch,
        "best_acc1": float(ckpt.get("best_acc1", 0.0)),
        "training_time": float(ckpt.get("training_time", -1.0)),
        # the reference only saves on epoch boundaries
        "step_in_epoch": 0,
        "data_position": -1,
        "geometry": (-1, -1, -1),
        "sharding": "",
    }
    return new_state, meta
