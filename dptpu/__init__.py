"""dptpu — a TPU-native distributed training framework.

A brand-new JAX/XLA/pjit implementation of the capabilities of the
``Esthesia/distributed-pytorch`` reference suite (ImageNet-1k classification
with torchvision-style CNNs at single-device, single-host multi-chip, and
multi-host pod scale), redesigned TPU-first:

* NCCL/Gloo process groups + DistributedDataParallel's bucketed gradient
  all-reduce (reference imagenet_ddp.py:104-105,127) become SPMD
  ``shard_map``/``pjit`` over a ``jax.sharding.Mesh`` with ``lax.pmean``
  gradients compiled onto ICI/DCN collectives.
* NVIDIA Apex mixed precision (imagenet_ddp_apex.py:169-172) becomes a
  native bf16 compute policy — no loss scaling needed on TPU.
* The CUDA-stream DataPrefetcher (imagenet_ddp_apex.py:304-351) becomes a
  double-buffered host pipeline with async ``device_put`` and on-device
  fused uint8→bf16 normalization.

Subpackages: ``config``, ``models``, ``ops``, ``data``, ``parallel``,
``train``, ``resilience``, ``utils``, ``cli``, ``native``.
"""

__version__ = "0.1.0"
