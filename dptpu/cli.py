"""Console entry points — shared by the repo-root reference-parity scripts
and the installed ``dptpu-*`` commands (pyproject [project.scripts]).

Besides the three reference-parity trainers, the ``dptpu`` multi-command
(``main``) fronts the dptpu-native subsystems; its first subcommand is
``dptpu serve`` — the batched inference engine (dptpu/serve)."""

from dptpu.config import parse_config
from dptpu.train import fit


def _report_preemption(result):
    """A graceful preemption is a SUCCESS (exit 0): the mid-epoch
    checkpoint is on disk and a ``--resume`` run replays the sampler to
    the exact saved position (bit-identical trajectory — see
    dptpu/resilience)."""
    if result.get("preempted"):
        print(
            "preempted: mid-epoch checkpoint saved; rerun with "
            "--resume <run dir> to continue where this run stopped"
        )


def main_ddp(argv=None):
    """imagenet_ddp.py: multi-host data-parallel training."""
    cfg = parse_config(argv, variant="ddp")
    result = fit(cfg)
    if result.get("early_stopped"):
        print(f"early stop: training_time {result['training_time']:.1f}s")
    _report_preemption(result)
    return result


def main_nd(argv=None):
    """nd_imagenet.py: single-device / fallback-everything training."""
    cfg = parse_config(argv, variant="nd")
    result = fit(cfg)
    _report_preemption(result)
    return result


def main_apex(argv=None):
    """imagenet_ddp_apex.py: bf16 mixed-precision training (env:// rendezvous)."""
    cfg = parse_config(argv, variant="apex").replace(dist_url="env://")
    result = fit(cfg)
    _report_preemption(result)
    return result


# Installed-command wrappers (pyproject [project.scripts]): setuptools
# wraps an entry point as ``sys.exit(fn())``, and ``sys.exit(<dict>)``
# exits 1 — which would break the exit-0 contract graceful preemption
# (and every successful run) depends on. The repo-root scripts and tests
# keep calling the result-returning ``main_*`` directly.

def build_serve_parser():
    """``dptpu serve`` flags. Env twins (``DPTPU_SERVE_*``) WIN over
    these when set — the precedence every dptpu knob follows — and BOTH
    sources go through the same ``serve_knobs`` validation, so a typo'd
    value fails fast pre-compile whichever way it arrived."""
    import argparse

    p = argparse.ArgumentParser(
        prog="dptpu serve",
        description="batched inference: AOT bucket compilation + "
                    "continuous dynamic batching (dptpu/serve)",
    )
    p.add_argument("-a", "--arch", default="resnet50", metavar="ARCH",
                   help="registry architecture, or a comma list of "
                        "[name=]arch entries to co-serve several models "
                        "behind one router (e.g. 'resnet50,tiny=resnet18')")
    p.add_argument("--buckets", default=None, metavar="N,N,...",
                   help="AOT batch-size bucket ladder (default 1,4,16,64; "
                        "env DPTPU_SERVE_BUCKETS)")
    p.add_argument("--max-delay-ms", type=float, default=None,
                   help="batcher coalescing budget (default 5.0; env "
                        "DPTPU_SERVE_MAX_DELAY_MS)")
    p.add_argument("--placement", default=None,
                   help="auto | replicated | tp (default auto; env "
                        "DPTPU_SERVE_PLACEMENT)")
    p.add_argument("--slots", type=int, default=None,
                   help="staging-ring depth (default 4; env "
                        "DPTPU_SERVE_SLOTS)")
    p.add_argument("--queue-depth", type=int, default=None,
                   help="admission bound: max admitted-but-unanswered "
                        "requests per model (default 64; env "
                        "DPTPU_SERVE_QUEUE_DEPTH)")
    p.add_argument("--priorities", default=None, metavar="H,N,L",
                   help="shed water marks as fractions of the queue "
                        "depth, high,normal,low (default 1.0,0.85,0.6; "
                        "env DPTPU_SERVE_PRIORITIES)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline, 0 = none "
                        "(default 0; env DPTPU_SERVE_DEADLINE_MS)")
    p.add_argument("--canary-fraction", type=float, default=None,
                   help="traffic fraction routed to a staged canary "
                        "generation (default 0.1; env "
                        "DPTPU_SERVE_CANARY_FRACTION)")
    p.add_argument("--canary-drift", type=float, default=None,
                   help="max|dlogit| vs baseline before auto-rollback "
                        "(default 50.0; env DPTPU_SERVE_CANARY_DRIFT)")
    p.add_argument("--canary-lat-factor", type=float, default=None,
                   help="canary/baseline batch-latency multiple before "
                        "auto-rollback (default 5.0; env "
                        "DPTPU_SERVE_CANARY_LAT_FACTOR)")
    p.add_argument("--precision", default=None,
                   help="serve precision: fp32 | bf16 | int8 (default "
                        "fp32; below fp32 needs --calib and deploys "
                        "through the canary drift gate; env "
                        "DPTPU_QUANT_PRECISION)")
    p.add_argument("--calib", default=None, metavar="PATH",
                   help="calibration artifact from `dptpu quantize` "
                        "(required for --precision bf16/int8; env "
                        "DPTPU_QUANT_CALIB)")
    p.add_argument("--quant-drift", type=float, default=None,
                   help="override the quantized rollout's max|dlogit| "
                        "gate (default 0 = the artifact's bound; env "
                        "DPTPU_QUANT_DRIFT)")
    p.add_argument("--quant-top1-min", type=float, default=None,
                   help="override the quantized rollout's top-1 "
                        "agreement floor (default 0 = the artifact's "
                        "bound; env DPTPU_QUANT_TOP1_MIN)")
    p.add_argument("--fleet", action="store_true",
                   help="run the FLEET FRONT instead of a local engine: "
                        "route requests over the serving hosts "
                        "registered in --fleet-dir (members are plain "
                        "`dptpu serve --fleet-dir ...` processes)")
    p.add_argument("--fleet-dir", default=None, metavar="DIR",
                   help="shared fleet membership directory (quorum KV); "
                        "setting it on a serving host registers that "
                        "host in the fleet (env DPTPU_FLEET_DIR)")
    p.add_argument("--fleet-heartbeat-s", type=float, default=None,
                   help="fleet member heartbeat period (default 1.0; "
                        "env DPTPU_FLEET_HEARTBEAT_S)")
    p.add_argument("--fleet-deadline-s", type=float, default=None,
                   help="heartbeat staleness before a member is "
                        "auto-drained from routing (default 3.0; env "
                        "DPTPU_FLEET_DEADLINE_S)")
    p.add_argument("--fleet-retries", type=int, default=None,
                   help="failover retries when a member connection "
                        "dies mid-request (default 2; env "
                        "DPTPU_FLEET_RETRIES)")
    p.add_argument("--pretrained", action="store_true",
                   help="load converted torchvision weights "
                        "($DPTPU_PRETRAINED_DIR/<arch>.npz)")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--selftest", type=int, default=0, metavar="N",
                   help="serve N synthetic requests through the full "
                        "bytes->batcher->engine path and exit (no "
                        "listener) — the smoke/readiness mode")
    return p


def parse_model_specs(raw: str):
    """``[name=]arch[,...]`` -> ordered (name, arch) pairs; the first
    entry is the router's default route. A bare arch names itself, so
    co-serving the same arch twice needs explicit names."""
    from dptpu.models import model_names

    pairs = []
    for spec in str(raw).split(","):
        spec = spec.strip()
        if not spec:
            continue
        name, _, arch = spec.rpartition("=")
        name = name or arch
        if arch not in model_names():
            raise ValueError(
                f"--arch={arch!r} is not a registry architecture "
                f"(e.g. {', '.join(model_names()[:4])}, ...; full list: "
                f"python -c 'from dptpu.models import model_names; "
                f"print(model_names())')"
            )
        if name in (n for n, _ in pairs):
            raise ValueError(
                f"--arch names model {name!r} twice (use name=arch to "
                f"co-serve one arch under distinct names)"
            )
        pairs.append((name, arch))
    if not pairs:
        raise ValueError("--arch needs at least one [name=]arch entry")
    return pairs


def serve_args_to_knobs(args):
    """CLI namespace -> validated ServeKnobs + arch check (the fail-fast
    moment: every bad knob OR unknown name raises BEFORE any compile)."""
    from dptpu.serve import serve_knobs

    knobs = serve_knobs(
        buckets=args.buckets, max_delay_ms=args.max_delay_ms,
        placement=args.placement, slots=args.slots,
        queue_depth=args.queue_depth, priorities=args.priorities,
        deadline_ms=args.deadline_ms,
        canary_fraction=args.canary_fraction,
        canary_drift=args.canary_drift,
        canary_lat_factor=args.canary_lat_factor,
        precision=args.precision, calib=args.calib,
        quant_drift=args.quant_drift,
        quant_top1_min=args.quant_top1_min,
        fleet_dir=args.fleet_dir,
        fleet_heartbeat_s=args.fleet_heartbeat_s,
        fleet_deadline_s=args.fleet_deadline_s,
        fleet_retries=args.fleet_retries,
    )
    parse_model_specs(args.arch)
    return knobs


def main_serve(argv=None):
    """``dptpu serve``: load the model(s), AOT-compile each bucket
    ladder, and serve — over HTTP, or ``--selftest N`` synthetic
    requests. ``--fleet`` skips the local engine entirely and runs the
    fleet ROUTING TIER over the hosts registered in the fleet dir."""
    args = build_serve_parser().parse_args(argv)

    from dptpu.tune.artifact import apply_tuning, tune_knobs

    # the offline tuning artifact applies BEFORE knob resolution so
    # serve_knobs sees the tuned ladder — and only for knobs nothing
    # else set: env twins and explicit CLI flags always win (ISSUE 19)
    tune_conf = tune_knobs()
    if tune_conf["artifact"]:
        cli_set = set()
        if args.buckets is not None:
            cli_set.add("DPTPU_SERVE_BUCKETS")  # explicit --buckets wins
        apply_tuning(tune_conf["artifact"], cli_set=cli_set)
    knobs = serve_args_to_knobs(args)  # fail fast, pre-jax-compile

    if args.fleet:
        return _serve_fleet_front(args, knobs)
    specs = parse_model_specs(args.arch)

    from dptpu.serve import ModelRouter, build_served_model

    router = ModelRouter([
        build_served_model(
            name, arch, knobs, num_classes=args.num_classes,
            image_size=args.image_size, pretrained=args.pretrained,
            verbose=True,
        )
        for name, arch in specs
    ])
    if "serve_ladder" in tune_conf["control"]:
        from dptpu.tune.controller import (
            Controller,
            serve_ladder_actuator,
        )

        # one controller per model, ticked on that model's dispatch
        # thread between batches: sustained padding waste densifies the
        # ladder's widest gap (compile-before-publish, bounded budget)
        for name, m in router.models.items():
            m.batcher.attach_controller(Controller([
                serve_ladder_actuator(
                    m.engine, m.batcher,
                    interval_s=tune_conf["interval_s"],
                ),
            ]))
        print(f"=> tune control armed: serve_ladder on "
              f"{', '.join(router.models)} (interval "
              f"{tune_conf['interval_s']:g}s; disarm with "
              f"DPTPU_TUNE_CONTROL=off)")
    member = None
    try:
        if knobs.precision != "fp32":
            for name in router.models:
                gen = router.start_quantized(knobs, name)
                print(f"=> serve: staged {knobs.precision} generation "
                      f"{gen} for {name!r} behind the canary drift gate "
                      f"({knobs.calib})")
        if args.selftest:
            return _serve_selftest(router, args.selftest)
        if knobs.fleet_dir:
            from dptpu.serve.fleet import FleetMember

            member = FleetMember(
                knobs.fleet_dir, host=args.host, port=args.port,
                heartbeat_s=knobs.fleet_heartbeat_s,
            )
            print(f"=> serve: registered fleet member "
                  f"{member.member_id!r} in {knobs.fleet_dir}")
        print(
            f"=> dptpu serve: "
            f"{', '.join(f'{n} ({a})' for n, a in specs)} (buckets "
            f"{list(knobs.buckets)}) on http://{args.host}:{args.port} "
            f"— POST /predict[/<model>], GET /healthz, GET /readyz, "
            f"GET /metrics"
        )
        from dptpu.serve.http import serve_forever

        serve_forever(router, args.host, args.port)
        return {
            name: m.batcher.stats()["completed"]
            for name, m in router.models.items()
        }
    finally:
        if member is not None:
            member.close()
        router.close()


def _serve_fleet_front(args, knobs):
    """The ``--fleet`` routing tier: no local engine — requests fan out
    over the registered member hosts, a stale heartbeat auto-drains a
    member, and the PR-17 admission layer fronts the whole fleet."""
    if not knobs.fleet_dir:
        raise SystemExit(
            "--fleet needs the membership directory: set "
            "DPTPU_FLEET_DIR/--fleet-dir to the shared quorum-KV path "
            "the serving hosts register in"
        )
    from dptpu.serve.fleet import FleetRouter, serve_fleet_forever

    fleet = FleetRouter(
        knobs.fleet_dir, deadline_s=knobs.fleet_deadline_s,
        poll_s=knobs.fleet_heartbeat_s, retries=knobs.fleet_retries,
        queue_depth=knobs.queue_depth, priorities=knobs.priorities,
        deadline_ms=knobs.deadline_ms,
    )
    try:
        print(
            f"=> dptpu serve --fleet: routing over {knobs.fleet_dir} "
            f"on http://{args.host}:{args.port} (drain after "
            f"{knobs.fleet_deadline_s}s heartbeat silence, "
            f"{knobs.fleet_retries} failover retries)"
        )
        serve_fleet_forever(fleet, args.host, args.port)
        return fleet.stats()
    finally:
        fleet.close()


def _serve_selftest(router, n: int):
    """Readiness probe: N JPEG-encoded synthetic requests per model
    through the full admission -> bytes -> preprocess -> staging ->
    bucket -> logits path."""
    import io

    import numpy as np
    from PIL import Image

    out = {}
    for name, m in router.models.items():
        rng = np.random.RandomState(0)
        size = m.engine.image_size
        # keep outstanding work under the admission water mark: the
        # selftest proves the path, it must not shed itself
        window = max(1, m.admission.thresholds["normal"] // 2)
        futs = []
        for _ in range(n):
            buf = io.BytesIO()
            Image.fromarray(
                rng.randint(0, 256, (size, size, 3), dtype=np.uint8)
            ).save(buf, format="JPEG")
            if len(futs) >= window:
                futs.pop(0).result(timeout=120.0)
            futs.append(router.submit(data=buf.getvalue(), model=name))
        for f in futs:
            f.result(timeout=120.0)
        stats = m.batcher.stats()
        print(
            f"serve selftest [{name}]: {stats['completed']} ok, "
            f"{stats['failed']} failed, p50 "
            f"{stats['latency_ms']['p50']:.1f}ms p99 "
            f"{stats['latency_ms']['p99']:.1f}ms, buckets "
            f"{stats['bucket_counts']}"
        )
        out[name] = stats
    return out if len(out) > 1 else next(iter(out.values()))


def build_quantize_parser():
    """``dptpu quantize`` flags: offline post-training calibration of a
    serve model into a CRC-sealed artifact (dptpu/serve/quant.py)."""
    import argparse

    p = argparse.ArgumentParser(
        prog="dptpu quantize",
        description="calibrate per-channel int8 scales for a serve "
                    "model from a shard sample and commit them as a "
                    "provenance-stamped, CRC-sealed calibration "
                    "artifact (the only key that unlocks sub-fp32 "
                    "serving)",
    )
    p.add_argument("-a", "--arch", default="resnet50", metavar="ARCH",
                   help="registry architecture to calibrate")
    p.add_argument("-o", "--out", required=True, metavar="PATH",
                   help="calibration artifact output path")
    p.add_argument("--pretrained", action="store_true",
                   help="calibrate the converted torchvision weights "
                        "($DPTPU_PRETRAINED_DIR/<arch>.npz)")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--data", default=None, metavar="DIR",
                   help="packed-shard or ImageFolder directory to draw "
                        "the calibration sample from (default: "
                        "deterministic synthetic sample)")
    p.add_argument("--sample", type=int, default=64, metavar="N",
                   help="calibration sample size (default 64)")
    p.add_argument("--drift-bound", type=float, default=None,
                   help="max|dlogit| bound to stamp into the artifact "
                        "(default: measured max drift x 2 margin)")
    p.add_argument("--top1-min", type=float, default=None,
                   help="top-1 agreement floor to stamp into the "
                        "artifact (default: measured agreement less a "
                        "0.05 margin, floored at 0.5)")
    return p


def main_quantize(argv=None):
    """``dptpu quantize``: build the fp32 model, quantize, replay the
    calibration sample through BOTH forwards, and seal scales + the
    measured drift gate bounds into the artifact."""
    import numpy as np

    args = build_quantize_parser().parse_args(argv)
    if args.sample < 1:
        raise SystemExit(f"--sample {args.sample} must be >= 1")
    if args.arch is not None:
        parse_model_specs(args.arch.split(",")[0])

    from dptpu.serve.engine import ServeEngine
    from dptpu.serve.quant import (
        DRIFT_MARGIN,
        measure_drift,
        quantize_variables,
        save_calibration,
    )

    # one fp32 engine, replicated (quantized serving is replicated-only)
    bucket = max(2, min(16, args.sample))
    engine = ServeEngine(
        args.arch, buckets=(bucket,), placement="replicated",
        num_classes=args.num_classes, image_size=args.image_size,
        pretrained=args.pretrained, verbose=True,
    )
    sample = _calibration_sample(
        args.data, args.sample, args.image_size
    )

    params = engine._host_variables["params"]
    gen_q = engine.stage_weights(
        quantize_variables(engine._host_variables, "int8"),
        precision="int8",
    )
    try:
        base_parts, q_parts = [], []
        for i in range(0, len(sample), bucket):
            chunk = sample[i:i + bucket]
            n = len(chunk)
            if n < bucket:
                pad = np.broadcast_to(
                    chunk[0], (bucket - n,) + chunk.shape[1:]
                )
                chunk = np.concatenate([chunk, pad], axis=0)
            base_parts.append(engine.run_bucket(bucket, chunk, n))
            q_parts.append(engine.run_bucket(bucket, chunk, n, gen=gen_q))
        base = np.concatenate(base_parts, axis=0)
        quant = np.concatenate(q_parts, axis=0)
    finally:
        engine.discard_staged(gen_q)
    agree, drift = measure_drift(base, quant)

    drift_bound = (args.drift_bound if args.drift_bound is not None
                   else max(drift * DRIFT_MARGIN, 1e-3))
    top1_min = (args.top1_min if args.top1_min is not None
                else max(0.5, agree - 0.05))
    payload = save_calibration(
        args.out, arch=args.arch, params=params,
        stats={"top1_agreement": agree, "max_abs_dlogit": drift},
        bounds={"max_abs_dlogit": drift_bound,
                "min_top1_agreement": top1_min},
        num_classes=args.num_classes, image_size=args.image_size,
        sample_n=len(sample),
    )
    meta = payload["meta"]
    print(
        f"=> dptpu quantize: {args.arch} -> {args.out} "
        f"(weights {meta['weights_fingerprint']}, sample "
        f"{len(sample)}: top-1 agreement {agree:.3f}, max|dlogit| "
        f"{drift:.3g}; gate bounds: agreement >= {top1_min:.3f}, "
        f"drift <= {drift_bound:.3g})"
    )
    return meta


def _calibration_sample(data, n: int, image_size: int):
    """uint8 NHWC calibration batch: decoded val-pipeline rows from a
    packed-shard/ImageFolder dir when given, else a deterministic
    synthetic sample (load-test engines are random-init anyway — what
    matters is that serve-time traffic statistics see the SAME scales
    the gate bounds were measured with)."""
    import numpy as np

    if data is None:
        rng = np.random.RandomState(0)
        return rng.randint(
            0, 256, (n, image_size, image_size, 3), np.uint8
        )
    from dptpu.serve.preprocess import preprocess_bytes

    rows = []
    for path in _iter_image_files(data):
        with open(path, "rb") as f:
            try:
                rows.append(preprocess_bytes(f.read(), size=image_size))
            except ValueError:
                continue  # non-image file in the tree
        if len(rows) >= n:
            break
    if not rows:
        raise SystemExit(
            f"--data {data}: no decodable images found for the "
            f"calibration sample"
        )
    return np.stack(rows, axis=0)


def _iter_image_files(root):
    import os

    for dirpath, _, files in sorted(os.walk(root)):
        for f in sorted(files):
            yield os.path.join(dirpath, f)


def build_pack_parser():
    """``dptpu pack`` flags: ImageFolder tree → packed sequential
    shards (dptpu/data/shards.py). Deterministic: the same tree always
    packs to byte-identical shards."""
    import argparse

    p = argparse.ArgumentParser(
        prog="dptpu pack",
        description="pack an ImageFolder tree into CRC-sealed "
                    "sequential shards (+ manifest) that the streaming "
                    "data plane reads locally (O_DIRECT byte ring) or "
                    "over a store URL (HTTP range fetch)",
    )
    p.add_argument("src", metavar="SRC",
                   help="ImageFolder root — either one split "
                        "(class dirs directly inside) or a tree with "
                        "train/ and val/ splits (both are packed)")
    p.add_argument("dest", metavar="DEST",
                   help="output directory (split layout is mirrored)")
    p.add_argument("--shards", type=int, default=8, metavar="N",
                   help="shards per split (default 8)")
    p.add_argument("--verify", action="store_true",
                   help="deep-verify every written shard (header, "
                        "index and every sample extent CRC)")
    return p


def main_pack(argv=None):
    """``dptpu pack``: convert an ImageFolder tree into packed shards."""
    import os

    from dptpu.data.shards import verify_shard, write_shards

    args = build_pack_parser().parse_args(argv)
    if args.shards < 1:
        raise SystemExit(f"--shards {args.shards} must be >= 1")
    splits = [
        s for s in ("train", "val")
        if os.path.isdir(os.path.join(args.src, s))
    ]
    pairs = (
        [(os.path.join(args.src, s), os.path.join(args.dest, s))
         for s in splits]
        if splits else [(args.src, args.dest)]
    )
    out = {}
    for src, dest in pairs:
        print(f"=> packing {src} -> {dest} ({args.shards} shards)")
        manifest = write_shards(src, dest, args.shards, verbose=True)
        if args.verify:
            for s in manifest["shards"]:
                ok, reason = verify_shard(
                    os.path.join(dest, s["name"]), deep=True
                )
                if not ok:
                    raise SystemExit(f"verify failed: {reason}")
            print(f"   verified {len(manifest['shards'])} shards deep")
        out[dest] = manifest
    return out


def main(argv=None):
    """The ``dptpu`` multi-command: ``dptpu serve|pack|check [...]``."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: dptpu <subcommand> [args]\n\nsubcommands:\n"
              "  serve     batched inference engine (dptpu/serve)\n"
              "  quantize  offline int8 calibration -> CRC-sealed "
              "artifact (dptpu/serve/quant.py)\n"
              "  pack      ImageFolder -> packed sequential shards "
              "(dptpu/data/shards.py)\n"
              "  check     repo-invariant static analysis: AST lints + "
              "HLO budget gates (dptpu/analysis)\n"
              "  tune      offline knob autotuner -> CRC-sealed "
              "TUNING.json artifact (dptpu/tune)")
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "serve":
        return main_serve(rest)
    if cmd == "quantize":
        return main_quantize(rest)
    if cmd == "pack":
        return main_pack(rest)
    if cmd == "check":
        from dptpu.analysis.cli import main_check

        return main_check(rest)
    if cmd == "tune":
        from dptpu.tune.cli import main_tune

        return main_tune(rest)
    raise SystemExit(
        f"dptpu: unknown subcommand {cmd!r} "
        f"(available: serve, quantize, pack, check, tune)"
    )


def console_main(argv=None) -> int:
    out = main(argv)
    return out if isinstance(out, int) else 0


def console_ddp(argv=None) -> int:
    main_ddp(argv)
    return 0


def console_nd(argv=None) -> int:
    main_nd(argv)
    return 0


def console_apex(argv=None) -> int:
    main_apex(argv)
    return 0
