"""Console entry points — shared by the repo-root reference-parity scripts
and the installed ``dptpu-*`` commands (pyproject [project.scripts])."""

from dptpu.config import parse_config
from dptpu.train import fit


def main_ddp(argv=None):
    """imagenet_ddp.py: multi-host data-parallel training."""
    cfg = parse_config(argv, variant="ddp")
    result = fit(cfg)
    if result.get("early_stopped"):
        print(f"early stop: training_time {result['training_time']:.1f}s")
    return result


def main_nd(argv=None):
    """nd_imagenet.py: single-device / fallback-everything training."""
    cfg = parse_config(argv, variant="nd")
    return fit(cfg)


def main_apex(argv=None):
    """imagenet_ddp_apex.py: bf16 mixed-precision training (env:// rendezvous)."""
    cfg = parse_config(argv, variant="apex").replace(dist_url="env://")
    return fit(cfg)
