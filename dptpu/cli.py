"""Console entry points — shared by the repo-root reference-parity scripts
and the installed ``dptpu-*`` commands (pyproject [project.scripts]).

Besides the three reference-parity trainers, the ``dptpu`` multi-command
(``main``) fronts the dptpu-native subsystems; its first subcommand is
``dptpu serve`` — the batched inference engine (dptpu/serve)."""

from dptpu.config import parse_config
from dptpu.train import fit


def _report_preemption(result):
    """A graceful preemption is a SUCCESS (exit 0): the mid-epoch
    checkpoint is on disk and a ``--resume`` run replays the sampler to
    the exact saved position (bit-identical trajectory — see
    dptpu/resilience)."""
    if result.get("preempted"):
        print(
            "preempted: mid-epoch checkpoint saved; rerun with "
            "--resume <run dir> to continue where this run stopped"
        )


def main_ddp(argv=None):
    """imagenet_ddp.py: multi-host data-parallel training."""
    cfg = parse_config(argv, variant="ddp")
    result = fit(cfg)
    if result.get("early_stopped"):
        print(f"early stop: training_time {result['training_time']:.1f}s")
    _report_preemption(result)
    return result


def main_nd(argv=None):
    """nd_imagenet.py: single-device / fallback-everything training."""
    cfg = parse_config(argv, variant="nd")
    result = fit(cfg)
    _report_preemption(result)
    return result


def main_apex(argv=None):
    """imagenet_ddp_apex.py: bf16 mixed-precision training (env:// rendezvous)."""
    cfg = parse_config(argv, variant="apex").replace(dist_url="env://")
    result = fit(cfg)
    _report_preemption(result)
    return result


# Installed-command wrappers (pyproject [project.scripts]): setuptools
# wraps an entry point as ``sys.exit(fn())``, and ``sys.exit(<dict>)``
# exits 1 — which would break the exit-0 contract graceful preemption
# (and every successful run) depends on. The repo-root scripts and tests
# keep calling the result-returning ``main_*`` directly.

def build_serve_parser():
    """``dptpu serve`` flags. Env twins (``DPTPU_SERVE_*``) WIN over
    these when set — the precedence every dptpu knob follows — and BOTH
    sources go through the same ``serve_knobs`` validation, so a typo'd
    value fails fast pre-compile whichever way it arrived."""
    import argparse

    p = argparse.ArgumentParser(
        prog="dptpu serve",
        description="batched inference: AOT bucket compilation + "
                    "continuous dynamic batching (dptpu/serve)",
    )
    p.add_argument("-a", "--arch", default="resnet50", metavar="ARCH",
                   help="registry architecture, or a comma list of "
                        "[name=]arch entries to co-serve several models "
                        "behind one router (e.g. 'resnet50,tiny=resnet18')")
    p.add_argument("--buckets", default=None, metavar="N,N,...",
                   help="AOT batch-size bucket ladder (default 1,4,16,64; "
                        "env DPTPU_SERVE_BUCKETS)")
    p.add_argument("--max-delay-ms", type=float, default=None,
                   help="batcher coalescing budget (default 5.0; env "
                        "DPTPU_SERVE_MAX_DELAY_MS)")
    p.add_argument("--placement", default=None,
                   help="auto | replicated | tp (default auto; env "
                        "DPTPU_SERVE_PLACEMENT)")
    p.add_argument("--slots", type=int, default=None,
                   help="staging-ring depth (default 4; env "
                        "DPTPU_SERVE_SLOTS)")
    p.add_argument("--queue-depth", type=int, default=None,
                   help="admission bound: max admitted-but-unanswered "
                        "requests per model (default 64; env "
                        "DPTPU_SERVE_QUEUE_DEPTH)")
    p.add_argument("--priorities", default=None, metavar="H,N,L",
                   help="shed water marks as fractions of the queue "
                        "depth, high,normal,low (default 1.0,0.85,0.6; "
                        "env DPTPU_SERVE_PRIORITIES)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline, 0 = none "
                        "(default 0; env DPTPU_SERVE_DEADLINE_MS)")
    p.add_argument("--canary-fraction", type=float, default=None,
                   help="traffic fraction routed to a staged canary "
                        "generation (default 0.1; env "
                        "DPTPU_SERVE_CANARY_FRACTION)")
    p.add_argument("--canary-drift", type=float, default=None,
                   help="max|dlogit| vs baseline before auto-rollback "
                        "(default 50.0; env DPTPU_SERVE_CANARY_DRIFT)")
    p.add_argument("--canary-lat-factor", type=float, default=None,
                   help="canary/baseline batch-latency multiple before "
                        "auto-rollback (default 5.0; env "
                        "DPTPU_SERVE_CANARY_LAT_FACTOR)")
    p.add_argument("--pretrained", action="store_true",
                   help="load converted torchvision weights "
                        "($DPTPU_PRETRAINED_DIR/<arch>.npz)")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--selftest", type=int, default=0, metavar="N",
                   help="serve N synthetic requests through the full "
                        "bytes->batcher->engine path and exit (no "
                        "listener) — the smoke/readiness mode")
    return p


def parse_model_specs(raw: str):
    """``[name=]arch[,...]`` -> ordered (name, arch) pairs; the first
    entry is the router's default route. A bare arch names itself, so
    co-serving the same arch twice needs explicit names."""
    from dptpu.models import model_names

    pairs = []
    for spec in str(raw).split(","):
        spec = spec.strip()
        if not spec:
            continue
        name, _, arch = spec.rpartition("=")
        name = name or arch
        if arch not in model_names():
            raise ValueError(
                f"--arch={arch!r} is not a registry architecture "
                f"(e.g. {', '.join(model_names()[:4])}, ...; full list: "
                f"python -c 'from dptpu.models import model_names; "
                f"print(model_names())')"
            )
        if name in (n for n, _ in pairs):
            raise ValueError(
                f"--arch names model {name!r} twice (use name=arch to "
                f"co-serve one arch under distinct names)"
            )
        pairs.append((name, arch))
    if not pairs:
        raise ValueError("--arch needs at least one [name=]arch entry")
    return pairs


def serve_args_to_knobs(args):
    """CLI namespace -> validated ServeKnobs + arch check (the fail-fast
    moment: every bad knob OR unknown name raises BEFORE any compile)."""
    from dptpu.serve import serve_knobs

    knobs = serve_knobs(
        buckets=args.buckets, max_delay_ms=args.max_delay_ms,
        placement=args.placement, slots=args.slots,
        queue_depth=args.queue_depth, priorities=args.priorities,
        deadline_ms=args.deadline_ms,
        canary_fraction=args.canary_fraction,
        canary_drift=args.canary_drift,
        canary_lat_factor=args.canary_lat_factor,
    )
    parse_model_specs(args.arch)
    return knobs


def main_serve(argv=None):
    """``dptpu serve``: load the model(s), AOT-compile each bucket
    ladder, and serve — over HTTP, or ``--selftest N`` synthetic
    requests."""
    args = build_serve_parser().parse_args(argv)
    knobs = serve_args_to_knobs(args)  # fail fast, pre-jax-compile
    specs = parse_model_specs(args.arch)

    from dptpu.serve import ModelRouter, build_served_model

    router = ModelRouter([
        build_served_model(
            name, arch, knobs, num_classes=args.num_classes,
            image_size=args.image_size, pretrained=args.pretrained,
            verbose=True,
        )
        for name, arch in specs
    ])
    try:
        if args.selftest:
            return _serve_selftest(router, args.selftest)
        print(
            f"=> dptpu serve: "
            f"{', '.join(f'{n} ({a})' for n, a in specs)} (buckets "
            f"{list(knobs.buckets)}) on http://{args.host}:{args.port} "
            f"— POST /predict[/<model>], GET /healthz, GET /readyz, "
            f"GET /metrics"
        )
        from dptpu.serve.http import serve_forever

        serve_forever(router, args.host, args.port)
        return {
            name: m.batcher.stats()["completed"]
            for name, m in router.models.items()
        }
    finally:
        router.close()


def _serve_selftest(router, n: int):
    """Readiness probe: N JPEG-encoded synthetic requests per model
    through the full admission -> bytes -> preprocess -> staging ->
    bucket -> logits path."""
    import io

    import numpy as np
    from PIL import Image

    out = {}
    for name, m in router.models.items():
        rng = np.random.RandomState(0)
        size = m.engine.image_size
        # keep outstanding work under the admission water mark: the
        # selftest proves the path, it must not shed itself
        window = max(1, m.admission.thresholds["normal"] // 2)
        futs = []
        for _ in range(n):
            buf = io.BytesIO()
            Image.fromarray(
                rng.randint(0, 256, (size, size, 3), dtype=np.uint8)
            ).save(buf, format="JPEG")
            if len(futs) >= window:
                futs.pop(0).result(timeout=120.0)
            futs.append(router.submit(data=buf.getvalue(), model=name))
        for f in futs:
            f.result(timeout=120.0)
        stats = m.batcher.stats()
        print(
            f"serve selftest [{name}]: {stats['completed']} ok, "
            f"{stats['failed']} failed, p50 "
            f"{stats['latency_ms']['p50']:.1f}ms p99 "
            f"{stats['latency_ms']['p99']:.1f}ms, buckets "
            f"{stats['bucket_counts']}"
        )
        out[name] = stats
    return out if len(out) > 1 else next(iter(out.values()))


def build_pack_parser():
    """``dptpu pack`` flags: ImageFolder tree → packed sequential
    shards (dptpu/data/shards.py). Deterministic: the same tree always
    packs to byte-identical shards."""
    import argparse

    p = argparse.ArgumentParser(
        prog="dptpu pack",
        description="pack an ImageFolder tree into CRC-sealed "
                    "sequential shards (+ manifest) that the streaming "
                    "data plane reads locally (O_DIRECT byte ring) or "
                    "over a store URL (HTTP range fetch)",
    )
    p.add_argument("src", metavar="SRC",
                   help="ImageFolder root — either one split "
                        "(class dirs directly inside) or a tree with "
                        "train/ and val/ splits (both are packed)")
    p.add_argument("dest", metavar="DEST",
                   help="output directory (split layout is mirrored)")
    p.add_argument("--shards", type=int, default=8, metavar="N",
                   help="shards per split (default 8)")
    p.add_argument("--verify", action="store_true",
                   help="deep-verify every written shard (header, "
                        "index and every sample extent CRC)")
    return p


def main_pack(argv=None):
    """``dptpu pack``: convert an ImageFolder tree into packed shards."""
    import os

    from dptpu.data.shards import verify_shard, write_shards

    args = build_pack_parser().parse_args(argv)
    if args.shards < 1:
        raise SystemExit(f"--shards {args.shards} must be >= 1")
    splits = [
        s for s in ("train", "val")
        if os.path.isdir(os.path.join(args.src, s))
    ]
    pairs = (
        [(os.path.join(args.src, s), os.path.join(args.dest, s))
         for s in splits]
        if splits else [(args.src, args.dest)]
    )
    out = {}
    for src, dest in pairs:
        print(f"=> packing {src} -> {dest} ({args.shards} shards)")
        manifest = write_shards(src, dest, args.shards, verbose=True)
        if args.verify:
            for s in manifest["shards"]:
                ok, reason = verify_shard(
                    os.path.join(dest, s["name"]), deep=True
                )
                if not ok:
                    raise SystemExit(f"verify failed: {reason}")
            print(f"   verified {len(manifest['shards'])} shards deep")
        out[dest] = manifest
    return out


def main(argv=None):
    """The ``dptpu`` multi-command: ``dptpu serve|pack|check [...]``."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: dptpu <subcommand> [args]\n\nsubcommands:\n"
              "  serve   batched inference engine (dptpu/serve)\n"
              "  pack    ImageFolder -> packed sequential shards "
              "(dptpu/data/shards.py)\n"
              "  check   repo-invariant static analysis: AST lints + "
              "HLO budget gates (dptpu/analysis)")
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "serve":
        return main_serve(rest)
    if cmd == "pack":
        return main_pack(rest)
    if cmd == "check":
        from dptpu.analysis.cli import main_check

        return main_check(rest)
    raise SystemExit(
        f"dptpu: unknown subcommand {cmd!r} "
        f"(available: serve, pack, check)"
    )


def console_main(argv=None) -> int:
    out = main(argv)
    return out if isinstance(out, int) else 0


def console_ddp(argv=None) -> int:
    main_ddp(argv)
    return 0


def console_nd(argv=None) -> int:
    main_nd(argv)
    return 0


def console_apex(argv=None) -> int:
    main_apex(argv)
    return 0
