"""Console entry points — shared by the repo-root reference-parity scripts
and the installed ``dptpu-*`` commands (pyproject [project.scripts])."""

from dptpu.config import parse_config
from dptpu.train import fit


def _report_preemption(result):
    """A graceful preemption is a SUCCESS (exit 0): the mid-epoch
    checkpoint is on disk and a ``--resume`` run replays the sampler to
    the exact saved position (bit-identical trajectory — see
    dptpu/resilience)."""
    if result.get("preempted"):
        print(
            "preempted: mid-epoch checkpoint saved; rerun with "
            "--resume <run dir> to continue where this run stopped"
        )


def main_ddp(argv=None):
    """imagenet_ddp.py: multi-host data-parallel training."""
    cfg = parse_config(argv, variant="ddp")
    result = fit(cfg)
    if result.get("early_stopped"):
        print(f"early stop: training_time {result['training_time']:.1f}s")
    _report_preemption(result)
    return result


def main_nd(argv=None):
    """nd_imagenet.py: single-device / fallback-everything training."""
    cfg = parse_config(argv, variant="nd")
    result = fit(cfg)
    _report_preemption(result)
    return result


def main_apex(argv=None):
    """imagenet_ddp_apex.py: bf16 mixed-precision training (env:// rendezvous)."""
    cfg = parse_config(argv, variant="apex").replace(dist_url="env://")
    result = fit(cfg)
    _report_preemption(result)
    return result


# Installed-command wrappers (pyproject [project.scripts]): setuptools
# wraps an entry point as ``sys.exit(fn())``, and ``sys.exit(<dict>)``
# exits 1 — which would break the exit-0 contract graceful preemption
# (and every successful run) depends on. The repo-root scripts and tests
# keep calling the result-returning ``main_*`` directly.

def console_ddp(argv=None) -> int:
    main_ddp(argv)
    return 0


def console_nd(argv=None) -> int:
    main_nd(argv)
    return 0


def console_apex(argv=None) -> int:
    main_apex(argv)
    return 0
