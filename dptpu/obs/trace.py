"""Step-phase span tracing: preallocated ring + JSONL / Chrome-trace export.

The reference's observability is wall-clock meters plus explicit device
syncs (imagenet_ddp_apex.py:406, SURVEY §5); dptpu's device side is
covered by XLA traces (dptpu/utils/profiling.py). What was missing is
the HOST timeline that correlates them: where did each step's wall time
go — waiting on the loader, blocking on the H2D transfer, dispatching
the step, stalled on a checkpoint flush? ``Tracer`` answers that with
named spans recorded into a preallocated ring (no allocation churn on
the hot path beyond one tuple, no I/O until a drain), exported as

* a per-host JSONL event log (one span per line — greppable, diffable),
* a Chrome ``trace_event`` JSON that opens in Perfetto/chrome://tracing
  NEXT TO the XLA device trace, so a whole epoch's host phases and
  device ops sit on one timeline.

Span names are free-form; the canonical step phases the train loop
emits are ``data_wait`` / ``h2d`` / ``step`` / ``fetch`` / ``ckpt``
(see dptpu/obs/report.py for the category mapping). This module is
stdlib-only — it is imported by the data layer, which must stay
importable inside spawned decode workers (never JAX).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import List, Optional

from dptpu.utils.sync import OrderedLock


class _SpanCM:
    """Context-manager form of a span; ``record()`` is the hot-path API."""

    __slots__ = ("_tracer", "_name", "_step", "_t0")

    def __init__(self, tracer: "Tracer", name: str, step: int):
        self._tracer = tracer
        self._name = name
        self._step = step

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer.record(self._name, self._t0, t1 - self._t0,
                            step=self._step)
        return False


class _NullCM:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CM = _NullCM()


class NullTracer:
    """Disabled tracer: every call is a near-zero no-op (shared null
    context manager, no lock, no storage)."""

    enabled = False
    dropped = 0

    def span(self, name: str, step: int = -1):
        return _NULL_CM

    def record(self, name: str, t0: float, dur_s: float, step: int = -1):
        pass

    def snapshot(self) -> List[dict]:
        return []

    def drain(self) -> List[dict]:
        return []


class Tracer:
    """Span recorder over a preallocated ring buffer.

    * ``record(name, t0, dur_s, step=)`` — hot path: one tuple + one
      locked ring store (~1 µs). ``t0`` is in the ``time.perf_counter``
      domain; the tracer anchors that to wall time once at construction
      so exports carry real timestamps.
    * ``span(name)`` — context-manager sugar over ``record``.
    * ``drain()`` — spans since the last drain, oldest first, and
      resets the ring (the per-epoch consumption pattern);
      ``snapshot()`` reads without clearing (the in-flight profiling
      trigger's window read).
    * ring overflow OVERWRITES the oldest span and counts ``dropped``
      — tracing must never grow unbounded or stall the step loop.
    """

    enabled = True

    def __init__(self, capacity: int = 65536):
        if capacity < 2:
            raise ValueError(f"tracer capacity={capacity} must be >= 2")
        self.capacity = capacity
        self._buf: list = [None] * capacity  # guarded-by: _lock
        self._head = 0  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock
        # record() is called from EVERY thread (step loop, dispatcher,
        # prefetcher, writer), often while the caller holds its own
        # lock: the ring lock is the innermost rank by design
        self._lock = OrderedLock("obs.trace_ring")
        # anchor: wall = anchor_wall + (t_perf - anchor_perf)
        self.anchor_wall = time.time()
        self.anchor_perf = time.perf_counter()

    def span(self, name: str, step: int = -1) -> _SpanCM:
        return _SpanCM(self, name, step)

    def record(self, name: str, t0: float, dur_s: float, step: int = -1):
        rec = (name, t0, dur_s, step, threading.get_ident())
        with self._lock:
            self._buf[self._head] = rec
            self._head = (self._head + 1) % self.capacity
            if self._count < self.capacity:
                self._count += 1
            else:
                self.dropped += 1

    def _read_locked(self) -> List[tuple]:
        start = (self._head - self._count) % self.capacity
        return [
            self._buf[(start + i) % self.capacity]
            for i in range(self._count)
        ]

    def snapshot(self) -> List[dict]:
        """Spans currently in the ring (oldest first), without clearing."""
        with self._lock:
            recs = self._read_locked()
        return [self._to_dict(r) for r in recs]

    def drain(self) -> List[dict]:
        """Spans since the last drain (oldest first); resets the ring."""
        with self._lock:
            recs = self._read_locked()
            self._head = 0
            self._count = 0
        return [self._to_dict(r) for r in recs]

    def _to_dict(self, rec: tuple) -> dict:
        name, t0, dur_s, step, tid = rec
        return {
            "name": name,
            "ts": self.anchor_wall + (t0 - self.anchor_perf),
            "t0": t0,  # perf_counter domain, for window filtering
            "dur_s": dur_s,
            "step": step,
            "tid": tid,
        }


# ------------------------------------------------------------- exporters ----


def spans_to_chrome_events(spans, pid: Optional[int] = None) -> List[dict]:
    """Spans → Chrome ``trace_event`` objects (``ph: "X"`` complete
    events, µs timestamps) plus a process-name metadata record.

    The process is deliberately named ``dptpu Host spans`` so the device
    -trace parser (dptpu/utils/profiling.py) can never mistake the host
    track for a device track when both land in one merged timeline.
    """
    pid = os.getpid() if pid is None else pid
    events: List[dict] = [{
        "ph": "M", "pid": pid, "name": "process_name",
        "args": {"name": f"dptpu Host spans ({socket.gethostname()})"},
    }]
    for s in spans:
        events.append({
            "name": s["name"],
            "ph": "X",
            "pid": pid,
            "tid": s["tid"] % (1 << 31),  # chrome wants small-ish ints
            "ts": s["ts"] * 1e6,
            "dur": s["dur_s"] * 1e6,
            "args": {"step": s["step"]},
        })
    return events


class TraceSink:
    """Per-host span persistence under one directory.

    * ``<dir>/obs-<host>.jsonl`` — appended per ``add_spans`` call (one
      span per line) plus any structured events (``log_event``): the
      greppable log.
    * ``<dir>/obs-<host>.trace.json`` — Chrome trace_event JSON,
      STREAMED: events are appended as they arrive (no per-run buffer —
      a 90-epoch run must not hold a million event dicts in RAM or
      rewrite a growing file once per epoch) and the array is closed at
      ``close()``. A killed run leaves the array unterminated, which
      Perfetto's JSON importer accepts (trailing data is tolerated by
      design in the trace_event format).
    """

    def __init__(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        host = socket.gethostname()
        self.jsonl_path = os.path.join(directory, f"obs-{host}.jsonl")
        self.chrome_path = os.path.join(directory, f"obs-{host}.trace.json")
        self._jsonl = open(self.jsonl_path, "a")
        if os.path.exists(self.chrome_path):
            # a resumed run must not truncate the preempted run's
            # timeline (the JSONL sibling appends; the Chrome file is
            # one JSON document per run, so rotate the old one aside)
            i = 1
            while os.path.exists(f"{self.chrome_path}.{i}"):
                i += 1
            os.replace(self.chrome_path, f"{self.chrome_path}.{i}")
        self._chrome = open(self.chrome_path, "w")
        self._chrome.write('{"displayTimeUnit": "ms", "traceEvents": [\n')
        self._chrome.write(json.dumps(spans_to_chrome_events([])[0]))
        self._chrome.flush()
        self._closed = False

    @property
    def jsonl_file(self):
        """The shared append handle (metric sinks write through it so
        spans and metric flushes interleave in ONE per-host log)."""
        return self._jsonl

    def add_spans(self, spans):
        if self._closed or not spans:
            return
        for s in spans:
            rec = {k: s[k] for k in ("name", "ts", "dur_s", "step", "tid")}
            rec["kind"] = "span"
            self._jsonl.write(json.dumps(rec) + "\n")
        self._jsonl.flush()
        for e in spans_to_chrome_events(spans):
            if e["ph"] == "X":
                self._chrome.write(",\n" + json.dumps(e))
        self._chrome.flush()

    def log_event(self, kind: str, payload: dict):
        """Structured non-span record (metric flushes, reports)."""
        if self._closed:
            return
        self._jsonl.write(
            json.dumps({"kind": kind, "ts": time.time(), **payload}) + "\n"
        )
        self._jsonl.flush()

    def close(self):
        if self._closed:
            return
        self._chrome.write("\n]}\n")
        self._chrome.close()
        self._jsonl.close()
        self._closed = True
