"""Epoch attribution: host spans → "where did this epoch's time go".

Consumes one epoch's drained spans (dptpu/obs/trace.py) and produces the
per-phase breakdown large-scale ImageNet runs live and die by (straggler
and input-starvation diagnosis — Mikami et al. 1811.05233, Ying et al.
2004.13336 both lean on exactly this per-phase step accounting):

* ``data_wait`` — host blocked waiting for the loader (collect/lease
  included);
* ``h2d`` — host-to-device transfer (the DevicePrefetcher's put/block);
* ``device`` — step dispatch + the lagged metric fetch (host time spent
  feeding/syncing the device; the DEVICE-side truth lives in XLA traces
  — dptpu/utils/profiling.py — which these host spans complement, never
  replace);
* ``ckpt`` — checkpoint submits/flushes on the step thread (async
  writer time off-thread is reported separately, it overlaps compute);
* ``other`` — the residual against epoch wall time (loop bookkeeping,
  pipeline construction). A healthy tracer keeps coverage >= 95%.

Nested spans are handled by EXCLUSIVE-time accounting (a ``data_wait``
interval containing an ``h2d`` interval contributes only the
non-overlapped part), so categories sum to at most wall time instead of
double-counting. Per-step totals come from the loop's ``iter`` spans:
p50/p90/max step time plus an anomalous-step log (steps slower than
``anomaly_x`` × p50, with their own phase breakdown) — the "why is step
41k slow" first answer without a profiler session.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional

from dptpu.obs.metrics import _quantile

# span name -> attribution category. "iter" is the per-step envelope —
# used for step statistics, excluded from category accounting (it would
# double-count every phase it contains).
SPAN_CATEGORY = {
    "data_wait": "data_wait",
    "collect": "data_wait",
    "lease_wait": "data_wait",
    "h2d": "h2d",
    "step": "device",
    "fetch": "device",
    "eval_step": "device",
    "ckpt": "ckpt",
    "ckpt_flush": "ckpt",
}
CATEGORIES = ("data_wait", "h2d", "device", "ckpt")
# spans that run on helper threads by design and therefore OVERLAP the
# step timeline: reported separately, never part of the wall budget
ASYNC_SPANS = ("ckpt_write",)


def exclusive_durations(spans: List[dict]) -> List[tuple]:
    """Per-span exclusive duration: ``dur_s`` minus time covered by
    spans nested inside it (same thread, interval containment). Returns
    ``[(span, exclusive_s), ...]``. O(n log n) sweep per thread."""
    out = []
    by_tid: Dict[int, List[dict]] = {}
    for s in spans:
        by_tid.setdefault(s["tid"], []).append(s)
    for tid_spans in by_tid.values():
        # sort by start, longest first on ties → parents precede children
        tid_spans.sort(key=lambda s: (s["t0"], -s["dur_s"]))
        stack: List[list] = []  # [span, child_time]
        for s in tid_spans:
            while stack and s["t0"] >= stack[-1][0]["t0"] + \
                    stack[-1][0]["dur_s"] - 1e-12:
                top, child_time = stack.pop()
                out.append((top, max(top["dur_s"] - child_time, 0.0)))
            if stack:
                stack[-1][1] += s["dur_s"]
            stack.append([s, 0.0])
        while stack:
            top, child_time = stack.pop()
            out.append((top, max(top["dur_s"] - child_time, 0.0)))
    return out


def _categorized_exclusive(spans: List[dict]) -> List[tuple]:
    """``[(span, category, exclusive_s), ...]`` for every categorized
    budget span ("iter" envelopes and async-thread spans excluded)."""
    out = []
    for span, excl in exclusive_durations(
        [s for s in spans
         if s["name"] != "iter" and s["name"] not in ASYNC_SPANS]
    ):
        cat = SPAN_CATEGORY.get(span["name"])
        if cat is not None:
            out.append((span, cat, excl))
    return out


def attribute_spans(spans: List[dict]) -> Dict[str, float]:
    """Category → exclusive seconds over an arbitrary span window (the
    epoch report and the in-flight trigger both use this)."""
    sums = {c: 0.0 for c in CATEGORIES}
    for _, cat, excl in _categorized_exclusive(spans):
        sums[cat] += excl
    return sums


def attribute_epoch(spans: List[dict], wall_s: float,
                    anomaly_x: float = 3.0,
                    max_anomalies: int = 10) -> dict:
    """One epoch's attribution report (see module docstring)."""
    categorized = _categorized_exclusive(spans)
    sums = {c: 0.0 for c in CATEGORIES}
    for _, cat, excl in categorized:
        sums[cat] += excl
    accounted = sum(sums.values())
    other = max(wall_s - accounted, 0.0)
    iters = [s for s in spans if s["name"] == "iter"]
    durs = sorted(s["dur_s"] for s in iters)
    p50 = _quantile(durs, 0.50)
    anomalies = []
    if p50 > 0:
        slow = sorted(
            (s for s in iters if s["dur_s"] > anomaly_x * p50),
            key=lambda s: -s["dur_s"],
        )[:max_anomalies]
        # per-step breakdown from the SAME exclusive accounting as the
        # category totals — raw durations would double-count a nested
        # collect inside its data_wait and print phases > step time
        by_step: Dict[int, Dict[str, float]] = {}
        for s, cat, excl in categorized:
            if s["step"] >= 0:
                d = by_step.setdefault(s["step"], {})
                d[cat] = d.get(cat, 0.0) + excl
        for s in slow:
            anomalies.append({
                "step": s["step"],
                "dur_s": round(s["dur_s"], 4),
                "x_p50": round(s["dur_s"] / p50, 2),
                "phases": {k: round(v, 4)
                           for k, v in by_step.get(s["step"], {}).items()},
            })
    async_ckpt = sum(
        s["dur_s"] for s in spans if s["name"] in ASYNC_SPANS
    )
    return {
        "wall_s": round(wall_s, 4),
        "data_wait_s": round(sums["data_wait"], 4),
        "h2d_s": round(sums["h2d"], 4),
        "device_s": round(sums["device"], 4),
        "ckpt_s": round(sums["ckpt"], 4),
        "other_s": round(other, 4),
        "coverage": round(accounted / wall_s, 4) if wall_s > 0 else 0.0,
        "ckpt_async_s": round(async_ckpt, 4),  # overlapped, not in budget
        "steps": len(iters),
        "step_p50_s": round(p50, 4),
        "step_p90_s": round(_quantile(durs, 0.90), 4),
        "step_max_s": round(durs[-1] if durs else 0.0, 4),
        "anomalous_steps": anomalies,
        "span_count": len(spans),
    }


class P2Quantile:
    """Streaming quantile estimate via the P² algorithm (Jain &
    Chlamtac, CACM 1985): five markers, O(1) memory and O(1) per
    observation — the right shape for a pod timeline that may span a
    90-epoch run's worth of step spans. Exact (sorted interpolation)
    below five observations; the classic parabolic/linear marker update
    beyond. ``value()`` is the current estimate of quantile ``q``."""

    __slots__ = ("q", "count", "_heights", "_pos", "_want", "_inc")

    def __init__(self, q: float = 0.5):
        if not 0.0 < q < 1.0:
            raise ValueError(f"P2Quantile q={q} must be in (0, 1)")
        self.q = q
        self.count = 0
        self._heights: List[float] = []  # marker heights q0..q4
        self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]  # actual positions n_i
        self._want = [1.0, 1.0 + 2 * q, 1.0 + 4 * q, 3.0 + 2 * q, 5.0]
        self._inc = [0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0]

    def add(self, x: float):
        x = float(x)
        self.count += 1
        h = self._heights
        if self.count <= 5:
            h.append(x)
            h.sort()
            return
        # locate the cell; extremes extend the end markers
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 0
            while k < 3 and x >= h[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            self._pos[i] += 1.0
        for i in range(5):
            self._want[i] += self._inc[i]
        # adjust the three interior markers toward their desired spots
        for i in (1, 2, 3):
            d = self._want[i] - self._pos[i]
            if (d >= 1.0 and self._pos[i + 1] - self._pos[i] > 1.0) or \
                    (d <= -1.0 and self._pos[i - 1] - self._pos[i] < -1.0):
                s = 1.0 if d >= 1.0 else -1.0
                # parabolic (P²) estimate; fall back to linear if it
                # would break marker monotonicity
                nl, ni, nr = self._pos[i - 1], self._pos[i], self._pos[i + 1]
                hp = h[i] + s / (nr - nl) * (
                    (ni - nl + s) * (h[i + 1] - h[i]) / (nr - ni)
                    + (nr - ni - s) * (h[i] - h[i - 1]) / (ni - nl)
                )
                if not h[i - 1] < hp < h[i + 1]:
                    j = i + int(s)
                    hp = h[i] + s * (h[j] - h[i]) / (self._pos[j] - ni)
                h[i] = hp
                self._pos[i] += s

    def value(self) -> float:
        if self.count == 0:
            return 0.0
        if self.count <= 5:
            return _quantile(sorted(self._heights), self.q)
        return self._heights[2]


# merged-timeline temp files still on disk (conftest leak guard: every
# merge must either complete its atomic rename or unlink its temp)
_LIVE_MERGE_TMPS: set = set()


def live_merge_tmp_count() -> int:
    return len(_LIVE_MERGE_TMPS)


def merge_pod_timeline(directory: str, out_path: Optional[str] = None,
                       window_s: float = 60.0,
                       straggler_factor: float = 1.5) -> dict:
    """Chief-side collector: merge every per-host ``obs-<host>.jsonl``
    under ``directory`` into ONE pod timeline (ROADMAP item 3c).

    Streaming pass — constant memory per host via :class:`P2Quantile`,
    so a week-long pod log merges without loading it: per-host p50/p90
    for every span category, per-host ``iter`` (step-time) quantiles
    bucketed into ``window_s`` wall-clock windows ("what changed at
    14:07" = the window whose p50 jumped), the epoch reports each host
    logged, and a straggler verdict (hosts whose step p50 exceeds
    ``straggler_factor`` × the pod-wide p50 — only meaningful with >= 2
    hosts; a 1-host pod reports an empty list).

    ``out_path`` (optional) writes the merged timeline atomically
    (tempfile + rename in the target directory; the temp is tracked so
    the test suite's leak guard can prove none is ever left behind).
    """
    hosts: Dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(directory, "obs-*.jsonl"))):
        host = os.path.basename(path)[len("obs-"):-len(".jsonl")]
        h = hosts.setdefault(host, {
            "spans": {},  # name -> {count, p50 P2, p90 P2}
            "iter_p50": P2Quantile(0.5), "iter_p90": P2Quantile(0.9),
            "iter_count": 0,
            "windows": {},  # int(ts // window_s) -> {count, p50 P2}
            "epochs": [],
            "events": 0,
            "bad_lines": 0,
        })
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    h["bad_lines"] += 1
                    continue
                kind = rec.get("kind")
                if kind == "span":
                    name, dur = rec.get("name"), rec.get("dur_s", 0.0)
                    s = h["spans"].setdefault(
                        name,
                        {"count": 0, "p50": P2Quantile(0.5),
                         "p90": P2Quantile(0.9)},
                    )
                    s["count"] += 1
                    s["p50"].add(dur)
                    s["p90"].add(dur)
                    if name == "iter":
                        h["iter_p50"].add(dur)
                        h["iter_p90"].add(dur)
                        h["iter_count"] += 1
                        w = h["windows"].setdefault(
                            int(rec.get("ts", 0.0) // window_s),
                            {"count": 0, "p50": P2Quantile(0.5)},
                        )
                        w["count"] += 1
                        w["p50"].add(dur)
                elif kind == "epoch_report":
                    h["epochs"].append({
                        k: rec[k] for k in
                        ("epoch", "wall_s", "data_wait_s", "device_s",
                         "step_p50_s")
                        if k in rec
                    })
                else:
                    h["events"] += 1
    pod_p50 = P2Quantile(0.5)
    out_hosts = {}
    for host, h in hosts.items():
        windows = []
        prev = None
        for wk in sorted(h["windows"]):
            w = h["windows"][wk]
            p50 = round(w["p50"].value(), 6)
            windows.append({
                "t0": wk * window_s,
                "steps": w["count"],
                "step_p50_s": p50,
                # the "what changed at 14:07" hook: this window's p50
                # relative to the previous window's
                "vs_prev": round(p50 / prev, 3) if prev else 1.0,
            })
            prev = p50 or prev
        out_hosts[host] = {
            "steps": h["iter_count"],
            "step_p50_s": round(h["iter_p50"].value(), 6),
            "step_p90_s": round(h["iter_p90"].value(), 6),
            "spans": {
                name: {"count": s["count"],
                       "p50_s": round(s["p50"].value(), 6),
                       "p90_s": round(s["p90"].value(), 6)}
                for name, s in sorted(h["spans"].items())
            },
            "windows": windows,
            "epochs": h["epochs"],
            "bad_lines": h["bad_lines"],
        }
        if h["iter_count"]:
            pod_p50.add(h["iter_p50"].value())
    pod = round(pod_p50.value(), 6)
    stragglers = []
    if len([h for h in out_hosts.values() if h["steps"]]) >= 2 and pod > 0:
        stragglers = sorted(
            host for host, h in out_hosts.items()
            if h["steps"] and h["step_p50_s"] > straggler_factor * pod
        )
    timeline = {
        "directory": directory,
        "window_s": window_s,
        "hosts": out_hosts,
        "pod_step_p50_s": pod,
        "straggler_factor": straggler_factor,
        "stragglers": stragglers,
    }
    if out_path is not None:
        tmp = out_path + ".tmp"
        _LIVE_MERGE_TMPS.add(tmp)
        try:
            with open(tmp, "w") as f:
                json.dump(timeline, f, indent=1)
                f.write("\n")
            os.replace(tmp, out_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        finally:
            _LIVE_MERGE_TMPS.discard(tmp)
    return timeline


def format_report(report: dict, epoch: Optional[int] = None) -> str:
    """Console rendering of :func:`attribute_epoch` (one block per
    epoch, additive next to the reference's contractual meter lines)."""
    wall = max(report["wall_s"], 1e-9)
    head = f"== obs epoch {epoch}" if epoch is not None else "== obs"
    parts = [
        f"{head}: wall {report['wall_s']:.1f}s | "
        + " | ".join(
            f"{k[:-2]} {report[k]:.2f}s "
            f"({100.0 * report[k] / wall:.1f}%)"
            for k in ("data_wait_s", "h2d_s", "device_s", "ckpt_s",
                      "other_s")
        )
        + f" | coverage {100.0 * report['coverage']:.1f}%"
    ]
    parts.append(
        f"   step time p50 {report['step_p50_s'] * 1e3:.1f}ms "
        f"p90 {report['step_p90_s'] * 1e3:.1f}ms "
        f"max {report['step_max_s'] * 1e3:.1f}ms "
        f"over {report['steps']} steps"
        + (f" | async ckpt {report['ckpt_async_s']:.2f}s overlapped"
           if report["ckpt_async_s"] else "")
    )
    for a in report["anomalous_steps"]:
        phases = " ".join(f"{k}={v:.3f}s" for k, v in a["phases"].items())
        parts.append(
            f"   anomalous step {a['step']}: {a['dur_s']:.3f}s "
            f"({a['x_p50']}x p50) {phases}"
        )
    return "\n".join(parts)
