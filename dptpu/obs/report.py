"""Epoch attribution: host spans → "where did this epoch's time go".

Consumes one epoch's drained spans (dptpu/obs/trace.py) and produces the
per-phase breakdown large-scale ImageNet runs live and die by (straggler
and input-starvation diagnosis — Mikami et al. 1811.05233, Ying et al.
2004.13336 both lean on exactly this per-phase step accounting):

* ``data_wait`` — host blocked waiting for the loader (collect/lease
  included);
* ``h2d`` — host-to-device transfer (the DevicePrefetcher's put/block);
* ``device`` — step dispatch + the lagged metric fetch (host time spent
  feeding/syncing the device; the DEVICE-side truth lives in XLA traces
  — dptpu/utils/profiling.py — which these host spans complement, never
  replace);
* ``ckpt`` — checkpoint submits/flushes on the step thread (async
  writer time off-thread is reported separately, it overlaps compute);
* ``other`` — the residual against epoch wall time (loop bookkeeping,
  pipeline construction). A healthy tracer keeps coverage >= 95%.

Nested spans are handled by EXCLUSIVE-time accounting (a ``data_wait``
interval containing an ``h2d`` interval contributes only the
non-overlapped part), so categories sum to at most wall time instead of
double-counting. Per-step totals come from the loop's ``iter`` spans:
p50/p90/max step time plus an anomalous-step log (steps slower than
``anomaly_x`` × p50, with their own phase breakdown) — the "why is step
41k slow" first answer without a profiler session.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from dptpu.obs.metrics import _quantile

# span name -> attribution category. "iter" is the per-step envelope —
# used for step statistics, excluded from category accounting (it would
# double-count every phase it contains).
SPAN_CATEGORY = {
    "data_wait": "data_wait",
    "collect": "data_wait",
    "lease_wait": "data_wait",
    "h2d": "h2d",
    "step": "device",
    "fetch": "device",
    "eval_step": "device",
    "ckpt": "ckpt",
    "ckpt_flush": "ckpt",
}
CATEGORIES = ("data_wait", "h2d", "device", "ckpt")
# spans that run on helper threads by design and therefore OVERLAP the
# step timeline: reported separately, never part of the wall budget
ASYNC_SPANS = ("ckpt_write",)


def exclusive_durations(spans: List[dict]) -> List[tuple]:
    """Per-span exclusive duration: ``dur_s`` minus time covered by
    spans nested inside it (same thread, interval containment). Returns
    ``[(span, exclusive_s), ...]``. O(n log n) sweep per thread."""
    out = []
    by_tid: Dict[int, List[dict]] = {}
    for s in spans:
        by_tid.setdefault(s["tid"], []).append(s)
    for tid_spans in by_tid.values():
        # sort by start, longest first on ties → parents precede children
        tid_spans.sort(key=lambda s: (s["t0"], -s["dur_s"]))
        stack: List[list] = []  # [span, child_time]
        for s in tid_spans:
            while stack and s["t0"] >= stack[-1][0]["t0"] + \
                    stack[-1][0]["dur_s"] - 1e-12:
                top, child_time = stack.pop()
                out.append((top, max(top["dur_s"] - child_time, 0.0)))
            if stack:
                stack[-1][1] += s["dur_s"]
            stack.append([s, 0.0])
        while stack:
            top, child_time = stack.pop()
            out.append((top, max(top["dur_s"] - child_time, 0.0)))
    return out


def _categorized_exclusive(spans: List[dict]) -> List[tuple]:
    """``[(span, category, exclusive_s), ...]`` for every categorized
    budget span ("iter" envelopes and async-thread spans excluded)."""
    out = []
    for span, excl in exclusive_durations(
        [s for s in spans
         if s["name"] != "iter" and s["name"] not in ASYNC_SPANS]
    ):
        cat = SPAN_CATEGORY.get(span["name"])
        if cat is not None:
            out.append((span, cat, excl))
    return out


def attribute_spans(spans: List[dict]) -> Dict[str, float]:
    """Category → exclusive seconds over an arbitrary span window (the
    epoch report and the in-flight trigger both use this)."""
    sums = {c: 0.0 for c in CATEGORIES}
    for _, cat, excl in _categorized_exclusive(spans):
        sums[cat] += excl
    return sums


def attribute_epoch(spans: List[dict], wall_s: float,
                    anomaly_x: float = 3.0,
                    max_anomalies: int = 10) -> dict:
    """One epoch's attribution report (see module docstring)."""
    categorized = _categorized_exclusive(spans)
    sums = {c: 0.0 for c in CATEGORIES}
    for _, cat, excl in categorized:
        sums[cat] += excl
    accounted = sum(sums.values())
    other = max(wall_s - accounted, 0.0)
    iters = [s for s in spans if s["name"] == "iter"]
    durs = sorted(s["dur_s"] for s in iters)
    p50 = _quantile(durs, 0.50)
    anomalies = []
    if p50 > 0:
        slow = sorted(
            (s for s in iters if s["dur_s"] > anomaly_x * p50),
            key=lambda s: -s["dur_s"],
        )[:max_anomalies]
        # per-step breakdown from the SAME exclusive accounting as the
        # category totals — raw durations would double-count a nested
        # collect inside its data_wait and print phases > step time
        by_step: Dict[int, Dict[str, float]] = {}
        for s, cat, excl in categorized:
            if s["step"] >= 0:
                d = by_step.setdefault(s["step"], {})
                d[cat] = d.get(cat, 0.0) + excl
        for s in slow:
            anomalies.append({
                "step": s["step"],
                "dur_s": round(s["dur_s"], 4),
                "x_p50": round(s["dur_s"] / p50, 2),
                "phases": {k: round(v, 4)
                           for k, v in by_step.get(s["step"], {}).items()},
            })
    async_ckpt = sum(
        s["dur_s"] for s in spans if s["name"] in ASYNC_SPANS
    )
    return {
        "wall_s": round(wall_s, 4),
        "data_wait_s": round(sums["data_wait"], 4),
        "h2d_s": round(sums["h2d"], 4),
        "device_s": round(sums["device"], 4),
        "ckpt_s": round(sums["ckpt"], 4),
        "other_s": round(other, 4),
        "coverage": round(accounted / wall_s, 4) if wall_s > 0 else 0.0,
        "ckpt_async_s": round(async_ckpt, 4),  # overlapped, not in budget
        "steps": len(iters),
        "step_p50_s": round(p50, 4),
        "step_p90_s": round(_quantile(durs, 0.90), 4),
        "step_max_s": round(durs[-1] if durs else 0.0, 4),
        "anomalous_steps": anomalies,
        "span_count": len(spans),
    }


def format_report(report: dict, epoch: Optional[int] = None) -> str:
    """Console rendering of :func:`attribute_epoch` (one block per
    epoch, additive next to the reference's contractual meter lines)."""
    wall = max(report["wall_s"], 1e-9)
    head = f"== obs epoch {epoch}" if epoch is not None else "== obs"
    parts = [
        f"{head}: wall {report['wall_s']:.1f}s | "
        + " | ".join(
            f"{k[:-2]} {report[k]:.2f}s "
            f"({100.0 * report[k] / wall:.1f}%)"
            for k in ("data_wait_s", "h2d_s", "device_s", "ckpt_s",
                      "other_s")
        )
        + f" | coverage {100.0 * report['coverage']:.1f}%"
    ]
    parts.append(
        f"   step time p50 {report['step_p50_s'] * 1e3:.1f}ms "
        f"p90 {report['step_p90_s'] * 1e3:.1f}ms "
        f"max {report['step_max_s'] * 1e3:.1f}ms "
        f"over {report['steps']} steps"
        + (f" | async ckpt {report['ckpt_async_s']:.2f}s overlapped"
           if report["ckpt_async_s"] else "")
    )
    for a in report["anomalous_steps"]:
        phases = " ".join(f"{k}={v:.3f}s" for k, v in a["phases"].items())
        parts.append(
            f"   anomalous step {a['step']}: {a['dur_s']:.3f}s "
            f"({a['x_p50']}x p50) {phases}"
        )
    return "\n".join(parts)
