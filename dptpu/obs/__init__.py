"""dptpu.obs — unified step-phase tracing, metrics registry, and
on-demand in-flight profiling.

One subsystem replaces the previously uncorrelated surfaces (console
meters, ``feed_stats`` threading, the ``writer.add_scalar`` ladder,
manual ``profile_device_time`` sessions):

* :class:`Tracer` — ``span("data_wait") / span("h2d") / span("step") /
  span("ckpt")`` context managers over a preallocated ring, drained to
  a per-host JSONL log + Chrome-trace JSON (opens in Perfetto next to
  XLA device traces);
* :class:`Registry` — one namespace of counters/gauges/histograms with
  sink fan-out (console / TensorBoard / JSONL);
* :class:`ProfileTrigger` — SIGUSR2 or a sentinel file arms
  ``jax.profiler.trace`` for the next N steps of a LIVE ``fit()`` and
  emits a merged host-span + device-op attribution table;
* :func:`attribute_epoch` — the per-epoch data-wait/h2d/device/ckpt/
  other breakdown with p50/p90/max step time and an anomalous-step log.

Module-level accessors (``get_tracer``/``get_registry``) let every
layer publish without threading handles through constructors; ``fit()``
configures real instances per run and ``reset()`` restores the inert
defaults afterward. The package root is stdlib-only (the data layer
imports it; spawned decode workers must never see JAX).

Env knobs (validated fail-fast by :func:`obs_knobs`, the locked knob
contract):

* ``DPTPU_OBS`` — enable tracing + the epoch attribution report
  (default on; overhead is gated < 2% by scripts/run_obsbench.py);
* ``DPTPU_OBS_RING`` — span ring capacity (default 65536, >= 64);
* ``DPTPU_OBS_DIR`` — directory for the JSONL span/metric log and the
  Chrome trace (unset = in-memory attribution only);
* ``DPTPU_OBS_TRACE_STEPS`` — steps per on-demand trace window
  (default 8, >= 1);
* ``DPTPU_OBS_TRIGGER`` — sentinel file path armed by ``touch`` (the
  non-signal trigger path, e.g. from a container exec);
* ``DPTPU_OBS_ANOMALY`` — anomalous-step threshold as a multiple of
  the p50 step time (default 3.0, > 1).
"""

from __future__ import annotations

import os

from dptpu.envknob import env_bool, env_float, env_int, env_str
from dptpu.obs.metrics import (
    ConsoleSink,
    Counter,
    Gauge,
    Histogram,
    JsonlSink,
    Registry,
    TensorBoardSink,
)
from dptpu.obs.report import (
    SPAN_CATEGORY,
    P2Quantile,
    attribute_epoch,
    attribute_spans,
    exclusive_durations,
    format_report,
    merge_pod_timeline,
)
from dptpu.obs.trace import (
    NullTracer,
    Tracer,
    TraceSink,
    spans_to_chrome_events,
)
from dptpu.obs.trigger import ProfileTrigger

__all__ = [
    "Tracer", "NullTracer", "TraceSink", "spans_to_chrome_events",
    "Registry", "Counter", "Gauge", "Histogram",
    "TensorBoardSink", "JsonlSink", "ConsoleSink",
    "ProfileTrigger",
    "attribute_epoch", "attribute_spans", "exclusive_durations",
    "format_report", "SPAN_CATEGORY", "P2Quantile", "merge_pod_timeline",
    "get_tracer", "set_tracer", "get_registry", "set_registry",
    "reset", "obs_knobs",
]

# ------------------------------------------------- module-level instances ----

_tracer = NullTracer()
_registry = Registry()


def get_tracer():
    """The process-wide tracer (a :class:`NullTracer` until ``fit()`` —
    or a test — installs a real one)."""
    return _tracer


def set_tracer(tracer):
    global _tracer
    _tracer = tracer
    return tracer


def get_registry() -> Registry:
    """The process-wide metrics registry (always usable; sinks are only
    attached by a configured run)."""
    return _registry


def set_registry(registry: Registry) -> Registry:
    global _registry
    _registry = registry
    return registry


def reset():
    """Restore the inert defaults (run teardown / test isolation)."""
    set_tracer(NullTracer())
    set_registry(Registry())


# ----------------------------------------------------------------- knobs ----


def obs_knobs(environ=None) -> dict:
    """Validated ``DPTPU_OBS_*`` env knobs (the locked fail-fast
    contract: unset means default, every explicit-but-invalid value
    raises with an actionable message)."""
    env = environ if environ is not None else os.environ
    enabled = env_bool("DPTPU_OBS", True, environ=env)
    ring = env_int("DPTPU_OBS_RING", 65536, environ=env)
    if ring < 64:
        raise ValueError(
            f"DPTPU_OBS_RING={ring} must be >= 64 spans (the ring holds "
            f"~6 spans/step; smaller rings drop the epoch's head)"
        )
    trace_steps = env_int("DPTPU_OBS_TRACE_STEPS", 8, environ=env)
    if trace_steps < 1:
        raise ValueError(
            f"DPTPU_OBS_TRACE_STEPS={trace_steps} must be >= 1 step "
            f"per on-demand trace window"
        )
    anomaly = env_float("DPTPU_OBS_ANOMALY", 3.0, environ=env)
    if anomaly <= 1.0:
        raise ValueError(
            f"DPTPU_OBS_ANOMALY={anomaly} must be > 1 (a multiple of "
            f"the p50 step time; e.g. DPTPU_OBS_ANOMALY=3)"
        )
    return {
        "enabled": enabled,
        "ring": ring,
        "dir": env_str("DPTPU_OBS_DIR", None, environ=env),
        "trace_steps": trace_steps,
        "trigger": env_str("DPTPU_OBS_TRIGGER", None, environ=env),
        "anomaly": anomaly,
    }
