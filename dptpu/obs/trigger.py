"""On-demand in-flight profiling: arm a device trace on a LIVE run.

"Why is step 41k slow" used to require killing the job and restarting
it under ``DPTPU_PROFILE`` / a ``profile_device_time`` session. The
:class:`ProfileTrigger` removes the restart: send the training process
``SIGUSR2`` (or touch the ``DPTPU_OBS_TRIGGER`` sentinel file) and the
NEXT ``DPTPU_OBS_TRACE_STEPS`` steps of the running ``fit()`` are traced
with ``jax.profiler.trace``; when the window closes the trigger parses
the XLA trace (dptpu/utils/profiling.py), snapshots the host spans that
covered the same window (dptpu/obs/trace.py), and writes + prints one
MERGED host-phase + device-op attribution table — no restart, no lost
training time beyond the trace itself.

States: idle → armed (signal/sentinel seen) → active (trace running,
counting steps) → idle. ``tick()`` is called once per training step by
the loop's ``on_step`` hook; in the idle state with no sentinel it is a
single attribute check. The signal handler only sets a flag (handlers
must stay async-signal-safe); all profiler work happens on the step
thread inside ``tick()``.

JAX is imported lazily — this module is reachable from the data layer's
package but must never pull jax into spawned decode workers.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Optional

from dptpu.obs.report import attribute_spans


class ProfileTrigger:
    """Arm-on-demand ``jax.profiler`` window over a live step loop."""

    def __init__(self, out_dir: str, trace_steps: int = 8, tracer=None,
                 sentinel: Optional[str] = None, verbose: bool = True,
                 signum: int = signal.SIGUSR2):
        if trace_steps < 1:
            raise ValueError(
                f"trace_steps={trace_steps} must be >= 1 step"
            )
        self.out_dir = out_dir
        self.trace_steps = trace_steps
        self.tracer = tracer
        self.sentinel = sentinel
        self.verbose = verbose
        self.signum = signum
        # set by the SIGUSR2 handler (or arm()/the sentinel on the step
        # thread) and consumed by tick(): an async-signal flag on
        # purpose — a lock inside a signal handler could self-deadlock
        # the main thread it interrupts, and the worst a torn flip can
        # do is arm one extra capture
        self._armed = False  # dptpu: allow-guarded-by(async-signal flag: the handler may only SET it and tick consumes it; taking a lock inside a signal handler could self-deadlock the interrupted main thread, and a torn flip at worst arms one extra capture)
        self._active = False
        self._ticks = 0  # steps seen since install (the fallback label)
        self._disabled_reason: Optional[str] = None
        self._steps_in_window = 0
        self._window_t0 = 0.0
        self._window_step0 = -1
        self._window_spans: list = []  # drained-past-us spans (absorb)
        self._captures = 0
        self._old_handler = None
        self._installed = False
        self._sentinel_mtime: Optional[float] = None
        self.last_report: Optional[dict] = None

    # ------------------------------------------------------------ arming ----

    def _handle(self, signum, frame):
        self._armed = True

    def install(self):
        """Install the SIGUSR2 handler (main thread only — elsewhere the
        sentinel file remains the arming path, same as every signal-based
        guard in dptpu)."""
        if threading.current_thread() is threading.main_thread():
            self._old_handler = signal.signal(self.signum, self._handle)
            self._installed = True
        return self

    def uninstall(self):
        if self._installed:
            signal.signal(self.signum, self._old_handler)
            self._installed = False
        if self._active:
            # never leave a dangling profiler session behind a dying fit
            try:
                self._stop_window(aborted=True)
            except Exception:
                pass

    def arm(self):
        """Programmatic arming (benches/tests; signal and sentinel are
        the operational paths)."""
        self._armed = True

    def absorb(self, spans):
        """Called by whoever DRAINS the shared tracer (fit's epoch
        report does) while a window may be open: spans inside the
        window are kept here so the merged report still covers them —
        a window straddling an epoch boundary must not lose its first
        steps to the boundary drain."""
        if self._active:
            self._window_spans.extend(
                s for s in spans if s["t0"] >= self._window_t0
            )

    def _sentinel_fired(self) -> bool:
        if self.sentinel is None:
            return False
        try:
            mtime = os.path.getmtime(self.sentinel)
        except OSError:
            return False
        # consume the sentinel so one touch = one capture; if the file
        # can't be removed (read-only dir), fall back to mtime edge
        # detection so it doesn't re-trigger forever
        try:
            os.remove(self.sentinel)
        except OSError:
            if self._sentinel_mtime == mtime:
                return False
            self._sentinel_mtime = mtime
        return True

    # ----------------------------------------------------------- stepping ----

    def tick(self, step: int = -1):
        """Called once per completed training step. ``step`` is an
        optional label; callers that don't track one (the loop's
        argument-less ``on_step`` hook) get the trigger's own count of
        steps seen since install."""
        self._ticks += 1
        if self._disabled_reason is not None:
            return
        if self._active:
            self._steps_in_window += 1
            if self._steps_in_window >= self.trace_steps:
                self._stop_window()
            return
        if self._armed or self._sentinel_fired():
            self._armed = False
            self._start_window(step if step >= 0 else self._ticks)

    def _trace_dir(self) -> str:
        return os.path.join(
            self.out_dir, f"ondemand-{self._captures:03d}"
        )

    def _start_window(self, step: int):
        import jax

        path = self._active_dir = self._trace_dir()
        os.makedirs(path, exist_ok=True)
        try:
            jax.profiler.start_trace(path)
        except Exception as e:
            # e.g. another trace is already running (DPTPU_PROFILE epoch
            # trace): stand down for this run instead of crashing a live
            # training job over observability
            self._disabled_reason = str(e)
            if self.verbose:
                print(
                    f"=> obs trigger: cannot start device trace "
                    f"({e}); on-demand profiling disabled for this run"
                )
            return
        self._active = True
        self._steps_in_window = 0
        self._window_t0 = time.perf_counter()
        self._window_step0 = step
        self._window_spans = []
        if self.verbose:
            print(
                f"=> obs trigger: device trace armed for the next "
                f"{self.trace_steps} steps -> {path}"
            )

    def _stop_window(self, aborted: bool = False):
        import jax

        jax.profiler.stop_trace()
        self._active = False
        self._captures += 1
        if aborted:
            return
        window_s = time.perf_counter() - self._window_t0
        path = self._active_dir
        report = self._build_report(path, window_s)
        self._window_spans = []
        out_path = os.path.join(path, "attribution.json")
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
        self.last_report = report
        if self.verbose:
            print(self.format_report(report))
            print(f"=> obs trigger: wrote {out_path}")

    # ------------------------------------------------------------ reports ----

    def _build_report(self, trace_path: str, window_s: float) -> dict:
        # host side: spans whose start falls inside the traced window —
        # any absorbed (drained-past-us) spans first, then what's still
        # in the ring
        cutoff = self._window_t0
        spans = list(self._window_spans)
        if self.tracer is not None:
            spans += [s for s in self.tracer.snapshot()
                      if s["t0"] >= cutoff]
        host = attribute_spans(spans)
        iters = sorted(
            s["dur_s"] for s in spans if s["name"] == "iter"
        )
        report = {
            "trace_dir": trace_path,
            "window_s": round(window_s, 4),
            "steps": self.trace_steps,
            "first_step": self._window_step0,
            "host_phases_s": {k: round(v, 4) for k, v in host.items()},
            "host_step_p50_s": round(
                iters[len(iters) // 2], 4) if iters else 0.0,
        }
        # device side: parse the XLA trace; a host-only trace (backend
        # exports no device tracks) degrades to host-span attribution
        # with the parser's explanation attached instead of failing the
        # live run
        try:
            from dptpu.utils.profiling import (
                load_trace_dir,
                parse_perfetto_trace,
            )

            merged = load_trace_dir(trace_path)
            total_ms, per_op = parse_perfetto_trace(
                merged, iters=self.trace_steps
            )
            top = sorted(per_op.items(), key=lambda kv: -kv[1])[:12]
            report["device_ms_per_step"] = round(total_ms, 3)
            report["device_top_ops_ms"] = {
                k: round(v, 3) for k, v in top
            }
        except (RuntimeError, OSError) as e:
            report["device_trace_error"] = str(e)
        return report

    @staticmethod
    def format_report(report: dict) -> str:
        lines = [
            f"== on-demand profile: {report['steps']} steps from step "
            f"{report['first_step']} ({report['window_s']:.2f}s wall)"
        ]
        host = report["host_phases_s"]
        lines.append(
            "   host: " + " | ".join(
                f"{k} {v:.3f}s" for k, v in host.items()
            )
            + f" | step p50 {report['host_step_p50_s'] * 1e3:.1f}ms"
        )
        if "device_ms_per_step" in report:
            lines.append(
                f"   device: {report['device_ms_per_step']:.3f} "
                f"ms/step across top ops:"
            )
            for op, ms in report["device_top_ops_ms"].items():
                lines.append(f"     {op[:48]:48s} {ms:8.3f} ms")
        else:
            lines.append(
                f"   device: unavailable — "
                f"{report.get('device_trace_error', 'no trace')}"
            )
        return "\n".join(lines)
