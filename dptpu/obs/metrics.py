"""Metrics registry: one namespace of counters/gauges/histograms, one
sink fan-out.

Before this module, every telemetry family had its own plumbing path:
``feed_stats`` threaded through ``train_one_epoch`` into per-epoch stats,
a hand-maintained ``writer.add_scalar`` ladder in ``fit``, and ad hoc
console prints. The :class:`Registry` collapses the fan-out: producers
publish named instruments, ``flush(step)`` snapshots every instrument
once and emits the scalars to EVERY attached sink — TensorBoard
(:class:`TensorBoardSink`), the per-host JSONL log (:class:`JsonlSink`),
and the console (:class:`ConsoleSink`) — so adding a sink (or a metric)
is one line, not three parallel edits.

Instrument semantics:

* ``Counter`` — monotonic; flush emits the cumulative value.
* ``Gauge`` — last-set value.
* ``Histogram`` — windowed observations; flush emits
  ``<name>/p50|p90|max|mean|count`` and RESETS the window (per-epoch
  distributions when flushed per epoch, like the train loop does).

Stdlib-only (imported by the data layer — never JAX).
"""

from __future__ import annotations

import json
import time
from typing import Dict, List


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        # owned-by: producer — inc() is a lock-free read-modify-write,
        # so two producers racing the same counter can tear ONE
        # increment (undercount a stat, never corrupt: the store itself
        # is GIL-atomic); the flushing thread reads a possibly-stale
        # snapshot. The ShmDecodeCache torn-counter trade, recorded in
        # CONCURRENCY.md.
        self.value = 0.0

    def inc(self, n: float = 1.0):
        self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        # owned-by: producer — set() is one GIL-atomic float store;
        # last writer wins, the flushing thread reads whatever is
        # current
        self.value = 0.0

    def set(self, v: float):
        self.value = float(v)


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank quantile over an already-sorted window."""
    if not sorted_vals:
        return 0.0
    i = min(int(round(q * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[i]


class Histogram:
    __slots__ = ("_window",)

    def __init__(self):
        # owned-by: producer — observe() is one GIL-atomic list append;
        # snapshot(reset=True) on the flushing thread swaps in a fresh
        # list, so an observation landing between the sort and the swap
        # is dropped from both windows — a bounded per-flush undercount,
        # not corruption (CONCURRENCY.md known-gaps)
        self._window: List[float] = []

    def observe(self, v: float):
        self._window.append(float(v))

    def snapshot(self, reset: bool = False) -> Dict[str, float]:
        vals = sorted(self._window)
        if reset:
            self._window = []
        if not vals:
            return {"count": 0.0, "mean": 0.0, "p50": 0.0, "p90": 0.0,
                    "p99": 0.0, "max": 0.0}
        return {
            "count": float(len(vals)),
            "mean": sum(vals) / len(vals),
            "p50": _quantile(vals, 0.50),
            "p90": _quantile(vals, 0.90),
            "p99": _quantile(vals, 0.99),
            "max": vals[-1],
        }


class Registry:
    """Named instruments + sink fan-out. ``counter``/``gauge``/
    ``histogram`` are get-or-create; re-registering a name as a
    different kind raises (two producers silently sharing a name with
    different semantics is a bug, not a merge)."""

    def __init__(self):
        # single-writer: instruments are registered and flushed from
        # the train loop; background producers only mutate instrument
        # VALUES (GIL-atomic float/int stores), never these containers
        self._metrics: Dict[str, object] = {}  # owned-by: train-loop
        self._sinks: list = []  # owned-by: train-loop

    def _get(self, name: str, cls):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls()
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}"
            )
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def set_scalars(self, mapping: Dict[str, float]):
        """Bulk-set gauges (the per-epoch stats publishing path)."""
        for k, v in mapping.items():
            self.gauge(k).set(v)

    def add_sink(self, sink):
        self._sinks.append(sink)

    @property
    def sinks(self):
        return tuple(self._sinks)

    def scalars(self, reset_histograms: bool = False) -> Dict[str, float]:
        """One flat tag→value snapshot of every instrument (histograms
        expand to ``name/p50`` etc.), deterministically ordered."""
        out: Dict[str, float] = {}
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if isinstance(m, Histogram):
                for stat, v in m.snapshot(reset=reset_histograms).items():
                    out[f"{name}/{stat}"] = v
            else:
                out[name] = m.value
        return out

    def flush(self, step: int):
        """Snapshot every instrument and fan the scalars out to every
        sink (histogram windows reset — per-flush distributions)."""
        scalars = self.scalars(reset_histograms=True)
        for sink in self._sinks:
            for tag, value in scalars.items():
                sink.emit(tag, value, step)
            end = getattr(sink, "flush_end", None)
            if end is not None:
                end(step)


# ----------------------------------------------------------------- sinks ----


class TensorBoardSink:
    """Bridge to dptpu's zero-dependency event writer
    (dptpu/utils/tensorboard.py) — or anything with ``add_scalar``."""

    def __init__(self, writer):
        self.writer = writer

    def emit(self, tag: str, value: float, step: int):
        self.writer.add_scalar(tag, value, step)


class JsonlSink:
    """One JSON line per flush: ``{"kind": "metrics", "step": N,
    "wall_time": ..., "scalars": {...}}`` — the machine-readable epoch
    record next to the span log."""

    def __init__(self, path_or_file):
        self._file = (
            open(path_or_file, "a") if isinstance(path_or_file, str)
            else path_or_file
        )
        self._pending: Dict[str, float] = {}

    def emit(self, tag: str, value: float, step: int):
        self._pending[tag] = value

    def flush_end(self, step: int):
        self._file.write(json.dumps({
            "kind": "metrics", "step": step, "wall_time": time.time(),
            "scalars": self._pending,
        }) + "\n")
        self._file.flush()
        self._pending = {}

    def close(self):
        if not self._file.closed:
            self._file.close()


class ConsoleSink:
    """Compact one-line console surface per flush, filtered by tag
    prefix (default: the ``Obs/`` attribution family) — additive next to
    the reference's contractual meter lines, never replacing them."""

    def __init__(self, prefixes=("Obs/",), print_fn=print):
        self.prefixes = tuple(prefixes)
        self._print = print_fn
        self._pending: Dict[str, float] = {}

    def emit(self, tag: str, value: float, step: int):
        if any(tag.startswith(p) for p in self.prefixes):
            self._pending[tag] = value

    def flush_end(self, step: int):
        if self._pending:
            parts = " ".join(
                f"{t.split('/', 1)[1]}={v:.4g}"
                for t, v in sorted(self._pending.items())
            )
            self._print(f"Obs[{step}]: {parts}")
        self._pending = {}
