"""SqueezeNet 1.0 / 1.1, torchvision-architecture-exact, NHWC.

Discovered through the lowercase-callable registry like every other arch
(imagenet_ddp.py:19-21, e.g. ``-a squeezenet1_0``). Fresh Flax build of
torchvision's ``squeezenet.py``:

* 1.0: 7x7/2 conv (96) -> fires (16,64,64)x2,(32,128,128) -> pool ->
  (32,128,128),(48,192,192)x2,(64,256,256) -> pool -> (64,256,256);
* 1.1: 3x3/2 conv (64) with the pools moved earlier (the "2.4x less
  computation" variant);
* Fire module: 1x1 squeeze -> ReLU -> concat(1x1 expand, 3x3 expand), all
  with bias;
* classifier: Dropout(0.5) -> 1x1 conv to num_classes -> ReLU -> global
  average pool (fully-convolutional head — no Linear).

torchvision's max pools here use ``ceil_mode=True``; ``ceil_max_pool``
reproduces that by padding the bottom/right with -inf exactly when the
ceil-rounded output needs it. Init matches torchvision: the final conv
N(0, 0.01), every other conv ``kaiming_uniform_`` (bound sqrt(6/fan_in)),
all biases 0. Param counts locked in tests/test_models.py
(squeezenet1_0 = 1,248,424 / squeezenet1_1 = 1,235,496).
"""

from functools import partial
from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from dptpu.models.layers import ceil_max_pool
from dptpu.models.registry import register_model

# kaiming_uniform_(a=0, fan_in, leaky_relu): bound sqrt(6/fan_in)
kaiming_uniform_fan_in = nn.initializers.variance_scaling(
    2.0, "fan_in", "uniform"
)


class Fire(nn.Module):
    squeeze: int
    expand1x1: int
    expand3x3: int
    conv: Any

    @nn.compact
    def __call__(self, x):
        s = nn.relu(self.conv(self.squeeze, (1, 1), name="squeeze")(x))
        e1 = nn.relu(self.conv(self.expand1x1, (1, 1), name="expand1x1")(s))
        e3 = nn.relu(
            self.conv(
                self.expand3x3, (3, 3), padding=((1, 1), (1, 1)),
                name="expand3x3",
            )(s)
        )
        return jnp.concatenate([e1, e3], axis=-1)


# (squeeze, expand1x1, expand3x3) per fire module; "P" = ceil max pool
_PLANS = {
    "1_0": [
        ("conv", 96, 7, 2), "P",
        ("fire", 16, 64, 64), ("fire", 16, 64, 64), ("fire", 32, 128, 128),
        "P",
        ("fire", 32, 128, 128), ("fire", 48, 192, 192),
        ("fire", 48, 192, 192), ("fire", 64, 256, 256),
        "P",
        ("fire", 64, 256, 256),
    ],
    "1_1": [
        ("conv", 64, 3, 2), "P",
        ("fire", 16, 64, 64), ("fire", 16, 64, 64), "P",
        ("fire", 32, 128, 128), ("fire", 32, 128, 128), "P",
        ("fire", 48, 192, 192), ("fire", 48, 192, 192),
        ("fire", 64, 256, 256), ("fire", 64, 256, 256),
    ],
}


class SqueezeNet(nn.Module):
    version: str = "1_0"
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Any = None  # no BN; accepted for API uniformity
    bn_dtype: Any = None  # likewise

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=kaiming_uniform_fan_in,
            bias_init=nn.initializers.zeros,
        )
        fire_idx = 1
        for spec in _PLANS[self.version]:
            if spec == "P":
                x = ceil_max_pool(x)
            elif spec[0] == "conv":
                _, feats, k, s = spec
                x = nn.relu(
                    conv(feats, (k, k), strides=(s, s), name="conv1")(x)
                )
            else:
                _, sq, e1, e3 = spec
                fire_idx += 1
                x = Fire(squeeze=sq, expand1x1=e1, expand3x3=e3, conv=conv,
                         name=f"fire{fire_idx}")(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        # final conv: N(0, 0.01) kernel, zero bias (torchvision final_conv)
        x = conv(
            self.num_classes, (1, 1),
            kernel_init=nn.initializers.normal(0.01),
            name="final_conv",
        )(x)
        x = nn.relu(x)
        return x.mean(axis=(1, 2))  # AdaptiveAvgPool2d((1,1)) + flatten


@register_model
def squeezenet1_0(**kw):
    return SqueezeNet(version="1_0", **kw)


@register_model
def squeezenet1_1(**kw):
    return SqueezeNet(version="1_1", **kw)
