"""ConvNeXt tiny/small/base/large, torchvision-architecture-exact, NHWC.

Registry-discoverable (imagenet_ddp.py:19-21, ``-a convnext_tiny``).
Fresh Flax build of torchvision's ``convnext.py``:

* stem 4x4/4 conv WITH bias + LayerNorm (eps 1e-6);
* four stages of CNBlocks with 2x2/2 LayerNorm+conv downsampling
  between them;
* CNBlock: 7x7 depthwise conv (bias) -> LayerNorm -> Linear 4x -> GELU
  -> Linear back -> per-channel layer scale (init 1e-6) -> row-mode
  stochastic depth -> residual. In NHWC the torch Permute pair around
  the LN/Linear sandwich disappears — the whole block is already
  channels-last;
* head: global average pool -> LayerNorm -> Linear.

Stochastic depth probability ramps to the per-variant rate as
``rate * block_id / (total - 1)``. Init matches torchvision:
trunc_normal(0.02) conv/linear kernels, zero biases. Param counts
locked in tests/test_models.py (tiny = 28,589,128).
"""

from functools import partial
from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from dptpu.models.layers import StochasticDepth, torch_trunc_normal_init
from dptpu.models.registry import register_variants

# name -> (dims, depths, stochastic_depth_rate)
_VARIANTS = {
    "tiny": ((96, 192, 384, 768), (3, 3, 9, 3), 0.1),
    "small": ((96, 192, 384, 768), (3, 3, 27, 3), 0.4),
    "base": ((128, 256, 512, 1024), (3, 3, 27, 3), 0.5),
    "large": ((192, 384, 768, 1536), (3, 3, 27, 3), 0.5),
}

_trunc02 = torch_trunc_normal_init(0.02)


class CNBlock(nn.Module):
    dim: int
    sd_prob: float
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        y = nn.Conv(
            self.dim, (7, 7), padding=((3, 3), (3, 3)),
            feature_group_count=self.dim, use_bias=True,
            dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=_trunc02, bias_init=nn.initializers.zeros,
            name="dw",
        )(x)
        y = nn.LayerNorm(
            epsilon=1e-6, dtype=self.dtype, param_dtype=self.param_dtype,
            name="norm",
        )(y)
        dense = partial(
            nn.Dense, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=_trunc02, bias_init=nn.initializers.zeros,
        )
        y = dense(4 * self.dim, name="mlp_1")(y)
        y = nn.gelu(y, approximate=False)
        y = dense(self.dim, name="mlp_2")(y)
        scale = self.param(
            "layer_scale",
            nn.initializers.constant(1e-6), (self.dim,), jnp.float32,
        )
        y = y * scale.astype(y.dtype)
        y = StochasticDepth(self.sd_prob, deterministic=not train)(y)
        return (x + y).astype(y.dtype)


class ConvNeXt(nn.Module):
    variant: str = "tiny"
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Any = None  # no BN; accepted for API uniformity
    bn_dtype: Any = None  # likewise

    @nn.compact
    def __call__(self, x, train: bool = False):
        dims, depths, sd_rate = _VARIANTS[self.variant]
        ln = partial(
            nn.LayerNorm, epsilon=1e-6, dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        conv = partial(
            nn.Conv, use_bias=True, dtype=self.dtype,
            param_dtype=self.param_dtype, kernel_init=_trunc02,
            bias_init=nn.initializers.zeros,
        )
        x = conv(dims[0], (4, 4), strides=(4, 4), padding="VALID",
                 name="stem_conv")(x)
        x = ln(name="stem_norm")(x)
        total = sum(depths)
        block_id = 0
        for si, (dim, depth) in enumerate(zip(dims, depths)):
            if si:
                x = ln(name=f"downsample{si}_norm")(x)
                x = conv(dim, (2, 2), strides=(2, 2), padding="VALID",
                         name=f"downsample{si}_conv")(x)
            for bi in range(depth):
                x = CNBlock(
                    dim=dim, sd_prob=sd_rate * block_id / (total - 1.0),
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    name=f"stage{si}_block{bi}",
                )(x, train)
                block_id += 1
        x = x.mean(axis=(1, 2))
        x = ln(name="head_norm")(x)
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=_trunc02, bias_init=nn.initializers.zeros,
            name="head",
        )(x)


register_variants(ConvNeXt, "convnext", _VARIANTS)
