"""Vision Transformer (ViT B/L/H), torchvision-architecture-exact, NHWC.

Registry-discoverable (imagenet_ddp.py:19-21, ``-a vit_b_16``). Fresh
Flax build of torchvision's ``vision_transformer.py``:

* patchify via a patch-size/patch-stride conv WITH bias, flattened
  row-major over the spatial grid (the same order torch's
  ``reshape(B, hidden, S).permute`` produces, so converted pos
  embeddings line up);
* learned class token (zeros init) prepended, learned position
  embedding (N(0, 0.02)) added over the ``S + 1`` sequence;
* pre-LN encoder layers (LayerNorm eps 1e-6): LN -> multi-head
  self-attention (one fused qkv projection == torch's
  ``in_proj_weight``, out projection) -> residual; LN -> MLP
  (Linear -> GELU -> Linear, xavier-uniform weights, N(0, 1e-6)
  biases) -> residual;
* final LN, classify from the class token through a ZERO-initialized
  Linear head (torchvision zero-inits ``heads.head``).

Attention goes through ``dptpu.ops.sequence_parallel``: on one device
it is the plain scaled-dot-product (two einsums around an f32 softmax,
straight onto the MXU); with ``seq_axis_name`` set and the token axis
sharded over that mesh axis under ``shard_map``, it runs as Ulysses
all-to-all or ring attention (``seq_mode``). The embedding stage
(class token prepend + pos-embedding add) indexes absolute positions,
so shard the ENCODER: replicate up to the embedding output, then
partition the token axis (and ``encoder/pos_embedding``'s axis 1) with
the same spec — tests/test_sequence_parallel.py shows the pattern at
encoder-layer level. Param counts locked in tests/test_models.py
(vit_b_16 at 224 = 86,567,656).
"""

import math
from functools import partial
from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from dptpu.models.layers import torch_trunc_normal_init, uniform_bound_init
from dptpu.models.registry import register_variants
from dptpu.ops.sequence_parallel import sequence_parallel_attention

# name -> (patch, layers, heads, hidden, mlp)
_VARIANTS = {
    "b_16": (16, 12, 12, 768, 3072),
    "b_32": (32, 12, 12, 768, 3072),
    "l_16": (16, 24, 16, 1024, 4096),
    "l_32": (32, 24, 16, 1024, 4096),
    "h_14": (14, 32, 16, 1280, 5120),
}


# torch's xavier_uniform_: U(±sqrt(6/(fan_in+fan_out))) — identical to
# flax's for the 2-D Dense kernels it is applied to
xavier_uniform = nn.initializers.xavier_uniform()


class SelfAttention(nn.Module):
    """torch ``nn.MultiheadAttention`` semantics: fused qkv projection
    (xavier-uniform, zero bias), scaled dot-product, out projection
    (torch Linear default init, zero bias).

    The fused projection's output axis is stored **head-major**:
    ``(head0: q,k,v)(head1: q,k,v)…`` — i.e. ``(h, heads, 3, hd)``
    flattened — NOT torch's ``[q|k|v]`` concatenation. Random init is
    layout-blind (iid columns) and the pretrained converter permutes
    torch's ``in_proj_weight/bias`` into this order
    (dptpu/models/pretrained.py, kind ``vit_qkv``). The payoff is
    tensor parallelism: a plain contiguous ``P(None, "model")`` split of
    the fused kernel is head-aligned for any mesh size dividing
    ``heads``, so GSPMD head-group attention TP (dptpu/parallel/gspmd.py
    ``vit_tp_specs``) needs no resharding — each device projects and
    attends its own head group, and the row-parallel out projection's
    psum is the block's single all-reduce.

    Migration: converted ``.npz`` weights and flax checkpoints both
    carry a ``qkv_layout`` marker now; unmarked (pre-round-4,
    [q|k|v]-major) ViT files are auto-permuted on load — params AND the
    momentum trace (``pretrained.load_pretrained_variables``,
    ``train.checkpoint.load_checkpoint``).

    ``seq_axis_name`` turns on sequence/context parallelism: under a
    ``shard_map`` whose in/out specs shard the token axis over that mesh
    axis, attention runs as Ulysses all-to-all or ring attention
    (``seq_mode``) — see dptpu/ops/sequence_parallel.py. Every other ViT
    sublayer is position-wise, so the encoder layer works on sequence
    shards unchanged."""

    heads: int
    dtype: Any
    param_dtype: Any
    seq_axis_name: Optional[str] = None
    seq_mode: str = "ulysses"

    @nn.compact
    def __call__(self, x, kv_mask=None):
        h = x.shape[-1]
        hd = h // self.heads
        dense = partial(
            nn.Dense, dtype=self.dtype, param_dtype=self.param_dtype
        )
        qkv = dense(
            3 * h, kernel_init=xavier_uniform,
            bias_init=nn.initializers.zeros, name="in_proj",
        )(x)
        # head-major layout (see class docstring): (…, heads, 3, hd)
        qkv = qkv.reshape(qkv.shape[:-1] + (self.heads, 3, hd))
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        y = sequence_parallel_attention(
            q, k, v, self.seq_axis_name, self.seq_mode, kv_mask=kv_mask
        )
        y = y.reshape(y.shape[:-2] + (h,))
        return dense(
            h,
            kernel_init=uniform_bound_init(1.0 / math.sqrt(h)),
            bias_init=nn.initializers.zeros,
            name="out_proj",
        )(y)


class EncoderLayer(nn.Module):
    heads: int
    mlp_dim: int
    dtype: Any
    param_dtype: Any
    seq_axis_name: Optional[str] = None
    seq_mode: str = "ulysses"

    @nn.compact
    def __call__(self, x, kv_mask=None):
        ln = partial(
            nn.LayerNorm, epsilon=1e-6, dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        dense = partial(
            nn.Dense, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=xavier_uniform,
            bias_init=nn.initializers.normal(1e-6),
        )
        y = ln(name="ln_1")(x)
        y = SelfAttention(
            heads=self.heads, dtype=self.dtype,
            param_dtype=self.param_dtype, name="self_attention",
            seq_axis_name=self.seq_axis_name, seq_mode=self.seq_mode,
        )(y, kv_mask=kv_mask)
        x = x + y
        y = ln(name="ln_2")(x)
        y = dense(self.mlp_dim, name="mlp_1")(y)
        y = nn.gelu(y, approximate=False)
        y = dense(x.shape[-1], name="mlp_2")(y)
        return x + y


class Encoder(nn.Module):
    """``seq_shard_tokens=False`` (default): tokens arrive however the
    caller laid them out — replicated on one device, or already
    token-sharded under a hand-written ``shard_map`` whose specs also
    shard ``pos_embedding``'s axis 1 (the library-level recipe,
    tests/test_sequence_parallel.py).

    ``seq_shard_tokens=True`` (the trainer's ``DPTPU_SP`` path —
    requires ``seq_axis_name``): tokens arrive REPLICATED over the
    sequence axis; the encoder adds the (replicated, exact) position
    embedding, right-pads the token axis to a multiple of the axis
    size, slices this device's chunk, and runs the layers
    sequence-parallel with a key-validity mask so padding never enters
    a softmax. Returns the LOCAL post-LN chunk — the caller recovers
    global tokens (VisionTransformer psums the device-0 cls row). No
    param is sharded, so state creation, checkpointing and eval reuse
    the plain replicated layout untouched."""

    layers: int
    heads: int
    mlp_dim: int
    dtype: Any
    param_dtype: Any
    seq_axis_name: Optional[str] = None
    seq_mode: str = "ulysses"
    seq_shard_tokens: bool = False

    @nn.compact
    def __call__(self, x):
        pos = self.param(
            "pos_embedding", nn.initializers.normal(0.02),
            (1, x.shape[1], x.shape[2]), jnp.float32,
        )
        x = x + pos.astype(x.dtype)
        kv_mask = None
        if self.seq_shard_tokens:
            from jax import lax

            if self.seq_axis_name is None:
                raise ValueError("seq_shard_tokens needs seq_axis_name")
            from dptpu.ops.sequence_parallel import axis_size

            n = axis_size(self.seq_axis_name)
            s_tot = x.shape[1]
            chunk = -(-s_tot // n)  # ceil: pad S+1 up to a multiple of n
            x = jnp.pad(x, ((0, 0), (0, chunk * n - s_tot), (0, 0)))
            idx = lax.axis_index(self.seq_axis_name)
            x = lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=1)
            kv_mask = (idx * chunk + jnp.arange(chunk)) < s_tot
        for i in range(self.layers):
            x = EncoderLayer(
                heads=self.heads, mlp_dim=self.mlp_dim, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"encoder_layer_{i}",
                seq_axis_name=self.seq_axis_name, seq_mode=self.seq_mode,
            )(x, kv_mask=kv_mask)
        return nn.LayerNorm(
            epsilon=1e-6, dtype=self.dtype, param_dtype=self.param_dtype,
            name="ln",
        )(x)


class VisionTransformer(nn.Module):
    variant: str = "b_16"
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Any = None  # no BN; accepted for API uniformity
    bn_dtype: Any = None  # likewise
    seq_axis_name: Optional[str] = None  # sequence parallelism (see above)
    seq_mode: str = "ulysses"
    seq_shard_tokens: bool = False  # trainer path: see Encoder docstring

    @nn.compact
    def __call__(self, x, train: bool = False):
        patch, layers, heads, hidden, mlp = _VARIANTS[self.variant]
        n, h, w, _ = x.shape
        if h % patch or w % patch:
            raise ValueError(
                f"vit_{self.variant} needs image size divisible by {patch}"
            )
        fan_in = 3 * patch * patch
        x = nn.Conv(
            hidden, (patch, patch), strides=(patch, patch), padding="VALID",
            use_bias=True, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=torch_trunc_normal_init(math.sqrt(1.0 / fan_in)),
            bias_init=nn.initializers.zeros,
            name="conv_proj",
        )(x)
        x = x.reshape(n, -1, hidden)  # row-major spatial flatten == torch
        cls = self.param(
            "class_token", nn.initializers.zeros, (1, 1, hidden), jnp.float32
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(x.dtype), (n, 1, hidden)), x], axis=1
        )
        x = Encoder(
            layers=layers, heads=heads, mlp_dim=mlp, dtype=self.dtype,
            param_dtype=self.param_dtype, name="encoder",
            seq_axis_name=self.seq_axis_name, seq_mode=self.seq_mode,
            seq_shard_tokens=self.seq_shard_tokens,
        )(x)
        if self.seq_shard_tokens:
            # x is this device's LOCAL post-LN chunk; the cls token is
            # row 0 of sequence-rank 0's chunk — zero it elsewhere and
            # one psum replicates it, so the head (and loss) compute
            # identically on every sequence member
            from jax import lax

            idx = lax.axis_index(self.seq_axis_name)
            cls_tok = jnp.where(idx == 0, x[:, 0], jnp.zeros_like(x[:, 0]))
            pooled = lax.psum(cls_tok, self.seq_axis_name)
        else:
            pooled = x[:, 0]
        return nn.Dense(
            self.num_classes,
            dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=nn.initializers.zeros,
            bias_init=nn.initializers.zeros,
            name="head",
        )(pooled)


register_variants(VisionTransformer, "vit", _VARIANTS)
