"""MobileNetV2, torchvision-architecture-exact, NHWC.

Reachable through the discovery registry like every torchvision callable
(imagenet_ddp.py:19-21, ``-a mobilenet_v2``). Fresh Flax build of
torchvision's ``mobilenetv2.py``:

* stem 3x3/2 ConvBNReLU6 (32);
* 17 inverted residuals — 1x1 expand (ratio 6, skipped at ratio 1) ->
  3x3 depthwise (``feature_group_count = hidden``) -> 1x1 linear
  projection, residual add when stride 1 and matching channels;
* head 1x1 ConvBNReLU6 to 1280 -> global average pool -> Dropout(0.2) ->
  Linear. All activations are ReLU6 (clip at 6 preserves low-precision
  ranges — convenient for bf16 too).

Channel counts go through torchvision's ``_make_divisible`` (divisor 8).
Init matches: conv kernels kaiming-normal fan-out, BN 1/0, classifier
N(0, 0.01) with zero bias. Parameter count (3,504,872) locked in
tests/test_models.py.
"""

from functools import partial
from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from dptpu.models.layers import kaiming_normal_fan_out
from dptpu.models.registry import register_model

# (expand_ratio, out_channels, repeats, first_stride)
_SETTINGS = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:  # never round down by more than 10%
        new_v += divisor
    return int(new_v)


class InvertedResidual(nn.Module):
    out_ch: int
    stride: int
    expand_ratio: int
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x):
        inp = x.shape[-1]
        hidden = int(round(inp * self.expand_ratio))
        y = x
        idx = 0
        if self.expand_ratio != 1:
            y = self.conv(hidden, (1, 1), name=f"conv_{idx}")(y)
            y = self.norm(name=f"bn_{idx}")(y)
            y = nn.relu6(y)
            idx += 1
        y = self.conv(
            hidden, (3, 3),
            strides=(self.stride, self.stride),
            padding=((1, 1), (1, 1)),
            feature_group_count=hidden,
            name=f"conv_{idx}",
        )(y)
        y = self.norm(name=f"bn_{idx}")(y)
        y = nn.relu6(y)
        y = self.conv(self.out_ch, (1, 1), name=f"conv_{idx + 1}")(y)
        y = self.norm(name=f"bn_{idx + 1}")(y)
        if self.stride == 1 and inp == self.out_ch:
            y = (x + y).astype(y.dtype)
        return y


class MobileNetV2(nn.Module):
    num_classes: int = 1000
    width_mult: float = 1.0
    dropout_rate: float = 0.2
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    bn_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=kaiming_normal_fan_out,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        in_ch = _make_divisible(32 * self.width_mult)
        last_ch = _make_divisible(1280 * max(1.0, self.width_mult))
        x = conv(in_ch, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                 name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu6(x)
        block = 0
        for t, c, n, s in _SETTINGS:
            out_ch = _make_divisible(c * self.width_mult)
            for i in range(n):
                x = InvertedResidual(
                    out_ch=out_ch,
                    stride=s if i == 0 else 1,
                    expand_ratio=t,
                    conv=conv,
                    norm=norm,
                    name=f"block{block}",
                )(x)
                block += 1
        x = conv(last_ch, (1, 1), name="head_conv")(x)
        x = norm(name="head_bn")(x)
        x = nn.relu6(x)
        x = x.mean(axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.normal(0.01),
            bias_init=nn.initializers.zeros,
            name="classifier",
        )(x)
        return x


@register_model
def mobilenet_v2(**kw):
    return MobileNetV2(**kw)
