"""ResNet family (18/34/50/101/152), torchvision-architecture-exact, NHWC.

In the reference these come from ``torchvision.models.resnet*``
(imagenet_ddp.py:108-114; canonical arch is resnet50, imagenet_ddp.py:26-30).
This is a fresh Flax implementation matching torchvision's architecture
bit-for-bit in structure (verified by parameter count in
tests/test_models.py):

* 7×7/2 stem conv (no bias) → BN → ReLU → 3×3/2 max pool.
* BasicBlock (18/34) / Bottleneck (50/101/152) with expansion 4; the stride
  lives on the 3×3 conv (torchvision's ResNet "v1.5" placement).
* 1×1-conv + BN downsample on the first block of stages 2-4.
* Global average pool → Dense classifier.

TPU-first choices: NHWC layout (MXU-friendly, channels minor), a ``dtype``
compute policy (bf16 replaces Apex AMP, imagenet_ddp_apex.py:169-172) with
BatchNorm *statistics* always accumulated in fp32 (flax promotes the
reductions) while BN activation I/O follows the compute dtype unless
``bn_dtype=float32`` pins it (the strict ``keep_batchnorm_fp32`` analog,
imagenet_ddp_apex.py:93 — fp32 BN I/O between bf16 convs costs ~25%
throughput in extra HBM traffic), and an optional ``bn_axis_name`` that turns on
cross-replica (sync) BN via ``lax.pmean`` inside ``shard_map`` — the
``apex.parallel.convert_syncbn_model`` analog (imagenet_ddp_apex.py:146-148).
``bn_axis_name=None`` (default) keeps per-replica batch statistics, matching
DDP's default non-synced BN.
"""

from functools import partial
from typing import Any, Callable, Optional, Sequence, Type

import jax.numpy as jnp
from flax import linen as nn
from jax import lax

from dptpu.models.layers import (
    FusedBNReLUPool,
    kaiming_normal_fan_out,
    max_pool_same_as_torch,
    torch_default_bias_init,
    torch_default_kernel_init,
)
from dptpu.models.registry import register_model


class BasicBlock(nn.Module):
    planes: int
    stride: int
    conv: Callable
    norm: Callable
    expansion: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(
            self.planes,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=((1, 1), (1, 1)),
            name="conv1",
        )(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = self.conv(self.planes, (3, 3), padding=((1, 1), (1, 1)), name="conv2")(y)
        y = self.norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.planes * self.expansion,
                (1, 1),
                strides=(self.stride, self.stride),
                name="downsample_conv",
            )(x)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu((residual + y).astype(y.dtype))


class Bottleneck(nn.Module):
    planes: int
    stride: int
    conv: Callable
    norm: Callable
    expansion: int = 4
    # torchvision's width generalization: the 1x1/3x3 pair runs at
    # int(planes * base_width / 64) * groups channels, the 3x3 grouped —
    # (64, 1) is plain ResNet, (128, 1) wide_resnet*_2, (4, 32)
    # resnext50_32x4d, (8, 32) resnext101_32x8d
    base_width: int = 64
    groups: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        width = int(self.planes * self.base_width / 64) * self.groups
        y = self.conv(width, (1, 1), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        # stride on the 3x3 conv: torchvision ResNet v1.5
        y = self.conv(
            width,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=((1, 1), (1, 1)),
            feature_group_count=self.groups,
            name="conv2",
        )(y)
        y = self.norm(name="bn2")(y)
        y = nn.relu(y)
        y = self.conv(self.planes * self.expansion, (1, 1), name="conv3")(y)
        y = self.norm(name="bn3")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.planes * self.expansion,
                (1, 1),
                strides=(self.stride, self.stride),
                name="downsample_conv",
            )(x)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu((residual + y).astype(y.dtype))


class _Stem(nn.Module):
    """The 7×7/2 stem conv, with an optional space-to-depth fast path.

    The parameter is ALWAYS the torchvision-shaped ``kernel [7,7,3,64]``
    (checkpoints interchange freely between modes); in ``space_to_depth``
    mode the input is rearranged into 2×2 blocks ([B,224,224,3] →
    [B,116,116,12] after padding) and the kernel is zero-padded to 8×8 and
    folded to [4,4,12,64] *inside the compiled step* — mathematically
    identical output, but the MXU sees 12 input channels and a dense
    stride-1 conv instead of a 3-channel stride-2 one (3/128 lane
    occupancy), the standard TPU ResNet stem optimization.
    """

    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", kaiming_normal_fan_out, (7, 7, 3, 64), self.param_dtype
        ).astype(self.dtype)
        x = x.astype(self.dtype)
        dn = ("NHWC", "HWIO", "NHWC")
        if not self.space_to_depth:
            return lax.conv_general_dilated(
                x, kernel, (2, 2), ((3, 3), (3, 3)), dimension_numbers=dn
            )
        b, h, w, c = x.shape
        if h % 2 or w % 2:
            raise ValueError(
                f"space-to-depth stem requires even input H/W, got {h}x{w}"
            )
        # pad to the conv's receptive field, rounded up even for 2×2 blocks
        xp = jnp.pad(x, ((0, 0), (3, 5), (3, 5), (0, 0)))
        hp, wp = h + 8, w + 8
        xp = xp.reshape(b, hp // 2, 2, wp // 2, 2, c)
        xp = xp.transpose(0, 1, 3, 2, 4, 5).reshape(b, hp // 2, wp // 2, 4 * c)
        k = jnp.pad(kernel, ((0, 1), (0, 1), (0, 0), (0, 0)))  # 7→8, zeros
        k = k.reshape(4, 2, 4, 2, c, 64)
        k = k.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, 64)
        out = lax.conv_general_dilated(
            xp, k, (1, 1), "VALID", dimension_numbers=dn
        )
        # the extra tail position exists only because of even-size padding
        return out[:, : (h + 6 - 7) // 2 + 1, : (w + 6 - 7) // 2 + 1, :]


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Type[nn.Module]
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    # BN I/O dtype. None → follow ``dtype``. Statistics/params stay fp32
    # either way (flax promotes reductions to f32), so this only controls
    # whether activations round-trip through f32 between bf16 convs —
    # keeping it bf16 preserves XLA fusion and halves BN HBM traffic while
    # retaining the keep_batchnorm_fp32 guarantee where it matters (the
    # running statistics and learned scale/shift).
    bn_dtype: Optional[Any] = None
    # space-to-depth stem (see _Stem): identical math + identical params,
    # faster on MXU. Requires even input H/W.
    stem_space_to_depth: bool = False
    # fused stem pool: run bn1 -> relu -> maxpool as the custom-VJP region
    # of dptpu.ops.fused_stem (Pallas kernels on TPU). Identical params and
    # batch_stats (checkpoints interchange); activation numerics shift by
    # <= 1 ulp because the affine folds the statistics before multiplying.
    # Opt-in (DPTPU_FUSED_STEM=1): correct and parity-tested, but measured
    # slower than XLA's native stem on v5e Mosaic — see PERF.md.
    fused_stem: bool = False
    # Bottleneck width generalization (see Bottleneck): plain ResNet is
    # (64, 1); wide_resnet*_2 use base_width 128; resnext* use groups 32.
    base_width: int = 64
    groups: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=kaiming_normal_fan_out,
        )
        bn_momentum = 0.9  # torch BN momentum 0.1 == flax EMA decay 0.9
        bn_epsilon = 1e-5
        bn_io_dtype = self.bn_dtype if self.bn_dtype is not None else self.dtype
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=bn_momentum,
            epsilon=bn_epsilon,
            dtype=bn_io_dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        x = _Stem(
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            space_to_depth=self.stem_space_to_depth,
            name="conv1",
        )(x)
        if self.fused_stem:
            x = FusedBNReLUPool(
                use_running_average=not train,
                momentum=bn_momentum,
                epsilon=bn_epsilon,
                axis_name=self.bn_axis_name,
                dtype=bn_io_dtype,
                name="bn1",
            )(x)
        else:
            x = norm(name="bn1")(x)
            x = nn.relu(x)
            x = max_pool_same_as_torch(x, 3, 2, 1)
        if self.block_cls is Bottleneck:
            width_kw = {"base_width": self.base_width, "groups": self.groups}
        else:
            if self.groups != 1 or self.base_width != 64:
                # torchvision raises the same way: BasicBlock has no width
                # generalization (only Bottleneck archs are wide/grouped)
                raise ValueError(
                    "BasicBlock only supports groups=1 and base_width=64"
                )
            width_kw = {}
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                x = self.block_cls(
                    planes=64 * 2**i,
                    stride=2 if i > 0 and j == 0 else 1,
                    conv=conv,
                    norm=norm,
                    name=f"layer{i + 1}_block{j}",
                    **width_kw,
                )(x)
        x = x.mean(axis=(1, 2))  # AdaptiveAvgPool2d((1,1)) + flatten
        fan_in = x.shape[-1]
        x = nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=torch_default_kernel_init,
            bias_init=torch_default_bias_init(fan_in),
            name="fc",
        )(x)
        return x


def _resnet(stage_sizes, block_cls, **kwargs):
    return ResNet(stage_sizes=stage_sizes, block_cls=block_cls, **kwargs)


@register_model
def resnet18(**kw):
    return _resnet([2, 2, 2, 2], BasicBlock, **kw)


@register_model
def resnet34(**kw):
    return _resnet([3, 4, 6, 3], BasicBlock, **kw)


@register_model
def resnet50(**kw):
    return _resnet([3, 4, 6, 3], Bottleneck, **kw)


@register_model
def resnet101(**kw):
    return _resnet([3, 4, 23, 3], Bottleneck, **kw)


@register_model
def resnet152(**kw):
    return _resnet([3, 8, 36, 3], Bottleneck, **kw)


@register_model
def wide_resnet50_2(**kw):
    return _resnet([3, 4, 6, 3], Bottleneck, base_width=128, **kw)


@register_model
def wide_resnet101_2(**kw):
    return _resnet([3, 4, 23, 3], Bottleneck, base_width=128, **kw)


@register_model
def resnext50_32x4d(**kw):
    return _resnet([3, 4, 6, 3], Bottleneck, base_width=4, groups=32, **kw)


@register_model
def resnext101_32x8d(**kw):
    return _resnet([3, 4, 23, 3], Bottleneck, base_width=8, groups=32, **kw)
