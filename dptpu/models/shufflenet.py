"""ShuffleNetV2 (x0_5 / x1_0 / x1_5 / x2_0), torchvision-exact, NHWC.

Discovered via the registry like the rest of the zoo
(imagenet_ddp.py:19-21, e.g. ``-a shufflenet_v2_x1_0``). Fresh Flax build
of torchvision's ``shufflenetv2.py``:

* stem 3x3/2 conv (24) BN ReLU -> 3x3/2 max pool;
* three stages of (4, 8, 4) units. A stride-2 unit runs both branches on
  the full input (branch1: dw3x3/2 + pw; branch2: pw + dw3x3/2 + pw) and
  concatenates; a stride-1 unit splits channels in half, transforms one
  half, concatenates back. Every unit ends with channel_shuffle(groups=2)
  — in NHWC that is a reshape/transpose on the minor dim, which XLA folds
  into the surrounding ops;
* 1x1 conv to the final width -> global average pool -> fc.

torchvision applies no custom init here, so convs (bias-free) and the fc
use torch defaults (kaiming-uniform(a=sqrt 5) == U(+-1/sqrt fan_in)).
Param counts locked in tests/test_models.py (x1_0 = 2,278,604).
"""

from functools import partial
from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from dptpu.models.layers import (
    max_pool_same_as_torch,
    torch_default_bias_init,
    torch_default_kernel_init,
)
from dptpu.models.registry import register_model

_STAGE_REPEATS = (4, 8, 4)
_STAGE_OUT = {
    "x0_5": (24, 48, 96, 192, 1024),
    "x1_0": (24, 116, 232, 464, 1024),
    "x1_5": (24, 176, 352, 704, 1024),
    "x2_0": (24, 244, 488, 976, 2048),
}


def channel_shuffle(x, groups: int = 2):
    b, h, w, c = x.shape
    x = x.reshape(b, h, w, groups, c // groups)
    x = x.transpose(0, 1, 2, 4, 3)
    return x.reshape(b, h, w, c)


class ShuffleUnit(nn.Module):
    out_ch: int
    stride: int
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x):
        branch_ch = self.out_ch // 2
        if self.stride == 1:
            x1, x2 = jnp.split(x, 2, axis=-1)
        else:
            x1 = x2 = x
            # branch1 only exists for stride-2 units
            b1 = self.conv(
                x1.shape[-1], (3, 3), strides=(self.stride, self.stride),
                padding=((1, 1), (1, 1)), feature_group_count=x1.shape[-1],
                name="branch1_dw",
            )(x1)
            b1 = self.norm(name="branch1_dw_bn")(b1)
            b1 = self.conv(branch_ch, (1, 1), name="branch1_pw")(b1)
            b1 = self.norm(name="branch1_pw_bn")(b1)
            x1 = nn.relu(b1)

        y = self.conv(branch_ch, (1, 1), name="branch2_pw1")(x2)
        y = self.norm(name="branch2_pw1_bn")(y)
        y = nn.relu(y)
        y = self.conv(
            branch_ch, (3, 3), strides=(self.stride, self.stride),
            padding=((1, 1), (1, 1)), feature_group_count=branch_ch,
            name="branch2_dw",
        )(y)
        y = self.norm(name="branch2_dw_bn")(y)
        y = self.conv(branch_ch, (1, 1), name="branch2_pw2")(y)
        y = self.norm(name="branch2_pw2_bn")(y)
        y = nn.relu(y)
        return channel_shuffle(jnp.concatenate([x1, y], axis=-1))


class ShuffleNetV2(nn.Module):
    width: str = "x1_0"
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    bn_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=torch_default_kernel_init,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        chans = _STAGE_OUT[self.width]
        x = conv(chans[0], (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                 name="conv1")(x)
        x = norm(name="conv1_bn")(x)
        x = nn.relu(x)
        x = max_pool_same_as_torch(x, 3, 2, 1)
        for stage, repeats in enumerate(_STAGE_REPEATS):
            out_ch = chans[stage + 1]
            for i in range(repeats):
                x = ShuffleUnit(
                    out_ch=out_ch,
                    stride=2 if i == 0 else 1,
                    conv=conv,
                    norm=norm,
                    name=f"stage{stage + 2}_unit{i}",
                )(x)
        x = conv(chans[4], (1, 1), name="conv5")(x)
        x = norm(name="conv5_bn")(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        x = nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=torch_default_kernel_init,
            bias_init=torch_default_bias_init(chans[4]),
            name="fc",
        )(x)
        return x


@register_model
def shufflenet_v2_x0_5(**kw):
    return ShuffleNetV2(width="x0_5", **kw)


@register_model
def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(width="x1_0", **kw)


@register_model
def shufflenet_v2_x1_5(**kw):
    return ShuffleNetV2(width="x1_5", **kw)


@register_model
def shufflenet_v2_x2_0(**kw):
    return ShuffleNetV2(width="x2_0", **kw)
