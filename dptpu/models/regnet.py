"""RegNet X / Y families, torchvision-architecture-exact, NHWC.

Registry-discoverable (imagenet_ddp.py:19-21, ``-a regnet_y_400mf``).
Fresh Flax build of torchvision's ``regnet.py`` (the pycls "Designing
Network Design Spaces" recipe):

* stage widths/depths are GENERATED, not tabulated: a linear width ramp
  ``w_0 + w_a * j`` is quantized onto the log grid ``w_0 * w_m^k``,
  snapped to multiples of 8, and consecutive equal widths merge into
  stages; widths are then rounded to be divisible by the (possibly
  clamped) group width;
* stem 3x3/2 conv(32) BN ReLU; every stage opens with a stride-2 block;
* ResBottleneckBlock: 1x1 conv BN ReLU -> 3x3 GROUP conv BN ReLU ->
  optional squeeze-excitation (Y models, reduce to
  ``round(0.25 * block_input)``, ReLU -> sigmoid) -> 1x1 conv BN, with a
  1x1/stride-2 BN projection shortcut whenever shape changes, ReLU after
  the residual add;
* head: global average pool -> Linear.

Init matches torchvision: convs N(0, sqrt(2/(k*k*out))) (== kaiming
fan-out), BN 1/0, Linear N(0, 0.01) with zero bias. Param counts locked
in tests/test_models.py.
"""

import math
from functools import partial
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from dptpu.models.layers import SqueezeExcite, kaiming_normal_fan_out
from dptpu.models.mobilenet import _make_divisible
from dptpu.models.registry import register_variants

# name -> (depth, w_0, w_a, w_m, group_width, se_ratio)
_VARIANTS = {
    "x_400mf": (22, 24, 24.48, 2.54, 16, None),
    "x_800mf": (16, 56, 35.73, 2.28, 16, None),
    "x_1_6gf": (18, 80, 34.01, 2.25, 24, None),
    "x_3_2gf": (25, 88, 26.31, 2.25, 48, None),
    "x_8gf": (23, 80, 49.56, 2.88, 120, None),
    "x_16gf": (22, 216, 55.59, 2.1, 128, None),
    "x_32gf": (23, 320, 69.86, 2.0, 168, None),
    "y_400mf": (16, 48, 27.89, 2.09, 8, 0.25),
    "y_800mf": (14, 56, 38.84, 2.4, 16, 0.25),
    "y_1_6gf": (27, 48, 20.71, 2.65, 24, 0.25),
    "y_3_2gf": (21, 80, 42.63, 2.66, 24, 0.25),
    "y_8gf": (17, 192, 76.82, 2.19, 56, 0.25),
    "y_16gf": (18, 200, 106.23, 2.48, 112, 0.25),
    "y_32gf": (20, 232, 115.89, 2.53, 232, 0.25),
    "y_128gf": (27, 456, 160.83, 2.52, 264, 0.25),
}


def stage_params(variant: str):
    """[(width, depth, group_width)] per stage — torchvision's
    ``BlockParams.from_init_params`` + group-compatibility adjustment."""
    depth, w_0, w_a, w_m, group, _ = _VARIANTS[variant]
    ramp = w_0 + w_a * np.arange(depth)
    k = np.round(np.log(ramp / w_0) / math.log(w_m))
    widths = (np.round(w_0 * np.power(w_m, k) / 8) * 8).astype(int)
    stages = []  # consecutive equal widths merge into one stage
    for w in widths:
        if stages and stages[-1][0] == w:
            stages[-1][1] += 1
        else:
            stages.append([int(w), 1])
    out = []
    for w, d in stages:
        g = min(group, w)  # bottleneck_multiplier = 1: w_bot == w
        out.append((_make_divisible(w, g), d, g))
    return out


class ResBottleneckBlock(nn.Module):
    w_in: int
    w_out: int
    stride: int
    group_width: int
    se_ratio: Optional[float]
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x):
        shortcut = x
        if self.w_in != self.w_out or self.stride != 1:
            shortcut = self.conv(
                self.w_out, (1, 1), strides=(self.stride, self.stride),
                name="proj",
            )(x)
            shortcut = self.norm(name="proj_bn")(shortcut)
        y = self.conv(self.w_out, (1, 1), name="a")(x)
        y = nn.relu(self.norm(name="a_bn")(y))
        y = self.conv(
            self.w_out, (3, 3), strides=(self.stride, self.stride),
            padding=((1, 1), (1, 1)),
            feature_group_count=self.w_out // self.group_width, name="b",
        )(y)
        y = nn.relu(self.norm(name="b_bn")(y))
        if self.se_ratio is not None:
            y = SqueezeExcite(
                reduced=int(round(self.se_ratio * self.w_in)),
                conv=self.conv, act=nn.relu, gate=nn.sigmoid, name="se",
            )(y)
        y = self.conv(self.w_out, (1, 1), name="c")(y)
        y = self.norm(name="c_bn")(y)
        return nn.relu((shortcut + y).astype(y.dtype))


class RegNet(nn.Module):
    variant: str = "y_400mf"
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    bn_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=kaiming_normal_fan_out,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        se_ratio = _VARIANTS[self.variant][5]
        x = conv(32, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                 name="stem_conv")(x)
        x = nn.relu(norm(name="stem_bn")(x))
        w_in = 32
        for si, (w, d, g) in enumerate(stage_params(self.variant)):
            for bi in range(d):
                x = ResBottleneckBlock(
                    w_in=w_in if bi == 0 else w, w_out=w,
                    stride=2 if bi == 0 else 1, group_width=g,
                    se_ratio=se_ratio, conv=conv, norm=norm,
                    name=f"stage{si}_block{bi}",
                )(x)
            w_in = w
        x = x.mean(axis=(1, 2))
        return nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.normal(0.01),
            bias_init=nn.initializers.zeros,
            name="fc",
        )(x)


register_variants(RegNet, "regnet", _VARIANTS)
