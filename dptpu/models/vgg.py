"""VGG family (11/13/16/19, ± BatchNorm), torchvision-architecture-exact, NHWC.

Reference uses ``torchvision.models.vgg*`` via the arch registry
(imagenet_ddp.py:19-21,108-114); BASELINE.md config 4 exercises VGG-16 with
lr=0.01 (the no-BN path — the same reason nd_imagenet.py:163-169 wraps only
``model.features`` in DataParallel for these nets). Configs A/B/D/E are the
standard torchvision tables; classifier is 512·7·7 → 4096 → 4096 → classes
with dropout. Init matches torchvision's ``_initialize_weights``:
kaiming-normal(fan_out) convs with zero bias, N(0, 0.01) classifier kernels
with zero bias, BN γ=1/β=0. Parameter counts are locked in
tests/test_models.py.
"""

from typing import Any, Optional, Sequence, Union

import jax.numpy as jnp
from flax import linen as nn

from dptpu.models.layers import (
    adaptive_avg_pool,
    kaiming_normal_fan_out,
    max_pool_same_as_torch,
)
from dptpu.models.registry import register_model

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]]
    batch_norm: bool = False
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    # BN I/O dtype; None → follow ``dtype``. Statistics stay fp32 (flax
    # promotes reductions), matching keep_batchnorm_fp32 where it matters.
    bn_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        layer_idx = 0
        for v in self.cfg:
            if v == "M":
                x = max_pool_same_as_torch(x, 2, 2, 0)
                layer_idx += 1
                continue
            x = nn.Conv(
                v,
                (3, 3),
                padding=((1, 1), (1, 1)),
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=kaiming_normal_fan_out,
                bias_init=nn.initializers.zeros,
                name=f"features_{layer_idx}",
            )(x)
            layer_idx += 1
            if self.batch_norm:
                x = nn.BatchNorm(
                    use_running_average=not train,
                    momentum=0.9,
                    epsilon=1e-5,
                    dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
                    param_dtype=jnp.float32,
                    axis_name=self.bn_axis_name,
                    name=f"features_{layer_idx}",
                )(x)
                layer_idx += 1
            x = nn.relu(x)
            layer_idx += 1  # the ReLU slot in torchvision's Sequential numbering
        x = adaptive_avg_pool(x, 7)
        x = x.reshape((x.shape[0], -1))
        dense = lambda features, name: nn.Dense(  # noqa: E731
            features,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.normal(stddev=0.01),
            bias_init=nn.initializers.zeros,
            name=name,
        )
        x = dense(4096, "classifier_0")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = dense(4096, "classifier_3")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = dense(self.num_classes, "classifier_6")(x)
        return x


def _vgg(cfg, batch_norm, **kw):
    return VGG(cfg=tuple(_CFGS[cfg]), batch_norm=batch_norm, **kw)


@register_model
def vgg11(**kw):
    return _vgg("A", False, **kw)


@register_model
def vgg11_bn(**kw):
    return _vgg("A", True, **kw)


@register_model
def vgg13(**kw):
    return _vgg("B", False, **kw)


@register_model
def vgg13_bn(**kw):
    return _vgg("B", True, **kw)


@register_model
def vgg16(**kw):
    return _vgg("D", False, **kw)


@register_model
def vgg16_bn(**kw):
    return _vgg("D", True, **kw)


@register_model
def vgg19(**kw):
    return _vgg("E", False, **kw)


@register_model
def vgg19_bn(**kw):
    return _vgg("E", True, **kw)
