"""Pretrained-weight loading: torchvision state dicts -> dptpu variables.

The reference exposes ``--pretrained`` by constructing
``models.__dict__[arch](pretrained=True)`` (imagenet_ddp.py:30-31,109-111),
which downloads torchvision weights. This environment has no network, so
dptpu splits the feature into two halves:

* an **offline converter** (``python -m dptpu.tools.convert_torchvision``)
  that reads a torchvision checkpoint (``.pth`` via torch's CPU unpickler,
  or an ``.npz`` of numpy arrays keyed by torch names) and writes
  ``<dir>/<arch>.npz`` in dptpu's native layout;
* a **runtime loader** with zero torch dependency: ``--pretrained`` finds
  ``<arch>.npz`` under ``$DPTPU_PRETRAINED_DIR`` (default ``./pretrained``)
  and initializes the train state from it.

Key mapping covers every in-tree family. dptpu module names intentionally
mirror torchvision's (``features_3`` <-> ``features.3``,
``layer1_block0`` <-> ``layer1.0``), so the map is mechanical:

=========== ==========================  =============================
collection  dptpu leaf                  torch leaf
=========== ==========================  =============================
params      ``kernel`` (conv, HWIO)     ``weight`` (OIHW, transposed)
params      ``kernel`` (dense, IO)      ``weight`` (OI, transposed)
params      ``scale`` (BN)              ``weight``
params      ``bias``                    ``bias``
batch_stats ``mean`` / ``var``          ``running_mean`` / ``running_var``
=========== ==========================  =============================

``num_batches_tracked`` buffers are dropped (dptpu's schedules are pure
functions of the global step).

One transpose subtlety: a Linear that consumes a *flattened conv map*
(alexnet/vgg first classifier, googlenet aux fc1) sees CHW-ordered inputs
in torch but HWC-ordered inputs here, so its kernel needs a spatial
permutation, not just the OI->IO transpose — handled by the
``dense_chw`` kinds below (shapes alone would silently match).

Fidelity evidence (``scripts/check_tv_parity.py``, committed as
TV_PARITY.json): the conversion round-trips at LOGIT level exactly —
dptpu params -> torch layout (``_to_torch``) -> back through
``convert_state_dict`` -> forward gives ``max|Δlogit| = 0.0`` for
resnet50, vit_b_16 and swin_t (every permute/transpose kind inverts
bit-exactly), and the val pipeline is pixel-exact to torchvision's
``Resize(256)→CenterCrop(224)`` (±1 LSB; dptpu/data/transforms.py).
Run the harness where torch+torchvision exist for the published-weight
cross-framework ``max|Δlogit|`` / top-1-agreement numbers per arch.
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

import jax
import numpy as np

_LEAF_TO_TORCH = {
    "kernel": "weight",
    "scale": "weight",
    "bias": "bias",
    "mean": "running_mean",
    "var": "running_var",
}

# torchvision squeezenet Sequential indices of fire modules, per version
_SQUEEZE_FIRE_IDX = {
    "1_0": {2: 3, 3: 4, 4: 5, 5: 7, 6: 8, 7: 9, 8: 10, 9: 12},
    "1_1": {2: 3, 3: 4, 4: 6, 5: 7, 6: 9, 7: 10, 8: 11, 9: 12},
}


def _vit_torch_module(mod: Tuple[str, ...]) -> str:
    """ViT paths. torch: conv_proj, raw class_token /
    encoder.pos_embedding Parameters, encoder.layers.encoder_layer_{i}
    with ln_1 / self_attention (raw fused in_proj_weight + out_proj
    Linear) / ln_2 / mlp (Sequential: Linears at 0 and 3), encoder.ln,
    heads.head. "{}"-bearing returns are formatted with the torch leaf
    name by torch_key_map (raw-Parameter keys have no ".weight" suffix).
    """
    if not mod:
        return "{}"  # class_token
    if mod[0] in ("conv_proj", "head"):
        return {"conv_proj": "conv_proj", "head": "heads.head"}[mod[0]]
    if len(mod) == 1:
        return "encoder.{}"  # pos_embedding
    if mod[1] == "ln":
        return "encoder.ln"
    base = f"encoder.layers.{mod[1]}"
    sub = mod[2]
    if sub == "self_attention":
        if mod[3] == "in_proj":
            return f"{base}.self_attention.in_proj_{{}}"
        return f"{base}.self_attention.out_proj"
    m = {"ln_1": "ln_1", "ln_2": "ln_2", "mlp_1": "mlp.0", "mlp_2": "mlp.3"}
    return f"{base}.{m[sub]}"


def _torch_module(arch: str, mod: Tuple[str, ...]) -> str:
    """Map a dptpu module path (tuple of names) to the torch module path."""
    if arch.startswith("vit_"):
        return _vit_torch_module(mod)
    head = mod[0]
    if arch.startswith(("resnet", "wide_resnet", "resnext")):
        if head.startswith("layer"):
            layer, block = head.split("_block")
            sub = {"downsample_conv": "downsample.0",
                   "downsample_bn": "downsample.1"}.get(mod[1], mod[1])
            return f"{layer}.{block}.{sub}"
        return head  # conv1 / bn1 / fc
    if arch == "alexnet" or arch.startswith("vgg"):
        prefix, idx = head.rsplit("_", 1)
        return f"{prefix}.{idx}"
    if arch.startswith("densenet"):
        if head in ("conv0", "norm0", "norm5"):
            return f"features.{head}"
        if head.startswith("denseblock"):
            block, layer = head.split("_layer")
            return f"features.{block}.denselayer{layer}.{mod[1]}"
        if head.startswith("transition"):
            return f"features.{head}.{mod[1]}"
        return head  # classifier
    if arch.startswith("mobilenet_v3"):
        from dptpu.models.mobilenet_v3 import _LARGE, _SMALL

        table = _LARGE if arch.endswith("large") else _SMALL
        if head == "stem_conv":
            return "features.0.0"
        if head == "stem_bn":
            return "features.0.1"
        if head == "head_conv":
            return f"features.{len(table) + 1}.0"
        if head == "head_bn":
            return f"features.{len(table) + 1}.1"
        if head == "pre_classifier":
            return "classifier.0"
        if head == "classifier":
            return "classifier.3"
        # blocks: torch wraps each stage in a .block Sequential whose
        # indices depend on whether expand and SE exist
        k = int(head[5:])
        kernel, expanded, out, use_se, act, stride = table[k]
        inp = 16 if k == 0 else table[k - 1][2]
        has_expand = expanded != inp
        d = 1 if has_expand else 0  # depthwise position
        se_pos, proj = d + 1, d + 1 + (1 if use_se else 0)
        sub = mod[1]
        m = {"expand": "block.0.0", "expand_bn": "block.0.1",
             "dw": f"block.{d}.0", "dw_bn": f"block.{d}.1",
             "project": f"block.{proj}.0", "project_bn": f"block.{proj}.1"}
        if sub == "se":
            return f"features.{k + 1}.block.{se_pos}.{mod[2]}"
        return f"features.{k + 1}.{m[sub]}"
    if arch == "mobilenet_v2":
        # torchvision Sequential: features.0 stem ConvBNReLU, features.1..17
        # inverted residuals, features.18 head, classifier.1 Linear
        if head == "stem_conv":
            return "features.0.0"
        if head == "stem_bn":
            return "features.0.1"
        if head == "head_conv":
            return "features.18.0"
        if head == "head_bn":
            return "features.18.1"
        if head.startswith("block"):
            k = int(head[5:])
            kind, i = mod[1].split("_")
            i = int(i)
            expand = k != 0  # only the first block runs expand_ratio 1
            if expand:
                sub = {("conv", 0): "conv.0.0", ("bn", 0): "conv.0.1",
                       ("conv", 1): "conv.1.0", ("bn", 1): "conv.1.1",
                       ("conv", 2): "conv.2", ("bn", 2): "conv.3"}[(kind, i)]
            else:
                sub = {("conv", 0): "conv.0.0", ("bn", 0): "conv.0.1",
                       ("conv", 1): "conv.1", ("bn", 1): "conv.2"}[(kind, i)]
            return f"features.{k + 1}.{sub}"
        return "classifier.1"
    if arch == "googlenet":
        # plain dotted join, with torchvision's branchN Sequential indices
        # (branch2_1 -> branch2.1); aux1/aux2 and conv1..3 join directly
        out = ".".join(mod)
        for b in ("branch2", "branch3", "branch4"):
            out = out.replace(f"{b}_", f"{b}.")
        return out
    if arch == "inception_v3":
        return ".".join(mod)  # names mirror torchvision module paths
    if arch.startswith("shufflenet_v2"):
        # torch: conv1/conv5 are Sequential(conv, bn); units are
        # stage{s}.{i} with branch1 = (dw, bn, pw, bn) and branch2 =
        # (pw, bn, relu, dw, bn, pw, bn, relu)
        if head in ("conv1", "conv5"):
            return f"{head}.0"
        if head in ("conv1_bn", "conv5_bn"):
            return f"{head[:5]}.1"
        if head == "fc":
            return "fc"
        stage, unit = head.split("_unit")
        sub = {"branch1_dw": "branch1.0", "branch1_dw_bn": "branch1.1",
               "branch1_pw": "branch1.2", "branch1_pw_bn": "branch1.3",
               "branch2_pw1": "branch2.0", "branch2_pw1_bn": "branch2.1",
               "branch2_dw": "branch2.3", "branch2_dw_bn": "branch2.4",
               "branch2_pw2": "branch2.5", "branch2_pw2_bn": "branch2.6"}[mod[1]]
        return f"{stage}.{unit}.{sub}"
    if arch.startswith("mnasnet"):
        # torch: one flat `layers` Sequential — 0/1 stem conv+bn, 3/4 sep
        # dw+bn, 6/7 sep pw+bn, 8..13 the six stacks of inverted residuals
        # (each block a Sequential named `layers` again), 14/15 head
        flat = {"stem_conv": "layers.0", "stem_bn": "layers.1",
                "sep_dw": "layers.3", "sep_dw_bn": "layers.4",
                "sep_pw": "layers.6", "sep_pw_bn": "layers.7",
                "head_conv": "layers.14", "head_bn": "layers.15",
                "classifier": "classifier.1"}
        if head in flat:
            return flat[head]
        k = int(head[5:])  # block index -> (stack, index-in-stack)
        repeats = (3, 3, 3, 2, 4, 1)
        stack = 0
        while k >= repeats[stack]:
            k -= repeats[stack]
            stack += 1
        sub = {"pw1": "layers.0", "pw1_bn": "layers.1",
               "dw": "layers.3", "dw_bn": "layers.4",
               "pw2": "layers.6", "pw2_bn": "layers.7"}[mod[1]]
        return f"layers.{8 + stack}.{k}.{sub}"
    if arch.startswith("squeezenet"):
        version = arch.split("squeezenet")[1]
        if head == "conv1":
            return "features.0"
        if head.startswith("fire"):
            idx = _SQUEEZE_FIRE_IDX[version][int(head[4:])]
            return f"features.{idx}.{mod[1]}"
        return "classifier.1"  # final_conv
    if arch.startswith("efficientnet"):
        # torch: features.0 stem, features.{s+1}.{i}.block.* stages (the
        # block Sequential's indices depend on expand/kind), features.{S+1}
        # head, classifier.1 Linear
        from dptpu.models.efficientnet import block_table

        stages = block_table(arch[len("efficientnet_"):])
        flat = {"stem_conv": "features.0.0", "stem_bn": "features.0.1",
                "head_conv": f"features.{len(stages) + 1}.0",
                "head_bn": f"features.{len(stages) + 1}.1",
                "classifier": "classifier.1"}
        if head in flat:
            return flat[head]
        si, bi = (int(x) for x in head[len("stage"):].split("_block"))
        kind, e, _, _, _, _ = stages[si][bi]
        sub = mod[1]
        if kind == "fused":
            m = {"fused": "block.0.0", "fused_bn": "block.0.1",
                 "project": "block.1.0", "project_bn": "block.1.1"}
            return f"features.{si + 1}.{bi}.{m[sub]}"
        d = 1 if e != 1 else 0  # depthwise position after optional expand
        if sub == "se":
            return f"features.{si + 1}.{bi}.block.{d + 1}.{mod[2]}"
        m = {"expand": "block.0.0", "expand_bn": "block.0.1",
             "dw": f"block.{d}.0", "dw_bn": f"block.{d}.1",
             "project": f"block.{d + 2}.0", "project_bn": f"block.{d + 2}.1"}
        return f"features.{si + 1}.{bi}.{m[sub]}"
    if arch.startswith("convnext"):
        # torch: features.0 stem (conv, LayerNorm2d), stages at odd
        # features indices with .block Sequential (dw conv 0, LN 2,
        # Linears 3/5) + raw layer_scale, downsamples (LN, conv) at even
        # indices, classifier (LN, Flatten, Linear)
        flat = {"stem_conv": "features.0.0", "stem_norm": "features.0.1",
                "head_norm": "classifier.0", "head": "classifier.2"}
        if head in flat:
            return flat[head]
        if head.startswith("downsample"):
            si = int(head[len("downsample"):head.index("_")])
            return f"features.{2 * si}.{0 if head.endswith('_norm') else 1}"
        si, bi = (int(v) for v in head[len("stage"):].split("_block"))
        base = f"features.{2 * si + 1}.{bi}"
        if len(mod) == 1:
            return base + ".{}"  # raw layer_scale Parameter
        m = {"dw": "block.0", "norm": "block.2",
             "mlp_1": "block.3", "mlp_2": "block.5"}
        return f"{base}.{m[mod[1]]}"
    if arch.startswith("swin"):
        # torch: features.0 patch embed (conv 0, Permute 1, LN 2),
        # stages at odd indices (norm1/norm2, attn with qkv/proj Linears
        # + raw relative_position_bias_table / logit_scale + cpb_mlp
        # Sequential, mlp Linears at 0/3), PatchMerging at even indices,
        # final norm + head
        flat = {"patch_conv": "features.0.0", "patch_norm": "features.0.2",
                "norm": "norm", "head": "head"}
        if head in flat:
            return flat[head]
        if head.startswith("merge"):
            si = int(head[len("merge"):])
            return f"features.{2 * si + 2}.{mod[1]}"
        si, bi = (int(v) for v in head[len("stage"):].split("_block"))
        base = f"features.{2 * si + 1}.{bi}"
        sub = mod[1]
        if sub == "attn":
            if len(mod) == 2:
                return f"{base}.attn.{{}}"  # raw rpb table / logit_scale
            m = {"qkv": "qkv", "proj": "proj",
                 "cpb_mlp_1": "cpb_mlp.0", "cpb_mlp_2": "cpb_mlp.2"}
            return f"{base}.attn.{m[mod[2]]}"
        m = {"norm1": "norm1", "norm2": "norm2",
             "mlp_1": "mlp.0", "mlp_2": "mlp.3"}
        return f"{base}.{m[sub]}"
    if arch == "maxvit_t":
        # torch: stem (two Conv2dNormActivations), blocks.{b}.layers.{l}
        # .layers with MBconv (nested .layers OrderedDict + .proj
        # shortcut) / window_attention / grid_attention (attn_layer 0=LN
        # 1=RelativePositionalMultiHeadAttention, mlp_layer Sequential),
        # classifier (pool, flatten, LN, Linear, Tanh, Linear)
        flat = {"stem_conv": "stem.0.0", "stem_bn": "stem.0.1",
                "stem_conv2": "stem.1.0", "head_norm": "classifier.2",
                "pre_head": "classifier.3", "head": "classifier.5"}
        if head in flat:
            return flat[head]
        b, l = head[len("block"):].split("_layer")
        base = f"blocks.{b}.layers.{l}.layers"
        sub = mod[1]
        if sub == "mbconv":
            mb = f"{base}.MBconv"
            if mod[2] == "proj":
                return f"{mb}.proj.1"  # avg-pool at proj.0 (stride 2)
            if mod[2] == "se":
                return f"{mb}.layers.squeeze_excitation.{mod[3]}"
            m = {"pre_norm": "layers.pre_norm",
                 "conv_a": "layers.conv_a.0", "conv_a_bn": "layers.conv_a.1",
                 "conv_b": "layers.conv_b.0", "conv_b_bn": "layers.conv_b.1",
                 "conv_c": "layers.conv_c"}
            return f"{mb}.{m[mod[2]]}"
        part = {"window_attn": "window_attention",
                "grid_attn": "grid_attention"}[sub]
        if len(mod) == 2:
            return f"{base}.{part}.attn_layer.1.{{}}"  # raw rpb table
        m = {"attn_norm": "attn_layer.0", "to_qkv": "attn_layer.1.to_qkv",
             "merge": "attn_layer.1.merge", "mlp_norm": "mlp_layer.0",
             "mlp_1": "mlp_layer.1", "mlp_2": "mlp_layer.3"}
        return f"{base}.{part}.{m[mod[2]]}"
    if arch.startswith("regnet"):
        # torch: stem Conv2dNormActivation, trunk_output.block{s+1} stages
        # of blocks named "block{s+1}-{i}", BottleneckTransform under .f
        # with a/b/se/c members, head Linear at fc
        flat = {"stem_conv": "stem.0", "stem_bn": "stem.1", "fc": "fc"}
        if head in flat:
            return flat[head]
        si, bi = (int(x) for x in head[len("stage"):].split("_block"))
        base = f"trunk_output.block{si + 1}.block{si + 1}-{bi}"
        sub = mod[1]
        if sub == "se":
            return f"{base}.f.se.{mod[2]}"
        m = {"proj": "proj.0", "proj_bn": "proj.1",
             "a": "f.a.0", "a_bn": "f.a.1", "b": "f.b.0", "b_bn": "f.b.1",
             "c": "f.c.0", "c_bn": "f.c.1"}
        return f"{base}.{m[sub]}"
    raise ValueError(f"no torchvision key mapping for arch {arch!r}")


def torch_key_map(arch: str, variables) -> Dict[str, Tuple[str, Tuple[str, ...], str]]:
    """``{torch_key: (collection, dptpu_path, kind)}`` for every leaf.

    ``kind`` is ``conv`` (4-D kernel, needs OIHW->HWIO), ``dense`` (2-D
    kernel, needs OI->IO) or ``direct``.
    """
    out = {}
    for collection in ("params", "batch_stats"):
        tree = variables.get(collection, {})
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        for path, leaf in flat:
            names = tuple(p.key for p in path)
            tmod = _torch_module(arch, names[:-1])
            if "{}" in tmod:
                # raw torch Parameters (ViT class_token / pos_embedding)
                # keep their own leaf name inside the "{}" template; all
                # other leaves stay on the strict whitelist
                tleaf = _LEAF_TO_TORCH.get(names[-1], names[-1])
            else:
                tleaf = _LEAF_TO_TORCH[names[-1]]
            if len(names) >= 2 and (
                (arch.startswith("vit_") and names[-2] == "in_proj")
                or (arch.startswith("swin") and names[-2] == "qkv")
            ):
                # fused qkv: torch stores [q|k|v]-major, dptpu stores
                # head-major (vit.py SelfAttention / swin.py _QKVDense
                # docstrings) — the converter permutes in addition to
                # the OI->IO transpose. Kind tag is "vit_qkv" for
                # historical reasons; it covers swin too.
                kind = ("vit_qkv", _qkv_heads(arch, names), names[-1])
            elif names[-1] == "kernel":
                if leaf.ndim == 4:
                    kind = "conv"
                else:
                    chw = _DENSE_CHW.get((arch.split("_bn")[0].rstrip("0123456789"), names[:-1])) \
                        or _DENSE_CHW.get((arch, names[:-1]))
                    kind = ("dense_chw", chw) if chw else "dense"
            elif names[-1] == "layer_scale":
                kind = "layer_scale"  # torch (C,1,1) <-> NHWC (C,)
            else:
                kind = "direct"
            key = tmod.format(tleaf) if "{}" in tmod else f"{tmod}.{tleaf}"
            assert key not in out, f"duplicate torch key {key}"
            out[key] = (collection, names, kind)
    return out


# Linears that consume a FLATTENED conv map: (family-or-arch, module path)
# -> the (C, H, W) the torch weight's input axis factorizes as. Flax
# flattens those maps HWC, torch flattens CHW, so these kernels need a
# spatial permutation on top of the OI->IO transpose.
_DENSE_CHW = {
    ("alexnet", ("classifier_1",)): (256, 6, 6),
    ("vgg", ("classifier_0",)): (512, 7, 7),
    ("googlenet", ("aux1", "fc1")): (128, 4, 4),
    ("googlenet", ("aux2", "fc1")): (128, 4, 4),
}


def _from_torch(arr: np.ndarray, kind) -> np.ndarray:
    arr = np.asarray(arr)
    if kind == "conv":
        return np.transpose(arr, (2, 3, 1, 0))  # OIHW -> HWIO
    if kind == "dense":
        return np.transpose(arr, (1, 0))  # OI -> IO
    if isinstance(kind, tuple) and kind[0] == "dense_chw":
        c, h, w = kind[1]
        o = arr.shape[0]
        # torch (O, C*H*W) -> flax (H*W*C, O): reorder the input axis to
        # the NHWC flatten order before transposing
        return np.transpose(
            arr.reshape(o, c, h, w), (2, 3, 1, 0)
        ).reshape(h * w * c, o)
    if kind == "layer_scale":
        return arr.reshape(-1)  # torch (C,1,1) -> NHWC (C,)
    if isinstance(kind, tuple) and kind[0] == "vit_qkv":
        _, heads, leaf = kind
        if leaf == "kernel":
            arr = np.transpose(arr, (1, 0))  # (3h, h) -> (h, 3h) [q|k|v]
        return qkv_permute(arr, heads, to_head_major=True)
    return arr


def _to_torch(arr: np.ndarray, kind) -> np.ndarray:
    arr = np.asarray(arr)
    if kind == "conv":
        return np.transpose(arr, (3, 2, 0, 1))  # HWIO -> OIHW
    if kind == "dense":
        return np.transpose(arr, (1, 0))
    if isinstance(kind, tuple) and kind[0] == "dense_chw":
        c, h, w = kind[1]
        o = arr.shape[-1]
        return np.transpose(
            arr.reshape(h, w, c, o), (3, 2, 0, 1)
        ).reshape(o, c * h * w)
    if kind == "layer_scale":
        return arr.reshape(-1, 1, 1)  # NHWC (C,) -> torch (C,1,1)
    if isinstance(kind, tuple) and kind[0] == "vit_qkv":
        _, heads, leaf = kind
        arr = qkv_permute(arr, heads, to_head_major=False)
        if leaf == "kernel":
            return np.transpose(arr, (1, 0))
        return arr
    return arr


def convert_state_dict(arch: str, state_dict: Dict[str, np.ndarray],
                       template_variables, kmap=None):
    """torch-keyed arrays -> dptpu ``{"params", "batch_stats"}`` variables.

    ``template_variables`` (from ``model.init``) fixes the tree structure
    and validates shapes. Raises on missing or mismatched keys so a wrong
    checkpoint fails loudly rather than half-loading. ``kmap`` accepts a
    precomputed ``torch_key_map(arch, template_variables)`` so callers
    that already built one (train/checkpoint.py) skip the rebuild.
    """
    if kmap is None:
        kmap = torch_key_map(arch, template_variables)
    out = {"params": {}, "batch_stats": {}}

    def set_path(tree, names, value):
        for n in names[:-1]:
            tree = tree.setdefault(n, {})
        tree[names[-1]] = value

    missing = [k for k in kmap if k not in state_dict]
    if missing:
        raise KeyError(
            f"state dict for {arch} is missing {len(missing)} keys, e.g. "
            f"{missing[:3]}"
        )
    flat_template = {
        (c, names): leaf
        for c in ("params", "batch_stats")
        for names, leaf in (
            (tuple(p.key for p in path), leaf)
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                template_variables.get(c, {}))[0]
        )
    }
    for key, (collection, names, kind) in kmap.items():
        arr = _from_torch(state_dict[key], kind).astype(np.float32)
        want = flat_template[(collection, names)].shape
        if tuple(arr.shape) != tuple(want):
            raise ValueError(
                f"{key}: converted shape {arr.shape} != expected {want}"
            )
        set_path(out[collection], names, arr)
    return out


# ---------------------------------------------------------------------------
# npz round trip + runtime resolution
# ---------------------------------------------------------------------------

# Layout versioning: fused-qkv columns are stored HEAD-MAJOR (see
# dptpu/models/vit.py SelfAttention / dptpu/models/swin.py _QKVDense).
# npz files and flax checkpoints record the layout marker; files whose
# marker predates a family's head-major switch are [q|k|v]-major for
# that family and get migrated on load. Same shapes either way, so the
# marker is the ONLY way to tell them apart. History: "head_major"
# covered ViT only (early round 4 — swin was still [q|k|v]-major under
# that marker); "head_major2" covers ViT + Swin.
QKV_LAYOUT = "head_major2"
# markers under which a family's qkv leaves are ALREADY head-major
_HEAD_MAJOR_MARKERS = {
    "vit_": ("head_major", "head_major2"),
    "swin": ("head_major2",),
}


def qkv_needs_migration(arch: str, marker) -> bool:
    """True when an artifact with layout ``marker`` (None/"" = unmarked,
    pre-round-4) stores ``arch``'s fused qkv in [q|k|v]-major order and
    must be permuted to head-major on load."""
    for prefix, ok in _HEAD_MAJOR_MARKERS.items():
        if arch.startswith(prefix):
            return marker not in ok
    return False


def _qkv_heads(arch: str, names) -> int:
    """Head count of the fused-qkv leaf at tree path ``names`` — fixed
    per arch for ViT, per STAGE for Swin (the stage index is parsed from
    the ``stage{si}_block{bi}`` path element)."""
    if arch.startswith("vit_"):
        from dptpu.models.vit import _VARIANTS

        return _VARIANTS[arch[len("vit_"):]][2]
    from dptpu.models.swin import _VARIANTS

    stage = next(n for n in names if str(n).startswith("stage"))
    si = int(str(stage)[len("stage"):].split("_block")[0])
    return _VARIANTS[arch[len("swin_"):]][2][si]


def qkv_permute(arr: np.ndarray, heads: int, *, to_head_major: bool):
    """The ONE definition of the qkv column permutation, used by the
    torch converters and the legacy-layout migrations alike.

    The fused projection's output axis (size 3h) factors as
    ``(3, heads, hd)`` in [q|k|v]-major order and ``(heads, 3, hd)`` in
    head-major order; this swaps the two leading factors in whichever
    direction is asked. Works on the kernel's last axis (h, 3h) and the
    bias (3h,)."""
    lead = arr.shape[:-1]
    n3h = arr.shape[-1]
    h = n3h // 3
    a, b = ((3, heads) if to_head_major else (heads, 3))
    ndim = len(lead)
    perm = tuple(range(ndim)) + (ndim + 1, ndim, ndim + 2)
    return arr.reshape(lead + (a, b, h // heads)).transpose(perm).reshape(
        lead + (n3h,)
    )


def save_npz(path: str, variables) -> None:
    flat = {"__meta__/qkv_layout": np.asarray(QKV_LAYOUT)}
    for collection in ("params", "batch_stats"):
        for p, leaf in jax.tree_util.tree_flatten_with_path(
                variables.get(collection, {}))[0]:
            key = collection + "/" + "/".join(k.key for k in p)
            flat[key] = np.asarray(leaf)
    np.savez(path, **flat)


def load_npz(path: str):
    out = {"params": {}, "batch_stats": {}}
    with np.load(path) as data:
        for key in data.files:
            collection, *names = key.split("/")
            if collection == "__meta__":
                continue  # layout markers — read via npz_meta
            tree = out[collection]
            for n in names[:-1]:
                tree = tree.setdefault(n, {})
            tree[names[-1]] = data[key]
    return out


def npz_meta(path: str) -> Dict[str, str]:
    """The ``__meta__/*`` markers of a converted-weights file (empty for
    files written before markers existed)."""
    out = {}
    with np.load(path) as data:
        for key in data.files:
            if key.startswith("__meta__/"):
                out[key[len("__meta__/"):]] = str(data[key])
    return out


def _qkv_to_head_major(arch: str, variables):
    """Migrate a [q|k|v]-major ViT/Swin tree (pre-round-4 conversion) to
    the head-major storage layout. Works on any dict tree whose fused
    qkv leaves sit at ``…/in_proj/{kernel,bias}`` (ViT) or
    ``…/qkv/{kernel,bias}`` (Swin) — the variables dict, a bare params
    tree, or a momentum trace mirroring params."""

    def fix(path, leaf):
        names = tuple(p.key for p in path)
        if len(names) >= 2 and names[-2] in ("in_proj", "qkv"):
            return qkv_permute(
                np.asarray(leaf), _qkv_heads(arch, names),
                to_head_major=True,
            )
        return leaf

    return jax.tree_util.tree_map_with_path(fix, variables)


def weights_search_dirs():
    from dptpu.envknob import env_str

    env = env_str("DPTPU_PRETRAINED_DIR")
    return [env] if env else ["pretrained", "."]


def find_weights(arch: str):
    """Resolve ``<arch>.npz``; None if absent."""
    for d in weights_search_dirs():
        p = os.path.join(d, f"{arch}.npz")
        if os.path.exists(p):
            return p
    return None


def require_weights(arch: str) -> str:
    """``find_weights`` or raise the one canonical instructions error."""
    path = find_weights(arch)
    if path is None:
        raise FileNotFoundError(
            f"--pretrained: no converted weights found for {arch!r} "
            f"(searched {weights_search_dirs()} for {arch}.npz). Convert a "
            f"torchvision checkpoint offline with: python -m "
            f"dptpu.tools.convert_torchvision <ckpt.pth> -a {arch} -o "
            f"pretrained/  (set DPTPU_PRETRAINED_DIR to use another "
            f"directory)"
        )
    return path


def load_pretrained_variables(arch: str, model, input_shape=(1, 224, 224, 3)):
    """Load converted weights for ``arch`` and validate against ``model``.

    The pytree structure must match the model's own ``init`` exactly
    (num_classes mismatches surface as shape errors here, matching
    torchvision's strict load semantics).
    """
    path = require_weights(arch)
    loaded = load_npz(path)
    if qkv_needs_migration(arch, npz_meta(path).get("qkv_layout")):
        # converted before this family's head-major qkv switch: same
        # shapes, permuted columns — migrate silently-correctly
        loaded = _qkv_to_head_major(arch, loaded)
    template = model.init(
        jax.random.PRNGKey(0), np.zeros(input_shape, np.float32), train=False
    )
    t_struct = jax.tree_util.tree_structure(
        {"params": template["params"],
         "batch_stats": template.get("batch_stats", {})}
    )
    l_struct = jax.tree_util.tree_structure(loaded)
    if t_struct != l_struct:
        raise ValueError(
            f"{path} does not match the {arch} parameter tree "
            f"(wrong arch or stale conversion?)"
        )
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(loaded)[0],
        jax.tree_util.tree_flatten_with_path(
            {"params": template["params"],
             "batch_stats": template.get("batch_stats", {})})[0],
    ):
        if tuple(a.shape) != tuple(b.shape):
            name = "/".join(str(k.key) for k in pa)
            raise ValueError(
                f"{path}: {name} has shape {a.shape}, model wants {b.shape} "
                f"(num_classes mismatch?)"
            )
    return loaded
