"""MaxViT-T, torchvision-architecture-exact, NHWC.

Registry-discoverable (imagenet_ddp.py:19-21, ``-a maxvit_t``). Fresh
Flax build of torchvision's ``maxvit.py`` ("MaxViT: Multi-Axis Vision
Transformer"):

* stem: 3x3/2 conv BN GELU -> 3x3 conv (bias);
* four blocks of MaxVit layers, each layer a fixed trio:
  - **MBConv** (pre-norm BN -> 1x1 expand (4x) BN GELU -> 3x3/stride
    depthwise BN GELU -> SiLU squeeze-excitation (0.25 of OUT channels)
    -> 1x1 project with bias), shortcut = [3x3/2 avg pool ->] 1x1 conv;
  - **window attention**: partition into 7x7 LOCAL windows, pre-LN
    relative-position multi-head attention (head_dim 32) + MLP(4x GELU),
    both residual with row-mode stochastic depth;
  - **grid attention**: the dual axis — partition into a 7x7 GLOBAL
    strided grid (window partition of size H/7, axes swapped) and run
    the same attention over the sparse grid tokens;
* classifier: global average pool -> LayerNorm -> Linear -> Tanh ->
  Linear (no bias).

Window/grid partitioning is trace-time reshape/transpose (the feature
sizes 56/28/14/7 at 224 input are static), so XLA sees batched MXU
matmuls; input H/W must be divisible by 7 after each stride-2 stage
(224/448/... work). Stochastic depth ramps 0 -> 0.2 over all layers.
Init: convs/Linears N(0, 0.02) with zero bias, BN 1/0, bias table
trunc_normal(0.02) (torchvision's _init_weights). Param count locked in
tests/test_models.py (30,919,624).
"""

import math
from functools import partial
from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from dptpu.models.layers import SqueezeExcite, StochasticDepth
from dptpu.models.registry import register_model
from dptpu.models.swin import _relative_position_index, torch_trunc_normal_init

# maxvit_t geometry
_STEM = 64
_CHANNELS = (64, 128, 256, 512)
_LAYERS = (2, 2, 5, 2)
_HEAD_DIM = 32
_PARTITION = 7
_SD_RATE = 0.2

_normal02 = nn.initializers.normal(0.02)


class MBConv(nn.Module):
    out_ch: int
    stride: int
    sd_prob: float
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x, train: bool):
        in_ch = x.shape[-1]
        mid = 4 * self.out_ch
        sqz = self.out_ch // 4
        shortcut = x
        if self.stride != 1 or in_ch != self.out_ch:
            if self.stride == 2:
                # torch AvgPool2d(3, 2, 1) default: padded zeros COUNT in
                # the divisor (count_include_pad=True)
                shortcut = nn.avg_pool(
                    shortcut, (3, 3), strides=(2, 2),
                    padding=((1, 1), (1, 1)), count_include_pad=True,
                )
            shortcut = self.conv(
                self.out_ch, (1, 1), use_bias=True, name="proj"
            )(shortcut)
        y = self.norm(name="pre_norm")(x)
        y = self.conv(mid, (1, 1), name="conv_a")(y)
        y = nn.gelu(self.norm(name="conv_a_bn")(y), approximate=False)
        y = self.conv(
            mid, (3, 3), strides=(self.stride, self.stride),
            padding=((1, 1), (1, 1)), feature_group_count=mid, name="conv_b",
        )(y)
        y = nn.gelu(self.norm(name="conv_b_bn")(y), approximate=False)
        y = SqueezeExcite(
            reduced=sqz, conv=self.conv, act=nn.silu, gate=nn.sigmoid,
            name="se",
        )(y)
        y = self.conv(self.out_ch, (1, 1), use_bias=True, name="conv_c")(y)
        y = StochasticDepth(self.sd_prob, deterministic=not train)(y)
        return (shortcut + y).astype(y.dtype)


class RelPosAttention(nn.Module):
    """Pre-LN relative-position MHA + MLP over partitioned tokens
    (x: (batch, n_partitions, seq, C))."""

    head_dim: int
    partition: int
    sd_prob: float
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        c = x.shape[-1]
        heads = c // self.head_dim
        seq = self.partition * self.partition
        dense = partial(
            nn.Dense, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=_normal02, bias_init=nn.initializers.zeros,
        )
        ln = partial(
            nn.LayerNorm, epsilon=1e-5, dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        sd = StochasticDepth(self.sd_prob, deterministic=not train)

        y = ln(name="attn_norm")(x)
        qkv = dense(3 * c, name="to_qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = q.shape[:-1] + (heads, self.head_dim)
        q, k, v = (t.reshape(shape) for t in (q, k, v))
        # torchvision quirk: scale_factor = feat_dim**-0.5 (the FULL
        # channel dim, not head_dim) — pretrained weights expect it
        attn = jnp.einsum("bpqhd,bpkhd->bphqk", q * c ** -0.5, k)
        rpb = self.param(
            "relative_position_bias_table", torch_trunc_normal_init(0.02),
            ((2 * self.partition - 1) ** 2, heads), jnp.float32,
        )
        idx = _relative_position_index(self.partition).reshape(-1)
        bias = rpb[idx].reshape(seq, seq, heads).transpose(2, 0, 1)
        attn = attn + bias.astype(attn.dtype)[None, None]
        attn = nn.softmax(attn.astype(jnp.float32), axis=-1).astype(x.dtype)
        y = jnp.einsum("bphqk,bpkhd->bpqhd", attn, v)
        y = y.reshape(y.shape[:-2] + (c,))
        y = dense(c, name="merge")(y)
        x = x + sd(y)

        y = ln(name="mlp_norm")(x)
        y = dense(4 * c, name="mlp_1")(y)
        y = nn.gelu(y, approximate=False)
        y = dense(c, name="mlp_2")(y)
        return x + sd(y)


class MaxVitLayer(nn.Module):
    out_ch: int
    stride: int
    sd_prob: float
    conv: Any
    norm: Any
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        p = _PARTITION
        x = MBConv(
            out_ch=self.out_ch, stride=self.stride, sd_prob=self.sd_prob,
            conv=self.conv, norm=self.norm, name="mbconv",
        )(x, train)
        b, h, w, c = x.shape
        if h != w or h % p:
            raise ValueError(
                f"maxvit needs square feature sizes divisible by {p}; got "
                f"{h}x{w} (input 224/448/... works)"
            )
        attn = partial(
            RelPosAttention, head_dim=_HEAD_DIM, partition=p,
            sd_prob=self.sd_prob, dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        # window attention: local p x p tiles
        y = x.reshape(b, h // p, p, w // p, p, c).transpose(0, 1, 3, 2, 4, 5)
        y = y.reshape(b, (h // p) * (w // p), p * p, c)
        y = attn(name="window_attn")(y, train)
        y = y.reshape(b, h // p, w // p, p, p, c).transpose(0, 1, 3, 2, 4, 5)
        x = y.reshape(b, h, w, c)
        # grid attention: p x p global strided grid (partition by the
        # complementary size g, then swap partition/token axes)
        g = h // p
        y = x.reshape(b, p, g, w // g, g, c).transpose(0, 1, 3, 2, 4, 5)
        y = y.reshape(b, p * (w // g), g * g, c)
        y = y.transpose(0, 2, 1, 3)  # tokens = the p*p strided positions
        y = attn(name="grid_attn")(y, train)
        y = y.transpose(0, 2, 1, 3)
        y = y.reshape(b, p, w // g, g, g, c).transpose(0, 1, 3, 2, 4, 5)
        return y.reshape(b, h, w, c)


class MaxVit(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Any = None
    bn_dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, kernel_init=_normal02,
            bias_init=nn.initializers.zeros,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.99, epsilon=1e-3,  # torch BN(eps 1e-3, momentum .01)
            dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        x = conv(_STEM, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                 name="stem_conv")(x)
        x = nn.gelu(norm(name="stem_bn")(x), approximate=False)
        x = conv(_STEM, (3, 3), padding=((1, 1), (1, 1)), use_bias=True,
                 name="stem_conv2")(x)
        total = sum(_LAYERS)
        idx = 0
        for bi, (ch, depth) in enumerate(zip(_CHANNELS, _LAYERS)):
            for li in range(depth):
                # torchvision ramps 0 -> sd_rate over the flat layer list
                x = MaxVitLayer(
                    out_ch=ch, stride=2 if li == 0 else 1,
                    sd_prob=_SD_RATE * idx / (total - 1.0),
                    conv=conv, norm=norm, dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    name=f"block{bi}_layer{li}",
                )(x, train)
                idx += 1
        x = x.mean(axis=(1, 2))
        dense = partial(
            nn.Dense, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=_normal02, bias_init=nn.initializers.zeros,
        )
        x = nn.LayerNorm(
            epsilon=1e-5, dtype=self.dtype, param_dtype=self.param_dtype,
            name="head_norm",
        )(x)
        x = jnp.tanh(dense(_CHANNELS[-1], name="pre_head")(x))
        return dense(self.num_classes, use_bias=False, name="head")(x)


@register_model
def maxvit_t(**kw):
    return MaxVit(**kw)
