"""MNASNet (0_5 / 0_75 / 1_0 / 1_3), torchvision-exact, NHWC.

Registry-discoverable like the rest (imagenet_ddp.py:19-21, e.g.
``-a mnasnet1_0``). Fresh Flax build of torchvision's ``mnasnet.py``:

* stem 3x3/2 conv BN ReLU -> depthwise-separable (dw3x3 + pw) block;
* six stacks of inverted residuals with the NAS-chosen kernel sizes and
  expansions: (k3 t3 n3 s2), (k5 t3 n3 s2), (k5 t6 n3 s2), (k3 t6 n2 s1),
  (k5 t6 n4 s2), (k3 t6 n1 s1);
* head 1x1 conv to 1280 -> global average pool -> Dropout(0.2) -> Linear.

Depths scale by alpha through ``_round_to_multiple_of(d * alpha, 8)``.
torchvision runs these BNs with momentum 0.0003 (flax EMA decay 0.9997) —
preserved, it matters for eval parity on short runs. Init matches:
convs kaiming-normal fan-out, classifier kaiming-uniform over fan_out
with sigmoid gain (bound sqrt(3 / fan_out)). Param counts locked in
tests/test_models.py (mnasnet1_0 = 4,383,312).
"""

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from dptpu.models.layers import kaiming_normal_fan_out
from dptpu.models.registry import register_model

_BN_DECAY = 0.9997  # torch _BN_MOMENTUM = 1 - 0.9997
# (kernel, expansion, repeats, first_stride) per stack
_STACKS = ((3, 3, 3, 2), (5, 3, 3, 2), (5, 6, 3, 2),
           (3, 6, 2, 1), (5, 6, 4, 2), (3, 6, 1, 1))
_BASE_DEPTHS = (32, 16, 24, 40, 80, 96, 192, 320)


def _round_to_multiple_of(val, divisor=8):
    new_val = max(divisor, int(val + divisor / 2) // divisor * divisor)
    return new_val if new_val >= 0.9 * val else new_val + divisor


def _depths(alpha):
    return [_round_to_multiple_of(d * alpha) for d in _BASE_DEPTHS]


def _classifier_kernel_init(key, shape, dtype=jnp.float32):
    # torchvision: kaiming_uniform_(mode="fan_out", nonlinearity="sigmoid")
    # on the (out, in) torch weight -> bound sqrt(3 / fan_out); flax shape
    # is (in, out) so fan_out = shape[-1]
    bound = np.sqrt(3.0 / shape[-1])
    return jax.random.uniform(key, shape, dtype, -bound, bound)


class MnasInvertedResidual(nn.Module):
    out_ch: int
    kernel: int
    stride: int
    expansion: int
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x):
        inp = x.shape[-1]
        mid = inp * self.expansion
        k, p = self.kernel, self.kernel // 2
        y = self.conv(mid, (1, 1), name="pw1")(x)
        y = nn.relu(self.norm(name="pw1_bn")(y))
        y = self.conv(
            mid, (k, k), strides=(self.stride, self.stride),
            padding=((p, p), (p, p)), feature_group_count=mid, name="dw",
        )(y)
        y = nn.relu(self.norm(name="dw_bn")(y))
        y = self.conv(self.out_ch, (1, 1), name="pw2")(y)
        y = self.norm(name="pw2_bn")(y)
        if self.stride == 1 and inp == self.out_ch:
            y = (x + y).astype(y.dtype)
        return y


class MNASNet(nn.Module):
    alpha: float = 1.0
    num_classes: int = 1000
    dropout_rate: float = 0.2
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    bn_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=kaiming_normal_fan_out,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=_BN_DECAY,
            epsilon=1e-5,
            dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        d = _depths(self.alpha)
        x = conv(d[0], (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                 name="stem_conv")(x)
        x = nn.relu(norm(name="stem_bn")(x))
        # depthwise-separable block
        x = conv(d[0], (3, 3), padding=((1, 1), (1, 1)),
                 feature_group_count=d[0], name="sep_dw")(x)
        x = nn.relu(norm(name="sep_dw_bn")(x))
        x = conv(d[1], (1, 1), name="sep_pw")(x)
        x = norm(name="sep_pw_bn")(x)
        block = 0
        for stack, (k, t, n, s) in enumerate(_STACKS):
            out_ch = d[stack + 2]
            for i in range(n):
                x = MnasInvertedResidual(
                    out_ch=out_ch,
                    kernel=k,
                    stride=s if i == 0 else 1,
                    expansion=t,
                    conv=conv,
                    norm=norm,
                    name=f"block{block}",
                )(x)
                block += 1
        x = conv(1280, (1, 1), name="head_conv")(x)
        x = nn.relu(norm(name="head_bn")(x))
        x = x.mean(axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=_classifier_kernel_init,
            bias_init=nn.initializers.zeros,  # torchvision zeroes it
            name="classifier",
        )(x)
        return x


@register_model
def mnasnet0_5(**kw):
    return MNASNet(alpha=0.5, **kw)


@register_model
def mnasnet0_75(**kw):
    return MNASNet(alpha=0.75, **kw)


@register_model
def mnasnet1_0(**kw):
    return MNASNet(alpha=1.0, **kw)


@register_model
def mnasnet1_3(**kw):
    return MNASNet(alpha=1.3, **kw)
