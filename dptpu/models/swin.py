"""Swin Transformer v1 (t/s/b) + v2 (t/s/b), torchvision-exact, NHWC.

Registry-discoverable (imagenet_ddp.py:19-21, ``-a swin_t``). Fresh Flax
build of torchvision's ``swin_transformer.py``:

* patch embed 4x4/4 conv + LayerNorm (eps 1e-5, swin's norm everywhere);
* four stages of blocks; between stages PatchMerging concatenates each
  2x2 neighborhood to 4C and reduces to 2C (v1 norms the 4C input, v2
  norms the 2C output);
* block: LN -> shifted-window attention -> stochastic depth -> residual;
  LN -> MLP(4x, GELU) -> stochastic depth -> residual. Blocks alternate
  shift 0 / window//2;
* window attention pads H/W up to window multiples, zeroes the shift
  when the window covers the padded axis, rolls, partitions windows with
  a reshape/transpose, and masks cross-region pairs with -100 in shifted
  windows. All of that is static trace-time Python — under jit it
  compiles to rolls + one big batched matmul chain on the MXU;
* v1 adds a learned (2w-1)^2 x heads relative-position-bias table; v2
  replaces it with a log-spaced continuous-position MLP
  (2 -> 512 -> heads, bias 16*sigmoid), L2-normalized q/k cosine
  attention with a per-head clamped-exp ``logit_scale``;
* head: final LN -> global average pool -> Linear.

Init matches torchvision: every Linear trunc_normal(0.02) with zero
bias (the SwinTransformer-level loop overrides the per-block MLP
xavier init), patch conv torch-default, bias table trunc_normal(0.02).
Param counts locked in tests/test_models.py (swin_t = 28,288,354).

The fused qkv projection's output axis is stored HEAD-MAJOR (same
layout, same converter permutation, and same tensor-parallelism
rationale as dptpu/models/vit.py — see ``_QKVDense`` below and
``swin_tp_specs`` in dptpu/parallel/gspmd.py).
"""

import math
from functools import partial
from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from dptpu.models.layers import (
    StochasticDepth,
    torch_default_bias_init,
    torch_default_kernel_init,
    torch_trunc_normal_init,
)
from dptpu.models.registry import register_variants

# name -> (embed, depths, heads, window, stochastic_depth_rate, v2)
_VARIANTS = {
    "t": (96, (2, 2, 6, 2), (3, 6, 12, 24), 7, 0.2, False),
    "s": (96, (2, 2, 18, 2), (3, 6, 12, 24), 7, 0.3, False),
    "b": (128, (2, 2, 18, 2), (4, 8, 16, 32), 7, 0.5, False),
    "v2_t": (96, (2, 2, 6, 2), (3, 6, 12, 24), 8, 0.2, True),
    "v2_s": (96, (2, 2, 18, 2), (3, 6, 12, 24), 8, 0.3, True),
    "v2_b": (128, (2, 2, 18, 2), (4, 8, 16, 32), 8, 0.5, True),
}

_trunc02 = torch_trunc_normal_init(0.02)


def _relative_position_index(ws: int) -> np.ndarray:
    """(ws^2, ws^2) lookup into the (2ws-1)^2 relative-position table."""
    coords = np.stack(
        np.meshgrid(np.arange(ws), np.arange(ws), indexing="ij")
    ).reshape(2, -1)
    rel = (coords[:, :, None] - coords[:, None, :]).transpose(1, 2, 0)
    rel += ws - 1
    return rel[..., 0] * (2 * ws - 1) + rel[..., 1]


def _coords_table(ws: int) -> np.ndarray:
    """v2 log-spaced normalized coordinate table ((2ws-1)^2, 2)."""
    r = np.arange(-(ws - 1), ws, dtype=np.float32)
    table = np.stack(np.meshgrid(r, r, indexing="ij"), axis=-1)
    table = table / (ws - 1) * 8.0
    table = np.sign(table) * np.log2(np.abs(table) + 1.0) / 3.0
    return table.reshape(-1, 2)


def _shift_mask(hp: int, wp: int, ws: int, sh: int, sw: int) -> np.ndarray:
    """Additive (-100 off-region) attention mask (nW, ws^2, ws^2) for
    shifted windows — static, computed from trace-time shapes."""
    img = np.zeros((hp, wp), np.int32)
    hs = ((0, hp - ws), (hp - ws, hp - sh), (hp - sh, hp)) if sh else ((0, hp),)
    wss = ((0, wp - ws), (wp - ws, wp - sw), (wp - sw, wp)) if sw else ((0, wp),)
    region = 0
    for h0, h1 in hs:
        for w0, w1 in wss:
            img[h0:h1, w0:w1] = region
            region += 1
    mw = img.reshape(hp // ws, ws, wp // ws, ws).transpose(0, 2, 1, 3)
    mw = mw.reshape(-1, ws * ws)
    return np.where(
        mw[:, None, :] != mw[:, :, None], -100.0, 0.0
    ).astype(np.float32)


class _QKVDense(nn.Module):
    """qkv projection whose K positions of the bias are functionally
    zeroed — torchvision's v2 attention clones ``qkv_bias`` and zeroes
    the K third on every forward, so those slots never contribute and
    never receive gradient; the param itself stays checkpoint-shaped
    (``attn.qkv.bias``). The output axis is stored HEAD-MAJOR
    (``(heads, 3, hd)`` flattened — same layout and same TP rationale
    as dptpu/models/vit.py SelfAttention; the converter permutes torch's
    ``[q|k|v]``-major weights), so the zero mask targets the per-head K
    slots, not a contiguous middle third."""

    features: int
    heads: int
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", _trunc02, (x.shape[-1], self.features),
            self.param_dtype,
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), self.param_dtype
        )
        mask = np.ones((self.heads, 3, self.features // (3 * self.heads)),
                       np.float32)
        mask[:, 1, :] = 0.0  # K slots, head-major layout
        bias = bias * jnp.asarray(mask.reshape(-1), bias.dtype)
        return x.astype(self.dtype) @ kernel.astype(self.dtype) \
            + bias.astype(self.dtype)


class ShiftedWindowAttention(nn.Module):
    heads: int
    window: int
    shift: int
    v2: bool
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        ws, hd = self.window, c // self.heads
        dense = partial(
            nn.Dense, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=_trunc02, bias_init=nn.initializers.zeros,
        )
        pad_h, pad_w = (ws - h % ws) % ws, (ws - w % ws) % ws
        if pad_h or pad_w:
            x = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        hp, wp = h + pad_h, w + pad_w
        sh = 0 if ws >= hp else self.shift
        sw = 0 if ws >= wp else self.shift
        if sh or sw:
            x = jnp.roll(x, (-sh, -sw), axis=(1, 2))
        nh, nw = hp // ws, wp // ws
        xw = x.reshape(b, nh, ws, nw, ws, c).transpose(0, 1, 3, 2, 4, 5)
        xw = xw.reshape(b * nh * nw, ws * ws, c)

        if self.v2:
            qkv = _QKVDense(
                features=3 * c, heads=self.heads, dtype=self.dtype,
                param_dtype=self.param_dtype, name="qkv",
            )(xw)
        else:
            qkv = dense(3 * c, name="qkv")(xw)
        # head-major fused layout (see _QKVDense docstring): split into
        # per-head q/k/v and land directly on (batch, heads, tokens, hd)
        qkv = qkv.reshape(xw.shape[0], ws * ws, self.heads, 3, hd)
        qkv = qkv.transpose(0, 2, 3, 1, 4)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.v2:
            # cosine attention with per-head learned temperature
            logit_scale = self.param(
                "logit_scale",
                nn.initializers.constant(math.log(10.0)),
                (self.heads, 1, 1), jnp.float32,
            )
            q = q / jnp.maximum(
                jnp.linalg.norm(q, axis=-1, keepdims=True), 1e-12)
            k = k / jnp.maximum(
                jnp.linalg.norm(k, axis=-1, keepdims=True), 1e-12)
            attn = jnp.einsum("bhqd,bhkd->bhqk", q, k)
            attn = attn * jnp.exp(
                jnp.minimum(logit_scale, math.log(100.0))
            ).astype(attn.dtype)
        else:
            attn = jnp.einsum("bhqd,bhkd->bhqk", q * hd ** -0.5, k)

        idx = _relative_position_index(ws).reshape(-1)
        if self.v2:
            table = jnp.asarray(_coords_table(ws), self.dtype)
            cpb = dense(512, name="cpb_mlp_1")(table)
            cpb = dense(
                self.heads, use_bias=False, name="cpb_mlp_2"
            )(nn.relu(cpb))
            bias = cpb.reshape(-1, self.heads)[idx]
            bias = bias.reshape(ws * ws, ws * ws, self.heads)
            bias = 16.0 * nn.sigmoid(bias)
        else:
            rpb = self.param(
                "relative_position_bias_table", _trunc02,
                ((2 * ws - 1) ** 2, self.heads), jnp.float32,
            )
            bias = rpb[idx].reshape(ws * ws, ws * ws, self.heads)
        attn = attn + bias.transpose(2, 0, 1).astype(attn.dtype)[None]

        if sh or sw:
            mask = jnp.asarray(_shift_mask(hp, wp, ws, sh, sw))
            attn = attn.reshape(b, nh * nw, self.heads, ws * ws, ws * ws)
            attn = attn + mask[None, :, None].astype(attn.dtype)
            attn = attn.reshape(-1, self.heads, ws * ws, ws * ws)
        attn = nn.softmax(
            attn.astype(jnp.float32), axis=-1
        ).astype(x.dtype)
        y = jnp.einsum("bhqk,bhkd->bhqd", attn, v)
        y = y.transpose(0, 2, 1, 3).reshape(b * nh * nw, ws * ws, c)
        y = dense(c, name="proj")(y)

        y = y.reshape(b, nh, nw, ws, ws, c).transpose(0, 1, 3, 2, 4, 5)
        y = y.reshape(b, hp, wp, c)
        if sh or sw:
            y = jnp.roll(y, (sh, sw), axis=(1, 2))
        return y[:, :h, :w, :]


class SwinBlock(nn.Module):
    heads: int
    window: int
    shift: int
    sd_prob: float
    v2: bool
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        ln = partial(
            nn.LayerNorm, epsilon=1e-5, dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        sd = StochasticDepth(self.sd_prob, deterministic=not train)
        attn = ShiftedWindowAttention(
            heads=self.heads, window=self.window, shift=self.shift,
            v2=self.v2, dtype=self.dtype, param_dtype=self.param_dtype,
            name="attn",
        )
        # v2 is res-post-norm: the LN moves from the branch input to the
        # branch output (torchvision SwinTransformerBlockV2)
        if self.v2:
            x = x + sd(ln(name="norm1")(attn(x)))
        else:
            x = x + sd(attn(ln(name="norm1")(x)))
        dense = partial(
            nn.Dense, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=_trunc02, bias_init=nn.initializers.zeros,
        )
        c = x.shape[-1]

        def mlp(y):
            y = dense(4 * c, name="mlp_1")(y)
            y = nn.gelu(y, approximate=False)
            return dense(c, name="mlp_2")(y)

        if self.v2:
            return x + sd(ln(name="norm2")(mlp(x)))
        return x + sd(mlp(ln(name="norm2")(x)))


class PatchMerging(nn.Module):
    v2: bool
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        if h % 2 or w % 2:
            x = jnp.pad(x, ((0, 0), (0, h % 2), (0, w % 2), (0, 0)))
        x = jnp.concatenate(
            [x[:, 0::2, 0::2], x[:, 1::2, 0::2],
             x[:, 0::2, 1::2], x[:, 1::2, 1::2]], axis=-1
        )
        ln = partial(
            nn.LayerNorm, epsilon=1e-5, dtype=self.dtype,
            param_dtype=self.param_dtype, name="norm",
        )
        reduction = nn.Dense(
            2 * c, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, kernel_init=_trunc02,
            name="reduction",
        )
        if self.v2:
            return ln()(reduction(x))
        return reduction(ln()(x))


class SwinTransformer(nn.Module):
    variant: str = "t"
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Any = None  # no BN; accepted for API uniformity
    bn_dtype: Any = None  # likewise

    @nn.compact
    def __call__(self, x, train: bool = False):
        embed, depths, heads, window, sd_rate, v2 = _VARIANTS[self.variant]
        x = nn.Conv(
            embed, (4, 4), strides=(4, 4), padding="VALID", use_bias=True,
            dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=torch_default_kernel_init,
            bias_init=torch_default_bias_init(3 * 4 * 4),
            name="patch_conv",
        )(x)
        x = nn.LayerNorm(
            epsilon=1e-5, dtype=self.dtype, param_dtype=self.param_dtype,
            name="patch_norm",
        )(x)
        total = sum(depths)
        block_id = 0
        for si, (depth, nheads) in enumerate(zip(depths, heads)):
            for bi in range(depth):
                x = SwinBlock(
                    heads=nheads, window=window,
                    shift=0 if bi % 2 == 0 else window // 2,
                    sd_prob=sd_rate * block_id / (total - 1.0),
                    v2=v2, dtype=self.dtype, param_dtype=self.param_dtype,
                    name=f"stage{si}_block{bi}",
                )(x, train)
                block_id += 1
            if si < len(depths) - 1:
                x = PatchMerging(
                    v2=v2, dtype=self.dtype, param_dtype=self.param_dtype,
                    name=f"merge{si}",
                )(x)
        x = nn.LayerNorm(
            epsilon=1e-5, dtype=self.dtype, param_dtype=self.param_dtype,
            name="norm",
        )(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=_trunc02, bias_init=nn.initializers.zeros,
            name="head",
        )(x)


register_variants(SwinTransformer, "swin", _VARIANTS)
