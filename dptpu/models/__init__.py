"""In-tree Flax model zoo with torchvision registry semantics.

Importing this package populates the registry (the analog of torchvision's
module-dict discovery, imagenet_ddp.py:19-21). ``model_names()`` and
``create_model()`` are the CLI-facing surface.
"""

from dptpu.models import alexnet as _alexnet  # noqa: F401
from dptpu.models import convnext as _convnext  # noqa: F401
from dptpu.models import densenet as _densenet  # noqa: F401
from dptpu.models import efficientnet as _efficientnet  # noqa: F401
from dptpu.models import googlenet as _googlenet  # noqa: F401
from dptpu.models import inception as _inception  # noqa: F401
from dptpu.models import maxvit as _maxvit  # noqa: F401
from dptpu.models import mnasnet as _mnasnet  # noqa: F401
from dptpu.models import mobilenet as _mobilenet  # noqa: F401
from dptpu.models import mobilenet_v3 as _mobilenet_v3  # noqa: F401
from dptpu.models import regnet as _regnet  # noqa: F401
from dptpu.models import resnet as _resnet  # noqa: F401
from dptpu.models import shufflenet as _shufflenet  # noqa: F401
from dptpu.models import squeezenet as _squeezenet  # noqa: F401
from dptpu.models import swin as _swin  # noqa: F401
from dptpu.models import vgg as _vgg  # noqa: F401
from dptpu.models import vit as _vit  # noqa: F401
from dptpu.models.registry import create_model, model_names, register_model

__all__ = ["create_model", "model_names", "register_model"]
