"""Shared layer utilities: torchvision-matching initializers and pooling.

The reference builds models straight from ``torchvision.models``
(imagenet_ddp.py:108-114), so convergence parity depends on matching
torchvision's initialization conventions (SURVEY.md §7 hard part (c)):

* ``kaiming_normal_(mode='fan_out', nonlinearity='relu')`` for ResNet/VGG
  convs — here ``variance_scaling(2.0, 'fan_out', 'normal')`` (identical
  distribution; flax computes conv fan_out as out_channels × receptive
  field, same as torch).
* torch's default Linear/Conv init (``kaiming_uniform_(a=sqrt(5))`` +
  bias ``U(±1/sqrt(fan_in))``) for AlexNet and ResNet's fc layer — the
  kernel bound simplifies to exactly ``1/sqrt(fan_in)``.
* ``normal(0, 0.01)`` for VGG classifier Linears.

Layout is NHWC throughout (TPU-native — the MXU wants channels minor; this
is also what the reference's ``--channels-last`` flag asks for,
imagenet_ddp_apex.py:95,133-136).
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from dptpu.ops.fused_stem import affine_relu_pool

# kaiming_normal(mode='fan_out', nonlinearity='relu'): N(0, sqrt(2/fan_out))
kaiming_normal_fan_out = nn.initializers.variance_scaling(
    2.0, "fan_out", "normal"
)


def torch_default_kernel_init(key, shape, dtype=jnp.float32):
    """torch's default Linear/Conv kernel init: kaiming_uniform(a=sqrt(5)).

    bound = sqrt(6 / ((1 + a^2) * fan_in)) = 1/sqrt(fan_in).
    ``shape`` is flax convention: (..., fan_in, fan_out) for Dense,
    (kh, kw, in, out) for Conv (fan_in = in × kh × kw).
    """
    fan_in = int(np.prod(shape[:-1]))
    bound = 1.0 / np.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def torch_default_bias_init(fan_in):
    """torch default bias init: U(±1/sqrt(fan_in)) with fan_in of the layer."""
    return uniform_bound_init(1.0 / np.sqrt(fan_in))


def torch_trunc_normal_init(std, bound=2.0):
    """``torch.nn.init.trunc_normal_(std=std)``: N(0, std²) truncated at
    ABSOLUTE ±bound (so ±bound/std sigmas — effectively untruncated for
    the std ≈ 0.02 used by ViT/Swin/ConvNeXt). jax's
    ``initializers.truncated_normal`` instead truncates at ±2σ without
    renormalizing (actual std ≈ 0.88·std), so it does NOT match."""

    def init(key, shape, dtype=jnp.float32):
        cut = bound / std
        return std * jax.random.truncated_normal(key, -cut, cut, shape, dtype)

    return init


def uniform_bound_init(bound):
    """U(±bound) initializer (torchvision's Linear init for EfficientNet
    and others uses U(±1/sqrt(out_features)))."""

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


class SqueezeExcite(nn.Module):
    """torchvision SqueezeExcitation: avg pool -> 1x1 reduce -> act ->
    1x1 expand -> gate (convs with bias). MobileNetV3 uses relu /
    hard_sigmoid, EfficientNet silu / sigmoid."""

    reduced: int
    conv: Any
    act: Any = nn.relu
    gate: Any = nn.sigmoid

    @nn.compact
    def __call__(self, x):
        s = x.mean(axis=(1, 2), keepdims=True)
        s = self.conv(self.reduced, (1, 1), use_bias=True, name="fc1")(s)
        s = self.act(s)
        s = self.conv(x.shape[-1], (1, 1), use_bias=True, name="fc2")(s)
        return x * self.gate(s)


class StochasticDepth(nn.Module):
    """torchvision ``StochasticDepth(p, mode="row")``: drop a residual
    branch per SAMPLE with probability ``p``, scaling survivors by
    ``1/(1-p)``. Identity when deterministic or p == 0 (so it traces to
    nothing at eval and for the un-scaled early blocks)."""

    rate: float
    deterministic: bool

    @nn.compact
    def __call__(self, x):
        if self.deterministic or self.rate == 0.0:
            return x
        keep = 1.0 - self.rate
        rng = self.make_rng("dropout")
        mask = jax.random.bernoulli(rng, keep, (x.shape[0],) + (1,) * (x.ndim - 1))
        return jnp.where(mask, x / keep, jnp.zeros_like(x)).astype(x.dtype)


def max_pool_same_as_torch(x, window, stride, padding):
    """``nn.MaxPool2d(window, stride, padding)`` on NHWC input.

    torch pads with -inf implicitly for max pooling; flax's ``nn.max_pool``
    pads with -inf as well when given explicit padding tuples.
    """
    return nn.max_pool(
        x,
        (window, window),
        strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
    )


class FusedBNReLUPool(nn.Module):
    """BN -> ReLU -> MaxPool2d(3,2,1) with the fused custom-VJP region.

    Drop-in replacement for the resnet stem's ``BatchNorm -> relu ->
    max_pool`` sequence (imagenet_ddp.py:108-114 via torchvision resnet).
    Parameter/stat names and shapes match ``nn.BatchNorm`` exactly
    (``scale``/``bias`` params, ``mean``/``var`` batch_stats), so
    checkpoints interchange with the unfused model. BN statistics follow
    flax semantics: f32 accumulation, biased batch variance, EMA update
    ``ra = momentum * ra + (1 - momentum) * batch``, optional cross-replica
    ``lax.pmean`` via ``axis_name`` (the SyncBN analog). The normalize +
    ReLU + pool themselves run as ``dptpu.ops.fused_stem.affine_relu_pool``
    with the statistics folded into a per-channel affine.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, z):
        c = z.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            zf = z.astype(jnp.float32)
            mean = zf.mean(axis=(0, 1, 2))
            mean2 = (zf * zf).mean(axis=(0, 1, 2))
            if self.axis_name is not None:
                mean, mean2 = jax.lax.pmean((mean, mean2), self.axis_name)
            var = mean2 - mean * mean  # flax's biased batch variance
            if not self.is_initializing():
                ra_mean.value = (self.momentum * ra_mean.value
                                 + (1.0 - self.momentum) * mean)
                ra_var.value = (self.momentum * ra_var.value
                                + (1.0 - self.momentum) * var)
        gamma_t = scale * jax.lax.rsqrt(var + self.epsilon)
        beta_t = bias - mean * gamma_t
        return affine_relu_pool(
            z, gamma_t.astype(self.dtype), beta_t.astype(self.dtype)
        )


def ceil_max_pool(x, window=3, stride=2):
    """``nn.MaxPool2d(window, stride, ceil_mode=True)`` on NHWC input —
    the ceil-rounded output grid, realized by -inf bottom/right padding
    exactly when needed (used by SqueezeNet and GoogLeNet)."""
    _, h, w, _ = x.shape
    oh = -(-(h - window) // stride) + 1
    ow = -(-(w - window) // stride) + 1
    pad_h = max(0, (oh - 1) * stride + window - h)
    pad_w = max(0, (ow - 1) * stride + window - w)
    return nn.max_pool(
        x, (window, window), strides=(stride, stride),
        padding=((0, pad_h), (0, pad_w)),
    )


def adaptive_avg_pool(x, output_size):
    """``nn.AdaptiveAvgPool2d(output_size)`` on NHWC input, torch semantics.

    Output bin i covers rows [floor(i*H/out), ceil((i+1)*H/out)). Fast paths:
    global pooling (out=1) is a plain mean; exact division is a reshape-mean
    (both fuse into the surrounding XLA program). The general path unrolls
    over the (static, small ≤7) output grid.
    """
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size
    _, h, w, _ = x.shape
    if (oh, ow) == (1, 1):
        return x.mean(axis=(1, 2), keepdims=True)
    if h == oh and w == ow:
        return x
    if h % oh == 0 and w % ow == 0:
        n, _, _, c = x.shape
        x = x.reshape(n, oh, h // oh, ow, w // ow, c)
        return x.mean(axis=(2, 4))
    if h < oh or w < ow:
        raise ValueError(
            f"adaptive_avg_pool upsampling ({h}x{w} -> {oh}x{ow}) unsupported; "
            "use input images >= 64x64 for AlexNet/VGG"
        )
    rows = []
    for i in range(oh):
        r0, r1 = (i * h) // oh, -(-((i + 1) * h) // oh)
        cols = []
        for j in range(ow):
            c0, c1 = (j * w) // ow, -(-((j + 1) * w) // ow)
            cols.append(x[:, r0:r1, c0:c1, :].mean(axis=(1, 2)))
        rows.append(jnp.stack(cols, axis=1))
    return jnp.stack(rows, axis=1)
