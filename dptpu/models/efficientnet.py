"""EfficientNet B0-B7 + V2 S/M/L, torchvision-architecture-exact, NHWC.

Registry-discoverable (imagenet_ddp.py:19-21, ``-a efficientnet_b0``).
Fresh Flax build of torchvision's ``efficientnet.py``:

* v1 scales one base table of MBConv blocks (expand 1x1 -> depthwise k×k
  -> squeeze-excitation -> project 1x1, SiLU activations) by per-variant
  width/depth multipliers, channels rounded via ``_make_divisible(c, 8)``
  and depths via ``ceil(n * depth_mult)``;
* v2 uses explicit per-variant tables whose early stages are FusedMBConv
  (single k×k expand conv, no depthwise / no SE);
* squeeze-excitation reduces to ``max(1, block_input // 4)`` channels
  (the BLOCK input, not the expanded width), SiLU then sigmoid gate;
* residual blocks apply row-mode stochastic depth with probability
  ``0.2 * block_id / total_blocks``;
* head 1x1 conv BN SiLU -> global average pool -> Dropout -> Linear.

BatchNorm eps/momentum follow torchvision: defaults for B0-B4, (1e-3,
0.01) for B5-B7, eps 1e-3 for V2. Init matches torchvision: convs
kaiming-normal fan-out, BN 1/0, classifier U(±1/sqrt(out_features)) with
zero bias. Param counts locked in tests/test_models.py.
"""

import math
from functools import partial
from typing import Any, Optional, Tuple

import jax.numpy as jnp
from flax import linen as nn

from dptpu.models.layers import (
    SqueezeExcite,
    StochasticDepth,
    kaiming_normal_fan_out,
    uniform_bound_init,
)
from dptpu.models.mobilenet import _make_divisible
from dptpu.models.registry import register_variants

# Base (B0) MBConv table: (expand, kernel, stride, in, out, layers).
_V1_BASE = (
    (1, 3, 1, 32, 16, 1),
    (6, 3, 2, 16, 24, 2),
    (6, 5, 2, 24, 40, 2),
    (6, 3, 2, 40, 80, 3),
    (6, 5, 1, 80, 112, 3),
    (6, 5, 2, 112, 192, 4),
    (6, 3, 1, 192, 320, 1),
)
# name -> (width_mult, depth_mult, dropout, bn_eps, bn_momentum[torch])
_V1_VARIANTS = {
    "b0": (1.0, 1.0, 0.2, 1e-5, 0.1),
    "b1": (1.0, 1.1, 0.2, 1e-5, 0.1),
    "b2": (1.1, 1.2, 0.3, 1e-5, 0.1),
    "b3": (1.2, 1.4, 0.3, 1e-5, 0.1),
    "b4": (1.4, 1.8, 0.4, 1e-5, 0.1),
    "b5": (1.6, 2.2, 0.4, 1e-3, 0.01),
    "b6": (1.8, 2.6, 0.5, 1e-3, 0.01),
    "b7": (2.0, 3.1, 0.5, 1e-3, 0.01),
}
# V2 tables: (kind, expand, kernel, stride, in, out, layers)
_V2_TABLES = {
    "v2_s": (
        ("fused", 1, 3, 1, 24, 24, 2),
        ("fused", 4, 3, 2, 24, 48, 4),
        ("fused", 4, 3, 2, 48, 64, 4),
        ("mb", 4, 3, 2, 64, 128, 6),
        ("mb", 6, 3, 1, 128, 160, 9),
        ("mb", 6, 3, 2, 160, 256, 15),
    ),
    "v2_m": (
        ("fused", 1, 3, 1, 24, 24, 3),
        ("fused", 4, 3, 2, 24, 48, 5),
        ("fused", 4, 3, 2, 48, 80, 5),
        ("mb", 4, 3, 2, 80, 160, 7),
        ("mb", 6, 3, 1, 160, 176, 14),
        ("mb", 6, 3, 2, 176, 304, 18),
        ("mb", 6, 3, 1, 304, 512, 5),
    ),
    "v2_l": (
        ("fused", 1, 3, 1, 32, 32, 4),
        ("fused", 4, 3, 2, 32, 64, 7),
        ("fused", 4, 3, 2, 64, 96, 7),
        ("mb", 4, 3, 2, 96, 192, 10),
        ("mb", 6, 3, 1, 192, 224, 19),
        ("mb", 6, 3, 2, 224, 384, 25),
        ("mb", 6, 3, 1, 384, 640, 7),
    ),
}
_V2_DROPOUT = {"v2_s": 0.2, "v2_m": 0.3, "v2_l": 0.4}


def block_table(variant: str):
    """Expanded per-block config: list of stages, each a list of
    (kind, expand, kernel, stride, in, out). Shared with the torchvision
    key mapping in dptpu/models/pretrained.py."""
    if variant.startswith("v2"):
        stages = []
        for kind, e, k, s, ci, co, n in _V2_TABLES[variant]:
            blocks = []
            for i in range(n):
                blocks.append(
                    (kind, e, k, s if i == 0 else 1, ci if i == 0 else co, co)
                )
            stages.append(blocks)
        return stages
    width, depth, _, _, _ = _V1_VARIANTS[variant]
    adjust = lambda c: _make_divisible(c * width, 8)
    stages = []
    for e, k, s, ci, co, n in _V1_BASE:
        ci, co = adjust(ci), adjust(co)
        blocks = []
        for i in range(int(math.ceil(n * depth))):
            blocks.append(
                ("mb", e, k, s if i == 0 else 1, ci if i == 0 else co, co)
            )
        stages.append(blocks)
    return stages


def head_channels(variant: str) -> Tuple[int, int]:
    """(stem_channels, last_conv_channels) per torchvision's builder."""
    if variant.startswith("v2"):
        return _V2_TABLES[variant][0][4], 1280
    width = _V1_VARIANTS[variant][0]
    adjust = lambda c: _make_divisible(c * width, 8)
    return adjust(32), 4 * adjust(320)


class MBConv(nn.Module):
    expand: int
    kernel: int
    stride: int
    in_ch: int
    out_ch: int
    sd_prob: float
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x, train: bool):
        expanded = self.in_ch * self.expand
        y = x
        if expanded != self.in_ch:
            y = self.conv(expanded, (1, 1), name="expand")(y)
            y = nn.silu(self.norm(name="expand_bn")(y))
        k, p = self.kernel, self.kernel // 2
        y = self.conv(
            expanded, (k, k), strides=(self.stride, self.stride),
            padding=((p, p), (p, p)), feature_group_count=expanded,
            name="dw",
        )(y)
        y = nn.silu(self.norm(name="dw_bn")(y))
        y = SqueezeExcite(
            reduced=max(1, self.in_ch // 4), conv=self.conv,
            act=nn.silu, gate=nn.sigmoid, name="se",
        )(y)
        y = self.conv(self.out_ch, (1, 1), name="project")(y)
        y = self.norm(name="project_bn")(y)
        if self.stride == 1 and self.in_ch == self.out_ch:
            y = StochasticDepth(self.sd_prob, deterministic=not train)(y)
            y = (x + y).astype(y.dtype)
        return y


class FusedMBConv(nn.Module):
    expand: int
    kernel: int
    stride: int
    in_ch: int
    out_ch: int
    sd_prob: float
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x, train: bool):
        expanded = self.in_ch * self.expand
        k, p = self.kernel, self.kernel // 2
        if expanded != self.in_ch:
            y = self.conv(
                expanded, (k, k), strides=(self.stride, self.stride),
                padding=((p, p), (p, p)), name="fused",
            )(x)
            y = nn.silu(self.norm(name="fused_bn")(y))
            y = self.conv(self.out_ch, (1, 1), name="project")(y)
            y = self.norm(name="project_bn")(y)
        else:
            y = self.conv(
                self.out_ch, (k, k), strides=(self.stride, self.stride),
                padding=((p, p), (p, p)), name="fused",
            )(x)
            y = nn.silu(self.norm(name="fused_bn")(y))
        if self.stride == 1 and self.in_ch == self.out_ch:
            y = StochasticDepth(self.sd_prob, deterministic=not train)(y)
            y = (x + y).astype(y.dtype)
        return y


class EfficientNet(nn.Module):
    variant: str = "b0"
    num_classes: int = 1000
    stochastic_depth_rate: float = 0.2
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    bn_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=kaiming_normal_fan_out,
        )
        if self.variant.startswith("v2"):
            eps, momentum, dropout = 1e-3, 0.1, _V2_DROPOUT[self.variant]
        else:
            _, _, dropout, eps, momentum = _V1_VARIANTS[self.variant]
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=1.0 - momentum,  # torch momentum -> flax convention
            epsilon=eps,
            dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        stages = block_table(self.variant)
        stem_ch, last_ch = head_channels(self.variant)
        total = sum(len(s) for s in stages)

        x = conv(stem_ch, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                 name="stem_conv")(x)
        x = nn.silu(norm(name="stem_bn")(x))
        block_id = 0
        for si, stage in enumerate(stages):
            for bi, (kind, e, k, s, ci, co) in enumerate(stage):
                cls = FusedMBConv if kind == "fused" else MBConv
                x = cls(
                    expand=e, kernel=k, stride=s, in_ch=ci, out_ch=co,
                    sd_prob=self.stochastic_depth_rate * block_id / total,
                    conv=conv, norm=norm, name=f"stage{si}_block{bi}",
                )(x, train)
                block_id += 1
        x = conv(last_ch, (1, 1), name="head_conv")(x)
        x = nn.silu(norm(name="head_bn")(x))
        x = x.mean(axis=(1, 2))
        x = nn.Dropout(dropout, deterministic=not train)(x)
        return nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=uniform_bound_init(1.0 / math.sqrt(self.num_classes)),
            bias_init=nn.initializers.zeros,
            name="classifier",
        )(x)


register_variants(
    EfficientNet, "efficientnet", list(_V1_VARIANTS) + list(_V2_TABLES)
)
