"""MobileNetV3 (large / small), torchvision-architecture-exact, NHWC.

Registry-discoverable (imagenet_ddp.py:19-21, ``-a mobilenet_v3_large``).
Fresh Flax build of torchvision's ``mobilenetv3.py``:

* stem 3x3/2 conv (16) BN hardswish;
* inverted residuals with per-block kernel (3/5), expansion, optional
  squeeze-excitation (reduce to ``_make_divisible(expanded / 4)``, ReLU ->
  hardsigmoid gate), and ReLU or hardswish nonlinearity per the NAS
  tables;
* head 1x1 conv BN hardswish -> global average pool -> Linear(+hardswish,
  Dropout 0.2) -> Linear classifier (the two-layer classifier is where
  v3 differs from v2's single Linear).

Channel rounding via ``_make_divisible(c, 8)``. Init matches torchvision:
convs kaiming-normal fan-out, BN 1/0, Linears N(0, 0.01) with zero bias.
Param counts locked in tests/test_models.py (large = 5,483,032 /
small = 2,542,856).
"""

from functools import partial
from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from dptpu.models.layers import SqueezeExcite, kaiming_normal_fan_out
from dptpu.models.mobilenet import _make_divisible
from dptpu.models.registry import register_model

# (kernel, expanded, out, use_se, activation, stride) per block;
# activation: "RE" relu / "HS" hardswish — torchvision's bneck tables
_LARGE = (
    (3, 16, 16, False, "RE", 1),
    (3, 64, 24, False, "RE", 2),
    (3, 72, 24, False, "RE", 1),
    (5, 72, 40, True, "RE", 2),
    (5, 120, 40, True, "RE", 1),
    (5, 120, 40, True, "RE", 1),
    (3, 240, 80, False, "HS", 2),
    (3, 200, 80, False, "HS", 1),
    (3, 184, 80, False, "HS", 1),
    (3, 184, 80, False, "HS", 1),
    (3, 480, 112, True, "HS", 1),
    (3, 672, 112, True, "HS", 1),
    (5, 672, 160, True, "HS", 2),
    (5, 960, 160, True, "HS", 1),
    (5, 960, 160, True, "HS", 1),
)
_SMALL = (
    (3, 16, 16, True, "RE", 2),
    (3, 72, 24, False, "RE", 2),
    (3, 88, 24, False, "RE", 1),
    (5, 96, 40, True, "HS", 2),
    (5, 240, 40, True, "HS", 1),
    (5, 240, 40, True, "HS", 1),
    (5, 120, 48, True, "HS", 1),
    (5, 144, 48, True, "HS", 1),
    (5, 288, 96, True, "HS", 2),
    (5, 576, 96, True, "HS", 1),
    (5, 576, 96, True, "HS", 1),
)
_LAST = {"large": (960, 1280), "small": (576, 1024)}


def _act(kind, x):
    return nn.relu(x) if kind == "RE" else nn.hard_swish(x)


class Bneck(nn.Module):
    kernel: int
    expanded: int
    out_ch: int
    use_se: bool
    act: str
    stride: int
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x):
        inp = x.shape[-1]
        y = x
        if self.expanded != inp:
            y = self.conv(self.expanded, (1, 1), name="expand")(y)
            y = _act(self.act, self.norm(name="expand_bn")(y))
        k, p = self.kernel, self.kernel // 2
        y = self.conv(
            self.expanded, (k, k), strides=(self.stride, self.stride),
            padding=((p, p), (p, p)), feature_group_count=self.expanded,
            name="dw",
        )(y)
        y = _act(self.act, self.norm(name="dw_bn")(y))
        if self.use_se:
            y = SqueezeExcite(
                reduced=_make_divisible(self.expanded // 4),
                conv=self.conv, act=nn.relu, gate=nn.hard_sigmoid, name="se",
            )(y)
        y = self.conv(self.out_ch, (1, 1), name="project")(y)
        y = self.norm(name="project_bn")(y)
        if self.stride == 1 and inp == self.out_ch:
            y = (x + y).astype(y.dtype)
        return y


class MobileNetV3(nn.Module):
    size: str = "large"
    num_classes: int = 1000
    dropout_rate: float = 0.2
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    bn_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=kaiming_normal_fan_out,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.99,  # torchvision v3 BN momentum 0.01
            epsilon=1e-3,  # and eps 0.001
            dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        table = _LARGE if self.size == "large" else _SMALL
        last_conv, last_dense = _LAST[self.size]
        x = conv(16, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                 name="stem_conv")(x)
        x = nn.hard_swish(norm(name="stem_bn")(x))
        for i, (k, e, o, se, act, s) in enumerate(table):
            x = Bneck(kernel=k, expanded=e, out_ch=_make_divisible(o),
                      use_se=se, act=act, stride=s, conv=conv, norm=norm,
                      name=f"block{i}")(x)
        x = conv(last_conv, (1, 1), name="head_conv")(x)
        x = nn.hard_swish(norm(name="head_bn")(x))
        x = x.mean(axis=(1, 2))
        dense = partial(
            nn.Dense,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.normal(0.01),
            bias_init=nn.initializers.zeros,
        )
        x = dense(last_dense, name="pre_classifier")(x)
        x = nn.hard_swish(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return dense(self.num_classes, name="classifier")(x)


@register_model
def mobilenet_v3_large(**kw):
    return MobileNetV3(size="large", **kw)


@register_model
def mobilenet_v3_small(**kw):
    return MobileNetV3(size="small", **kw)
