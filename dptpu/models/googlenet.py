"""GoogLeNet (Inception v1), torchvision-architecture-exact, NHWC.

Registry-discoverable (imagenet_ddp.py:19-21, ``-a googlenet``). Fresh
Flax build of torchvision's ``googlenet.py``:

* BasicConv2d everywhere: bias-free conv -> BN(eps 1e-3) -> ReLU;
* stem 7x7/2 (64) -> ceil-pool -> 1x1 (64) -> 3x3 (192) -> ceil-pool;
* nine Inception modules (3a..5b) with the classic four branches — note
  torchvision's historical quirk, preserved here: the "5x5" branch
  actually uses a 3x3 kernel;
* optional auxiliary heads (on 4a and 4d): avg-pool to 4x4 -> 1x1 (128)
  -> fc 1024 -> dropout 0.7 -> fc. Default ``aux_logits=False``
  (6,624,904 params, torchvision's documented count); ``aux_logits=True``
  adds them to the tree (13,004,888 = 6,624,904 + 2 x 3,189,992) as an
  **inference-frozen eval/conversion mode**: their BN always uses running
  stats (so nothing keeps the branch alive and XLA dead-code-eliminates
  the unused forward) and no gradient reaches them. Note that optimizer
  weight decay still nominally applies to any parameter, so TRAIN with
  the default and use ``aux_logits=True`` to round-trip aux-bearing
  torchvision checkpoints or evaluate converted weights. Either way this
  is deliberately MORE usable than the reference, whose scripts crash on
  googlenet's train-mode namedtuple output (``criterion(GoogLeNetOutputs,
  target)``); dptpu trains the main head exactly as the reference's loss
  would if it could. (Standard checkpoints convert fine with the default
  too: extra torch keys are ignored.)

Init: torchvision uses truncated-normal(std 0.01) for conv/linear weights
(absolute clip +-2.0, which at std 0.01 is effectively untruncated; flax's
truncated_normal clips at +-2 std — indistinguishable in practice). BN
scale 1 / bias 0; Linear biases keep torch's untouched default
U(+-1/sqrt(fan_in)) — torchvision's init loop only reassigns weights.
"""

from functools import partial
from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from dptpu.models.layers import (
    adaptive_avg_pool,
    ceil_max_pool,
    torch_default_bias_init,
)
from dptpu.models.registry import register_model

_trunc001 = nn.initializers.truncated_normal(stddev=0.01)


class BasicConv2d(nn.Module):
    features: int
    kernel: tuple
    conv: Any
    norm: Any
    stride: int = 1
    padding: tuple = ((0, 0), (0, 0))

    @nn.compact
    def __call__(self, x):
        x = self.conv(
            self.features, self.kernel, strides=(self.stride, self.stride),
            padding=self.padding, name="conv",
        )(x)
        return nn.relu(self.norm(name="bn")(x))


class InceptionModule(nn.Module):
    ch1: int
    ch3red: int
    ch3: int
    ch5red: int
    ch5: int
    pool_proj: int
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x):
        bc = partial(BasicConv2d, conv=self.conv, norm=self.norm)
        b1 = bc(self.ch1, (1, 1), name="branch1")(x)
        b2 = bc(self.ch3red, (1, 1), name="branch2_0")(x)
        b2 = bc(self.ch3, (3, 3), padding=((1, 1), (1, 1)),
                name="branch2_1")(b2)
        b3 = bc(self.ch5red, (1, 1), name="branch3_0")(x)
        # torchvision quirk: the "5x5" branch is a 3x3 conv (kept for
        # checkpoint compatibility with the original implementation bug)
        b3 = bc(self.ch5, (3, 3), padding=((1, 1), (1, 1)),
                name="branch3_1")(b3)
        b4 = nn.max_pool(x, (3, 3), strides=(1, 1),
                         padding=((1, 1), (1, 1)))
        b4 = bc(self.pool_proj, (1, 1), name="branch4_1")(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionAux(nn.Module):
    """Inference-frozen aux head: BN reads running stats (never updates),
    dropout deterministic — keeps the unused branch fully dead code under
    train so XLA prunes it, and converted stats stay put."""

    num_classes: int
    conv: Any
    frozen_norm: Any
    dense: Any

    @nn.compact
    def __call__(self, x):
        x = adaptive_avg_pool(x, 4)
        x = BasicConv2d(128, (1, 1), conv=self.conv, norm=self.frozen_norm,
                        name="conv")(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(self.dense(1024, torch_default_bias_init(2048),
                               name="fc1")(x))
        return self.dense(self.num_classes, torch_default_bias_init(1024),
                          name="fc2")(x)


# (ch1, ch3red, ch3, ch5red, ch5, pool_proj) per module; "P" = ceil pool
_MODULES = [
    ("inception3a", (64, 96, 128, 16, 32, 32)),
    ("inception3b", (128, 128, 192, 32, 96, 64)), "P",
    ("inception4a", (192, 96, 208, 16, 48, 64)),
    ("inception4b", (160, 112, 224, 24, 64, 64)),
    ("inception4c", (128, 128, 256, 24, 64, 64)),
    ("inception4d", (112, 144, 288, 32, 64, 64)),
    ("inception4e", (256, 160, 320, 32, 128, 128)), "P2",
    ("inception5a", (256, 160, 320, 32, 128, 128)),
    ("inception5b", (384, 192, 384, 48, 128, 128)),
]


class GoogLeNet(nn.Module):
    num_classes: int = 1000
    aux_logits: bool = False
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    bn_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=_trunc001,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-3,  # torchvision BasicConv2d eps=0.001
            dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        def dense(features, bias_init, name):
            # torchvision's init loop only touches weights: Linear biases
            # keep torch's default U(+-1/sqrt(fan_in))
            return nn.Dense(
                features,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=_trunc001,
                bias_init=bias_init,
                name=name,
            )

        frozen_norm = partial(norm, use_running_average=True)
        bc = partial(BasicConv2d, conv=conv, norm=norm)
        x = bc(64, (7, 7), stride=2, padding=((3, 3), (3, 3)), name="conv1")(x)
        x = ceil_max_pool(x)
        x = bc(64, (1, 1), name="conv2")(x)
        x = bc(192, (3, 3), padding=((1, 1), (1, 1)), name="conv3")(x)
        x = ceil_max_pool(x)
        aux1 = aux2 = None
        for spec in _MODULES:
            if spec == "P":
                x = ceil_max_pool(x)
                continue
            if spec == "P2":
                x = ceil_max_pool(x, window=2, stride=2)
                continue
            name, chans = spec
            x = InceptionModule(*chans, conv=conv, norm=norm, name=name)(x)
            # aux heads hang off 4a and 4d (torchvision placement); their
            # outputs are traced but unused — XLA prunes the dead compute,
            # while the params stay in the tree for --pretrained parity
            if self.aux_logits and name == "inception4a":
                aux1 = InceptionAux(self.num_classes, conv=conv,
                                    frozen_norm=frozen_norm, dense=dense,
                                    name="aux1")(x)
            elif self.aux_logits and name == "inception4d":
                aux2 = InceptionAux(self.num_classes, conv=conv,
                                    frozen_norm=frozen_norm, dense=dense,
                                    name="aux2")(x)
        del aux1, aux2  # main-head training; see module docstring
        x = x.mean(axis=(1, 2))  # adaptive avg pool (1,1) + flatten
        x = nn.Dropout(0.2, deterministic=not train)(x)
        return dense(self.num_classes, torch_default_bias_init(1024),
                     name="fc")(x)


@register_model
def googlenet(**kw):
    return GoogLeNet(**kw)
