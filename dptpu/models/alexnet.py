"""AlexNet, torchvision-architecture-exact, NHWC.

Reference uses ``torchvision.models.alexnet`` (discoverable via
imagenet_ddp.py:19-21; the AlexNet/VGG DataParallel special case is
nd_imagenet.py:163-169, and BASELINE.md config 4 runs it with lr=0.01).
Architecture: 5-conv feature stack with 3 max pools → adaptive 6×6 average
pool → Dropout/4096/4096/num_classes classifier. torchvision applies no
custom init to AlexNet, so every layer uses torch's default
kaiming-uniform(a=√5) kernel + U(±1/√fan_in) bias — reproduced here.
Parameter count (61,100,840) is locked in tests/test_models.py.
"""

from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from dptpu.models.layers import (
    adaptive_avg_pool,
    max_pool_same_as_torch,
    torch_default_bias_init,
    torch_default_kernel_init,
)
from dptpu.models.registry import register_model


class AlexNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Any = None  # no BN in AlexNet; accepted for API uniformity
    bn_dtype: Any = None  # likewise accepted for API uniformity

    def _conv(self, features, kernel, stride, padding, in_features, name):
        return nn.Conv(
            features,
            (kernel, kernel),
            strides=(stride, stride),
            padding=((padding, padding), (padding, padding)),
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=torch_default_kernel_init,
            bias_init=torch_default_bias_init(in_features * kernel * kernel),
            name=name,
        )

    def _dense(self, features, fan_in, name):
        return nn.Dense(
            features,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=torch_default_kernel_init,
            bias_init=torch_default_bias_init(fan_in),
            name=name,
        )

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = self._conv(64, 11, 4, 2, 3, "features_0")(x)
        x = nn.relu(x)
        x = max_pool_same_as_torch(x, 3, 2, 0)
        x = self._conv(192, 5, 1, 2, 64, "features_3")(x)
        x = nn.relu(x)
        x = max_pool_same_as_torch(x, 3, 2, 0)
        x = self._conv(384, 3, 1, 1, 192, "features_6")(x)
        x = nn.relu(x)
        x = self._conv(256, 3, 1, 1, 384, "features_8")(x)
        x = nn.relu(x)
        x = self._conv(256, 3, 1, 1, 256, "features_10")(x)
        x = nn.relu(x)
        x = max_pool_same_as_torch(x, 3, 2, 0)
        x = adaptive_avg_pool(x, 6)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = self._dense(4096, 256 * 6 * 6, "classifier_1")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = self._dense(4096, 4096, "classifier_4")(x)
        x = nn.relu(x)
        x = self._dense(self.num_classes, 4096, "classifier_6")(x)
        return x


@register_model
def alexnet(**kw):
    return AlexNet(**kw)
