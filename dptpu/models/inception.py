"""Inception v3, torchvision-architecture-exact, NHWC (299x299 native).

Registry-discoverable (``-a inception_v3``). The reference's Apex script
rejects this arch outright (imagenet_ddp_apex.py:209-210) and the ddp/nd
scripts crash on its train-mode namedtuple output, so dptpu goes one
better: the main head trains normally; the auxiliary head is optional
(``aux_logits=True`` adds it to the parameter tree, traced but unused —
XLA prunes the dead compute; default False). Param counts:
23,834,568 without aux, 27,161,264 with — the latter is torchvision's
documented number (its default constructor carries the aux head).

Structure per torchvision ``inception.py``: BasicConv2d (bias-free conv
-> BN eps 1e-3 -> ReLU) stem 3x3/2 32 -> 3x3 32 -> 3x3p1 64 -> pool ->
1x1 80 -> 3x3 192 -> pool; InceptionA x3 (5x5 + double-3x3 + pool
branches), InceptionB (stride-2 reduction), InceptionC x4 (factorized
1x7/7x1 chains at c7 = 128/160/160/192), InceptionD (reduction),
InceptionE x2 (split 1x3/3x1 pairs); dropout 0.5; fc. ``transform_input``
reproduces torchvision's pretrained input rescaling. Init: truncated
normal, std 0.1 for convs except the aux head's documented 0.01/0.001.
"""

from functools import partial
from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from dptpu.models.googlenet import BasicConv2d
from dptpu.models.layers import max_pool_same_as_torch, torch_default_bias_init
from dptpu.models.registry import register_model


def _trunc(std):
    return nn.initializers.truncated_normal(stddev=std)


def _avg_pool_3x3_pad1(x):
    # torch AvgPool2d(3, stride=1, padding=1) with count_include_pad=True
    s = nn.avg_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)),
                    count_include_pad=True)
    return s


class InceptionA(nn.Module):
    pool_features: int
    bc: Any

    @nn.compact
    def __call__(self, x):
        b1 = self.bc(64, (1, 1), name="branch1x1")(x)
        b5 = self.bc(48, (1, 1), name="branch5x5_1")(x)
        b5 = self.bc(64, (5, 5), padding=((2, 2), (2, 2)),
                     name="branch5x5_2")(b5)
        b3 = self.bc(64, (1, 1), name="branch3x3dbl_1")(x)
        b3 = self.bc(96, (3, 3), padding=((1, 1), (1, 1)),
                     name="branch3x3dbl_2")(b3)
        b3 = self.bc(96, (3, 3), padding=((1, 1), (1, 1)),
                     name="branch3x3dbl_3")(b3)
        bp = _avg_pool_3x3_pad1(x)
        bp = self.bc(self.pool_features, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    bc: Any

    @nn.compact
    def __call__(self, x):
        b3 = self.bc(384, (3, 3), stride=2, name="branch3x3")(x)
        bd = self.bc(64, (1, 1), name="branch3x3dbl_1")(x)
        bd = self.bc(96, (3, 3), padding=((1, 1), (1, 1)),
                     name="branch3x3dbl_2")(bd)
        bd = self.bc(96, (3, 3), stride=2, name="branch3x3dbl_3")(bd)
        bp = max_pool_same_as_torch(x, 3, 2, 0)
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    c7: int
    bc: Any

    @nn.compact
    def __call__(self, x):
        c7 = self.c7
        b1 = self.bc(192, (1, 1), name="branch1x1")(x)
        b7 = self.bc(c7, (1, 1), name="branch7x7_1")(x)
        b7 = self.bc(c7, (1, 7), padding=((0, 0), (3, 3)),
                     name="branch7x7_2")(b7)
        b7 = self.bc(192, (7, 1), padding=((3, 3), (0, 0)),
                     name="branch7x7_3")(b7)
        bd = self.bc(c7, (1, 1), name="branch7x7dbl_1")(x)
        bd = self.bc(c7, (7, 1), padding=((3, 3), (0, 0)),
                     name="branch7x7dbl_2")(bd)
        bd = self.bc(c7, (1, 7), padding=((0, 0), (3, 3)),
                     name="branch7x7dbl_3")(bd)
        bd = self.bc(c7, (7, 1), padding=((3, 3), (0, 0)),
                     name="branch7x7dbl_4")(bd)
        bd = self.bc(192, (1, 7), padding=((0, 0), (3, 3)),
                     name="branch7x7dbl_5")(bd)
        bp = _avg_pool_3x3_pad1(x)
        bp = self.bc(192, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    bc: Any

    @nn.compact
    def __call__(self, x):
        b3 = self.bc(192, (1, 1), name="branch3x3_1")(x)
        b3 = self.bc(320, (3, 3), stride=2, name="branch3x3_2")(b3)
        b7 = self.bc(192, (1, 1), name="branch7x7x3_1")(x)
        b7 = self.bc(192, (1, 7), padding=((0, 0), (3, 3)),
                     name="branch7x7x3_2")(b7)
        b7 = self.bc(192, (7, 1), padding=((3, 3), (0, 0)),
                     name="branch7x7x3_3")(b7)
        b7 = self.bc(192, (3, 3), stride=2, name="branch7x7x3_4")(b7)
        bp = max_pool_same_as_torch(x, 3, 2, 0)
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    bc: Any

    @nn.compact
    def __call__(self, x):
        b1 = self.bc(320, (1, 1), name="branch1x1")(x)
        b3 = self.bc(384, (1, 1), name="branch3x3_1")(x)
        b3 = jnp.concatenate([
            self.bc(384, (1, 3), padding=((0, 0), (1, 1)),
                    name="branch3x3_2a")(b3),
            self.bc(384, (3, 1), padding=((1, 1), (0, 0)),
                    name="branch3x3_2b")(b3),
        ], axis=-1)
        bd = self.bc(448, (1, 1), name="branch3x3dbl_1")(x)
        bd = self.bc(384, (3, 3), padding=((1, 1), (1, 1)),
                     name="branch3x3dbl_2")(bd)
        bd = jnp.concatenate([
            self.bc(384, (1, 3), padding=((0, 0), (1, 1)),
                    name="branch3x3dbl_3a")(bd),
            self.bc(384, (3, 1), padding=((1, 1), (0, 0)),
                    name="branch3x3dbl_3b")(bd),
        ], axis=-1)
        bp = _avg_pool_3x3_pad1(x)
        bp = self.bc(192, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3Aux(nn.Module):
    """Inference-frozen aux head (see googlenet.InceptionAux): BN reads
    running stats so the unused branch stays dead code under train."""

    num_classes: int
    conv01: Any
    frozen_norm: Any
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x):
        bc = partial(BasicConv2d, conv=self.conv01, norm=self.frozen_norm)
        a = nn.avg_pool(x, (5, 5), strides=(3, 3))
        a = bc(128, (1, 1), name="conv0")(a)
        a = bc(768, (5, 5), name="conv1")(a)
        a = a.mean(axis=(1, 2))
        return nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=_trunc(0.001),
            bias_init=torch_default_bias_init(768),
            name="fc",
        )(a)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    aux_logits: bool = False
    transform_input: bool = False
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    bn_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        def conv_with(std):
            return partial(
                nn.Conv,
                use_bias=False,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=_trunc(std),
            )

        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-3,
            dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        bc = partial(BasicConv2d, conv=conv_with(0.1), norm=norm)
        if self.transform_input:
            # torchvision's pretrained input remapping (inception.py)
            ch = [
                x[..., i:i + 1] * s + b
                for i, (s, b) in enumerate([
                    (0.229 / 0.5, (0.485 - 0.5) / 0.5),
                    (0.224 / 0.5, (0.456 - 0.5) / 0.5),
                    (0.225 / 0.5, (0.406 - 0.5) / 0.5),
                ])
            ]
            x = jnp.concatenate(ch, axis=-1)
        x = bc(32, (3, 3), stride=2, name="Conv2d_1a_3x3")(x)
        x = bc(32, (3, 3), name="Conv2d_2a_3x3")(x)
        x = bc(64, (3, 3), padding=((1, 1), (1, 1)), name="Conv2d_2b_3x3")(x)
        x = max_pool_same_as_torch(x, 3, 2, 0)
        x = bc(80, (1, 1), name="Conv2d_3b_1x1")(x)
        x = bc(192, (3, 3), name="Conv2d_4a_3x3")(x)
        x = max_pool_same_as_torch(x, 3, 2, 0)
        x = InceptionA(pool_features=32, bc=bc, name="Mixed_5b")(x)
        x = InceptionA(pool_features=64, bc=bc, name="Mixed_5c")(x)
        x = InceptionA(pool_features=64, bc=bc, name="Mixed_5d")(x)
        x = InceptionB(bc=bc, name="Mixed_6a")(x)
        x = InceptionC(c7=128, bc=bc, name="Mixed_6b")(x)
        x = InceptionC(c7=160, bc=bc, name="Mixed_6c")(x)
        x = InceptionC(c7=160, bc=bc, name="Mixed_6d")(x)
        x = InceptionC(c7=192, bc=bc, name="Mixed_6e")(x)
        if self.aux_logits:
            # inference-frozen, traced but unused (XLA prunes the dead
            # branch); params stay in the tree for --pretrained round trips
            _ = InceptionV3Aux(
                self.num_classes,
                conv01=conv_with(0.01),
                frozen_norm=partial(norm, use_running_average=True),
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="AuxLogits",
            )(x)
        x = InceptionD(bc=bc, name="Mixed_7a")(x)
        x = InceptionE(bc=bc, name="Mixed_7b")(x)
        x = InceptionE(bc=bc, name="Mixed_7c")(x)
        x = x.mean(axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=_trunc(0.1),
            bias_init=torch_default_bias_init(2048),  # torch default kept
            name="fc",
        )(x)


@register_model
def inception_v3(**kw):
    return InceptionV3(**kw)
