"""Model registry with torchvision discovery semantics.

The reference discovers architectures as "any lowercase, non-dunder,
callable name in ``torchvision.models.__dict__``" (imagenet_ddp.py:19-21)
and instantiates with ``models.__dict__[args.arch]()``
(imagenet_ddp.py:111-114). This registry reproduces that contract for the
in-tree Flax zoo: ``model_names()`` feeds the CLI ``choices`` and
``create_model(name)`` is the ``models.__dict__[arch]()`` analog.
"""

_REGISTRY = {}


def register_model(fn):
    """Decorator: register a lowercase factory under its function name."""
    name = fn.__name__
    assert name.islower() and not name.startswith("__")
    _REGISTRY[name] = fn
    return fn


def register_variants(model_cls, prefix, variants, field="variant"):
    """Register ``{prefix}_{v}`` factories for a config-parameterized
    model class (EfficientNet/RegNet/ViT-style variant tables)."""
    for v in variants:
        def fn(_v=v, **kw):
            return model_cls(**{field: _v}, **kw)

        fn.__name__ = f"{prefix}_{v}"
        register_model(fn)


def model_names():
    """Sorted architecture names (imagenet_ddp.py:19-21 semantics)."""
    return sorted(_REGISTRY)


def create_model(name, pretrained=False, **kwargs):
    """``models.__dict__[arch](pretrained=...)`` analog (imagenet_ddp.py:108-114).

    With ``pretrained=True`` the converted-weights file for ``name`` must
    exist (``$DPTPU_PRETRAINED_DIR`` or ``./pretrained``); this validates
    it up front so the CLI fails fast with conversion instructions. The
    weights themselves are applied at init time via
    ``dptpu.models.pretrained.load_pretrained_variables`` (flax modules
    are stateless, so construction cannot carry them the way torch does).
    """
    if name not in _REGISTRY:
        raise KeyError(f"unknown architecture {name!r}; choices: {model_names()}")
    if pretrained:
        from dptpu.models.pretrained import require_weights

        require_weights(name)
    return _REGISTRY[name](**kwargs)
