"""Model registry with torchvision discovery semantics.

The reference discovers architectures as "any lowercase, non-dunder,
callable name in ``torchvision.models.__dict__``" (imagenet_ddp.py:19-21)
and instantiates with ``models.__dict__[args.arch]()``
(imagenet_ddp.py:111-114). This registry reproduces that contract for the
in-tree Flax zoo: ``model_names()`` feeds the CLI ``choices`` and
``create_model(name)`` is the ``models.__dict__[arch]()`` analog.
"""

from jax.sharding import PartitionSpec as P

from dptpu.parallel.rules import AUTO_FSDP

_REGISTRY = {}

# --------------------------------------------------------------------------
# Partition rules: ONE declaration per family covers DP x TP x FSDP.
#
# Each table is an ordered (regex, spec) list over the FULL {data, model}
# axis vocabulary, resolved by dptpu/parallel/rules.py
# ``match_partition_rules`` (first match wins against the "/"-joined param
# path; the mandatory ``.*`` fallback closes every table). Consumers
# PROJECT the one table onto their mesh: keep ``model`` and you get the
# Megatron TP placement (the specs tests/test_gspmd.py locks, and what
# serve uses); keep ``data`` and you get the ZeRO-3/FSDP layout; keep both
# and one declaration yields the combined DPxTPxFSDP placement. The
# ``(^|/)`` anchors pin whole path segments — ``proj`` must not claim
# ``out_proj`` — reproducing the old per-module name checks exactly.
#
# Grammar per rule:
#   P("data", "model")     kernel: dim0 FSDP-sharded, dim1 column-parallel
#   P("model", "data")     kernel: dim0 row-parallel, dim1 FSDP-sharded
#   P(("data", "model"))   bias of a column-parallel layer: its one dim
#                          carries both axes (TP projection -> P("model"),
#                          FSDP projection -> P("data"))
#   P("data")              bias of a row-parallel layer: TP-replicated
#   AUTO_FSDP              everything else: largest evenly-divisible dim
#                          over ``data`` (mesh.largest_divisible_dim),
#                          replicated under pure TP
#
# Family notes (the WHY lives with the old spec functions' docstrings,
# now in dptpu/parallel/gspmd.py consumer docs): ViT and Swin fused-qkv
# kernels are stored head-major, so the contiguous column split is
# head-aligned; Swin v1's relative-position-bias table and v2's
# logit_scale/cpb_mlp_2 shard on their heads dim (the variant-specific
# rows are dead on the OTHER variant by construction — the check rule
# aggregates liveness across the family, not per model); ConvNeXt only
# TPs its pointwise MLP pair; classic CNNs and MaxViT take the pure
# AUTO_FSDP table (conv TP is deliberately not shipped — see
# gspmd.dp_specs).

VIT_RULES = (
    (r"(^|/)(in_proj|mlp_1)/kernel$", P("data", "model")),
    (r"(^|/)(in_proj|mlp_1)/bias$", P(("data", "model"))),
    (r"(^|/)(out_proj|mlp_2)/kernel$", P("model", "data")),
    (r"(^|/)(out_proj|mlp_2)/bias$", P("data")),
    (r".*", AUTO_FSDP),
)

SWIN_RULES = (
    (r"(^|/)(qkv|cpb_mlp_2|mlp_1)/kernel$", P("data", "model")),
    (r"(^|/)(qkv|cpb_mlp_2|mlp_1)/bias$", P(("data", "model"))),
    (r"(^|/)(proj|mlp_2)/kernel$", P("model", "data")),
    (r"(^|/)(proj|mlp_2)/bias$", P("data")),
    (r"(^|/)logit_scale$", P("model")),
    (r"(^|/)relative_position_bias_table$", P("data", "model")),
    (r".*", AUTO_FSDP),
)

CONVNEXT_RULES = (
    (r"(^|/)mlp_1/kernel$", P("data", "model")),
    (r"(^|/)mlp_1/bias$", P(("data", "model"))),
    (r"(^|/)mlp_2/kernel$", P("model", "data")),
    (r"(^|/)mlp_2/bias$", P("data")),
    (r".*", AUTO_FSDP),
)

GENERIC_RULES = ((r".*", AUTO_FSDP),)

FAMILY_RULES = {
    "vit": VIT_RULES,
    "swin": SWIN_RULES,
    "convnext": CONVNEXT_RULES,
    "generic": GENERIC_RULES,
}


def partition_family(arch: str) -> str:
    """Family key for an arch name — arch-name-only (no params needed)
    so ``fit()`` can pick mesh geometry BEFORE model construction, the
    same early-decision contract ``gspmd.tp_rule_for_arch`` keeps.

    ``DPTPU_RULES=<family>`` overrides the name-derived family for EVERY
    placement consumer at once (ZeRO-3, GSPMD, serve TP) — the escape
    hatch for an arch whose name doesn't encode its structure (a custom
    registry entry with ViT-shaped blocks can opt into the vit table
    instead of the generic AUTO_FSDP fallback). Fail-fast contract: an
    unknown family raises naming the valid choices."""
    from dptpu.envknob import env_choice

    override = env_choice("DPTPU_RULES", tuple(sorted(FAMILY_RULES)), None)
    if override is not None:
        return override
    if arch.startswith("vit_"):
        return "vit"
    if arch.startswith("swin"):
        return "swin"
    if arch.startswith("convnext"):
        return "convnext"
    return "generic"


def partition_rules_for_arch(arch: str):
    """THE sharding declaration for an arch: its family's ordered rules
    table. Every placement consumer (ZeRO-3 state layout, GSPMD/pjit
    shardings, serve TP) projects this one table."""
    return FAMILY_RULES[partition_family(arch)]


def register_model(fn):
    """Decorator: register a lowercase factory under its function name."""
    name = fn.__name__
    assert name.islower() and not name.startswith("__")
    _REGISTRY[name] = fn
    return fn


def register_variants(model_cls, prefix, variants, field="variant"):
    """Register ``{prefix}_{v}`` factories for a config-parameterized
    model class (EfficientNet/RegNet/ViT-style variant tables)."""
    for v in variants:
        def fn(_v=v, **kw):
            return model_cls(**{field: _v}, **kw)

        fn.__name__ = f"{prefix}_{v}"
        register_model(fn)


def model_names():
    """Sorted architecture names (imagenet_ddp.py:19-21 semantics)."""
    return sorted(_REGISTRY)


def create_model(name, pretrained=False, **kwargs):
    """``models.__dict__[arch](pretrained=...)`` analog (imagenet_ddp.py:108-114).

    With ``pretrained=True`` the converted-weights file for ``name`` must
    exist (``$DPTPU_PRETRAINED_DIR`` or ``./pretrained``); this validates
    it up front so the CLI fails fast with conversion instructions. The
    weights themselves are applied at init time via
    ``dptpu.models.pretrained.load_pretrained_variables`` (flax modules
    are stateless, so construction cannot carry them the way torch does).
    """
    if name not in _REGISTRY:
        raise KeyError(f"unknown architecture {name!r}; choices: {model_names()}")
    if pretrained:
        from dptpu.models.pretrained import require_weights

        require_weights(name)
    return _REGISTRY[name](**kwargs)
