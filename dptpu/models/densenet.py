"""DenseNet family (121/161/169/201), torchvision-architecture-exact, NHWC.

The reference discovers these through the lowercase-callable registry
(imagenet_ddp.py:19-21, e.g. ``-a densenet121``). Fresh Flax
implementation of torchvision's ``densenet.py`` structure:

* stem: 7x7/2 conv (``num_init_features``) -> BN -> ReLU -> 3x3/2 max pool;
* dense blocks of bottleneck layers ``BN -> ReLU -> 1x1 conv
  (bn_size * growth) -> BN -> ReLU -> 3x3 conv (growth)``, each layer's
  output concatenated onto the running feature map (channels-minor concat
  is free in NHWC — it is exactly the memory layout the MXU wants);
* transitions ``BN -> ReLU -> 1x1 conv (halve channels) -> 2x2/2 avg pool``
  between blocks;
* final BN -> ReLU -> global average pool -> Linear classifier (with bias).

Init matches torchvision's ``_DenseNet.__init__`` loop: conv kernels
``kaiming_normal_`` (torch default mode='fan_in'), BN scale 1 / bias 0,
classifier bias 0 with torch's default kaiming-uniform kernel. Parameter
counts are locked in tests/test_models.py (densenet121 = 7,978,856).

Same compute-policy surface as ResNet: ``dtype`` (bf16 compute),
``bn_dtype`` (pin BN I/O to f32), ``bn_axis_name`` (SyncBN pmean).
"""

from functools import partial
from typing import Any, Optional, Sequence

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from dptpu.models.layers import (
    max_pool_same_as_torch,
    torch_default_kernel_init,
)
from dptpu.models.registry import register_model

# kaiming_normal_(mode='fan_in', nonlinearity='relu') — torchvision's
# DenseNet conv init (ResNet uses fan_out; DenseNet keeps torch's default)
kaiming_normal_fan_in = nn.initializers.variance_scaling(
    2.0, "fan_in", "normal"
)


class DenseLayer(nn.Module):
    growth_rate: int
    bn_size: int
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x):
        y = self.norm(name="norm1")(x)
        y = nn.relu(y)
        y = self.conv(self.bn_size * self.growth_rate, (1, 1), name="conv1")(y)
        y = self.norm(name="norm2")(y)
        y = nn.relu(y)
        y = self.conv(
            self.growth_rate, (3, 3), padding=((1, 1), (1, 1)), name="conv2"
        )(y)
        return jnp.concatenate([x, y], axis=-1)


class Transition(nn.Module):
    out_features: int
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x):
        x = self.norm(name="norm")(x)
        x = nn.relu(x)
        x = self.conv(self.out_features, (1, 1), name="conv")(x)
        return nn.avg_pool(x, (2, 2), strides=(2, 2))


class DenseNet(nn.Module):
    block_config: Sequence[int]
    growth_rate: int
    num_init_features: int
    bn_size: int = 4
    num_classes: int = 1000
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    bn_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=kaiming_normal_fan_in,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.bn_dtype if self.bn_dtype is not None else self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        x = conv(
            self.num_init_features,
            (7, 7),
            strides=(2, 2),
            padding=((3, 3), (3, 3)),
            name="conv0",
        )(x)
        x = norm(name="norm0")(x)
        x = nn.relu(x)
        x = max_pool_same_as_torch(x, 3, 2, 1)
        features = self.num_init_features
        for i, n_layers in enumerate(self.block_config):
            for j in range(n_layers):
                x = DenseLayer(
                    growth_rate=self.growth_rate,
                    bn_size=self.bn_size,
                    conv=conv,
                    norm=norm,
                    name=f"denseblock{i + 1}_layer{j + 1}",
                )(x)
            features += n_layers * self.growth_rate
            if i != len(self.block_config) - 1:
                features //= 2
                x = Transition(
                    out_features=features,
                    conv=conv,
                    norm=norm,
                    name=f"transition{i + 1}",
                )(x)
        x = norm(name="norm5")(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))  # adaptive_avg_pool2d((1,1)) + flatten
        x = nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=torch_default_kernel_init,
            bias_init=nn.initializers.zeros,  # torchvision: constant_(bias, 0)
            name="classifier",
        )(x)
        return x


def _densenet(block_config, growth_rate, num_init_features, **kwargs):
    return DenseNet(
        block_config=block_config,
        growth_rate=growth_rate,
        num_init_features=num_init_features,
        **kwargs,
    )


@register_model
def densenet121(**kw):
    return _densenet((6, 12, 24, 16), 32, 64, **kw)


@register_model
def densenet161(**kw):
    return _densenet((6, 12, 36, 24), 48, 96, **kw)


@register_model
def densenet169(**kw):
    return _densenet((6, 12, 32, 32), 32, 64, **kw)


@register_model
def densenet201(**kw):
    return _densenet((6, 12, 48, 32), 32, 64, **kw)
