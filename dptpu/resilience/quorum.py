"""Quorum mid-epoch saves: barrier-with-deadline over the pod's
coordination seam.

The gap (open since PR 2, fit.py's ``_preempt_save_ok``): on a sharded
multi-host run, a preemption signal that reaches only ONE host cannot
safely save — the gathered checkpoint is a collective, and a host that
enters it alone hangs the pod. Today that host just skips the save and
the boundary checkpoint stands, losing up to an epoch.

This module closes it with a tiny agreement protocol over a key-value
store (the "coordination seam" — on a real pod the jax.distributed
coordination service every rank already rendezvoused through; on one
machine, or in tests, a shared directory):

1. the host that caught the signal posts a STOP REQUEST;
2. every host polls the store once per optimizer step; on seeing the
   request each posts READY = its own completed-step count;
3. once all ``num_hosts`` READY keys exist, the agreed stop step is
   ``max(ready)`` — every host keeps stepping to exactly that step
   (deterministic: all hosts train the same global step sequence), so
   the pod stops POD-CONSISTENTLY and the chief's mid-epoch save names
   a position every host actually reached;
4. a barrier-with-deadline guards the gathered save itself: only when
   every host checked in does anyone enter the collective.

On seeing the request a host posts READY and HOLDS inside the tick
until the pod agrees — a fast host must not dispatch past the agreed
step, or the pod would stop at different dispatch counts. Every wait is
bounded by ``DPTPU_QUORUM_DEADLINE_S``: a host that never answers (it
is the one being preempted to death, after all) degrades the protocol
loudly — the requester stops at its own step and the save falls back to
the PR-2 rules (skip the gathered save rather than hang). A single-host
run degenerates exactly to the PreemptionGuard path: the request, READY
and barrier are all satisfied by the one host in the same tick, and the
save lands at the same step a plain SIGTERM would have produced.

KNOWN LIMIT (multi-host, recorded in ROADMAP item 3 residuals): ticks
run on the host thread between steps, so a peer whose host thread is
parked inside a blocking device fetch (a metric sync of a step the
holding host has not dispatched, a synchronous checkpoint gather)
cannot post READY until that fetch resolves — if it never does, the
holder degrades at the deadline and the parked peer stays inside its
fetch. The train loop's lagged metric fetches make the window small
(it only syncs steps every host has already dispatched, except the
epoch-opening display), but closing it fully needs a tick source off
the host thread — real multi-host hardware work.

Transports:

* :class:`FileKVStore` — atomic-rename files under a shared directory
  (``DPTPU_QUORUM_DIR``). The test/bench seam, and a real option for
  single-machine multi-process pods or NFS-shared clusters.
* :class:`JaxKVStore` — the jax.distributed coordination service's
  key-value API, when a multi-host session is live. Best-effort by
  construction (the API is private); unavailable transports make
  :func:`make_coordinator` return None and fit keeps PR-2 behavior.
"""

from __future__ import annotations

import json
import os
import signal
import tempfile
import threading
import time
from typing import Dict, Optional

from dptpu.envknob import env_float
from dptpu.utils.sync import StopToken


def quorum_deadline_knob(environ=None) -> float:
    """``DPTPU_QUORUM_DEADLINE_S`` under the locked fail-fast contract:
    how long any quorum wait (READY collection, save barrier) may block
    before degrading. Default 30 s — short enough to fit inside every
    cloud provider's preemption grace window with room for the save."""
    deadline = env_float("DPTPU_QUORUM_DEADLINE_S", 30.0, environ)
    if deadline <= 0:
        raise ValueError(
            f"DPTPU_QUORUM_DEADLINE_S={deadline} must be > 0 seconds "
            f"(the bound on every quorum wait; e.g. "
            f"DPTPU_QUORUM_DEADLINE_S=30)"
        )
    return float(deadline)


class FileKVStore:
    """Key-value store over a shared directory: one file per key,
    written atomically (tempfile + rename in the same directory), so a
    reader never sees a torn value. Keys are flat names (the
    coordinator uses ``/``-free keys)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, key)

    def put(self, key: str, value: str):
        fd, tmp = tempfile.mkstemp(prefix=f".{key}.", dir=self.directory)
        try:
            with os.fdopen(fd, "w") as f:
                f.write(value)
            os.replace(tmp, self._path(key))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def get(self, key: str) -> Optional[str]:
        try:
            with open(self._path(key)) as f:
                return f.read()
        except OSError:
            return None

    def scan(self, prefix: str) -> Dict[str, str]:
        out = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if name.startswith(prefix) and not name.startswith("."):
                v = self.get(name)
                if v is not None:
                    out[name] = v
        return out


class JaxKVStore:
    """The jax.distributed coordination service as a KV transport.

    Uses the private client the rendezvous already established — the
    same seam every multi-host collective rides. ``available()`` gates
    construction; any API drift degrades to "no coordinator" rather
    than crashing a preempting pod."""

    def __init__(self, prefix: str = "dptpu_quorum/"):
        from jax._src.distributed import global_state

        if global_state.client is None:
            raise RuntimeError("jax.distributed client is not initialized")
        self._client = global_state.client
        self._prefix = prefix

    @staticmethod
    def available() -> bool:
        try:
            from jax._src.distributed import global_state

            return global_state.client is not None
        except Exception:
            return False

    def put(self, key: str, value: str):
        self._client.key_value_set(self._prefix + key, value)

    def get(self, key: str) -> Optional[str]:
        try:
            # non-blocking probe; absent keys raise in this API
            return self._client.key_value_try_get(self._prefix + key)
        except Exception:
            return None


class QuorumCoordinator:
    """The agreement protocol over a KV transport (see module doc).

    Host-indexed keys: ``stop`` (the request), ``ready-<h>`` (each
    host's completed step when it saw the request), ``barrier-<tag>-<h>``
    (save barrier check-ins), ``beat-<h>`` (liveness heartbeats for the
    chief-side lost-host verdict). All values are JSON with wall-clock
    timestamps, so deadline accounting works across hosts with roughly
    synchronized clocks (cloud pods are NTP-disciplined)."""

    def __init__(self, store, host_id: int, num_hosts: int,
                 deadline_s: float = 30.0, namespace: str = ""):
        if num_hosts < 1 or not 0 <= host_id < num_hosts:
            raise ValueError(
                f"quorum host_id {host_id} must be in [0, {num_hosts})"
            )
        self.store = store
        self.host_id = host_id
        self.num_hosts = num_hosts
        self.deadline_s = deadline_s
        # per-run-attempt key prefix: a restart pointed at the SAME
        # store (DPTPU_QUORUM_DIR is a config knob — it survives the
        # resume) must not re-read the previous attempt's stop request
        # and immediately re-preempt itself forever. fit derives the
        # namespace from the resume position, which every host shares.
        # Heartbeats stay UN-namespaced: liveness spans attempts and
        # missing_hosts already ages stale beats out by timestamp.
        self.namespace = namespace

    def _key(self, key: str) -> str:
        return self.namespace + key

    # -- stop request / agreement ------------------------------------------

    def request_stop(self, step: int, reason: str = "sigterm"):
        """Post the stop request (idempotent: first writer wins the
        ``reason``; later writers only confirm it exists)."""
        if self.store.get(self._key("stop")) is None:
            self.store.put(self._key("stop"), json.dumps({
                "reason": reason, "host": self.host_id, "step": int(step),
                "ts": time.time(),  # dptpu: allow-determinism(stop-record timestamp is operator telemetry; replay keys on step, never on ts)
            }))

    def pending_stop(self) -> Optional[dict]:
        raw = self.store.get(self._key("stop"))
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return {"reason": "unparseable", "ts": 0.0}

    def post_ready(self, step: int):
        self.store.put(self._key(f"ready-{self.host_id}"), json.dumps({
            "step": int(step), "ts": time.time(),  # dptpu: allow-determinism(ready-record timestamp is telemetry; the quorum agrees on the max ready STEP, never on ts)
        }))

    def ready_steps(self) -> Dict[int, int]:
        out = {}
        for h in range(self.num_hosts):
            raw = self.store.get(self._key(f"ready-{h}"))
            if raw is None:
                continue
            try:
                out[h] = int(json.loads(raw)["step"])
            except (ValueError, KeyError, TypeError):
                continue
        return out

    def agreed_step(self) -> Optional[int]:
        """``max(ready)`` once every host posted READY; None before.
        Deadline handling lives in the caller (QuorumSession), which
        knows when the request was first seen."""
        ready = self.ready_steps()
        if len(ready) < self.num_hosts:
            return None
        return max(ready.values())

    # -- save barrier -------------------------------------------------------

    def barrier(self, tag: str, timeout_s: Optional[float] = None,
                poll_s: float = 0.02) -> bool:
        """Check in and wait (bounded) for every host; True only when
        the full pod arrived — the caller may then enter the gathered
        save knowing no host joins the collective alone."""
        timeout_s = self.deadline_s if timeout_s is None else timeout_s
        self.store.put(self._key(f"barrier-{tag}-{self.host_id}"),
                       json.dumps({"ts": time.time()}))  # dptpu: allow-determinism(barrier arrival stamp is telemetry; the barrier itself runs on monotonic deadlines)
        deadline = time.monotonic() + timeout_s
        while True:
            present = sum(
                1 for h in range(self.num_hosts)
                if self.store.get(self._key(f"barrier-{tag}-{h}"))
                is not None
            )
            if present >= self.num_hosts:
                return True
            if time.monotonic() > deadline:
                return False
            time.sleep(poll_s)

    # -- liveness (chief-side lost-host verdict) ---------------------------

    def heartbeat(self, step: int):
        self.store.put(f"beat-{self.host_id}", json.dumps({
            "step": int(step), "ts": time.time(),  # dptpu: allow-determinism(heartbeat liveness IS wall-clock by design — staleness ages out by real elapsed time)
        }))

    def missing_hosts(self, timeout_s: Optional[float] = None) -> list:
        """Hosts with no heartbeat within ``timeout_s`` — the chief's
        "gone for good" input that ultimately triggers elastic resume
        (a host that never beat at all counts as missing too)."""
        timeout_s = self.deadline_s if timeout_s is None else timeout_s
        now = time.time()  # dptpu: allow-determinism(liveness aging compares heartbeat wall-clock stamps; no replayed value derives from it)
        gone = []
        for h in range(self.num_hosts):
            raw = self.store.get(f"beat-{h}")
            ts = None
            if raw is not None:
                try:
                    ts = float(json.loads(raw)["ts"])
                except (ValueError, KeyError, TypeError):
                    ts = None
            if ts is None or now - ts > timeout_s:
                gone.append(h)
        return gone


def make_coordinator(num_hosts: int, host_id: int, deadline_s: float,
                     directory: Optional[str] = None,
                     namespace: str = ""
                     ) -> Optional[QuorumCoordinator]:
    """Build the pod coordinator over the best available transport:
    an explicit shared directory (``DPTPU_QUORUM_DIR`` — tests, benches,
    single-machine pods, NFS clusters) wins; else the live
    jax.distributed KV service on a multi-host run; else None (fit
    keeps the PR-2 single-signal rules). ``namespace`` scopes the
    protocol keys to one run attempt (see QuorumCoordinator)."""
    if directory:
        return QuorumCoordinator(
            FileKVStore(directory), host_id, num_hosts, deadline_s,
            namespace=namespace,
        )
    if num_hosts > 1 and JaxKVStore.available():
        try:
            return QuorumCoordinator(
                JaxKVStore(), host_id, num_hosts, deadline_s,
                namespace=namespace,
            )
        except Exception:
            return None
    return None


class QuorumHeartbeat:
    """Liveness beats from a dedicated thread — the tick source OFF the
    host thread that ROADMAP item 3 residual (d) called for: a peer
    parked inside a blocking device fetch keeps beating, so the chief's
    ``missing_hosts`` verdict distinguishes "slow step" from "gone".

    Teardown rides the shared :class:`dptpu.utils.sync.StopToken`
    idiom: the loop blocks in ``Event.wait(interval)`` (never a bare
    ``time.sleep`` + flag poll), so ``close()`` wakes it immediately
    and joins promptly — the conftest thread census never sees a
    lingering beat thread.
    """

    def __init__(self, coordinator: QuorumCoordinator, step_fn,
                 interval_s: float = 1.0):
        if interval_s <= 0:
            raise ValueError(
                f"heartbeat interval_s={interval_s} must be > 0 seconds"
            )
        self.coord = coordinator
        self.interval_s = float(interval_s)
        self._step_fn = step_fn
        self._stop = StopToken()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dptpu-quorum-heartbeat"
        )
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.coord.heartbeat(int(self._step_fn()))
            except Exception:
                # liveness is best-effort by design: a flaky KV write
                # must never kill the beat loop (a missing beat ages
                # out; a dead beat thread looks like a dead host)
                pass

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def close(self):
        self._stop.stop()
        self._thread.join(timeout=5.0)


class QuorumSession:
    """Per-``fit()`` driver of the protocol: one ``tick()`` per
    completed optimizer step (riding the same post-step hook as fault
    injection), one ``should_stop()`` consult per loop iteration, one
    ``save_barrier()`` before the gathered preemption save.

    State machine: idle → (local signal or store-side request) READY
    posted → (all hosts ready) ARMED at ``max(ready)`` → (reached it)
    STOP. The deadline starts when this host first sees the request; on
    expiry it degrades — stop at the local step, remember
    ``degraded=True`` so ``save_barrier`` refuses and the PR-2 fallback
    rules decide the save."""

    def __init__(self, coordinator: QuorumCoordinator, guard,
                 deadline_s: Optional[float] = None):
        self.coord = coordinator
        self.guard = guard  # PreemptionGuard: .requested / .signum
        self.deadline_s = (
            coordinator.deadline_s if deadline_s is None else deadline_s
        )
        self.epoch = 0
        self.step = 0  # completed steps this epoch (position coords)
        self._posted_request = False
        self._ready_step: Optional[int] = None
        self._agreed: Optional[int] = None
        self._degraded = False
        self._stop = False
        self._reason = ""
        # heartbeats are throttled: liveness needs ~1 Hz, not one KV
        # write per optimizer step (the store may be the pod's real
        # coordination service). start_heartbeat() moves them onto a
        # dedicated QuorumHeartbeat thread; the inline tick beats are
        # the fallback when no thread was started (unit tests driving
        # tick() directly keep their behavior).
        self._beat_every_s = 1.0
        self._last_beat = 0.0
        self._hb: Optional[QuorumHeartbeat] = None

    # -- off-thread liveness ------------------------------------------------

    def start_heartbeat(self, interval_s: float = 1.0) -> QuorumHeartbeat:
        """Move liveness beats onto a dedicated thread (fit() does this
        right after arming the session). Idempotent."""
        if self._hb is None:
            # reading self.step from the beat thread is a single int
            # load of caller-owned state: atomic under the GIL, and a
            # one-step-stale beat is indistinguishable from a beat that
            # raced the step boundary
            self._hb = QuorumHeartbeat(
                self.coord, lambda: self.step, interval_s
            )
        return self._hb

    def close(self):
        """Stop the heartbeat thread (prompt — StopToken teardown)."""
        if self._hb is not None:
            self._hb.close()
            self._hb = None

    # -- position ----------------------------------------------------------

    def epoch_start(self, epoch: int, step: int):
        self.epoch = epoch
        self.step = step

    # -- the per-step tick --------------------------------------------------

    def tick(self):
        """Called once after every completed optimizer step."""
        self.step += 1
        if self._hb is None:
            now = time.monotonic()
            if now - self._last_beat >= self._beat_every_s:
                self.coord.heartbeat(self.step)
                self._last_beat = now
        if self._stop:
            return
        if self.guard is not None and self.guard.requested \
                and not self._posted_request:
            # this host caught the signal: make it pod-visible
            sig = getattr(self.guard, "signum", None)
            self.coord.request_stop(
                self.step,
                reason=signal.Signals(sig).name if sig else "local",
            )
            self._posted_request = True
        if self._ready_step is None:
            req = self.coord.pending_stop()
            if req is None:
                return
            self._reason = str(req.get("reason", ""))
            self._ready_step = self.step
            self.coord.post_ready(self.step)
            # the barrier-with-deadline on the READY set, INSIDE the
            # tick: this host must not dispatch another step until the
            # pod agrees on max(ready) — a fast host that kept stepping
            # could pass the agreed step before learning it, and the
            # pod would stop at different dispatch counts (the gather
            # would then wait on steps some hosts never dispatched).
            # The wait is bounded: a host that never answers degrades
            # the protocol instead of eating the whole grace window.
            deadline = time.monotonic() + self.deadline_s
            while self._agreed is None:
                self._agreed = self.coord.agreed_step()
                if self._agreed is not None:
                    break
                if time.monotonic() > deadline:
                    # stop at the local step, remember the degrade —
                    # the PR-2 save rules decide (no consistency claim)
                    self._degraded = True
                    self._agreed = self.step
                    break
                time.sleep(0.01)
        if self._agreed is not None and self.step >= self._agreed:
            self._stop = True

    # -- fault / control hooks ----------------------------------------------

    def request_remote(self, reason: str = "sigterm_one_host"):
        """Model a request arriving from ANOTHER host (the
        ``sigterm_one_host`` fault: this host catches nothing — it
        learns of the preemption from the store on its next tick)."""
        self.coord.request_stop(self.step, reason=reason)

    # -- loop consults ------------------------------------------------------

    def should_stop(self) -> bool:
        return self._stop

    def stop_signaled(self) -> bool:
        """A stop request exists (agreed or not) — the between-epoch
        check, where waiting for a formal agreement would pay another
        epoch's first step inside the grace window. Probes the STORE
        too: a remote request that landed while this host was inside
        validation or a boundary save (no ticks run there) must be
        visible before the next epoch's first step is paid."""
        if self._stop or self._ready_step is not None \
                or (self.guard is not None and self.guard.requested):
            return True
        return self.coord.pending_stop() is not None

    def save_barrier(self) -> bool:
        """True only when the whole pod checked in within the deadline:
        the gathered mid-epoch save is then safe even though only one
        host caught the signal. Degraded protocols refuse."""
        if self._degraded:
            return False
        return self.coord.barrier(f"save-e{self.epoch}-s{self.step}",
                                  timeout_s=self.deadline_s)

    def stats(self) -> dict:
        return {
            "hosts": self.coord.num_hosts,
            "reason": self._reason,
            "ready_step": self._ready_step,
            "agreed_step": self._agreed,
            "stopped_at": self.step if self._stop else None,
            "degraded": self._degraded,
        }
