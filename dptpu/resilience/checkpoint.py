"""Mid-epoch checkpoint rotation + corrupt-file-tolerant resume scanning.

The trainer's epoch-boundary ``checkpoint.pth.tar`` (the reference's
contract) stays untouched; this module adds rotated STEP checkpoints —
``checkpoint-e0003-s000120.pth.tar`` = "epoch 3, 120 batches consumed" —
written every ``--ckpt-steps`` steps and on preemption, keeping the last
``--ckpt-keep``. Resume goes through :func:`find_resumable`, which accepts
a file OR a directory, verifies candidates (content CRC when present,
structural parse otherwise), and falls back past corrupt/truncated files
to the newest verifiable one — under the deterministic ``(seed, epoch,
index)`` data contract, resuming from an OLDER position is always safe
(the replay reproduces the exact same trajectory, just re-earns some
steps), whereas trusting a torn file is not.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Optional

# NOTE: dptpu.train.checkpoint is imported lazily inside the functions
# below — importing it at module scope runs dptpu.train.__init__, which
# imports fit, which imports this package: a cycle. The names this module
# needs (save_checkpoint, split_payload, CHECKPOINT_NAME, ...) are stable.

CHECKPOINT_NAME = "checkpoint.pth.tar"  # mirrors dptpu.train.checkpoint
STEP_CHECKPOINT_RE = re.compile(r"^checkpoint-e(\d+)-s(\d+)\.pth\.tar$")


def step_checkpoint_name(epoch: int, step_in_epoch: int) -> str:
    return f"checkpoint-e{epoch:04d}-s{step_in_epoch:06d}.pth.tar"


def verify_checkpoint_bytes(raw: bytes, name: str = "<bytes>") -> tuple:
    """The byte-level half of :func:`verify_checkpoint` — shared by the
    local path and the store-URL path (a remote checkpoint is verified
    from its fetched bytes with the IDENTICAL rules)."""
    from dptpu.train.checkpoint import CorruptCheckpointError, split_payload

    if not raw:
        return False, "empty file (0 bytes)"
    if raw[:4] == b"PK\x03\x04" or raw[:2] == b"\x80\x02":
        return True, "torch-format (unverifiable, accepted)"
    try:
        payload, verified = split_payload(raw, name)
    except CorruptCheckpointError as e:
        return False, str(e)
    if verified:
        return True, "crc ok"
    try:
        from flax import serialization

        restored = serialization.msgpack_restore(payload)
    except Exception as e:
        return False, f"no crc footer and msgpack parse failed: {e}"
    if not isinstance(restored, dict):
        return False, "no crc footer and payload is not a dict"
    return True, "legacy footerless (structurally intact, accepted)"


def verify_checkpoint(path: str) -> tuple:
    """Cheap integrity triage without building a state template; returns
    ``(ok, reason)``. ``path`` may be a local file or a store URL.

    * empty file → rejected (crashed write);
    * dptpu file with CRC footer → CRC decides;
    * footerless flax file (pre-resilience) → accepted iff the msgpack
      envelope still parses to a dict (catches truncation, which also
      removes the footer a new-format file would have had);
    * reference torch file (zip / legacy-pickle magic) → accepted
    (no checksum to check; ``load_checkpoint`` handles the rest).
    """
    from dptpu.data.store import is_store_url, open_store, split_store_url

    if is_store_url(path):
        base, name = split_store_url(path)
        try:
            raw = open_store(base).get_bytes(name)
        except OSError as e:
            return False, f"unreadable: {e}"
        return verify_checkpoint_bytes(raw, path)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        return False, f"unreadable: {e}"
    return verify_checkpoint_bytes(raw, path)


def _candidates(directory: str):
    """Checkpoint files in ``directory`` (a local dir or a store URL),
    newest-first by mtime (the save order). ``model_best`` is a copy,
    not a resume point — excluded."""
    from dptpu.data.store import open_store

    store = open_store(directory)
    out = []
    try:
        entries = store.list()
    except OSError:
        return out
    for name, mtime in entries:
        if name == CHECKPOINT_NAME or STEP_CHECKPOINT_RE.match(name):
            out.append((mtime, name))
    out.sort(reverse=True)
    return [store.path_for(name) for _, name in out]


def find_resumable(path: str, verbose: bool = True) -> Optional[str]:
    """Resolve ``--resume PATH`` to the newest VERIFIABLE checkpoint.

    ``path`` may name a file (used if it verifies; otherwise its siblings
    are scanned) or a directory (scanned directly) — or the store-URL
    equivalent of either (``.pth.tar`` URLs are files, any other URL is
    scanned as a store prefix), with the IDENTICAL verify + fall-back-
    past-corrupt contract. Returns None when nothing loadable exists —
    the caller keeps the reference's warn-and-continue behavior
    (imagenet_ddp.py:152-153).
    """
    from dptpu.data.store import is_store_url, split_store_url

    tried = []
    if is_store_url(path):
        if path.endswith(".pth.tar"):
            ok, reason = verify_checkpoint(path)
            if ok:
                return path
            tried.append((path, reason))
            directory = split_store_url(path)[0]
        else:
            directory = path.rstrip("/")
    elif os.path.isfile(path):
        ok, reason = verify_checkpoint(path)
        if ok:
            return path
        tried.append((path, reason))
        directory = os.path.dirname(path) or "."
    elif os.path.isdir(path):
        directory = path
    else:
        return None
    for cand in _candidates(directory):
        if any(cand == t for t, _ in tried):
            continue
        ok, reason = verify_checkpoint(cand)
        if ok:
            if tried and verbose:
                skipped = ", ".join(
                    f"'{t}' ({r})" for t, r in tried
                )
                print(
                    f"=> resume fell back to '{cand}' — skipped corrupt "
                    f"checkpoint(s): {skipped}",
                    file=sys.stderr,
                )
            return cand
        tried.append((cand, reason))
    if tried and verbose:
        print(
            f"=> no verifiable checkpoint under '{directory}' — "
            + "; ".join(f"'{t}': {r}" for t, r in tried),
            file=sys.stderr,
        )
    return None


class CheckpointManager:
    """Rotated step-checkpoint writer (chief-only, like every other save).

    ``save_step`` writes ``checkpoint-e{epoch}-s{step}.pth.tar`` through
    the same atomic+fsync'd+CRC'd ``save_checkpoint`` path as boundary
    saves, runs the ``ckpt_truncate`` fault hook when a plan is armed,
    and prunes rotated files beyond ``keep`` (oldest first; the
    epoch-boundary ``checkpoint.pth.tar``/``model_best`` are never
    rotation victims).

    With an ``async_writer`` (dptpu.train.checkpoint
    .AsyncCheckpointWriter), cadence saves run entirely on the writer
    thread — device_get included — so ``--ckpt-steps`` stops stalling
    the step loop. ``sync=True`` (emergency/preemption saves) first
    drains the writer, then writes on the calling thread: the
    newest-mtime file the resume scanner trusts is always the true
    latest position, and a preempting process never exits before its
    final save is durable.
    """

    def __init__(self, directory: str = ".", keep: int = 3,
                 is_chief: bool = True, arch: str = "",
                 batch_size: Optional[int] = None, fault_plan=None,
                 async_writer=None, geometry=None, sharding: str = ""):
        if keep < 1:
            raise ValueError(f"ckpt keep={keep} must be >= 1")
        self.directory = directory
        self.keep = keep
        self.is_chief = is_chief
        self.arch = arch
        self.batch_size = batch_size
        self.fault_plan = fault_plan
        self.async_writer = async_writer
        # (world_size, global_batch, accum) stamped into every step
        # save so a changed-geometry --resume can name both tuples
        self.geometry = geometry
        # the run's sharding fingerprint ("<rules-hash>:<placement>" /
        # "replicated" — fit.py computes it), stamped so a --resume
        # under a changed sharding config can name both fingerprints
        self.sharding = sharding

    def save_step(self, state, *, epoch: int, step_in_epoch: int,
                  best_acc1: float = 0.0, sync: bool = False
                  ) -> Optional[str]:
        from dptpu import obs
        from dptpu.train.checkpoint import save_checkpoint

        if not self.is_chief:
            return None
        tracer = obs.get_tracer()
        # span labels use the 0-based index of the step whose completion
        # triggered the save (step_in_epoch counts steps CONSUMED) so
        # the attribution report's per-step join lines up with the
        # loop's data_wait/step/iter labels
        span_step = step_in_epoch - 1
        filename = step_checkpoint_name(epoch, step_in_epoch)
        from dptpu.data.store import is_store_url, open_store

        path = open_store(self.directory).path_for(filename)
        remote = is_store_url(path)
        run_async = self.async_writer is not None and not sync
        if run_async:
            import jax

            # the train step DONATES the old state's buffers to the next
            # step, so an enqueued snapshot must not reference them: take
            # device-side copies (async dispatch, ordered BEFORE the
            # donating step). The step loop still never blocks on a host
            # gather — the writer thread pays the device_get.
            state = jax.tree_util.tree_map(
                lambda x: x.copy() if hasattr(x, "copy") else x, state
            )

        # span naming decides attribution: "ckpt_write" marks work on
        # the WRITER thread (overlaps device compute → reported as
        # async, outside the wall budget); the same closure running
        # INLINE on a sync save stalls the step thread, so it records
        # as plain "ckpt" (nested in the outer ckpt span — exclusive
        # accounting keeps the sum exact)
        write_span = "ckpt_write" if run_async else "ckpt"

        def _write():
            with tracer.span(write_span, step=span_step):
                save_checkpoint(
                    state,
                    epoch=epoch,
                    arch=self.arch,
                    best_acc1=best_acc1,
                    is_best=False,
                    directory=self.directory,
                    is_chief=True,
                    filename=filename,
                    step_in_epoch=step_in_epoch,
                    data_position=(
                        step_in_epoch * self.batch_size
                        if self.batch_size is not None else None
                    ),
                    geometry=self.geometry,
                    sharding=self.sharding,
                )
                if self.fault_plan is not None and not remote:
                    # fault hooks (ckpt_truncate@save=N) count ACTUAL
                    # writes in write order, so they ride the writer
                    # thread too. ckpt_truncate tears the LOCAL file in
                    # place — a store URL has no file to tear, so the
                    # hook stands down there (never silently miscounts:
                    # the chaos benches always run against local dirs)
                    self.fault_plan.on_checkpoint_saved(path)
                self._rotate()

        if run_async:
            # submit may BLOCK on writer backpressure (max_pending):
            # that stall bills to the step thread, so span it
            with tracer.span("ckpt", step=span_step):
                self.async_writer.submit(_write)
            obs.get_registry().gauge("Obs/ckpt_queue_depth").set(
                self.async_writer.pending()
            )
            return path
        with tracer.span("ckpt", step=span_step):
            if self.async_writer is not None:
                # drain first: keep mtime order == save order (the
                # flush stall is recorded as a ckpt_flush span)
                self.async_writer.flush()
            _write()
        return path

    def flush(self):
        """Drain any queued async saves (no-op without a writer)."""
        if self.async_writer is not None:
            self.async_writer.flush()

    def _rotate(self):
        # prune by mtime (save order), NOT by (epoch, step): after a
        # corrupt-fallback resume an old torn higher-step file can still
        # sit in the directory, and position-ordering would keep it while
        # evicting the fresh valid saves — mtime matches find_resumable's
        # newest-first scan, so rotation and resume agree on "newest".
        # Listing + deletion go through the Store, so rotation works
        # identically against a --ckpt-dir store URL.
        from dptpu.data.store import open_store

        store = open_store(self.directory)
        files = []
        try:
            entries = store.list()
        except OSError:
            return
        for name, mtime in entries:
            m = STEP_CHECKPOINT_RE.match(name)
            if m:
                files.append((mtime, int(m.group(1)), int(m.group(2)), name))
        files.sort()  # oldest save first
        for _, _, _, name in files[: max(len(files) - self.keep, 0)]:
            try:
                store.delete(name)
            except OSError:
                pass
