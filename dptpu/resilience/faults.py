"""Fault-injection harness: env/config-driven chaos for resilience testing.

``DPTPU_FAULT`` holds a comma-separated list of fault specs; each spec is a
kind plus ``key=value`` modifiers joined by ``@`` or ``:`` (both separators
are accepted everywhere — ``sigterm@step=12`` and ``io_error:p=0.1`` read
naturally):

* ``sigterm@step=N`` — after the N-th optimizer step completes in this
  process, deliver SIGTERM to ourselves. Exercises the preemption path:
  the trainer must finish the in-flight step, save a mid-epoch
  checkpoint, and return cleanly (exit 0).
* ``worker_kill@step=N`` — after step N, SIGKILL one live data-worker
  process (process-mode loader only). Exercises the pool supervisor's
  crash-restart + span re-enqueue path.
* ``ckpt_truncate@save=N`` — truncate the N-th checkpoint written after
  arming (default the 1st) to half its bytes. Exercises the resume
  scanner's fall-back-past-corrupt-file path.
* ``io_error:p=F`` — each data-worker sample decode raises ``OSError``
  with probability F (per-worker deterministic RNG seeded from
  ``DPTPU_FAULT_SEED`` + worker id, so a retry of the same span draws a
  fresh outcome — a *transient* fault). Exercises span retries.
* ``sigterm_one_host@step=N`` — after step N, a preemption notice
  reaches this pod through the QUORUM coordinator as if ANOTHER host
  had caught the SIGTERM (this process receives no signal at all): the
  run must learn of it from the coordination store on its next tick,
  agree on a pod-consistent stop step, and save. Without a coordinator
  (no DPTPU_QUORUM_DIR, single process, no jax.distributed store) it
  degenerates to a plain local SIGTERM — exactly the PreemptionGuard
  path.
* ``host_lost@step=N`` — after step N, declare this pod's host set
  PERMANENTLY degraded (the "gone for good" verdict the chief's
  heartbeat monitor would reach): the trainer saves synchronously at
  the current position, marks the run ``host_lost`` and exits cleanly
  so the operator can restart on the smaller world with
  ``DPTPU_ELASTIC=1`` (the shrink-resume path).
* ``slow_host:factor=F[@step=K][@worker=W]`` — worker W (default 0)
  becomes a PERSISTENT straggler: every sample decode from its K-th
  (default 1st) onward sleeps ``F x 20 ms`` (``factor`` > 1; ``step``
  counts THAT worker's decodes — worker processes have no view of
  optimizer steps). Identical bytes, just late: drives the straggler
  controller's detect → re-split → evict escalation without ever
  touching bit-identity.
* ``worker_hang@index=K`` — a data worker decoding sample index K sleeps
  effectively forever. Deterministic (every retry hangs again), so it
  drives the watchdog all the way to pool-restart exhaustion and the
  graceful degrade to thread mode. Two optional modifiers turn the
  death into a STRAGGLER: ``s=F`` bounds the sleep to F seconds (a slow
  span, not a dead one — keep ``DPTPU_WORKER_TIMEOUT_S`` above it so
  the watchdog stays out of the way), and ``worker=W`` restricts the
  hang to worker id W — the decode-ahead straggler-injection mode
  (``worker_hang@index=K@s=2@worker=0``): only W stalls, so the
  speculative re-issue path can hand the span to a healthy worker.

Serve-side kinds (ISSUE 17 — injected by the serving tier,
dptpu/serve/batcher.py and the canary controller):

* ``serve_exception@request=N`` — the N-th request submitted to the
  batcher raises at the SUBMISSION boundary (before it claims a
  staging row). Exercises "a bad request fails alone": the caller gets
  the error, no batch and no row is touched.
* ``preprocess_crash@request=N`` — the N-th request's preprocessing
  raises AFTER its staging row is claimed. Exercises the
  fail-alone-in-batch path: the crashed request's future fails, its
  row is evicted at dispatch, every other request in the batch
  resolves normally.
* ``slow_model:factor=F`` — every dispatched bucket execution sleeps
  ``F x 20 ms`` before the compiled call (``factor`` > 1). Inflates
  service time without touching the engine: drives overload so
  admission shedding engages before the staging ring blocks.
* ``canary_drift`` — the next canary rollout stages PERTURBED weights
  (the controller adds a large constant to every parameter), so the
  logit-drift gate must fire and auto-rollback must trigger.

Worker-side kinds (``io_error``, ``worker_hang``) take effect in spawned
decode workers, which re-parse the inherited environment — no pickling of
the plan is needed. Trainer-side kinds fire from ``on_step``; step counts
are 1-based counts of steps executed by THIS process (a resumed run counts
from 1 again), which is what a chaos harness wants: "kill me N steps in".

This module is imported inside data workers: stdlib only, never JAX.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import time
from typing import Callable, Optional

from dptpu.envknob import env_int, env_str

_KINDS = ("sigterm", "worker_kill", "ckpt_truncate", "io_error",
          "worker_hang", "sigterm_one_host", "host_lost", "slow_host",
          "serve_exception", "preprocess_crash", "slow_model",
          "canary_drift")
_HANG_SECONDS = 3600.0
_SLOW_BASE_S = 0.02  # slow_host/slow_model: sleep per unit of factor


@dataclasses.dataclass
class _Fault:
    kind: str
    step: Optional[int] = None
    save: Optional[int] = None
    index: Optional[int] = None
    p: float = 0.0
    seconds: Optional[float] = None  # worker_hang: bounded straggler sleep
    worker: Optional[int] = None  # worker_hang/slow_host: worker id
    factor: Optional[float] = None  # slow_host/slow_model: multiple (> 1)
    request: Optional[int] = None  # serve_exception/preprocess_crash
    fired: bool = False


def _parse_one(spec: str) -> _Fault:
    parts = spec.replace("@", ":").split(":")
    kind = parts[0].strip()
    if kind not in _KINDS:
        raise ValueError(
            f"DPTPU_FAULT kind {kind!r} unknown — accepted kinds: "
            f"{', '.join(_KINDS)} (e.g. DPTPU_FAULT=sigterm@step=12)"
        )
    f = _Fault(kind=kind)
    for mod in parts[1:]:
        if "=" not in mod:
            raise ValueError(
                f"DPTPU_FAULT modifier {mod!r} in {spec!r} must be "
                f"key=value (step=N, save=N, index=K, p=F)"
            )
        key, val = (s.strip() for s in mod.split("=", 1))
        try:
            if key == "step":
                f.step = int(val)
            elif key == "save":
                f.save = int(val)
            elif key == "index":
                f.index = int(val)
            elif key == "p":
                f.p = float(val)
                if not 0.0 <= f.p <= 1.0:
                    raise ValueError
            elif key == "s":
                f.seconds = float(val)
                if f.seconds <= 0.0:
                    raise ValueError
            elif key == "worker":
                f.worker = int(val)
            elif key == "factor":
                f.factor = float(val)
                if f.factor <= 1.0:
                    raise ValueError
            elif key == "request":
                f.request = int(val)
                if f.request < 1:
                    raise ValueError
            else:
                raise KeyError
        except KeyError:
            raise ValueError(
                f"DPTPU_FAULT modifier key {key!r} in {spec!r} unknown "
                f"(accepted: step, save, index, p, s, worker, factor, "
                f"request)"
            ) from None
        except ValueError:
            raise ValueError(
                f"DPTPU_FAULT modifier {key}={val!r} in {spec!r} is not a "
                f"valid value"
            ) from None
    # arm-time validation so a typo'd plan fails before training starts
    if f.kind in ("sigterm", "worker_kill", "sigterm_one_host",
                  "host_lost") and f.step is None:
        raise ValueError(f"DPTPU_FAULT {spec!r} needs @step=N")
    if f.kind == "worker_hang" and f.index is None:
        raise ValueError(f"DPTPU_FAULT {spec!r} needs @index=K")
    if f.kind == "io_error" and not f.p:
        raise ValueError(f"DPTPU_FAULT {spec!r} needs :p=F with F > 0")
    if f.kind == "slow_host" and f.factor is None:
        raise ValueError(
            f"DPTPU_FAULT {spec!r} needs :factor=F with F > 1 (the "
            f"straggler's slowdown multiple, e.g. slow_host:factor=5)"
        )
    if f.kind in ("serve_exception", "preprocess_crash") \
            and f.request is None:
        raise ValueError(
            f"DPTPU_FAULT {spec!r} needs @request=N with N >= 1 (the "
            f"1-based submission that fails, e.g. "
            f"serve_exception@request=3)"
        )
    if f.kind == "slow_model" and f.factor is None:
        raise ValueError(
            f"DPTPU_FAULT {spec!r} needs :factor=F with F > 1 (the "
            f"per-batch service-time multiple, e.g. slow_model:factor=5)"
        )
    return f


class FaultPlan:
    """A parsed ``DPTPU_FAULT`` spec with the three injection hooks the
    trainer and the data workers call: ``on_step`` (trainer, after each
    optimizer step), ``on_checkpoint_saved`` (checkpoint writer), and
    ``worker_decode_hook`` (data worker, per sample)."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed
        self.faults = [
            _parse_one(s) for s in spec.split(",") if s.strip()
        ]
        if not self.faults:
            raise ValueError(f"DPTPU_FAULT={spec!r} parsed to no faults")
        self._steps_done = 0
        self._saves_done = 0
        self._kill_worker_cb: Optional[Callable] = None
        self._quorum_cb: Optional[Callable] = None
        self._host_lost_cb: Optional[Callable] = None
        self._worker_rng: Optional[random.Random] = None
        self._store_rng: Optional[random.Random] = None
        self._slow_decodes = 0  # slow_host: this worker's decode count

    @classmethod
    def from_env(cls, environ=None) -> Optional["FaultPlan"]:
        spec = env_str("DPTPU_FAULT", "", environ=environ)
        if not spec:
            return None
        return cls(spec, seed=env_int("DPTPU_FAULT_SEED", 0, environ))

    def bind_worker_kill(self, cb: Callable):
        """Wire the trainer-side ``worker_kill`` fault to a callable that
        SIGKILLs one live data worker (e.g. DataLoader.kill_one_worker)."""
        self._kill_worker_cb = cb

    def bind_quorum_request(self, cb: Callable):
        """Wire ``sigterm_one_host`` to the quorum session's remote-
        request hook (dptpu/resilience/quorum.py): the fault then models
        a preemption notice arriving from ANOTHER host through the
        coordination store. Unbound (no coordinator), the fault
        degenerates to a plain local SIGTERM."""
        self._quorum_cb = cb

    def bind_host_lost(self, cb: Callable):
        """Wire ``host_lost`` to the trainer's gone-for-good handler:
        sync save at the current position, mark the run, exit cleanly
        for an elastic restart. Unbound, it degenerates to SIGTERM
        (save-and-exit is still the right shape)."""
        self._host_lost_cb = cb

    # -- trainer-side hooks -------------------------------------------------

    def on_step(self):
        """Call once after each completed optimizer step."""
        self._steps_done += 1
        for f in self.faults:
            if f.fired or f.step != self._steps_done:
                continue
            if f.kind == "sigterm":
                f.fired = True
                os.kill(os.getpid(), signal.SIGTERM)
            elif f.kind == "sigterm_one_host":
                f.fired = True
                if self._quorum_cb is not None:
                    self._quorum_cb()
                else:
                    # no coordinator to carry the remote notice:
                    # degenerate to the PreemptionGuard path
                    os.kill(os.getpid(), signal.SIGTERM)
            elif f.kind == "host_lost":
                f.fired = True
                if self._host_lost_cb is not None:
                    self._host_lost_cb()
                else:
                    os.kill(os.getpid(), signal.SIGTERM)
            elif f.kind == "worker_kill":
                f.fired = True
                if self._kill_worker_cb is not None:
                    self._kill_worker_cb()

    def on_checkpoint_saved(self, path: str) -> bool:
        """Call after every checkpoint write; truncates the armed save in
        place (returns True when it fired) to simulate a partial write."""
        self._saves_done += 1
        for f in self.faults:
            if f.kind != "ckpt_truncate" or f.fired:
                continue
            if self._saves_done == (f.save or 1):
                f.fired = True
                size = os.path.getsize(path)
                with open(path, "r+b") as fh:
                    fh.truncate(max(size // 2, 1))
                return True
        return False

    # -- store-side hook ----------------------------------------------------

    def on_store_io(self, desc: str):
        """Call before every Store operation (dptpu/data/store.py): an
        ``io_error:p=F`` fault raises an injected transient ``OSError``
        with probability F — the range-fetch chaos path. A SEPARATE rng
        stream from the decode hook's (seeded off the fault seed alone),
        so store and decode injections don't perturb each other's draws;
        a retried op draws fresh, making the fault transient."""
        for f in self.faults:
            if f.kind != "io_error":
                continue
            if self._store_rng is None:
                self._store_rng = random.Random((self.seed << 16) ^ 0xB00C)
            if self._store_rng.random() < f.p:
                raise OSError(
                    f"injected io_error (p={f.p}) on store op {desc!r}"
                )

    # -- serve-side hooks ---------------------------------------------------

    def on_serve_submit(self, request_index: int):
        """Call per batcher submission (1-based), BEFORE a staging row
        is claimed: ``serve_exception@request=N`` makes the N-th
        submission raise at the boundary — the caller gets the error,
        nothing else is touched."""
        for f in self.faults:
            if f.kind == "serve_exception" and not f.fired \
                    and request_index == f.request:
                f.fired = True
                raise RuntimeError(
                    f"injected serve_exception on request {request_index}"
                )

    def on_serve_preprocess(self, request_index: int):
        """Call per request preprocess (1-based submission index), AFTER
        its staging row is claimed: ``preprocess_crash@request=N`` makes
        the N-th request's decode raise — the fail-alone-in-batch path."""
        for f in self.faults:
            if f.kind == "preprocess_crash" and not f.fired \
                    and request_index == f.request:
                f.fired = True
                raise RuntimeError(
                    f"injected preprocess_crash on request {request_index}"
                )

    def serve_model_delay_s(self) -> float:
        """Per-dispatched-batch extra service time: ``slow_model:factor=F``
        contributes ``F x 20 ms`` per bucket execution (0.0 unarmed)."""
        return sum(
            _SLOW_BASE_S * f.factor for f in self.faults
            if f.kind == "slow_model"
        )

    def canary_drift_armed(self) -> bool:
        """True when ``canary_drift`` is armed: the canary controller
        stages PERTURBED weights so the drift gate must fire (this
        module stays stdlib-only — the numeric perturbation lives in
        dptpu/serve/canary.py)."""
        return any(f.kind == "canary_drift" for f in self.faults)

    # -- worker-side hook ---------------------------------------------------

    def worker_decode_hook(self, worker_id: int, index: int):
        """Call per sample decode inside a data worker; may hang or raise
        an injected transient ``OSError``."""
        for f in self.faults:
            if f.kind == "slow_host" \
                    and worker_id == (f.worker if f.worker is not None
                                      else 0):
                # a persistent straggler, not a dead worker: identical
                # bytes, just late — the straggler controller's food
                self._slow_decodes += 1
                if self._slow_decodes >= (f.step or 1):
                    time.sleep(_SLOW_BASE_S * f.factor)
            elif f.kind == "worker_hang" and index == f.index \
                    and (f.worker is None or f.worker == worker_id):
                time.sleep(f.seconds if f.seconds else _HANG_SECONDS)
            elif f.kind == "io_error":
                if self._worker_rng is None:
                    self._worker_rng = random.Random(
                        (self.seed << 16) ^ (worker_id + 1)
                    )
                if self._worker_rng.random() < f.p:
                    raise OSError(
                        f"injected io_error (p={f.p}) decoding sample "
                        f"{index} in worker {worker_id}"
                    )
