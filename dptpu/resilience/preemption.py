"""Preemption guard: turn SIGTERM/SIGINT into a cooperative stop flag.

Shared-cluster preemption delivers SIGTERM with a grace window; the
reference just dies and loses everything since the last epoch-boundary
``torch.save``. ``PreemptionGuard`` installs handlers for the duration of
the training loop: the FIRST signal only sets ``requested`` — the loop
finishes the in-flight step, writes a mid-epoch checkpoint, and returns
normally (exit 0) — while a SECOND signal raises ``KeyboardInterrupt`` so
an operator hammering Ctrl-C still gets out promptly (the trainer's
emergency-save path catches it on the way up).

Signal handlers can only be installed from the main thread; elsewhere
(e.g. a fit() driven from a worker thread in tests) the guard degrades to
an inert flag instead of crashing.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional


class PreemptionGuard:
    """Context manager; ``requested`` flips on the first SIGTERM/SIGINT."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        # async-signal handoff state: the handler (the only writer
        # after install) sets both; the loop and the quorum tick only
        # read — single-writer by construction, no lock needed (and a
        # lock in a signal handler could self-deadlock the main thread)
        self.requested = False  # owned-by: signal-handler
        self.signum: Optional[int] = None  # owned-by: signal-handler
        self._old = {}  # owned-by: caller

    def _handler(self, signum, frame):
        if self.requested:
            raise KeyboardInterrupt(
                f"second signal {signal.Signals(signum).name} during "
                f"graceful preemption — aborting now"
            )
        self.requested = True
        self.signum = signum

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is threading.main_thread():
            for s in self.signals:
                self._old[s] = signal.signal(s, self._handler)
        return self

    def __exit__(self, *exc):
        for s, old in self._old.items():
            signal.signal(s, old)
        self._old = {}
        return False

    @property
    def signal_name(self) -> str:
        return signal.Signals(self.signum).name if self.signum else ""
