"""Elastic pod lifecycle: shrink/grow resume + straggler-driven control.

**Elastic resume** (ROADMAP item 3a). PR 10 stamped every checkpoint
with its ``(world_size, global_batch, accum)`` geometry and made a
mismatched ``--resume`` fail fast; this module makes it RESUME. The key
property is the sampler's interleaved shard assignment
(``dptpu/data/sampler.py``): shard ``i`` of ``N`` takes
``order[i::N]`` of the epoch's ``(seed, epoch)``-pure permutation, so
after ``k`` steps — every host having consumed ``k × host_batch``
samples — the UNION of visited indices is exactly
``order[: k × global_batch]``, for ANY factoring of the global batch
into hosts and devices. The visited prefix is geometry-independent.

A shrink (or grow) therefore reduces to arithmetic: the saved position
is ``consumed = step_in_epoch × global_batch_saved`` samples into the
epoch order, and the new geometry resumes at
``consumed / global_batch_new`` — a plain ``start_batch`` replay on the
new sampler — visiting exactly the untrained remainder
``order[consumed:]``. The only structural requirement is that
``consumed`` is a whole number of new-geometry steps; anything else
fails fast naming a dividing batch size (the locked knob contract).

Exactness contract (FAULTBENCH ``shrink_resume`` + tests): the visited
-index set over the resumed epoch is the set difference — Δ = ∅ — and
the elastic replay itself is deterministic (two identical elastic
resumes are bit-identical in params and loss). The TRAJECTORY is not
bit-identical to the old-geometry run — gradients now average over a
different global batch, which is the point of shrinking — so the LR is
rescaled per the linear-scaling rule and the delta is logged loudly.

**Straggler-driven control** (ROADMAP item 3c). The chief-side
collector (``dptpu/obs/report.py merge_pod_timeline``) answers "which
host/worker is slow" retroactively; :class:`StragglerController` closes
the loop LIVE: it consumes the shm pipeline's per-worker span-ack
latencies (streaming P² quantiles per worker), and when one worker's
p50 stays above ``DPTPU_STRAGGLER_FACTOR`` × its healthiest peer's for
``DPTPU_STRAGGLER_PERSIST`` consecutive ticks it escalates through the
existing seams:

1. **re-split** — the worker's pending span tail re-issues to the
   least-loaded healthy workers (the speculation machinery;
   ``straggler_reissues`` counts it) and the affinity router steers new
   spans away from it; the worker enters PROBATION on a fresh verdict
   window (cumulative history would keep convicting a worker whose
   transient slowdown already passed), judged only on fresh evidence
   (its draining backlog keeps acking, so a sick worker keeps
   convicting itself while a drained one neither escalates nor
   recovers on stale numbers);
2. **evict or restore** — fresh evidence still slow for another
   ``persist`` verdicts triggers the shm supervisor's eviction policy
   (the worker is killed; the pool restart re-enqueues its work —
   bit-identity preserved by the same first-writer-wins contract every
   chaos scenario already locks), while a healthy fresh verdict
   restores it to the affinity router;
3. **elastic** — a HOST gone for good (quorum heartbeats silent, or
   the ``host_lost`` fault) stops the run with a sync save; the
   operator restarts on the smaller world with ``DPTPU_ELASTIC=1``.

This module is trainer-side (imported lazily via dptpu.resilience);
the hot-path helpers stay numpy/stdlib so knob parsing never drags JAX
into tools that only want the arithmetic.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from dptpu.envknob import env_bool, env_float, env_int


def elastic_knobs(environ=None) -> dict:
    """The elastic-lifecycle env knobs, under the locked fail-fast
    contract (every explicit-but-invalid value raises, pre-compile):

    * ``DPTPU_ELASTIC`` — opt in to geometry re-mapping on ``--resume``
      (default off: a surprise geometry change should still fail fast);
    * ``DPTPU_QUORUM_DEADLINE_S`` — bound on every quorum wait
      (``dptpu/resilience/quorum.py``; > 0, default 30);
    * ``DPTPU_STRAGGLER_FACTOR`` — arm the straggler controller: a
      worker is slow when its span p50 exceeds this multiple of its
      healthiest peer's (> 1; unset = controller off);
    * ``DPTPU_STRAGGLER_PERSIST`` — consecutive slow verdicts before
      the re-split fires (>= 1, default 2; eviction follows after the
      same count again).
    """
    from dptpu.resilience.quorum import quorum_deadline_knob

    elastic = env_bool("DPTPU_ELASTIC", False, environ)
    deadline = quorum_deadline_knob(environ)
    factor = env_float("DPTPU_STRAGGLER_FACTOR", None, environ)
    if factor is not None and factor <= 1.0:
        raise ValueError(
            f"DPTPU_STRAGGLER_FACTOR={factor} must be > 1 (a worker is "
            f"a straggler when its span p50 exceeds factor x its "
            f"healthiest peer's; e.g. DPTPU_STRAGGLER_FACTOR=2.5)"
        )
    persist = env_int("DPTPU_STRAGGLER_PERSIST", 2, environ)
    if persist < 1:
        raise ValueError(
            f"DPTPU_STRAGGLER_PERSIST={persist} must be >= 1 "
            f"consecutive slow verdicts before the re-split fires"
        )
    return {
        "elastic": bool(elastic),
        "quorum_deadline_s": deadline,
        "straggler_factor": factor,
        "straggler_persist": int(persist),
    }


@dataclasses.dataclass(frozen=True)
class ElasticRemap:
    """The result of re-mapping a saved mid-epoch position onto a new
    geometry — everything fit() needs to wire the replay and log it."""

    saved_geometry: tuple  # (world, global_batch, accum) that saved
    new_geometry: tuple  # this run's tuple
    consumed: int  # global samples of the epoch already trained
    new_step: int  # start_batch on the new geometry
    accum_changed: bool  # virtual-replica streams differ (loud note)


def remap_resume_position(saved_geometry: Sequence[int],
                          new_geometry: Sequence[int],
                          step_in_epoch: int,
                          slices: int = 1,
                          num_examples: Optional[int] = None
                          ) -> ElasticRemap:
    """Re-map ``(epoch, step_in_epoch)`` saved under ``saved_geometry``
    onto ``new_geometry`` (see module docstring for why this is exact).

    Raises (fail fast, actionable — the locked contract):

    * when the shrunk world does not divide ``slices``
      (``dptpu/parallel/hierarchy.py elastic_slices_check`` — the
      message names the knob and both fallbacks);
    * when the consumed prefix is not a whole number of new-geometry
      steps (names a dividing global batch).
    """
    saved = tuple(int(g) for g in saved_geometry)
    new = tuple(int(g) for g in new_geometry)
    if len(saved) != 3 or len(new) != 3:
        raise ValueError(
            f"geometry tuples must be (world_size, global_batch, "
            f"accum); got saved={saved} new={new}"
        )
    if saved[1] <= 0 or new[1] <= 0:
        raise ValueError(
            f"elastic resume needs positive global batches; got "
            f"saved={saved} new={new}"
        )
    from dptpu.parallel.hierarchy import elastic_slices_check

    elastic_slices_check(new[0], slices)
    consumed = int(step_in_epoch) * saved[1]
    if num_examples is not None and consumed > num_examples:
        # the saved run was deep into the sampler's wrap-around padding
        # (dataset not divisible by the old host count): the padded
        # prefix depends on the OLD shard count, so the visited set is
        # no longer geometry-independent and the exact remap is void
        raise ValueError(
            f"elastic resume: the saved position ({consumed} samples) "
            f"is past the dataset's {num_examples} samples — the run "
            f"was inside the sampler's wrap-around padding, whose "
            f"order depends on the saved host count, so an exact "
            f"remainder replay is impossible. Pass --start-epoch to "
            f"restart from the next epoch boundary."
        )
    if consumed % new[1] != 0:
        divisors = sorted(
            b for b in range(1, consumed + 1) if consumed % b == 0
        )
        close = min(divisors, key=lambda b: abs(b - new[1]))
        raise ValueError(
            f"elastic resume: the saved position ({step_in_epoch} steps "
            f"x global batch {saved[1]} = {consumed} samples consumed) "
            f"is not a whole number of steps at the new global batch "
            f"{new[1]} — the remainder replay would split a batch. "
            f"Pick a global batch that divides {consumed} (e.g. "
            f"{close}), or resume on the saved geometry."
        )
    return ElasticRemap(
        saved_geometry=saved,
        new_geometry=new,
        consumed=consumed,
        new_step=consumed // new[1],
        accum_changed=saved[2] != new[2],
    )


def remainder_indices(num_examples: int, seed: int, epoch: int,
                      consumed: int, global_batch: int,
                      num_shards: int = 1):
    """The untrained remainder an elastic resume will visit, computed
    from the SAME pure sampler math the loaders run — the Δ = ∅ oracle
    FAULTBENCH and the tests gate against. Returns the (sorted) global
    sample indices of epoch ``epoch`` from position ``consumed``
    through the last whole ``global_batch`` (drop_last discipline),
    unioned across all ``num_shards`` hosts."""
    import numpy as np

    from dptpu.data.sampler import ShardedSampler

    visited = []
    per_host = global_batch // num_shards
    for shard in range(num_shards):
        s = ShardedSampler(num_examples, num_shards=num_shards,
                           shard_index=shard, shuffle=True, seed=seed)
        idx = s.indices(epoch)
        start = consumed // num_shards
        nb = (len(idx) - start) // per_host
        visited.append(idx[start:start + nb * per_host])
    return np.sort(np.concatenate(visited)) if visited else \
        np.empty((0,), np.int64)


# --------------------------------------------------------------- control ----


class StragglerController:
    """Chief-side live feedback loop over the feed's worker pool (see
    module docstring, item 3c). ``tick()`` rides fit's post-step hook;
    the loader seam (``DataLoader.worker_latency_observations`` /
    ``resplit_worker`` / ``evict_worker``) no-ops in thread mode, so the
    controller is always safe to arm."""

    def __init__(self, loader, factor: float, persist: int = 2,
                 min_obs: int = 4, on_event=None):
        if factor <= 1.0:
            raise ValueError(
                f"straggler factor={factor} must be > 1"
            )
        if persist < 1:
            raise ValueError(f"straggler persist={persist} must be >= 1")
        self.loader = loader
        self.factor = float(factor)
        self.persist = int(persist)
        self.min_obs = int(min_obs)
        self.on_event = on_event  # callable(kind, payload) — obs log
        self._p50 = {}  # worker -> P2Quantile (reset at each escalation)
        self._count = {}
        self._strikes = {}
        # workers in the post-re-split probation window: their verdict
        # restarts on a FRESH estimator (cumulative history would keep
        # convicting a worker whose transient slowdown already passed),
        # and the next persist slow verdicts escalate to eviction while
        # a healthy verdict restores them to the affinity router. A
        # suspect whose backlog drains before the verdict resolves
        # (routed away = no new spans = no new evidence) is PROBED
        # after ``probe_after`` evidence-free ticks: re-admitted to the
        # router with the verdict window still armed, so its next spans
        # decide — without the probe, a transiently-slow worker would
        # stay benched forever (neither restorable nor evictable).
        self._suspect = set()
        self._stale_ticks = {}  # suspect -> consecutive evidence-free ticks
        self.probe_after = max(2 * self.persist, 4)
        self.resplits = 0
        self.evictions = 0
        self.events = []

    def _emit(self, kind: str, payload: dict):
        self.events.append({"kind": kind, **payload})
        if self.on_event is not None:
            try:
                self.on_event(kind, payload)
            except Exception:
                pass

    def _reset_verdict(self, w):
        from dptpu.obs.report import P2Quantile

        self._p50[w] = P2Quantile(0.5)
        self._count[w] = 0
        self._strikes[w] = 0

    def rebind(self, loader):
        """Re-point at a REBUILT worker pool (the DPTPU_BATCH_RAMP phase
        switch closes the old loader and builds a new one at the full
        batch). Every estimator window, strike count, suspect set, and
        probation clock resets: worker ids restart from zero in the new
        pool, so a stale verdict would convict a fresh worker for its
        predecessor's latency. Escalation totals and the event log
        carry over — they describe the run, not the pool."""
        self.loader = loader
        self._p50.clear()
        self._count.clear()
        self._strikes.clear()
        self._suspect.clear()
        self._stale_ticks.clear()
        self._emit("straggler_rebind", {"workers": loader.num_workers})

    def tick(self):
        obs = self.loader.worker_latency_observations()
        fresh = {}
        for wid, lat in obs:
            if wid not in self._p50:
                self._reset_verdict(wid)
            self._p50[wid].add(lat)
            self._count[wid] += 1
            fresh[wid] = fresh.get(wid, 0) + 1
        # probation probes run before the ready gate: a drained suspect
        # is exactly the worker with too few fresh observations to ever
        # BE ready again on its own
        for w in sorted(self._suspect):
            if fresh.get(w):
                self._stale_ticks[w] = 0
                continue
            self._stale_ticks[w] = self._stale_ticks.get(w, 0) + 1
            if self._stale_ticks[w] >= self.probe_after:
                self._stale_ticks[w] = 0
                self.loader.restore_worker(w)  # routing only: verdict
                self._emit("straggler_probe", {"worker": w})  # stays armed
        ready = {w for w, c in self._count.items() if c >= self.min_obs}
        if len(ready) < 2:
            return  # slowness is relative: need a peer to compare with
        p50s = {w: self._p50[w].value() for w in ready}
        floor = min(p50s.values())
        if floor <= 0:
            return
        for w in sorted(ready):
            if not fresh.get(w):
                # no fresh evidence this tick: the verdict FREEZES. A
                # routed-away worker still acks its draining backlog,
                # so a genuinely sick worker keeps producing evidence
                # toward eviction; a drained one neither escalates nor
                # silently recovers on stale numbers.
                continue
            slow = p50s[w] > self.factor * floor
            if w in self._suspect:
                if not slow:
                    # probation passed on fresh evidence: rejoin the
                    # affinity router, verdict back to normal
                    self._suspect.discard(w)
                    self._stale_ticks.pop(w, None)
                    self._strikes[w] = 0
                    self.loader.restore_worker(w)
                    self._emit("straggler_restore", {
                        "worker": w, "p50_s": round(p50s[w], 4),
                    })
                    continue
                self._strikes[w] += 1
                if self._strikes[w] >= self.persist:
                    # escalation 2: the shm supervisor's eviction
                    # policy — kill the worker; the pool restart
                    # re-enqueues its work and clears the route-away
                    pid = self.loader.evict_worker(w)
                    self.evictions += 1
                    self._emit("straggler_evict", {
                        "worker": w, "pid": pid,
                        "p50_s": round(p50s[w], 4),
                    })
                    self._suspect.discard(w)
                    self._stale_ticks.pop(w, None)
                    self._reset_verdict(w)  # the replacement's slate
                continue
            if not slow:
                self._strikes[w] = 0
                continue
            self._strikes[w] += 1
            if self._strikes[w] >= self.persist:
                # escalation 1: re-split the span tail + route away,
                # then judge the eviction question on a FRESH window —
                # the post-re-split acks alone decide whether this
                # worker is sick or merely had a bad moment
                n = self.loader.resplit_worker(w)
                self.resplits += 1
                self._emit("straggler_resplit", {
                    "worker": w, "p50_s": round(p50s[w], 4),
                    "healthy_p50_s": round(floor, 4),
                    "reissued_spans": n,
                })
                self._suspect.add(w)
                self._stale_ticks[w] = 0
                self._reset_verdict(w)

    def stats(self) -> dict:
        return {
            "resplits": self.resplits,
            "evictions": self.evictions,
            "workers_observed": len(self._count),
            "events": list(self.events),
        }


__all__ = [
    "ElasticRemap",
    "StragglerController",
    "elastic_knobs",
    "remainder_indices",
    "remap_resume_position",
]
