"""Resilience layer: survive preemption, worker failure, torn writes —
and, since the elastic-lifecycle work, a pod that shrinks, loses hosts,
or drags a straggler.

Pillars (ISSUE 2 + ROADMAP item 3):

* **elastic resume** — :mod:`dptpu.resilience.elastic`: re-map a saved
  mid-epoch position onto a new ``(world_size, global_batch, accum)``
  (``DPTPU_ELASTIC=1``), replaying exactly the untrained remainder;
  plus the live straggler controller (re-split → evict → elastic);
* **quorum saves** — :mod:`dptpu.resilience.quorum`: pod-consistent
  mid-epoch checkpoints when only one host catches the SIGTERM, via a
  barrier-with-deadline over the coordination store;

* **preemption-safe mid-epoch checkpointing** — rotated, CRC-sealed step
  checkpoints (:mod:`dptpu.resilience.checkpoint`) whose ``(epoch,
  step_in_epoch, data_position)`` coordinates replay the deterministic
  ``(seed, epoch, index)`` sampler to the exact saved position, so a
  resumed run's trajectory is bit-identical to an uninterrupted one;
* **supervised data workers** — the shared-memory pool's watchdog /
  restart / span-retry / degrade-to-thread machinery lives with the pool
  in ``dptpu/data/shm.py``; its fault hooks come from here;
* **fault injection** — :mod:`dptpu.resilience.faults`, the
  ``DPTPU_FAULT`` chaos harness driven by ``scripts/run_faultbench.py``.

This ``__init__`` is LAZY (module ``__getattr__``): spawned data workers
import ``dptpu.resilience.faults`` for their fault hooks, and must not
drag the checkpoint module's jax/flax imports into every decode process.
"""

from __future__ import annotations

_EXPORTS = {
    "FaultPlan": "dptpu.resilience.faults",
    "PreemptionGuard": "dptpu.resilience.preemption",
    "CheckpointManager": "dptpu.resilience.checkpoint",
    "find_resumable": "dptpu.resilience.checkpoint",
    "step_checkpoint_name": "dptpu.resilience.checkpoint",
    "verify_checkpoint": "dptpu.resilience.checkpoint",
    # elastic pod lifecycle (ROADMAP item 3): geometry re-mapping,
    # straggler control, and the quorum save protocol
    "ElasticRemap": "dptpu.resilience.elastic",
    "StragglerController": "dptpu.resilience.elastic",
    "elastic_knobs": "dptpu.resilience.elastic",
    "remainder_indices": "dptpu.resilience.elastic",
    "remap_resume_position": "dptpu.resilience.elastic",
    "FileKVStore": "dptpu.resilience.quorum",
    "QuorumCoordinator": "dptpu.resilience.quorum",
    "QuorumSession": "dptpu.resilience.quorum",
    "make_coordinator": "dptpu.resilience.quorum",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
