"""Offline torchvision -> dptpu weight converter.

Usage::

    python -m dptpu.tools.convert_torchvision <checkpoint> -a resnet50 \
        [-o pretrained/] [--num-classes 1000]

``<checkpoint>`` is either a torchvision ``.pth``/``.pt`` state dict
(read with torch's CPU unpickler — torch is only needed HERE, never at
training time) or an ``.npz`` whose keys are the torch parameter names.
Writes ``<out>/<arch>.npz`` in dptpu's native layout, which
``--pretrained`` resolves at runtime (imagenet_ddp.py:109-111 semantics;
see dptpu/models/pretrained.py).
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def read_torch_state_dict(path: str):
    if path.endswith(".npz"):
        with np.load(path) as data:
            return {k: data[k] for k in data.files}
    try:
        import torch
    except ImportError as e:  # pragma: no cover
        raise SystemExit(
            "reading .pth checkpoints needs torch (CPU build is enough); "
            "alternatively convert to .npz with torch-name keys elsewhere"
        ) from e
    obj = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(obj, "state_dict"):
        obj = obj.state_dict()
    if "state_dict" in obj and all(
        hasattr(v, "numpy") for v in obj["state_dict"].values()
    ):
        obj = obj["state_dict"]
    return {
        k.removeprefix("module."): v.numpy()
        for k, v in obj.items()
        if hasattr(v, "numpy")
    }


def main(argv=None):
    from dptpu.models import create_model, model_names
    from dptpu.models.pretrained import convert_state_dict, save_npz

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("checkpoint")
    p.add_argument("-a", "--arch", required=True, choices=model_names())
    p.add_argument("-o", "--out-dir", default="pretrained")
    p.add_argument("--num-classes", default=1000, type=int)
    args = p.parse_args(argv)

    import jax

    model = create_model(args.arch, num_classes=args.num_classes)
    template = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 224, 224, 3), np.float32),
        train=False,
    )
    sd = read_torch_state_dict(args.checkpoint)
    variables = convert_state_dict(args.arch, sd, template)
    os.makedirs(args.out_dir, exist_ok=True)
    out = os.path.join(args.out_dir, f"{args.arch}.npz")
    save_npz(out, variables)
    n = sum(x.size for x in jax.tree_util.tree_leaves(variables))
    print(f"wrote {out} ({n:,} parameters + stats)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
