"""GSPMD (pjit-style) train step: sharding annotations, XLA collectives.

The DDP/ZeRO-1 steps express parallelism explicitly with ``shard_map``
(per-shard code + hand-placed collectives). This module is the OTHER
idiomatic TPU path — the scaling-book recipe: write single-program code,
annotate the param/batch shardings on ``jit``, and let XLA's SPMD
partitioner insert the all-reduces/all-gathers. Out of reference scope
(the reference is pure DDP, SURVEY.md §2c) but it is what the open
``model`` mesh axis exists for.

Shipped sharding rule: **Megatron-style tensor parallelism for the
full ViT encoder layer** (``vit_tp_specs``) — MLP column→row parallel
AND head-aligned attention TP (qkv column-parallel by head groups,
out-proj row-parallel; the head-major fused-qkv storage layout in
dptpu/models/vit.py is what makes the contiguous split head-aligned).
Exactly two partitioner-inserted all-reduces per encoder layer — one
per MLP, one per attention block — locked by the HLO inspection test
in tests/test_gspmd.py. Composes with data parallelism over the
``data`` axis of the same mesh: batch sharded ``P("data")``, gradients
all-reduced by the partitioner.

Semantics note: under GSPMD the whole global batch is one logical
program, so any BatchNorm computes GLOBAL batch statistics (SyncBN
behavior); ViT/ConvNeXt (LayerNorm) are unaffected. Parity with the
single-device step is locked in tests/test_gspmd.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dptpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

# NOTE: dptpu.train imports stay lazy (same cycle as dptpu/parallel/zero.py).


def dp_specs(params):
    """Pure data-parallel PartitionSpec tree for ANY zoo model: every
    param replicated, the batch sharded ``P("data")`` by the step's
    in_shardings — the GSPMD/pjit expression of DDP, usable by all 79
    archs (the shard_map step in dptpu/train/step.py is the explicit
    twin). The partitioner derives the gradient all-reduce from the
    shardings alone.

    Semantics note (same as the module docstring): under GSPMD the
    global batch is one logical program, so BatchNorm computes GLOBAL
    batch statistics — SyncBN behavior, exactly the single-device
    big-batch step's numbers (locked in tests/test_gspmd.py on
    resnet18). The shard_map DDP step instead keeps torch-DDP's
    per-replica BN by default.

    Conv tensor parallelism is deliberately NOT shipped: a bottleneck's
    three convs cannot alternate Megatron column/row pairing without
    either leaving the biggest conv replicated or paying a collective
    per conv (the residual stream pins the block boundary layout), and
    CNN channel counts (64-2048) are small enough that the data axis is
    always the profitable one on TPU. ViT encoder TP (below) is where
    the model axis earns its keep."""
    return jax.tree_util.tree_map(lambda _: P(), params)


def _mlp_pair_spec(names):
    """Shared Megatron column→row rule for an ``mlp_1``/``mlp_2`` Dense
    pair (the naming every transformer family in the zoo uses); None for
    any other leaf so family rules can layer their own branches."""
    mod = names[-2] if len(names) > 1 else ""
    if mod == "mlp_1":  # column-parallel
        return P(None, MODEL_AXIS) if names[-1] == "kernel" else P(MODEL_AXIS)
    if mod == "mlp_2":  # row-parallel: split the input dim
        return P(MODEL_AXIS, None) if names[-1] == "kernel" else P()
    return None


def vit_tp_specs(params):
    """PartitionSpec tree for ViT: Megatron tensor parallelism over the
    ``model`` axis for BOTH halves of every encoder layer, everything
    else replicated.

    MLP: first Linear column-parallel (kernel ``P(None, "model")``, bias
    ``P("model")``), second row-parallel (``P("model", None)``,
    replicated bias) — one partitioner-inserted all-reduce per MLP.

    Attention, head-aligned: the fused qkv kernel's output axis is
    stored head-major (``(heads, 3, hd)`` flattened — see
    dptpu/models/vit.py SelfAttention), so its contiguous
    ``P(None, "model")`` split assigns each device a whole head GROUP
    (q, k and v) whenever the model-axis size divides ``heads`` — the
    projection is column-parallel, the per-head attention math is
    embarrassingly parallel over the sharded heads axis, and the
    row-parallel ``out_proj`` (``P("model", None)``) closes the block
    with its single all-reduce. Mesh sizes that do not divide ``heads``
    still compile (GSPMD reshards) but lose the alignment; ViT heads are
    12/16, so 2/4-way model axes are always aligned."""

    def spec(path, leaf):
        names = [p.key for p in path]
        mlp = _mlp_pair_spec(names)
        if mlp is not None:
            return mlp
        mod = names[-2] if len(names) > 1 else ""
        if mod == "in_proj":  # column-parallel
            return P(None, MODEL_AXIS) if names[-1] == "kernel" else P(MODEL_AXIS)
        if mod == "out_proj":  # row-parallel: split the input dim
            return P(MODEL_AXIS, None) if names[-1] == "kernel" else P()
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def swin_tp_specs(params):
    """PartitionSpec tree for Swin v1/v2: Megatron tensor parallelism
    over the ``model`` axis for every block, everything else replicated.

    Same design as ``vit_tp_specs`` — the fused qkv kernel is stored
    head-major (dptpu/models/swin.py ``_QKVDense``), so its contiguous
    ``P(None, "model")`` split is head-aligned whenever the model-axis
    size divides the stage's head count; ``proj`` is row-parallel. The
    per-head side tensors shard on their heads dim too: v1's
    relative-position-bias table, v2's ``logit_scale`` and the
    ``cpb_mlp_2`` head projection (its 512-wide input MLP stays
    replicated — it is tiny). MLPs are column→row as usual.

    Head counts per stage are (3, 6, 12, 24)-shaped for t/s and
    (4, 8, 16, 32) for b: a model axis of 3 (t/s) or 4 (b) is aligned
    at EVERY stage; other sizes still compile (GSPMD reshards) but lose
    the alignment.

    Scope note: MaxViT (the zoo's third attention family) keeps its
    [q|k|v]-major fused qkv and no TP spec — it is a conv-attention
    hybrid whose MBConv blocks dominate, so the data axis (``dp_specs``)
    is the profitable one there, same verdict as pure CNNs."""

    def spec(path, leaf):
        names = [p.key for p in path]
        mlp = _mlp_pair_spec(names)
        if mlp is not None:
            return mlp
        mod = names[-2] if len(names) > 1 else ""
        if mod in ("qkv", "cpb_mlp_2"):  # column-parallel
            return P(None, MODEL_AXIS) if names[-1] == "kernel" else P(MODEL_AXIS)
        if mod == "proj":  # row-parallel: split the input dim
            return P(MODEL_AXIS, None) if names[-1] == "kernel" else P()
        if names[-1] == "logit_scale":  # (heads, 1, 1)
            return P(MODEL_AXIS)
        if names[-1] == "relative_position_bias_table":  # ((2w-1)^2, heads)
            return P(None, MODEL_AXIS)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def convnext_tp_specs(params):
    """PartitionSpec tree for ConvNeXt: Megatron column→row TP for every
    block's MLP pair over the ``model`` axis, everything else
    replicated.

    The CNBlock is ``dwconv → LayerNorm → mlp_1 (C→4C) → GELU → mlp_2
    (4C→C) → layer_scale``: the FLOPs live in the two pointwise Linears,
    which take the standard column/row split with ONE partitioner
    all-reduce per block. The depthwise conv is per-channel and
    negligible-FLOP, and ConvNeXt's LayerNorm normalizes over the
    channel dim — sharding channels there would buy a collective per
    LN — so dw/norm/layer_scale (and stem/downsample/head) stay
    replicated. Any model-axis size dividing every stage's 4·dim is
    aligned: stage hiddens run 384→3072 (tiny/small), 512→4096 (base),
    768→6144 (large) — all divisible by 2/4/8."""

    def spec(path, leaf):
        names = [p.key for p in path]
        mlp = _mlp_pair_spec(names)
        return mlp if mlp is not None else P()

    return jax.tree_util.tree_map_with_path(spec, params)


def tp_rule_for_arch(arch: str) -> str:
    """Name the tensor-parallel sharding rule for an arch.

    Three families get real TP: the two attention families with
    head-major fused-qkv storage (``vit_*`` → ``vit_tp_specs``;
    ``swin*`` v1/v2 → ``swin_tp_specs``) and ConvNeXt's MLP pair
    (``convnext_*`` → ``convnext_tp_specs``). Every other arch —
    classic CNNs and MaxViT (conv-hybrid, see ``swin_tp_specs`` scope
    note) — answers ``dp_specs``. Arch-name-only so ``fit()`` can
    decide BEFORE mesh construction: a dp fallback should get the flat
    full-width data mesh, not a factored one with a redundant model
    axis."""
    if arch.startswith("vit_"):
        return "vit_tp_specs"
    if arch.startswith("swin"):
        return "swin_tp_specs"
    if arch.startswith("convnext"):
        return "convnext_tp_specs"
    return "dp_specs"


def tp_specs_for_arch(arch: str, params):
    """``(rule_name, specs)`` for ``tp_rule_for_arch``'s choice."""
    rule = tp_rule_for_arch(arch)
    fn = {"vit_tp_specs": vit_tp_specs, "swin_tp_specs": swin_tp_specs,
          "convnext_tp_specs": convnext_tp_specs, "dp_specs": dp_specs}[rule]
    return rule, fn(params)


def _opt_shardings(opt_state, pshard, rep):
    """Momentum (optax ``TraceState``) mirrors the param tree exactly, so
    it takes the param shardings STRUCTURALLY; every other optimizer
    leaf replicates (shared walk: dptpu/train/state.py map_momentum)."""
    from dptpu.train.state import map_momentum

    return map_momentum(opt_state, lambda _: pshard, lambda _: rep)


def state_shardings(state, mesh: Mesh, param_specs):
    """TrainState of NamedShardings: params (and their momentum mirror in
    opt_state) follow ``param_specs``; step/batch_stats replicated."""
    pshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_specs
    )
    rep = NamedSharding(mesh, P())
    return state.replace(
        step=rep,
        params=pshard,
        batch_stats=jax.tree_util.tree_map(lambda _: rep, state.batch_stats),
        opt_state=_opt_shardings(state.opt_state, pshard, rep),
    )


def shard_gspmd_state(state, mesh: Mesh, param_specs):
    """Place a TrainState according to ``state_shardings``. NOTE: may
    alias the input's buffers — step only the returned state afterwards
    (the step donates its input)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s),
        state, state_shardings(state, mesh, param_specs),
    )


def make_gspmd_train_step(mesh: Mesh, state_template, param_specs,
                          compute_dtype=jnp.float32, lr_schedule=None,
                          seed: int = 0, accum_steps: int = 1,
                          label_smoothing: float = 0.0):
    """Single-program train step partitioned by XLA.

    Same contract as ``make_train_step``: ``step(state, batch) ->
    (state, metrics)``; ``batch`` is the GLOBAL batch (sharded
    ``P("data")`` on entry), metrics are global scalars. The gradient
    all-reduce over ``data`` and the TP all-reduces over ``model`` are
    inserted by the SPMD partitioner — there is no collective in this
    source; that also covers the LARS/LAMB per-layer norms (global
    reductions the partitioner lowers itself — no ``sumsq_reduce``
    hook needed) and gradient accumulation (``accum_steps=k`` scans
    GLOBAL microbatches of ``B/k``; BN stays global-per-microbatch,
    the SyncBN semantics this path always has).
    """
    from dptpu.train.step import train_step_body, tpu_compiler_options

    if lr_schedule is None:
        lr_schedule = lambda count: 0.1  # noqa: E731

    def step(state, batch):
        # one logical program over the global batch: the shared step body
        # with no shard-local scaling or explicit collectives — the SPMD
        # partitioner derives all communication from the shardings
        return train_step_body(  # dptpu: allow-shard-map(GSPMD is the one step with NO explicit axes: on_mesh=False, the SPMD partitioner derives every collective from the shardings)
            state, batch, compute_dtype=compute_dtype,
            lr_schedule=lr_schedule, seed=seed, axis_size=1, on_mesh=False,
            accum_steps=accum_steps, label_smoothing=label_smoothing,
        )

    st_shardings = state_shardings(state_template, mesh, param_specs)
    batch_shardings = {
        "images": NamedSharding(mesh, P(DATA_AXIS)),
        "labels": NamedSharding(mesh, P(DATA_AXIS)),
    }
    rep = NamedSharding(mesh, P())
    metric_keys = ["loss", "top1", "top5", "lr"]
    from dptpu.ops.optimizers import trust_ratio_stats

    if trust_ratio_stats(state_template.opt_state) is not None:
        metric_keys += ["trust_min", "trust_mean", "trust_max"]
    metric_shardings = {k: rep for k in metric_keys}
    return jax.jit(
        step,
        in_shardings=(st_shardings, batch_shardings),
        out_shardings=(st_shardings, metric_shardings),
        donate_argnums=0,
        compiler_options=tpu_compiler_options(),
    )
