"""GSPMD (pjit-style) train step: sharding annotations, XLA collectives.

The DDP/ZeRO-1 steps express parallelism explicitly with ``shard_map``
(per-shard code + hand-placed collectives). This module is the OTHER
idiomatic TPU path — the scaling-book recipe: write single-program code,
annotate the param/batch shardings on ``jit``, and let XLA's SPMD
partitioner insert the all-reduces/all-gathers. Out of reference scope
(the reference is pure DDP, SURVEY.md §2c) but it is what the open
``model`` mesh axis exists for.

Shipped sharding rule: **Megatron-style MLP tensor parallelism for
ViT** (``vit_tp_specs``) — each encoder MLP's first Linear is
column-parallel (kernel ``P(None, "model")``, bias ``P("model")``) and
the second row-parallel (``P("model", None)``, replicated bias), so the
two big matmuls per layer run on 1/M of the hidden dim per device and
XLA inserts exactly one all-reduce per MLP. Attention params stay
replicated (the fused qkv kernel's output axis crosses q/k/v boundaries
when sliced naively; head-aligned attention TP is what
``dptpu.ops.sequence_parallel`` + shard_map are for). Composes with
data parallelism over the ``data`` axis of the same mesh: batch sharded
``P("data")``, gradients all-reduced by the partitioner.

Semantics note: under GSPMD the whole global batch is one logical
program, so any BatchNorm computes GLOBAL batch statistics (SyncBN
behavior); ViT/ConvNeXt (LayerNorm) are unaffected. Parity with the
single-device step is locked in tests/test_gspmd.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dptpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

# NOTE: dptpu.train imports stay lazy (same cycle as dptpu/parallel/zero.py).


def vit_tp_specs(params):
    """PartitionSpec tree for ViT: Megatron MLP tensor parallelism over
    the ``model`` axis, everything else replicated."""

    def spec(path, leaf):
        names = [p.key for p in path]
        mod = names[-2] if len(names) > 1 else ""
        if mod == "mlp_1":  # column-parallel: split the 4h hidden dim
            return P(None, MODEL_AXIS) if names[-1] == "kernel" else P(MODEL_AXIS)
        if mod == "mlp_2":  # row-parallel: split the input dim
            return P(MODEL_AXIS, None) if names[-1] == "kernel" else P()
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def _opt_shardings(opt_state, pshard, rep):
    """Momentum (optax ``TraceState``) mirrors the param tree exactly, so
    it takes the param shardings STRUCTURALLY (matching by shape alone
    would misplace a replicated param whose shape collides with a
    TP-sharded one); every other optimizer leaf replicates."""
    import optax

    def rec(node):
        if isinstance(node, optax.TraceState):
            return optax.TraceState(trace=pshard)
        if isinstance(node, (tuple, list)) and not hasattr(node, "shape"):
            children = [rec(c) for c in node]
            if hasattr(node, "_fields"):  # NamedTuple (optax states)
                return type(node)(*children)
            return children if isinstance(node, list) else tuple(children)
        return jax.tree_util.tree_map(lambda _: rep, node)

    return rec(opt_state)


def state_shardings(state, mesh: Mesh, param_specs):
    """TrainState of NamedShardings: params (and their momentum mirror in
    opt_state) follow ``param_specs``; step/batch_stats replicated."""
    pshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_specs
    )
    rep = NamedSharding(mesh, P())
    return state.replace(
        step=rep,
        params=pshard,
        batch_stats=jax.tree_util.tree_map(lambda _: rep, state.batch_stats),
        opt_state=_opt_shardings(state.opt_state, pshard, rep),
    )


def shard_gspmd_state(state, mesh: Mesh, param_specs):
    """Place a TrainState according to ``state_shardings``. NOTE: may
    alias the input's buffers — step only the returned state afterwards
    (the step donates its input)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s),
        state, state_shardings(state, mesh, param_specs),
    )


def make_gspmd_train_step(mesh: Mesh, state_template, param_specs,
                          compute_dtype=jnp.float32, lr_schedule=None,
                          seed: int = 0):
    """Single-program train step partitioned by XLA.

    Same contract as ``make_train_step``: ``step(state, batch) ->
    (state, metrics)``; ``batch`` is the GLOBAL batch (sharded
    ``P("data")`` on entry), metrics are global scalars. The gradient
    all-reduce over ``data`` and the TP all-reduces over ``model`` are
    inserted by the SPMD partitioner — there is no collective in this
    source.
    """
    from dptpu.train.step import train_step_body, tpu_compiler_options

    if lr_schedule is None:
        lr_schedule = lambda count: 0.1  # noqa: E731

    def step(state, batch):
        # one logical program over the global batch: the shared step body
        # with no shard-local scaling or explicit collectives — the SPMD
        # partitioner derives all communication from the shardings
        return train_step_body(
            state, batch, compute_dtype=compute_dtype,
            lr_schedule=lr_schedule, seed=seed, axis_size=1, on_mesh=False,
        )

    st_shardings = state_shardings(state_template, mesh, param_specs)
    batch_shardings = {
        "images": NamedSharding(mesh, P(DATA_AXIS)),
        "labels": NamedSharding(mesh, P(DATA_AXIS)),
    }
    rep = NamedSharding(mesh, P())
    metric_shardings = {k: rep for k in ("loss", "top1", "top5", "lr")}
    return jax.jit(
        step,
        in_shardings=(st_shardings, batch_shardings),
        out_shardings=(st_shardings, metric_shardings),
        donate_argnums=0,
        compiler_options=tpu_compiler_options(),
    )
