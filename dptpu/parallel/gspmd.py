"""GSPMD (pjit-style) train step: sharding annotations, XLA collectives.

The DDP/ZeRO-1 steps express parallelism explicitly with ``shard_map``
(per-shard code + hand-placed collectives). This module is the OTHER
idiomatic TPU path — the scaling-book recipe: write single-program code,
annotate the param/batch shardings on ``jit``, and let XLA's SPMD
partitioner insert the all-reduces/all-gathers. Out of reference scope
(the reference is pure DDP, SURVEY.md §2c) but it is what the open
``model`` mesh axis exists for.

Shipped sharding rule: **Megatron-style tensor parallelism for the
full ViT encoder layer** (``vit_tp_specs``) — MLP column→row parallel
AND head-aligned attention TP (qkv column-parallel by head groups,
out-proj row-parallel; the head-major fused-qkv storage layout in
dptpu/models/vit.py is what makes the contiguous split head-aligned).
Exactly two partitioner-inserted all-reduces per encoder layer — one
per MLP, one per attention block — locked by the HLO inspection test
in tests/test_gspmd.py. Composes with data parallelism over the
``data`` axis of the same mesh: batch sharded ``P("data")``, gradients
all-reduced by the partitioner.

Semantics note: under GSPMD the whole global batch is one logical
program, so any BatchNorm computes GLOBAL batch statistics (SyncBN
behavior); ViT/ConvNeXt (LayerNorm) are unaffected. Parity with the
single-device step is locked in tests/test_gspmd.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dptpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_axis_names,
    squeeze_axes,
)

# NOTE: dptpu.train and dptpu.models imports stay lazy (same cycle rule as
# dptpu/parallel/zero.py — and models.registry imports parallel.rules at
# module scope, so a module-level registry import here would be circular).


def _family_rules(family: str):
    from dptpu.models.registry import FAMILY_RULES

    return FAMILY_RULES[family]


def _tp_project(rules, params):
    """Project a family rules table onto the pure-TP view: keep only the
    ``model`` axis, no divisibility clamp (mesh sizes that do not divide
    still compile — GSPMD reshards — matching the historical hand-written
    specs exactly, equality-locked in tests/test_gspmd.py)."""
    from dptpu.parallel.rules import match_partition_rules

    return match_partition_rules(rules, params, keep_axes=(MODEL_AXIS,))


def dp_specs(params):
    """Pure data-parallel PartitionSpec tree for ANY zoo model: every
    param replicated, the batch sharded ``P("data")`` by the step's
    in_shardings — the GSPMD/pjit expression of DDP, usable by all 79
    archs (the shard_map step in dptpu/train/step.py is the explicit
    twin). The partitioner derives the gradient all-reduce from the
    shardings alone. The GENERIC registry table projected onto the
    model axis: ``AUTO_FSDP`` resolves to replicated under pure TP.

    Semantics note (same as the module docstring): under GSPMD the
    global batch is one logical program, so BatchNorm computes GLOBAL
    batch statistics — SyncBN behavior, exactly the single-device
    big-batch step's numbers (locked in tests/test_gspmd.py on
    resnet18). The shard_map DDP step instead keeps torch-DDP's
    per-replica BN by default.

    Conv tensor parallelism is deliberately NOT shipped: a bottleneck's
    three convs cannot alternate Megatron column/row pairing without
    either leaving the biggest conv replicated or paying a collective
    per conv (the residual stream pins the block boundary layout), and
    CNN channel counts (64-2048) are small enough that the data axis is
    always the profitable one on TPU. ViT encoder TP (below) is where
    the model axis earns its keep."""
    return _tp_project(_family_rules("generic"), params)


def vit_tp_specs(params):
    """ViT Megatron TP placement — the registry ``VIT_RULES`` table
    projected onto the ``model`` axis (dptpu/models/registry.py is the
    declaration; this function remains the GSPMD/serve consumer name).

    MLP: first Linear column-parallel (kernel ``P(None, "model")``, bias
    ``P("model")``), second row-parallel (``P("model", None)``,
    replicated bias) — one partitioner-inserted all-reduce per MLP.

    Attention, head-aligned: the fused qkv kernel's output axis is
    stored head-major (``(heads, 3, hd)`` flattened — see
    dptpu/models/vit.py SelfAttention), so its contiguous
    ``P(None, "model")`` split assigns each device a whole head GROUP
    (q, k and v) whenever the model-axis size divides ``heads`` — the
    projection is column-parallel, the per-head attention math is
    embarrassingly parallel over the sharded heads axis, and the
    row-parallel ``out_proj`` (``P("model", None)``) closes the block
    with its single all-reduce. Mesh sizes that do not divide ``heads``
    still compile (GSPMD reshards) but lose the alignment; ViT heads are
    12/16, so 2/4-way model axes are always aligned."""
    return _tp_project(_family_rules("vit"), params)


def swin_tp_specs(params):
    """Swin v1/v2 Megatron TP placement — the registry ``SWIN_RULES``
    table projected onto the ``model`` axis.

    Same design as ``vit_tp_specs`` — the fused qkv kernel is stored
    head-major (dptpu/models/swin.py ``_QKVDense``), so its contiguous
    ``P(None, "model")`` split is head-aligned whenever the model-axis
    size divides the stage's head count; ``proj`` is row-parallel. The
    per-head side tensors shard on their heads dim too: v1's
    relative-position-bias table, v2's ``logit_scale`` and the
    ``cpb_mlp_2`` head projection (its 512-wide input MLP stays
    replicated — it is tiny). MLPs are column→row as usual. The v1-only
    and v2-only rows are dead on the other variant by construction —
    the ``dptpu check`` partition-rules gate aggregates rule liveness
    across the whole family, not per model.

    Head counts per stage are (3, 6, 12, 24)-shaped for t/s and
    (4, 8, 16, 32) for b: a model axis of 3 (t/s) or 4 (b) is aligned
    at EVERY stage; other sizes still compile (GSPMD reshards) but lose
    the alignment.

    Scope note: MaxViT (the zoo's third attention family) keeps its
    [q|k|v]-major fused qkv and no TP spec — it is a conv-attention
    hybrid whose MBConv blocks dominate, so the data axis (``dp_specs``)
    is the profitable one there, same verdict as pure CNNs."""
    return _tp_project(_family_rules("swin"), params)


def convnext_tp_specs(params):
    """ConvNeXt Megatron TP placement — the registry ``CONVNEXT_RULES``
    table projected onto the ``model`` axis: column→row TP for every
    block's MLP pair, everything else replicated.

    The CNBlock is ``dwconv → LayerNorm → mlp_1 (C→4C) → GELU → mlp_2
    (4C→C) → layer_scale``: the FLOPs live in the two pointwise Linears,
    which take the standard column/row split with ONE partitioner
    all-reduce per block. The depthwise conv is per-channel and
    negligible-FLOP, and ConvNeXt's LayerNorm normalizes over the
    channel dim — sharding channels there would buy a collective per
    LN — so dw/norm/layer_scale (and stem/downsample/head) stay
    replicated. Any model-axis size dividing every stage's 4·dim is
    aligned: stage hiddens run 384→3072 (tiny/small), 512→4096 (base),
    768→6144 (large) — all divisible by 2/4/8."""
    return _tp_project(_family_rules("convnext"), params)


# Legacy rule-name surface: fit()'s verbose line, serve's placement
# resolution and the spec tests all speak these names; each maps to the
# family whose registry table it projects.
_RULE_FOR_FAMILY = {
    "vit": "vit_tp_specs",
    "swin": "swin_tp_specs",
    "convnext": "convnext_tp_specs",
    "generic": "dp_specs",
}


def tp_rule_for_arch(arch: str) -> str:
    """Name the tensor-parallel sharding rule for an arch.

    Three families get real TP: the two attention families with
    head-major fused-qkv storage (``vit_*`` → ``vit_tp_specs``;
    ``swin*`` v1/v2 → ``swin_tp_specs``) and ConvNeXt's MLP pair
    (``convnext_*`` → ``convnext_tp_specs``). Every other arch —
    classic CNNs and MaxViT (conv-hybrid, see ``swin_tp_specs`` scope
    note) — answers ``dp_specs``. Arch-name-only so ``fit()`` can
    decide BEFORE mesh construction: a dp fallback should get the flat
    full-width data mesh, not a factored one with a redundant model
    axis. Family membership is the registry's
    ``partition_family`` — the one declaration point."""
    from dptpu.models.registry import partition_family

    return _RULE_FOR_FAMILY[partition_family(arch)]


def tp_specs_for_arch(arch: str, params):
    """``(rule_name, specs)`` for ``tp_rule_for_arch``'s choice."""
    from dptpu.models.registry import partition_family

    family = partition_family(arch)
    return _RULE_FOR_FAMILY[family], _tp_project(_family_rules(family), params)


def gspmd_specs_for_arch(arch: str, params, mesh: Mesh, *,
                         tp: bool = False, fsdp: bool = False):
    """The arch's registry rules table projected onto THIS mesh — the
    general GSPMD placement (``tp_specs_for_arch`` is the pure-TP
    special case kept for its locked name surface).

    ``fsdp=True`` keeps the ``data`` axis: params shard over the
    intra-slice data axis and the SPMD partitioner derives the ZeRO-3
    communication pattern itself — all-gather at use, reduce-scatter
    for the grads. On a ``{slice, data}``-factored mesh that is the
    hierarchical decomposition (RS over ICI, shard-sized AR over DCN,
    AG over ICI) the shard_map path hand-places; here the placement
    declaration alone produces it. ``tp=True`` keeps ``model``. FSDP
    projections clamp to mesh-size divisibility (clean tiles keep the
    per-link HLO budgets exact; a non-dividing leaf degrades to
    replicated, same as the shard_map paths)."""
    from dptpu.models.registry import partition_rules_for_arch
    from dptpu.parallel.rules import match_partition_rules

    keep = []
    if fsdp:
        keep.append(DATA_AXIS)
    if tp:
        keep.append(MODEL_AXIS)
    if not keep:
        return dp_specs(params)
    clamp = None
    if fsdp:
        clamp = {a: int(mesh.shape[a]) for a in keep if a in mesh.shape}
    return match_partition_rules(
        partition_rules_for_arch(arch), params,
        keep_axes=tuple(keep), clamp=clamp,
    )


def _opt_shardings(opt_state, pshard, rep):
    """Momentum (optax ``TraceState``) mirrors the param tree exactly, so
    it takes the param shardings STRUCTURALLY; every other optimizer
    leaf replicates (shared walk: dptpu/train/state.py map_momentum)."""
    from dptpu.train.state import map_momentum

    return map_momentum(opt_state, lambda _: pshard, lambda _: rep)


def state_shardings(state, mesh: Mesh, param_specs):
    """TrainState of NamedShardings: params (and their momentum mirror in
    opt_state) follow ``param_specs``; step/batch_stats replicated."""
    pshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_specs
    )
    rep = NamedSharding(mesh, P())
    return state.replace(
        step=rep,
        params=pshard,
        batch_stats=jax.tree_util.tree_map(lambda _: rep, state.batch_stats),
        opt_state=_opt_shardings(state.opt_state, pshard, rep),
    )


def shard_gspmd_state(state, mesh: Mesh, param_specs):
    """Place a TrainState according to ``state_shardings``. NOTE: may
    alias the input's buffers — step only the returned state afterwards
    (the step donates its input)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s),
        state, state_shardings(state, mesh, param_specs),
    )


def make_gspmd_bucket_reduce(mesh: Mesh):
    """Per-bucket gradient boundary for the GSPMD path.

    The shard_map overlap buckets call ``lax.psum`` explicitly; under
    plain ``jit`` there is no bound axis name to psum over, so the
    GSPMD spelling is a sharding CONSTRAINT: concat the bucket's grads
    flat and pin the result replicated. The partitioner must therefore
    materialize the fully-reduced value at that point — one fused
    all-reduce per bucket — and because ``OverlapPlan.wrap`` anchors
    this inside the backward via the per-bucket custom-VJP identity,
    the bucket reductions are scheduled interleaved with remaining
    backward compute instead of as one post-backward monolith
    (gated by ``hlo_accounting.overlap_evidence`` exactly as for
    shard_map, in the ``gspmd_overlap`` HLO budget config)."""
    from dptpu.parallel.overlap import _concat_flat, _split_flat

    rep = NamedSharding(mesh, P())

    def reduce_bucket(cts, idxs):
        vec = jax.lax.with_sharding_constraint(_concat_flat(cts), rep)
        return _split_flat(vec, cts)

    return reduce_bucket


def make_gspmd_train_step(mesh: Mesh, state_template, param_specs,
                          compute_dtype=jnp.float32, lr_schedule=None,
                          seed: int = 0, accum_steps: int = 1,
                          label_smoothing: float = 0.0,
                          overlap: bool = False,
                          bucket_bytes: int = None):
    """Single-program train step partitioned by XLA.

    Same contract as ``make_train_step``: ``step(state, batch) ->
    (state, metrics)``; ``batch`` is the GLOBAL batch (sharded over the
    mesh's data axes on entry — ``P("data")`` flat, ``P(("slice",
    "data"))`` on a hierarchical mesh), metrics are global scalars. The
    gradient all-reduce over ``data`` and the TP all-reduces over
    ``model`` are inserted by the SPMD partitioner — there is no
    collective in this source; that also covers the LARS/LAMB per-layer
    norms (global reductions the partitioner lowers itself — no
    ``sumsq_reduce`` hook needed) and gradient accumulation
    (``accum_steps=k`` scans GLOBAL microbatches of ``B/k``; BN stays
    global-per-microbatch, the SyncBN semantics this path always has).

    ``overlap=True`` buckets the gradient reductions
    (``make_gspmd_bucket_reduce``): per-bucket custom-VJP boundaries in
    the backward, replicated-constraint reductions the partitioner
    fuses one-per-bucket — PR 13's bucketing carried over to the pjit
    path.
    """
    from dptpu.parallel.overlap import DEFAULT_BUCKET_MB, OverlapPlan
    from dptpu.train.step import train_step_body, tpu_compiler_options

    if lr_schedule is None:
        lr_schedule = lambda count: 0.1  # noqa: E731
    overlap_plan = None
    if overlap:
        if bucket_bytes is None:
            bucket_bytes = int(DEFAULT_BUCKET_MB * 1024 * 1024)
        overlap_plan = OverlapPlan(bucket_bytes, make_gspmd_bucket_reduce(mesh))

    def step(state, batch):
        # one logical program over the global batch: the shared step body
        # with no shard-local scaling or explicit collectives — the SPMD
        # partitioner derives all communication from the shardings
        return train_step_body(  # dptpu: allow-shard-map(GSPMD is the one step with NO explicit axes: on_mesh=False, the SPMD partitioner derives every collective from the shardings)
            state, batch, compute_dtype=compute_dtype,
            lr_schedule=lr_schedule, seed=seed, axis_size=1, on_mesh=False,
            accum_steps=accum_steps, label_smoothing=label_smoothing,
            overlap_plan=overlap_plan,
        )

    st_shardings = state_shardings(state_template, mesh, param_specs)
    batch_spec = P(squeeze_axes(data_axis_names(mesh)))
    batch_shardings = {
        "images": NamedSharding(mesh, batch_spec),
        "labels": NamedSharding(mesh, batch_spec),
    }
    rep = NamedSharding(mesh, P())
    metric_keys = ["loss", "top1", "top5", "lr"]
    from dptpu.ops.optimizers import trust_ratio_stats

    if trust_ratio_stats(state_template.opt_state) is not None:
        metric_keys += ["trust_min", "trust_mean", "trust_max"]
    metric_shardings = {k: rep for k in metric_keys}
    return jax.jit(
        step,
        in_shardings=(st_shardings, batch_shardings),
        out_shardings=(st_shardings, metric_shardings),
        donate_argnums=0,
        compiler_options=tpu_compiler_options(),
    )
