"""ZeRO-1 / cross-replica weight-update sharding over the data axis.

The reference replicates optimizer state per process (SGD per rank,
imagenet_ddp.py:133-135; SURVEY.md §2c lists sharded optimizers as an
optional later optimization). On TPU the classic upgrade — Xu et al.'s
weight-update sharding, the PAPERS.md retrieval — falls out of the same
``shard_map`` step dptpu already uses for DDP:

* params and optimizer state live SHARDED along the data axis: each
  leaf splits on its LARGEST dimension that the axis size divides
  (lowest index on ties), replicated only when no dimension divides.
  Dim 0 alone would miss conv nets almost entirely — HWIO kernels
  lead with kernel height (1/3/7) — whereas the channel dims are
  near-always divisible, so ≥99% of params+momentum bytes shard for
  both resnet50 and vit_b_16 (asserted in tests/test_zero1.py via
  ``zero1_sharded_fraction``). Persistent per-chip memory for params
  + momentum drops ~1/N;
* inside the step each device ``all_gather``s the full params for
  forward/backward. The VJP of a tiled all-gather is ``psum_scatter``,
  so the gradient arrives REDUCE-SCATTERED — each device holds exactly
  its shard's global-sum gradient. Total collective traffic
  (all-gather + reduce-scatter) equals DDP's all-reduce; XLA overlaps
  both with compute;
* the SGD update (momentum, weight decay, LR) is elementwise, so each
  device updates only its own shard — identical math to DDP, locked by
  tests/test_zero1.py against the single-device big-batch step.

Checkpointing/eval work unchanged: sharded arrays are still global
jax.Arrays — ``np.asarray`` gathers for ``torch.save``-style
serialization, and the replicated-spec eval step reshards on entry (use
``gather_state`` once per validation pass to avoid re-gathering every
eval step).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:  # jax ≥ 0.8 top-level name; experimental path kept as fallback
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from dptpu.parallel.mesh import DATA_AXIS

# NOTE: dptpu.train is imported lazily inside make_zero1_train_step —
# a module-level import would close the cycle parallel/__init__ -> zero
# -> train/__init__ -> fit -> parallel/__init__ (partially initialized)
# whenever dptpu.parallel is imported before dptpu.train.


def _leaf_spec(leaf, n: int) -> P:
    """Shard the largest evenly-divisible dim over the data axis.

    Any divisible dim yields the same 1/N byte saving; the largest one
    (lowest index on ties) keeps per-device shards from degenerating to
    width-1 slices on mixed-shape leaves. Leaves with no divisible dim
    (tiny biases, scalars) stay replicated — they are a rounding error
    of the total (see ``zero1_sharded_fraction``)."""
    shape = getattr(leaf, "shape", ())
    best = -1
    for d, extent in enumerate(shape):
        if extent >= n and extent % n == 0 and (
            best < 0 or extent > shape[best]
        ):
            best = d
    if best < 0:
        return P()
    return P(*([None] * best), DATA_AXIS)


def _sharded_axis(spec: P) -> int:
    """Index of the data-sharded dim in a ``_leaf_spec`` result, -1 if
    replicated."""
    for d, name in enumerate(spec):
        if name == DATA_AXIS:
            return d
    return -1


def zero1_sharded_fraction(state, mesh: Mesh) -> float:
    """Fraction of params+opt_state BYTES that actually shard 1/N.

    This is the feature's headline claim made measurable: ~1/N
    persistent HBM per chip holds only if this is ≈1.0. Accepts a real
    TrainState or a ``jax.eval_shape`` ShapeDtypeStruct tree (no
    allocation needed)."""
    specs = zero1_state_specs(state, mesh)
    total = 0
    sharded = 0
    for part in ("params", "opt_state"):
        leaves = jax.tree_util.tree_leaves(getattr(state, part))
        spec_leaves = jax.tree_util.tree_leaves(
            getattr(specs, part), is_leaf=lambda x: isinstance(x, P)
        )
        for leaf, spec in zip(leaves, spec_leaves):
            nbytes = int(np.prod(leaf.shape) if leaf.shape else 1) * (
                jnp.dtype(leaf.dtype).itemsize
            )
            total += nbytes
            if _sharded_axis(spec) >= 0:
                sharded += nbytes
    return sharded / max(total, 1)


def zero1_state_specs(state, mesh: Mesh):
    """TrainState-shaped PartitionSpec tree: each params/opt_state leaf
    sharded on its largest evenly-divisible dim (``_leaf_spec``),
    everything else (step, batch_stats) replicated."""
    n = int(mesh.shape[DATA_AXIS])
    return state.replace(
        step=P(),
        params=jax.tree_util.tree_map(
            lambda l: _leaf_spec(l, n), state.params),
        batch_stats=jax.tree_util.tree_map(lambda _: P(), state.batch_stats),
        opt_state=jax.tree_util.tree_map(
            lambda l: _leaf_spec(l, n), state.opt_state),
    )


def shard_zero1_state(state, mesh: Mesh):
    """Place a (replicated) TrainState into the ZeRO-1 layout: each
    sharded leaf stores 1/N per device. Values are unchanged. NOTE:
    ``device_put`` may alias the input's buffers — after sharding, step
    only the returned state (the train steps donate their inputs)."""
    specs = zero1_state_specs(state, mesh)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs
    )


def gather_state(state, mesh: Mesh):
    """Re-replicate a ZeRO-1 state (e.g. once before a validation pass,
    so the replicated-spec eval step doesn't all-gather every batch)."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), state
    )


def make_zero1_train_step(mesh: Mesh, state_template, compute_dtype=jnp.float32,
                          lr_schedule=None, seed: int = 0):
    """ZeRO-1 variant of ``dptpu.train.step.make_train_step``.

    ``state_template`` fixes which leaves shard; it must be the SAME
    TrainState the returned step will receive (or share its
    ``apply_fn``/``tx`` objects) — those static fields are part of the
    pytree metadata that shard_map matches specs against. Returns
    ``step(state, batch) -> (state, metrics)`` with the SAME contract and
    math as the DDP step; ``state`` must be in the ``shard_zero1_state``
    layout and comes back in it.
    """
    from dptpu.train.step import train_step_body, tpu_compiler_options

    if lr_schedule is None:
        lr_schedule = lambda count: 0.1  # noqa: E731
    axis_size = int(mesh.shape[DATA_AXIS])
    specs = zero1_state_specs(state_template, mesh)

    def gather_params(params):
        # all-gather (along whichever dim _leaf_spec chose) -> full
        # params; the VJP of the tiled all-gather is psum_scatter, so
        # the gradient w.r.t. the local shards arrives already
        # reduce-scattered: each device gets its shard of the global
        # gradient sum with no separate all-reduce.
        def gather(x, s):
            d = _sharded_axis(s)
            if d < 0:
                return x
            return lax.all_gather(x, DATA_AXIS, axis=d, tiled=True)

        return jax.tree_util.tree_map(gather, params, specs.params)

    def step(state, batch):
        return train_step_body(
            state, batch, compute_dtype=compute_dtype,
            lr_schedule=lr_schedule, seed=seed, axis_size=axis_size,
            on_mesh=True, gather_params=gather_params,
        )

    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(specs, P(DATA_AXIS)),
        out_specs=(specs, P()),
    )
    return jax.jit(
        sharded, donate_argnums=0, compiler_options=tpu_compiler_options()
    )
