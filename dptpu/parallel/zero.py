"""ZeRO-1 / cross-replica weight-update sharding over the data axis.

The reference replicates optimizer state per process (SGD per rank,
imagenet_ddp.py:133-135; SURVEY.md §2c lists sharded optimizers as an
optional later optimization). On TPU the classic upgrade — Xu et al.'s
weight-update sharding, the PAPERS.md retrieval — falls out of the same
``shard_map`` step dptpu already uses for DDP:

* params and optimizer state live SHARDED along the data axis: each
  leaf splits on its LARGEST dimension that the axis size divides
  (lowest index on ties), replicated only when no dimension divides.
  Dim 0 alone would miss conv nets almost entirely — HWIO kernels
  lead with kernel height (1/3/7) — whereas the channel dims are
  near-always divisible, so ≥99% of params+momentum bytes shard for
  both resnet50 and vit_b_16 (asserted in tests/test_zero1.py via
  ``zero1_sharded_fraction``). Persistent per-chip memory for params
  + momentum drops ~1/N;
* inside the step each device ``all_gather``s the full params for
  forward/backward. The VJP of a tiled all-gather is ``psum_scatter``,
  so the gradient arrives REDUCE-SCATTERED — each device holds exactly
  its shard's global-sum gradient. Total collective traffic
  (all-gather + reduce-scatter) equals DDP's all-reduce; XLA overlaps
  both with compute;
* the ENTIRE optimizer update runs on the local shard — this is Xu et
  al.'s weight-update sharding (arXiv:2004.13336) in full: SGD's chain
  (momentum, weight decay, LR) is elementwise and needs nothing more;
  the LARS/LAMB trust ratios (dptpu/ops/optimizers.py) need per-LAYER
  norms, which each device completes from its shard-local partial
  sums with ONE psum of a tiny ``[L, 2]`` stack (``zero1_sumsq_reduce``
  below) — so optimizer FLOPs AND optimizer-state bytes scale 1/N with
  DP width while the per-step collective bytes stay at DDP's
  all-reduce volume plus those 2·L floats (at accum_steps=1; under
  gradient accumulation the all-gather + reduce-scatter pair runs once
  per MICROBATCH — K× the param bytes per step — where DDP's single
  post-scan psum does not scale with K). Identical math to the
  replicated update, locked by tests/test_zero1.py against the
  single-device big-batch step;
* the few leaves no dimension divides (tiny biases — a rounding error
  of the bytes) stay replicated; their gradients take an explicit
  ``lax.psum`` (the steps run ``check_rep=False``, so no implicit
  collective exists to cover them — see
  dptpu.train.step.shard_map_nocheck).

Checkpointing/eval work unchanged: sharded arrays are still global
jax.Arrays — ``np.asarray`` gathers for ``torch.save``-style
serialization, and the replicated-spec eval step reshards on entry (use
``gather_state`` once per validation pass to avoid re-gathering every
eval step).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dptpu.parallel.mesh import (
    DATA_AXIS,
    SLICE_AXIS,
    data_axis_names,
    data_parallel_width,
    squeeze_axes,
)

# NOTE: dptpu.train is imported lazily inside make_zero1_train_step —
# a module-level import would close the cycle parallel/__init__ -> zero
# -> train/__init__ -> fit -> parallel/__init__ (partially initialized)
# whenever dptpu.parallel is imported before dptpu.train.


def _leaf_spec(leaf, n: int) -> P:
    """Shard the largest evenly-divisible dim over the data axis.

    Any divisible dim yields the same 1/N byte saving; the largest one
    (lowest index on ties) keeps per-device shards from degenerating to
    width-1 slices on mixed-shape leaves. Leaves with no divisible dim
    (tiny biases, scalars) stay replicated — they are a rounding error
    of the total (see ``zero1_sharded_fraction``). The dim-selection
    rule is the SHARED ``mesh.largest_divisible_dim`` — the
    hierarchical reduce-scatter resolves through the same function, so
    its gradient shard is the update shard by construction. Delegates
    to ``rules.fsdp_auto_spec`` — the same resolver the rules tables'
    ``AUTO_FSDP`` fallback uses — so the ZeRO-1 layout and the
    table-driven placements share one implementation."""
    from dptpu.parallel.rules import fsdp_auto_spec

    return fsdp_auto_spec(getattr(leaf, "shape", ()), n)


def _sharded_axis(spec: P) -> int:
    """Index of the data-sharded dim in a ``_leaf_spec`` result, -1 if
    replicated."""
    for d, name in enumerate(spec):
        if name == DATA_AXIS:
            return d
    return -1


def _iter_state_bytes(state, mesh: Mesh):
    """Yield ``(nbytes, is_sharded)`` for every params/opt_state leaf
    under this state's ``zero1_state_specs`` — the ONE byte-accounting
    walk behind ``zero1_sharded_fraction`` and
    ``zero1_update_shard_bytes`` (a second copy of the zip would let
    the telemetry silently diverge from the headline claim). Accepts a
    real TrainState or a ``jax.eval_shape`` ShapeDtypeStruct tree."""
    specs = zero1_state_specs(state, mesh)
    for part in ("params", "opt_state"):
        leaves = jax.tree_util.tree_leaves(getattr(state, part))
        spec_leaves = jax.tree_util.tree_leaves(
            getattr(specs, part), is_leaf=lambda x: isinstance(x, P)
        )
        for leaf, spec in zip(leaves, spec_leaves):
            nbytes = int(np.prod(leaf.shape) if leaf.shape else 1) * (
                jnp.dtype(leaf.dtype).itemsize
            )
            yield nbytes, _sharded_axis(spec) >= 0


def zero1_sharded_fraction(state, mesh: Mesh) -> float:
    """Fraction of params+opt_state BYTES that actually shard 1/N.

    This is the feature's headline claim made measurable: ~1/N
    persistent HBM per chip holds only if this is ≈1.0."""
    total = 0
    sharded = 0
    for nbytes, is_sharded in _iter_state_bytes(state, mesh):
        total += nbytes
        if is_sharded:
            sharded += nbytes
    return sharded / max(total, 1)


def zero1_state_specs(state, mesh: Mesh):
    """TrainState-shaped PartitionSpec tree: each params/opt_state leaf
    sharded on its largest evenly-divisible dim (``_leaf_spec``),
    everything else (step, batch_stats) replicated."""
    n = int(mesh.shape[DATA_AXIS])
    return state.replace(
        step=P(),
        params=jax.tree_util.tree_map(
            lambda l: _leaf_spec(l, n), state.params),
        batch_stats=jax.tree_util.tree_map(lambda _: P(), state.batch_stats),
        opt_state=jax.tree_util.tree_map(
            lambda l: _leaf_spec(l, n), state.opt_state),
    )


def shard_zero1_state(state, mesh: Mesh):
    """Place a (replicated) TrainState into the ZeRO-1 layout: each
    sharded leaf stores 1/N per device. Values are unchanged. NOTE:
    ``device_put`` may alias the input's buffers — after sharding, step
    only the returned state (the train steps donate their inputs)."""
    specs = zero1_state_specs(state, mesh)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs
    )


def gather_state(state, mesh: Mesh):
    """Re-replicate a ZeRO-1 state (e.g. once before a validation pass,
    so the replicated-spec eval step doesn't all-gather every batch)."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), state
    )


def zero1_sumsq_reduce(param_specs):
    """Build the trust-ratio norm completer for the sharded update.

    The trust-ratio transforms (dptpu/ops/optimizers.py) hand over a
    params-structured tree of ``[sum(w²), sum(u²)]`` pairs computed on
    the LOCAL shard. Sharded leaves' partials sum across the data axis;
    replicated leaves' are already global (psum'ing them would count
    each copy N times). ALL pairs stack into one ``[L, 2]`` array so the
    completion is a single psum of ~2·L floats — the "one small psum"
    that keeps the whole optimizer math shard-local (arXiv:2004.13336).
    """
    spec_leaves = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P)
    )
    mask = np.array(
        [1.0 if _sharded_axis(s) >= 0 else 0.0 for s in spec_leaves],
        np.float32,
    )[:, None]

    def reduce(pairs_tree):
        leaves, treedef = jax.tree_util.tree_flatten(pairs_tree)
        if len(leaves) != len(spec_leaves):
            raise ValueError(
                f"trust-ratio pairs tree has {len(leaves)} leaves but the "
                f"ZeRO-1 spec tree has {len(spec_leaves)} — the optimizer "
                f"was built against a different param tree"
            )
        stacked = jnp.stack(leaves)
        total = lax.psum(stacked, DATA_AXIS)  # the ONE small psum
        completed = stacked * (1.0 - mask) + total * mask
        return jax.tree_util.tree_unflatten(
            treedef, [completed[i] for i in range(len(leaves))]
        )

    return reduce


def zero1_update_shard_bytes(state, mesh: Mesh) -> int:
    """Bytes of params + optimizer state ONE device reads/writes per
    update under the sharded weight update (the ``Opt/update_shard_bytes``
    gauge): sharded leaves count 1/N, replicated leaves in full. The
    replicated-update baseline is the same sum with N = 1."""
    n = int(mesh.shape[DATA_AXIS])
    return sum(
        nbytes // n if is_sharded else nbytes
        for nbytes, is_sharded in _iter_state_bytes(state, mesh)
    )


# --------------------------------------------------------------------------
# ZeRO-3 / FSDP: the rules-table generalization of the weight-update
# sharding above. ZeRO-1's placement is the per-leaf ``_leaf_spec``
# heuristic; ZeRO-3 instead resolves the arch's REGISTRY rules table
# (dptpu/models/registry.py FAMILY_RULES projected onto the data axis via
# dptpu/parallel/rules.py), so the FSDP shard dims are the ones the
# family declaration picked to compose with tensor parallelism, and the
# forward/backward boundary is an EXPLICIT custom-VJP pair: forward
# all-gather, backward psum_scatter — the backward gather IS the
# reduce-scatter, stated in source rather than inherited from the
# all-gather's VJP. Grads therefore stay shard-sized through the
# accumulation scan, the fp32 optimizer state stays shard-sized, and the
# per-chip params+grads+opt-state footprint is ~1/N (gated in SCALEBENCH
# and the ``zero3`` HLO budget config).
#
# make_zero1_train_step above is deliberately untouched: its compiled
# program is exact-matched by HLO_BUDGETS.json.


def zero3_param_specs(arch: str, params, mesh: Mesh):
    """The arch's registry rules table projected onto the intra-slice
    data axis — THE ZeRO-3 placement. Clamped to mesh-size
    divisibility (the tiled all-gather boundary needs even tiles; a
    non-dividing leaf degrades to replicated exactly like
    ``_leaf_spec``'s remainder). ``AUTO_FSDP`` rows resolve through the
    same ``largest_divisible_dim`` rule ZeRO-1 uses, so for a generic
    CNN this tree is bit-identical to ``zero1_state_specs``' params."""
    from dptpu.models.registry import partition_rules_for_arch
    from dptpu.parallel.rules import match_partition_rules

    n = int(mesh.shape[DATA_AXIS])
    return match_partition_rules(
        partition_rules_for_arch(arch), params,
        keep_axes=(DATA_AXIS,), clamp={DATA_AXIS: n},
    )


def zero3_state_specs(state, mesh: Mesh, param_specs):
    """TrainState-shaped spec tree for the ZeRO-3 layout: params follow
    the rules-table placement, momentum mirrors it STRUCTURALLY
    (``map_momentum`` — the update is shard-local, so the fp32 state
    lives exactly where its param shard lives), everything else
    replicated."""
    from dptpu.train.state import map_momentum

    return state.replace(
        step=P(),
        params=param_specs,
        batch_stats=jax.tree_util.tree_map(lambda _: P(), state.batch_stats),
        opt_state=map_momentum(
            state.opt_state, lambda _: param_specs, lambda _: P()
        ),
    )


def shard_zero3_state(state, mesh: Mesh, param_specs):
    """Place a (replicated) TrainState into the ZeRO-3 layout (see
    ``shard_zero1_state`` for the donation caveat — step only the
    returned state). Re-sharding an already-placed state is fine:
    ``device_put`` moves it — this is what the elastic resume path does
    after a geometry change."""
    specs = zero3_state_specs(state, mesh, param_specs)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs
    )


def state_shard_bytes(state, mesh: Mesh, specs) -> int:
    """Per-chip bytes of params + optimizer state under an explicit
    TrainState-shaped spec tree (``zero3_state_specs`` result) — the
    SCALEBENCH 1/N gate's numerator. Same accounting contract as
    ``zero1_update_shard_bytes``: sharded leaves count 1/N, replicated
    in full; N=1 (or an all-replicated spec tree) gives the DDP
    baseline."""
    n = int(mesh.shape[DATA_AXIS])
    total = 0
    for part in ("params", "opt_state"):
        leaves = jax.tree_util.tree_leaves(getattr(state, part))
        spec_leaves = jax.tree_util.tree_leaves(
            getattr(specs, part), is_leaf=lambda x: isinstance(x, P)
        )
        for leaf, spec in zip(leaves, spec_leaves):
            nbytes = int(np.prod(leaf.shape) if leaf.shape else 1) * (
                jnp.dtype(leaf.dtype).itemsize
            )
            total += nbytes // n if _sharded_axis(spec) >= 0 else nbytes
    return total


_GATHER_CACHE = {}


def _zero3_gather(d: int):
    """The explicit ZeRO-3 boundary for a leaf sharded on dim ``d``:
    forward is the tiled all-gather (full params on every device, used
    and discarded within the step), backward is the tiled
    ``psum_scatter`` on the SAME dim — each device receives exactly its
    shard of the global gradient sum, so the gradient is never
    materialized unsharded. This is what the all-gather's derived VJP
    does implicitly for ZeRO-1; stating it as a custom VJP pins the
    pairing against AD internals and gives the overlap plan a stable
    per-leaf anchor in the backward."""
    fn = _GATHER_CACHE.get(d)
    if fn is not None:
        return fn

    @jax.custom_vjp
    def gather(x):
        return lax.all_gather(x, DATA_AXIS, axis=d, tiled=True)

    def fwd(x):
        return lax.all_gather(x, DATA_AXIS, axis=d, tiled=True), None

    def bwd(_, ct):
        return (lax.psum_scatter(
            ct, DATA_AXIS, scatter_dimension=d, tiled=True),)

    gather.defvjp(fwd, bwd)
    _GATHER_CACHE[d] = gather
    return gather


def make_zero3_train_step(mesh: Mesh, state_template, param_specs,
                          compute_dtype=jnp.float32, lr_schedule=None,
                          seed: int = 0, accum_steps: int = 1,
                          label_smoothing: float = 0.0, tx_factory=None,
                          dcn_dtype: str = "fp32", overlap: bool = False,
                          bucket_bytes=None):
    """ZeRO-3/FSDP variant of ``make_zero1_train_step``: same contract
    (``state`` in the ``shard_zero3_state`` layout, back in it), same
    collective volume (gather + scatter = DDP's all-reduce bytes), but
    placement comes from the arch's rules table (``param_specs`` =
    ``zero3_param_specs``) and the gather/scatter boundary is the
    explicit ``_zero3_gather`` custom VJP. Composes exactly like
    ZeRO-1: ``accum_steps`` keeps the fp32 grad accumulator SHARD-sized
    (the scatter runs per microbatch inside the boundary's backward),
    a hierarchical mesh adds the shard-sized DCN hop once per update,
    and ``overlap=True`` buckets the DCN/remainder work in-backward
    (``make_zero1_bucket_reduce`` — the bucket engine is
    layout-agnostic, it only needs the sharded flags)."""
    from dptpu.parallel.hierarchy import (
        DCN_DTYPES,
        dcn_reduce_shard,
        is_hierarchical,
    )
    from dptpu.train.step import (
        shard_map_nocheck,
        tpu_compiler_options,
        train_step_body,
    )

    if dcn_dtype not in DCN_DTYPES:
        raise ValueError(
            f"dcn_dtype={dcn_dtype!r} must be one of "
            + "/".join(repr(d) for d in DCN_DTYPES)
        )
    if lr_schedule is None:
        lr_schedule = lambda count: 0.1  # noqa: E731
    hier = is_hierarchical(mesh)
    slices = int(mesh.shape[SLICE_AXIS]) if hier else 1
    axis_names = data_axis_names(mesh)
    axis_size = data_parallel_width(mesh)
    specs = zero3_state_specs(state_template, mesh, param_specs)
    tx = None
    if tx_factory is not None:
        tx = tx_factory(sumsq_reduce=zero1_sumsq_reduce(specs.params))
    else:
        from dptpu.ops.optimizers import trust_ratio_stats

        if trust_ratio_stats(state_template.opt_state) is not None:
            raise ValueError(
                "state uses a trust-ratio optimizer (LARS/LAMB) but no "
                "tx_factory was given — the sharded update would "
                "compute per-layer norms from local shards only. Pass "
                "tx_factory=partial(make_optimizer, momentum, wd, name) "
                "so the norm completer can be injected."
            )

    def gather_params(params):
        def gather(x, s):
            d = _sharded_axis(s)
            if d < 0:
                return x
            return _zero3_gather(d)(x)

        return jax.tree_util.tree_map(gather, params, specs.params)

    def reduce_grads(grads):
        # sharded leaves arrived scatter-reduced over the intra-slice
        # axis through the custom-VJP boundary; hierarchical meshes add
        # the shard-sized DCN hop, replicated remainders their explicit
        # psum — identical composition to the ZeRO-1 step.
        def red(g, s):
            if _sharded_axis(s) >= 0:
                return dcn_reduce_shard(g, SLICE_AXIS, dcn_dtype,
                                        slices=slices) if hier else g
            g = lax.psum(g, DATA_AXIS)
            return lax.psum(g, SLICE_AXIS) if hier else g

        return jax.tree_util.tree_map(red, grads, specs.params)

    overlap_plan = None
    if overlap:
        from dptpu.parallel.overlap import (
            DEFAULT_BUCKET_MB,
            OverlapPlan,
            make_zero1_bucket_reduce,
        )

        sharded_flags = [
            _sharded_axis(s) >= 0
            for s in jax.tree_util.tree_leaves(
                specs.params, is_leaf=lambda x: isinstance(x, P)
            )
        ]
        overlap_plan = OverlapPlan(
            bucket_bytes or int(DEFAULT_BUCKET_MB * 1e6),
            make_zero1_bucket_reduce(sharded_flags, hier, dcn_dtype,
                                     slices=slices),
        )
        reduce_grads = None  # the plan carries the whole reduction

    def step(state, batch):
        return train_step_body(
            state, batch, compute_dtype=compute_dtype,
            lr_schedule=lr_schedule, seed=seed, axis_size=axis_size,
            on_mesh=True, gather_params=gather_params,
            reduce_grads=reduce_grads, tx=tx, accum_steps=accum_steps,
            label_smoothing=label_smoothing, axis_names=axis_names,
            overlap_plan=overlap_plan,
        )

    batch_spec = P(squeeze_axes(axis_names))
    sharded = shard_map_nocheck(
        step,
        mesh=mesh,
        in_specs=(specs, batch_spec),
        out_specs=(specs, P()),
    )
    return jax.jit(
        sharded, donate_argnums=0, compiler_options=tpu_compiler_options()
    )


def make_zero1_train_step(mesh: Mesh, state_template, compute_dtype=jnp.float32,
                          lr_schedule=None, seed: int = 0,
                          accum_steps: int = 1, label_smoothing: float = 0.0,
                          tx_factory=None, dcn_dtype: str = "fp32",
                          overlap: bool = False,
                          bucket_bytes=None):
    """ZeRO-1 / sharded-weight-update variant of
    ``dptpu.train.step.make_train_step``.

    ``state_template`` fixes which leaves shard; it must be the SAME
    TrainState the returned step will receive (or share its
    ``apply_fn``/``tx`` objects) — those static fields are part of the
    pytree metadata that shard_map matches specs against. Returns
    ``step(state, batch) -> (state, metrics)`` with the SAME contract and
    math as the DDP step; ``state`` must be in the ``shard_zero1_state``
    layout and comes back in it.

    ``tx_factory(sumsq_reduce=...)`` rebuilds the optimizer with the
    shard-aware trust-ratio norm completer injected (same state
    structure, so the template's ``tx.init`` layout still matches); when
    None the template's own ``tx`` runs — correct for any elementwise
    chain (SGD), and for LARS/LAMB **only** via a factory.

    ``accum_steps=k`` composes with the sharding: each microbatch's
    gradient arrives reduce-scattered through the all-gather VJP, so the
    fp32 accumulator is SHARD-sized (1/N of the model — accumulation
    costs no replicated-gradient memory); params are re-gathered per
    microbatch, the price of never materializing full optimizer state.

    On a hierarchical ``{slice, data}`` mesh the composition is exactly
    the two-level engine's design (dptpu/parallel/hierarchy.py): state
    shards over the INTRA-slice axis (so the per-microbatch weight
    all-gather and its psum_scatter VJP stay on ICI — the all-gather
    moves weights, never gradients), and ``reduce_grads`` adds only the
    shard-sized cross-slice hop over DCN — ONCE per update, after the
    accumulation scan, optionally bf16-compressed (``dcn_dtype``).

    ``overlap=True`` (``DPTPU_OVERLAP=1``; dptpu/parallel/overlap.py):
    the per-leaf all-gather VJP already delivers each gradient
    reduce-scattered DURING backward — ZeRO-1's reduce-scatter is
    maximally bucketed by construction — so the plan buckets the work
    that used to run post-backward: per ``bucket_bytes`` bucket of
    (shard-local) leaves, the shard-sized DCN hop and the
    replicated-remainder psums concatenate into fused collectives
    issued in-backward right behind the VJP's reduce-scatter.
    Bit-identical to ``overlap=False`` (same collectives, same
    grouping).
    """
    from dptpu.parallel.hierarchy import (
        DCN_DTYPES,
        dcn_reduce_shard,
        is_hierarchical,
    )
    from dptpu.train.step import (
        shard_map_nocheck,
        tpu_compiler_options,
        train_step_body,
    )

    if dcn_dtype not in DCN_DTYPES:
        raise ValueError(
            f"dcn_dtype={dcn_dtype!r} must be one of "
            + "/".join(repr(d) for d in DCN_DTYPES)
        )
    if lr_schedule is None:
        lr_schedule = lambda count: 0.1  # noqa: E731
    hier = is_hierarchical(mesh)
    slices = int(mesh.shape[SLICE_AXIS]) if hier else 1
    axis_names = data_axis_names(mesh)
    # gradient normalizer spans ALL replicas (slices × dp_in_slice);
    # the state specs below shard over the intra-slice axis only
    axis_size = data_parallel_width(mesh)
    specs = zero1_state_specs(state_template, mesh)
    tx = None
    if tx_factory is not None:
        tx = tx_factory(sumsq_reduce=zero1_sumsq_reduce(specs.params))
    else:
        from dptpu.ops.optimizers import trust_ratio_stats

        if trust_ratio_stats(state_template.opt_state) is not None:
            # without the factory the template's own tx would run with
            # sumsq_reduce=None: every trust ratio computed from the
            # 1/N shard-local norms, never completed across the axis —
            # silently-wrong training that worsens with DP width
            raise ValueError(
                "state uses a trust-ratio optimizer (LARS/LAMB) but no "
                "tx_factory was given — the sharded update would "
                "compute per-layer norms from local shards only. Pass "
                "tx_factory=partial(make_optimizer, momentum, wd, name) "
                "so the norm completer can be injected."
            )

    def gather_params(params):
        # all-gather (along whichever dim _leaf_spec chose) -> full
        # params; the VJP of the tiled all-gather is psum_scatter, so
        # the gradient w.r.t. the local shards arrives already
        # reduce-scattered: each device gets its shard of the global
        # gradient sum with no separate all-reduce.
        def gather(x, s):
            d = _sharded_axis(s)
            if d < 0:
                return x
            return lax.all_gather(x, DATA_AXIS, axis=d, tiled=True)

        return jax.tree_util.tree_map(gather, params, specs.params)

    def reduce_grads(grads):
        # the all-gather VJP already reduce-scattered the sharded leaves
        # over the INTRA-slice axis; on a hierarchical mesh each shard
        # then takes the shard-sized cross-slice (DCN) hop — this is the
        # "reduce-scatter output IS the 1/N update shard" composition,
        # and it runs once per UPDATE (reduce_grads sits after the
        # accumulation scan), never per microbatch. The replicated
        # remainder (no divisible dim) needs its explicit cross-replica
        # sum — under check_rep=False nothing is implicit.
        def red(g, s):
            if _sharded_axis(s) >= 0:
                return dcn_reduce_shard(g, SLICE_AXIS, dcn_dtype,
                                        slices=slices) if hier else g
            g = lax.psum(g, DATA_AXIS)
            return lax.psum(g, SLICE_AXIS) if hier else g

        return jax.tree_util.tree_map(red, grads, specs.params)

    overlap_plan = None
    if overlap:
        from dptpu.parallel.overlap import (
            DEFAULT_BUCKET_MB,
            OverlapPlan,
            make_zero1_bucket_reduce,
        )

        sharded_flags = [
            _sharded_axis(s) >= 0
            for s in jax.tree_util.tree_leaves(
                specs.params, is_leaf=lambda x: isinstance(x, P)
            )
        ]
        overlap_plan = OverlapPlan(
            bucket_bytes or int(DEFAULT_BUCKET_MB * 1e6),
            make_zero1_bucket_reduce(sharded_flags, hier, dcn_dtype,
                                     slices=slices),
        )
        reduce_grads = None  # the plan carries the whole reduction

    def step(state, batch):
        return train_step_body(
            state, batch, compute_dtype=compute_dtype,
            lr_schedule=lr_schedule, seed=seed, axis_size=axis_size,
            on_mesh=True, gather_params=gather_params,
            reduce_grads=reduce_grads, tx=tx, accum_steps=accum_steps,
            label_smoothing=label_smoothing, axis_names=axis_names,
            overlap_plan=overlap_plan,
        )

    batch_spec = P(squeeze_axes(axis_names))
    sharded = shard_map_nocheck(
        step,
        mesh=mesh,
        in_specs=(specs, batch_spec),
        out_specs=(specs, P()),
    )
    return jax.jit(
        sharded, donate_argnums=0, compiler_options=tpu_compiler_options()
    )
