"""Multi-host rendezvous: the ``init_process_group`` analog.

The reference rendezvouses all ranks through NCCL/Gloo with either a TCP
master URL (``--dist-url tcp://ip:port``, imagenet_ddp.py:61-63,104-105) or
``env://`` (MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK, nd_imagenet.py:98-99;
imagenet_ddp_apex.py:113-125). On TPU the same contract maps onto
``jax.distributed.initialize(coordinator_address, num_processes,
process_id)`` — one *host* process per entry rather than one per chip,
because chips on a host are driven by a single SPMD program (SURVEY.md §1 L1).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple
from urllib.parse import urlparse

import jax

from dptpu.config import Config


def _resolve_rendezvous(cfg: Config) -> Tuple[Optional[str], int, int]:
    """Map the reference's (dist_url, world_size, rank) semantics onto
    (coordinator_address, num_processes, process_id)."""
    world_size, rank = cfg.world_size, cfg.rank
    if cfg.dist_url == "env://":
        # env:// overlay (nd_imagenet.py:98-99,124-125; apex :113-115)
        if world_size == -1:
            world_size = int(os.environ.get("WORLD_SIZE", "-1"))
        if rank == -1:
            rank = int(os.environ.get("RANK", "-1"))
        addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
        port = os.environ.get("MASTER_PORT", "23456")
        coordinator = f"{addr}:{port}"
    else:
        u = urlparse(cfg.dist_url)
        coordinator = f"{u.hostname}:{u.port or 23456}"
    return coordinator, world_size, rank


# What this process already rendezvoused as: (coordinator, world, rank).
# jax.distributed.initialize crashes if called twice, but a second fit()
# in one process (sweeps, notebooks, tests) is a legitimate pattern — the
# guard makes a matching re-init a no-op and a conflicting one an error.
_initialized: Optional[Tuple[str, int, int]] = None


def initialize_distributed(cfg: Config) -> bool:
    """Join the multi-host job if the config asks for one.

    Returns True when running multi-process. Safe to call in single-host
    mode (no-op, like the reference's conditional init, nd_imagenet.py:123)
    and safe to call AGAIN with the same rendezvous (no-op — a second
    ``fit()`` in one process must not crash); a different rendezvous in an
    already-joined process raises.
    The ``--dist-backend`` flag is accepted but ignored: collectives are
    always XLA's, compiled onto ICI within a slice and DCN across slices.
    ``DPTPU_RENDEZVOUS_TIMEOUT`` (seconds, default jax's 300) bounds how
    long this process waits for the others; a timeout raises an
    actionable error naming the coordinator instead of a bare backend
    trace (the reference blocks forever on a missing rank,
    imagenet_ddp.py:104 — a bounded, named failure is strictly kinder).
    """
    global _initialized
    coordinator, world_size, rank = _resolve_rendezvous(cfg)
    if world_size <= 1:
        return False
    if rank < 0:
        raise ValueError(
            "distributed run needs a rank (--rank or RANK env), got -1"
        )
    if _initialized is not None:
        if _initialized == (coordinator, world_size, rank):
            return True  # same job — idempotent re-entry
        raise RuntimeError(
            f"this process already joined a distributed job as "
            f"{_initialized} and cannot re-join as "
            f"{(coordinator, world_size, rank)} — jax.distributed "
            f"supports one rendezvous per process; start a new process "
            f"for a different job"
        )
    try:  # private API, best-effort: someone may have initialized jax
        from jax._src.distributed import global_state as _gs

        externally_initialized = _gs.client is not None
    except Exception:
        externally_initialized = False
    if externally_initialized:
        # jax.distributed is already up (driver/harness-initialized);
        # re-calling initialize would crash. Adopt the session ONLY if
        # the config describes the same world — a silent mismatch would
        # mis-shard every downstream mesh/batch computation.
        if (jax.process_count() != world_size
                or jax.process_index() != rank):
            raise RuntimeError(
                f"jax.distributed is already initialized as process "
                f"{jax.process_index()}/{jax.process_count()}, but the "
                f"config asks for rank {rank}/{world_size} — fix the "
                f"--world-size/--rank flags (or WORLD_SIZE/RANK env) to "
                f"match the live session, or start a new process"
            )
        _initialized = (coordinator, world_size, rank)
        return True
    from dptpu.envknob import env_int

    timeout_s = env_int("DPTPU_RENDEZVOUS_TIMEOUT")
    kwargs = (
        {"initialization_timeout": timeout_s}
        if timeout_s is not None else {}
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=world_size,
            process_id=rank,
            **kwargs,
        )
    except Exception as e:
        raise RuntimeError(
            f"rendezvous failed: rank {rank}/{world_size} could not join "
            f"the coordinator at {coordinator} "
            f"({type(e).__name__}: {e}). Check that every rank is "
            f"launched with the same --dist-url/--world-size, that rank 0 "
            f"is reachable on that address/port, and that no stale "
            f"process holds the port (process_cleanup.sh). "
            f"DPTPU_RENDEZVOUS_TIMEOUT=<seconds> bounds the wait."
        ) from e
    _initialized = (coordinator, world_size, rank)
    return True
