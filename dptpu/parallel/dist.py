"""Multi-host rendezvous: the ``init_process_group`` analog.

The reference rendezvouses all ranks through NCCL/Gloo with either a TCP
master URL (``--dist-url tcp://ip:port``, imagenet_ddp.py:61-63,104-105) or
``env://`` (MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK, nd_imagenet.py:98-99;
imagenet_ddp_apex.py:113-125). On TPU the same contract maps onto
``jax.distributed.initialize(coordinator_address, num_processes,
process_id)`` — one *host* process per entry rather than one per chip,
because chips on a host are driven by a single SPMD program (SURVEY.md §1 L1).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple
from urllib.parse import urlparse

import jax

from dptpu.config import Config


def _resolve_rendezvous(cfg: Config) -> Tuple[Optional[str], int, int]:
    """Map the reference's (dist_url, world_size, rank) semantics onto
    (coordinator_address, num_processes, process_id)."""
    world_size, rank = cfg.world_size, cfg.rank
    if cfg.dist_url == "env://":
        # env:// overlay (nd_imagenet.py:98-99,124-125; apex :113-115)
        if world_size == -1:
            world_size = int(os.environ.get("WORLD_SIZE", "-1"))
        if rank == -1:
            rank = int(os.environ.get("RANK", "-1"))
        addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
        port = os.environ.get("MASTER_PORT", "23456")
        coordinator = f"{addr}:{port}"
    else:
        u = urlparse(cfg.dist_url)
        coordinator = f"{u.hostname}:{u.port or 23456}"
    return coordinator, world_size, rank


def initialize_distributed(cfg: Config) -> bool:
    """Join the multi-host job if the config asks for one.

    Returns True when running multi-process. Safe to call in single-host
    mode (no-op, like the reference's conditional init, nd_imagenet.py:123).
    The ``--dist-backend`` flag is accepted but ignored: collectives are
    always XLA's, compiled onto ICI within a slice and DCN across slices.
    """
    coordinator, world_size, rank = _resolve_rendezvous(cfg)
    if world_size <= 1:
        return False
    if rank < 0:
        raise ValueError(
            "distributed run needs a rank (--rank or RANK env), got -1"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=world_size,
        process_id=rank,
    )
    return True
