"""Two-level (ICI × DCN) hierarchical gradient reduction.

On a multi-slice TPU pod the DCN hop between slices is an order of
magnitude slower than the ICI links inside a slice, but a flat
data-parallel all-reduce treats every link as equal: each chip moves
``2·(N-1)/N·P`` gradient bytes through a ring that crosses DCN at full
gradient width. Every ImageNet-in-minutes system reduces
hierarchically instead (Mikami et al., arXiv:1811.05233 — the 2D-Torus
reduce-scatter-first scheme; Yamazaki et al., arXiv:1903.12650 adds
reduced-precision exchange on the slow hop):

1. **reduce-scatter inside the slice (ICI)** — each of the ``I`` chips
   in a slice ends up with the slice-local sum of one ``1/I`` shard;
2. **all-reduce across slices (DCN)** — shard-sized: per-chip DCN
   traffic drops to ``~1/I`` of the flat all-reduce;
3. **all-gather inside the slice (ICI)** — every chip recovers the
   full globally-reduced gradient.

ICI bytes stay at the flat all-reduce's volume (the reduce-scatter +
all-gather pair IS a decomposed all-reduce); only the slow hop shrinks.
The engine is expressed with EXPLICIT collectives in the shard_map step
bodies (``check_rep=False``, the repo-wide discipline), over the
``{slice: S, data: N/S}`` mesh ``make_hierarchical_mesh`` builds.

**bf16 DCN compression** (``DPTPU_DCN_DTYPE=bf16``, opt-in; default
fp32): the shard is rounded to bf16 ONCE, all-gathered across slices,
and the ``S`` partials are summed locally in fp32 — bf16 on the wire,
fp32 accumulation (a bf16 ``psum`` would accumulate in bf16 on the
wire's reduction tree, compounding rounding with S). Gather-based
compression halves DCN bytes at S=2 and breaks even with the fp32
all-reduce at S=4 (``(S-1)·P/(2I)`` vs ``2·(S-1)/S·P/I``) — the
realistic multi-slice regime for this engine is 2-4 slices, and
COMMBENCH records the crossover. Only scatterable (shard-sized) leaves
compress; the replicated remainder (tiny biases, a rounding error of
the bytes) always reduces in fp32.

**Numerics / parity contract** (locked by tests/test_hierarchy.py and
the COMMBENCH parity gates): each hop is bit-identical to the flat
all-reduce in isolation — a pure-ICI mesh (1 slice) and a pure-DCN mesh
(chips/slice = 1) both produce params Δ=0 against the flat DDP step
over ≥5 fp32 steps, because XLA's all-reduce, reduce-scatter and the
slice-axis psum all sum linearly from rank 0. The COMPOSED two-level
reduction regroups the sum as (slice-0 partial) + (slice-1 partial) + …
where the flat all-reduce folds ranks in one linear chain, so composed
parity is exact-to-grouping: ≤1 ulp per addition, measured and bounded
(never hidden) in COMMBENCH. bf16-DCN drift is bounded separately.

ZeRO-1 composes for free (``dptpu/parallel/zero.py``): params/optimizer
state shard over the INTRA-slice axis, so the per-microbatch weight
all-gather stays on ICI, the all-gather VJP's psum_scatter IS hop 1,
and hop 2 runs once per UPDATE on the shard-sized gradient — the
reduce-scatter output is exactly the 1/I update shard, and the
all-gather moves weights, never gradients.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from dptpu.parallel.mesh import (
    DATA_AXIS,
    SLICE_AXIS,
    largest_divisible_dim,
)

DCN_DTYPES = ("fp32", "bf16", "bf16_a2a")


def hierarchy_knobs(cfg=None) -> tuple:
    """``(slices, dcn_dtype)`` under the locked fail-fast knob contract.

    * ``DPTPU_SLICES`` / ``--slices`` — number of DCN-connected slices
      the data axis factors into; the env twin OVERRIDES the CLI/config
      field (the repo-wide precedence). Must be >= 1 (1 = the flat
      single-level mesh) and must divide the world size (checked where
      the device count is known: ``make_hierarchical_mesh``).
    * ``DPTPU_DCN_DTYPE`` — ``fp32`` (default: the DCN all-reduce runs
      at full precision), ``bf16`` (gather-based compression of the
      cross-slice hop, fp32 accumulation; see module docstring), or
      ``bf16_a2a`` (all-to-all + local-accumulate: per-chip DCN bytes
      ~half the fp32 all-reduce at ANY slice count — gather-bf16's
      ``(S-1)·m`` receive volume loses to the fp32 all-reduce past S=4,
      the documented ceiling this mode breaks — at the cost of a second
      bf16 rounding on the reduced sum; see ``dcn_reduce_shard``).
    """
    from dptpu.envknob import env_choice, env_int

    slices = env_int("DPTPU_SLICES", None)
    if slices is None:
        slices = getattr(cfg, "slices", 1) if cfg is not None else 1
    if slices < 1:
        raise ValueError(
            f"DPTPU_SLICES/--slices {slices} must be >= 1 (1 keeps the "
            f"flat single-level data mesh)"
        )
    dcn_dtype = env_choice("DPTPU_DCN_DTYPE", DCN_DTYPES, default="fp32")
    return int(slices), dcn_dtype


def elastic_slices_check(world_size: int, slices: int):
    """Elastic-resume × ``--slices`` composition (ROADMAP item 3,
    elastic satellite): a SHRUNK world must still factor into the
    configured slice count, or the hierarchical mesh cannot build. The
    generic ``make_hierarchical_mesh`` divisibility error names only
    the mismatch; an elastic restart deserves the two actionable
    fallbacks, so this check runs FIRST on the elastic path and its
    message is locked by tests (tests/test_elastic.py).
    """
    if slices > 1 and world_size % slices != 0:
        divisors = [s for s in range(2, world_size + 1)
                    if world_size % s == 0]
        example = f"DPTPU_SLICES={divisors[0]}" if divisors \
            else "no slice count > 1 divides it"
        raise ValueError(
            f"elastic resume: the shrunk world of {world_size} devices "
            f"does not divide into DPTPU_SLICES/--slices={slices} "
            f"slices, so the hierarchical mesh cannot factor. Fix one "
            f"knob: drop slices (unset DPTPU_SLICES to run the flat "
            f"single-level data mesh) or pick a slice count that "
            f"divides {world_size} (e.g. {example})."
        )


def is_hierarchical(mesh: Optional[Mesh]) -> bool:
    return mesh is not None and SLICE_AXIS in mesh.axis_names


def _scatter_dim(shape, n: int) -> int:
    """The scatter dim for one gradient leaf: the SHARED
    ``mesh.largest_divisible_dim`` rule ZeRO-1 shards state by
    (``zero._leaf_spec`` resolves through the same function), so the
    gradient shard the reduce-scatter produces here is exactly the
    update shard ZeRO-1 owns — by construction, not by parallel
    maintenance. -1 when no dim divides (the leaf reduces unscattered).
    """
    return largest_divisible_dim(shape, n)


def dcn_reduce_shard(x, slices_axis: str = SLICE_AXIS,
                     dcn_dtype: str = "fp32", slices: Optional[int] = None):
    """The cross-slice (DCN) hop for one already-scattered shard.

    fp32: a plain shard-sized ``psum`` over the slice axis. bf16: round
    the shard to bf16 once, all-gather the S partials (bf16 on the
    wire — gather moves data without arithmetic, so no backend promotes
    it), and sum them locally in fp32, slice-major — fp32 accumulation
    with a deterministic order. Non-float32 shards (none in practice:
    grads follow the f32 params) pass through the fp32 path.

    bf16_a2a (arXiv:1903.12650's reduced-precision exchange married to
    a scatter-reduce): the shard flattens, pads to a multiple of S and
    splits into S chunks; one bf16 **all-to-all** gives each slice the
    S partials of ITS chunk, which it sums locally in fp32 (slice-major,
    deterministic), then a chunk-sized bf16 all-gather redistributes the
    reduced chunks. Per-chip DCN receive bytes are ``2·(S-1)/S·m`` bf16
    ≈ HALF the fp32 all-reduce's ``2·(S-1)/S·m`` fp32 at ANY S — unlike
    gather-bf16, whose ``(S-1)·m`` receive volume crosses the fp32
    all-reduce at S=4 (the ceiling this mode breaks). The price is a
    SECOND rounding: the fp32-accumulated chunk sum rounds to bf16 for
    the gather hop, where gather-bf16 rounds only the inputs. Needs the
    concrete slice count (``slices`` — a reshape extent; callers read it
    off the mesh) because axis sizes are not Python ints under tracing.
    """
    if dcn_dtype == "bf16" and x.dtype == jnp.float32:
        parts = lax.all_gather(
            x.astype(jnp.bfloat16), slices_axis, axis=0, tiled=False
        )
        return jnp.sum(parts.astype(jnp.float32), axis=0)
    if dcn_dtype == "bf16_a2a" and x.dtype == jnp.float32:
        if not slices or slices < 1:
            raise ValueError(
                "dcn_dtype='bf16_a2a' needs the concrete slice count: "
                "pass slices=int(mesh.shape['slice']) (the chunk split "
                "is a reshape, and axis sizes are traced values inside "
                "shard_map)"
            )
        if slices == 1:
            return x  # single slice: the DCN hop is the identity
        shape = x.shape
        flat = x.reshape(-1)
        m = flat.shape[0]
        pad = (-m) % slices
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)]
            )
        chunks = flat.reshape(slices, -1).astype(jnp.bfloat16)
        # chunk j of every slice travels to slice j: row k of the result
        # is slice k's partial of MY chunk
        parts = lax.all_to_all(
            chunks, slices_axis, split_axis=0, concat_axis=0, tiled=True
        ).reshape(slices, -1)
        mine = jnp.sum(parts.astype(jnp.float32), axis=0)
        # second rounding: the reduced chunk goes back over DCN in bf16
        full = lax.all_gather(
            mine.astype(jnp.bfloat16), slices_axis, axis=0, tiled=False
        ).astype(jnp.float32).reshape(-1)
        if pad:
            full = full[:m]
        return full.reshape(shape)
    return lax.psum(x, slices_axis)


def make_hierarchical_reduce(mesh: Mesh, dcn_dtype: str = "fp32"):
    """Build the DDP gradient-reduction hook for a hierarchical mesh:
    per leaf, reduce-scatter(ICI) → shard-sized all-reduce(DCN) →
    all-gather(ICI). Leaves with no dim the intra-slice width divides
    (tiny biases) psum over ICI and take the fp32 DCN hop whole —
    correct, and a rounding error of the bytes.

    Used by ``make_train_step``; ZeRO-1 does NOT use this — its
    all-gather VJP already delivers the intra-slice reduce-scatter, so
    it applies only ``dcn_reduce_shard`` (see make_zero1_train_step).
    """
    if dcn_dtype not in DCN_DTYPES:
        raise ValueError(
            f"DPTPU_DCN_DTYPE={dcn_dtype!r} must be one of "
            + "/".join(repr(d) for d in DCN_DTYPES)
        )
    n_in = int(mesh.shape[DATA_AXIS])
    n_slices = int(mesh.shape[SLICE_AXIS])

    def reduce_grads(grads):
        def red(g):
            d = _scatter_dim(getattr(g, "shape", ()), n_in)
            if d < 0:
                # unscatterable remainder: ICI psum + fp32 DCN psum
                return lax.psum(lax.psum(g, DATA_AXIS), SLICE_AXIS)
            sh = lax.psum_scatter(
                g, DATA_AXIS, scatter_dimension=d, tiled=True
            )
            sh = dcn_reduce_shard(sh, SLICE_AXIS, dcn_dtype,
                                  slices=n_slices)
            return lax.all_gather(sh, DATA_AXIS, axis=d, tiled=True)

        return jax.tree_util.tree_map(red, grads)

    return reduce_grads


def flat_replica_index(axis_names) -> jax.Array:
    """This shard's GLOBAL data-parallel replica id, flattened over the
    (possibly hierarchical) data axes in major-to-minor order — on a
    ``{slice, data}`` mesh, ``slice_idx · I + idx_in_slice``, which
    equals the flat mesh's ``axis_index("data")`` for the same chip (the
    slice-major batch layout), so dropout streams are geometry-stable.
    Uses the portable ``psum(1)`` axis-size spelling (``lax.axis_size``
    is missing in this container's jax — ROADMAP known constraint)."""
    idx = None
    for name in axis_names:
        i = lax.axis_index(name)
        idx = i if idx is None else idx * lax.psum(1, name) + i
    return idx
