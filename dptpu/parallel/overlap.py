"""Bucketed, backward-overlapped gradient communication.

Every dptpu step used to emit its gradient reduction as one per-leaf
sweep AFTER backward completed: ``lax.psum`` over the whole gradient
tree (or the ZeRO-1 / hierarchical per-leaf equivalents), which lowers
to one small collective per parameter leaf — 60+ latency-bound
instructions for a ResNet, none of which the compiler is obliged to
start before the last gradient exists.  The ImageNet-in-minutes systems
pipeline instead (arXiv:1711.00705's pipelined all-reduce; the c10d
bucketing engine the reference relies on, SURVEY.md §2b): gradients are
packed into a few size-bounded BUCKETS and each bucket's reduction is
issued the moment its gradients exist, so the network works while the
remaining backward computes.

The XLA-native translation (``DPTPU_OVERLAP=1``):

* **partition** — the parameter tree flattens and packs into buckets of
  at most ``DPTPU_BUCKET_MB`` (default 25 MB) in REVERSE flatten order:
  backward produces the LAST layers' gradients first, so the first
  bucket closed is the first one ready — the c10d ordering.  Tiny
  leaves (BN scales, biases) coalesce into shared buckets; a leaf
  larger than the bound gets its own bucket; buckets never mix dtypes
  (the flat concatenation below requires one element type).
* **in-backward issue** — each bucket's leaves pass through a
  per-bucket ``jax.custom_vjp`` identity whose backward rule performs
  the bucket's reduction on the cotangents: the reduction is therefore
  PART OF the backward graph, anchored to exactly the sub-graph that
  produces that bucket's gradients.  Buckets are independent (no
  ordering edges between them), so the compiled schedule is free to
  interleave each collective with the remaining backward computation —
  which is precisely what the HLO overlap-evidence gate
  (``dptpu check`` / ``hlo_accounting.overlap_evidence``) asserts.
* **fused transport** — within a bucket the leaves are flattened and
  concatenated into ONE contiguous buffer and reduced by ONE collective
  (per hop), replacing per-leaf collectives: latency amortizes over the
  bucket (the c10d win) while total bytes are EXACTLY the per-leaf
  sum — the HLO budget gate locks total collective bytes ≡ the
  unbucketed program's within 0.1%.

**Composition** (the same three step families as the unbucketed path):

* DDP, flat mesh — one ``psum`` of the flat bucket over the data axis.
* DDP, hierarchical ``{slice, data}`` mesh — the PR-10 ladder runs per
  bucket on the flat buffer: pad to a multiple of the intra-slice
  width, reduce-scatter(ICI) → shard-sized DCN hop (fp32 psum or the
  bf16 gather+local-sum compression) → all-gather(ICI) → unpad.
* ZeRO-1 — the per-leaf weight all-gather's VJP ALREADY delivers each
  gradient reduce-scattered during backward (the finest-grained
  bucketing); the plan buckets the work that used to run post-backward:
  the shard-sized cross-slice DCN hop and the replicated-remainder
  psums, concatenated per bucket and issued in-backward right after the
  VJP's reduce-scatter produces their inputs.
* ``--accum-steps k > 1`` — gradients accumulate UNREDUCED across the
  microbatch scan (the PR-6 contract: one reduction per update, never
  per microbatch), so the bucketed reduction runs once, after the scan,
  on the final accumulated gradients — same bucket collectives, without
  the in-backward placement (a reduction inside the scan body would pay
  k× the bytes).

**Bit-identity contract** (locked in tests/test_overlap.py and the
RACEBENCH/COMMBENCH parity gates): bucketing is a REGROUPING of the
same per-element reductions — a collective sums corresponding elements
across the same replicas whether the operand is one leaf or a
concatenation of leaves, and the in-backward placement feeds it the
same cotangent values the post-backward sweep would.  So
``DPTPU_OVERLAP=1`` at any bucket count produces params Δ=0 against
the unbucketed step, and multi-bucket ≡ single-bucket at Δ=0, for DDP,
ZeRO-1 and the hierarchical mesh alike.  The intra-bucket reduction
order is FIXED by the concatenation layout (reverse flatten order), so
the contract cannot drift with partition changes.

CPU-backend honesty (PARALLELISM.md): on this container overlap
evidence is the compiled HLO schedule — per-bucket collectives
interleaved with backward fusions — not a wall-clock win; virtual CPU
devices share one memory bus, so the time saved by overlapping a
"network" that is a memcpy cannot appear here.  RACEBENCH models the
wall-clock win with measured per-bucket compute against analytic DCN
bandwidth instead (scripts/run_racebench.py).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dptpu.parallel.mesh import DATA_AXIS, SLICE_AXIS

DEFAULT_BUCKET_MB = 25.0


def overlap_knobs() -> tuple:
    """``(overlap, bucket_bytes, bucket_explicit)`` under the locked
    fail-fast contract.

    * ``DPTPU_OVERLAP`` — opt-in: bucket the gradient reduction and
      issue each bucket in-backward (default off: the unbucketed
      per-leaf reduction, today's exact code path).
    * ``DPTPU_BUCKET_MB`` — bucket size bound in MB (float, > 0;
      default 25 — the c10d ballpark).  Read and validated even when
      overlap is off, so a typo'd value never waits silently for the
      day the opt-in flips; ``bucket_explicit`` reports whether the
      value was set (fit's advisory notice) so the knob keeps ONE
      parse site.
    """
    from dptpu.envknob import env_bool, env_float

    overlap = bool(env_bool("DPTPU_OVERLAP", False))
    bucket_mb = env_float("DPTPU_BUCKET_MB", None)
    explicit = bucket_mb is not None
    if bucket_mb is None:
        bucket_mb = DEFAULT_BUCKET_MB
    if bucket_mb <= 0:
        raise ValueError(
            f"DPTPU_BUCKET_MB={bucket_mb} must be > 0 MB (the bucket "
            f"size bound; fractional values are fine, e.g. "
            f"DPTPU_BUCKET_MB=0.5)"
        )
    return overlap, int(bucket_mb * 1e6), explicit


def _leaf_bytes(leaf) -> int:
    size = int(np.prod(leaf.shape)) if getattr(leaf, "shape", ()) else 1
    return size * jnp.dtype(leaf.dtype).itemsize


def partition_buckets(tree, bucket_bytes: int) -> List[List[int]]:
    """Partition a pytree's leaves into size-bounded buckets.

    Returns a list of buckets, each a list of indices into
    ``jax.tree_util.tree_leaves(tree)``.  Walk order is REVERSE flatten
    order (flax flattens modules in definition order, so reversed ≈
    reverse layer order — the gradients backward produces first land in
    the earliest buckets); a bucket closes when adding the next leaf
    would exceed ``bucket_bytes`` (a single over-sized leaf still gets
    its own bucket) or when the dtype changes (the flat concatenation
    requires one element type).  Consecutive tiny leaves coalesce into
    one bucket; ``bucket_bytes >= total`` degenerates to ONE bucket
    holding every leaf — the single-bucket ≡ unbucketed identity case.

    Deterministic in the tree structure alone (shapes + dtypes), so the
    partition — and with it the fixed intra-bucket reduction order — is
    stable across processes, steps and resumes.
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes={bucket_bytes} must be > 0")
    leaves = jax.tree_util.tree_leaves(tree)
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i in reversed(range(len(leaves))):
        leaf = leaves[i]
        nb = _leaf_bytes(leaf)
        dt = jnp.dtype(leaf.dtype)
        if cur and (dt != cur_dtype or cur_bytes + nb > bucket_bytes):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
        cur_dtype = dt
    if cur:
        buckets.append(cur)
    return buckets


def bucket_sizes_bytes(tree, buckets: Sequence[Sequence[int]]) -> List[int]:
    """Per-bucket payload bytes (telemetry / the RACEBENCH model)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return [sum(_leaf_bytes(leaves[i]) for i in b) for b in buckets]


def _concat_flat(arrs: Sequence[jax.Array]) -> jax.Array:
    if len(arrs) == 1:
        return arrs[0].reshape(-1)
    return jnp.concatenate([a.reshape(-1) for a in arrs])


def _split_flat(flat: jax.Array, like: Sequence[jax.Array]) -> List[jax.Array]:
    out, off = [], 0
    for ref in like:
        size = int(np.prod(ref.shape)) if ref.shape else 1
        out.append(flat[off:off + size].reshape(ref.shape))
        off += size
    return out


def hier_ladder_flat(flat: jax.Array, inner: int,
                     dcn_dtype: str = "fp32",
                     slices: Optional[int] = None) -> jax.Array:
    """The PR-10 three-hop ladder on one flat bucket buffer:
    reduce-scatter(ICI) → shard-sized DCN hop → all-gather(ICI).

    The buffer pads to a multiple of the intra-slice width ``inner`` so
    the scatter tiles evenly; the zero padding reduces to zero and is
    sliced off after the gather (the pad is < ``inner`` elements per
    bucket — noise against the 0.1% byte-parity gate).
    """
    from dptpu.parallel.hierarchy import dcn_reduce_shard

    n = flat.shape[0]
    pad = (-n) % inner
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    shard = lax.psum_scatter(flat, DATA_AXIS, scatter_dimension=0,
                             tiled=True)
    shard = dcn_reduce_shard(shard, SLICE_AXIS, dcn_dtype, slices=slices)
    full = lax.all_gather(shard, DATA_AXIS, axis=0, tiled=True)
    return full[:n] if pad else full


def make_ddp_bucket_reduce(hier: bool, dcn_dtype: str = "fp32",
                           inner: Optional[int] = None,
                           slices: Optional[int] = None) -> Callable:
    """The per-bucket reduction for the DDP step families.

    Flat mesh: one ``psum`` of the concatenated bucket over the data
    axis (the fused form of the per-leaf DDP all-reduce).  Hierarchical
    mesh: the three-hop ladder on the flat buffer — including the
    leaves the per-leaf ladder could not scatter (no divisible dim):
    inside a flat buffer everything scatters, so the unscatterable
    remainder stops crossing DCN at full width.
    """
    from dptpu.parallel.hierarchy import DCN_DTYPES

    if dcn_dtype not in DCN_DTYPES:
        raise ValueError(
            f"dcn_dtype={dcn_dtype!r} must be one of "
            + "/".join(repr(d) for d in DCN_DTYPES)
        )
    if hier and not inner:
        raise ValueError("hierarchical bucket reduce needs the "
                         "intra-slice width (inner)")

    def reduce_bucket(cts: List[jax.Array], idxs: List[int]):
        flat = _concat_flat(cts)
        if hier:
            red = hier_ladder_flat(flat, inner, dcn_dtype, slices=slices)
        else:
            red = lax.psum(flat, DATA_AXIS)
        return _split_flat(red, cts)

    return reduce_bucket


def make_zero1_bucket_reduce(sharded_flags: Sequence[bool], hier: bool,
                             dcn_dtype: str = "fp32",
                             slices: Optional[int] = None) -> Callable:
    """The per-bucket reduction for the ZeRO-1 (and ZeRO-3 — the engine
    is layout-agnostic, it only needs the sharded flags) step.

    The cotangents arriving here are what the weight all-gather's VJP
    produced: sharded leaves are ALREADY reduce-scattered over the
    intra-slice axis, replicated leaves (no divisible dim) carry raw
    local gradients.  Per bucket: the sharded shards concatenate and
    take the shard-sized cross-slice DCN hop (hierarchical mesh only —
    on a flat mesh they are complete and pass through untouched), and
    the replicated remainder concatenates into one explicit psum
    (sequential data-then-slice hops, matching the unbucketed step's
    grouping exactly — the Δ=0 contract).
    """

    def reduce_bucket(cts: List[jax.Array], idxs: List[int]):
        from dptpu.parallel.hierarchy import dcn_reduce_shard

        out = list(cts)
        shard_pos = [k for k, i in enumerate(idxs) if sharded_flags[i]]
        repl_pos = [k for k, i in enumerate(idxs) if not sharded_flags[i]]
        if hier and shard_pos:
            flat = _concat_flat([cts[k] for k in shard_pos])
            red = dcn_reduce_shard(flat, SLICE_AXIS, dcn_dtype,
                                   slices=slices)
            for k, r in zip(shard_pos,
                            _split_flat(red, [cts[k] for k in shard_pos])):
                out[k] = r
        if repl_pos:
            flat = _concat_flat([cts[k] for k in repl_pos])
            red = lax.psum(flat, DATA_AXIS)
            if hier:
                red = lax.psum(red, SLICE_AXIS)
            for k, r in zip(repl_pos,
                            _split_flat(red, [cts[k] for k in repl_pos])):
                out[k] = r
        return out

    return reduce_bucket


class OverlapPlan:
    """One step's bucketed-reduction plan: a bucket-size bound plus the
    per-bucket reduction, applied either IN-BACKWARD (``wrap`` — the
    ``accum_steps == 1`` path) or post-accumulation (``reduce``).  Both
    paths run the identical collectives on the identical values, so
    they are bit-identical to each other and to the unbucketed step.
    """

    def __init__(self, bucket_bytes: int, reduce_bucket: Callable):
        if bucket_bytes <= 0:
            raise ValueError(
                f"bucket_bytes={bucket_bytes} must be > 0 (DPTPU_BUCKET_MB)"
            )
        self.bucket_bytes = int(bucket_bytes)
        self.reduce_bucket = reduce_bucket

    def _buckets(self, tree) -> List[List[int]]:
        return partition_buckets(tree, self.bucket_bytes)

    def wrap(self, params):
        """Thread each bucket's leaves through a custom-VJP identity
        whose backward rule IS the bucket's reduction: autodiff anchors
        the collective to exactly the sub-graph producing that bucket's
        cotangents, so it is issued the moment those gradients exist —
        with no ordering edges to the other buckets (independent
        collectives, free to overlap the remaining backward)."""
        leaves, treedef = jax.tree_util.tree_flatten(params)
        new_leaves = list(leaves)
        for bucket in self._buckets(params):
            ident = _backward_reduce_identity(self.reduce_bucket,
                                              tuple(bucket))
            outs = ident(*[leaves[i] for i in bucket])
            for i, o in zip(bucket, outs):
                new_leaves[i] = o
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    def reduce(self, grads):
        """Post-hoc bucketed reduction (the gradient-accumulation path:
        ONE reduction per update, after the microbatch scan)."""
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        new_leaves = list(leaves)
        for bucket in self._buckets(grads):
            outs = self.reduce_bucket([leaves[i] for i in bucket],
                                      list(bucket))
            for i, o in zip(bucket, outs):
                new_leaves[i] = o
        return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _backward_reduce_identity(reduce_bucket: Callable, idxs: tuple):
    """A fresh custom-VJP identity for one bucket: forward passes the
    leaves through unchanged; backward applies the bucket reduction to
    the cotangents."""

    @jax.custom_vjp
    def ident(*leaves):
        return leaves

    def fwd(*leaves):
        return leaves, None

    def bwd(_, cts):
        return tuple(reduce_bucket(list(cts), list(idxs)))

    ident.defvjp(fwd, bwd)
    return ident
