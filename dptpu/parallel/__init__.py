"""Parallelism layer: device mesh, distributed init, SPMD sharding helpers.

The TPU-native replacement for the reference's L0-L2 stack (SURVEY.md §1):
NCCL/Gloo process groups + mp.spawn + DistributedDataParallel become one
process per host, a global ``jax.sharding.Mesh``, and ``shard_map``-compiled
collectives over ICI/DCN.
"""

from dptpu.parallel.dist import initialize_distributed
from dptpu.parallel.hierarchy import (
    dcn_reduce_shard,
    hierarchy_knobs,
    is_hierarchical,
    make_hierarchical_reduce,
)
from dptpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SLICE_AXIS,
    data_axis_names,
    data_parallel_width,
    data_sharding,
    make_hierarchical_mesh,
    make_mesh,
    replicated_sharding,
    shard_host_batch,
    squeeze_axes,
)
from dptpu.parallel.gspmd import (
    gspmd_specs_for_arch,
    make_gspmd_train_step,
    shard_gspmd_state,
    swin_tp_specs,
    vit_tp_specs,
)
from dptpu.parallel.rules import (
    AUTO_FSDP,
    match_partition_rules,
    rules_fingerprint,
)
from dptpu.parallel.zero import (
    gather_state,
    make_zero1_train_step,
    make_zero3_train_step,
    shard_zero1_state,
    shard_zero3_state,
    state_shard_bytes,
    zero1_sharded_fraction,
    zero1_state_specs,
    zero1_sumsq_reduce,
    zero1_update_shard_bytes,
    zero3_param_specs,
    zero3_state_specs,
)

__all__ = [
    "AUTO_FSDP",
    "DATA_AXIS",
    "MODEL_AXIS",
    "SLICE_AXIS",
    "data_axis_names",
    "data_parallel_width",
    "data_sharding",
    "dcn_reduce_shard",
    "gather_state",
    "gspmd_specs_for_arch",
    "hierarchy_knobs",
    "initialize_distributed",
    "is_hierarchical",
    "make_gspmd_train_step",
    "make_hierarchical_mesh",
    "make_hierarchical_reduce",
    "make_mesh",
    "make_zero1_train_step",
    "make_zero3_train_step",
    "match_partition_rules",
    "replicated_sharding",
    "rules_fingerprint",
    "shard_gspmd_state",
    "swin_tp_specs",
    "shard_host_batch",
    "shard_zero1_state",
    "shard_zero3_state",
    "squeeze_axes",
    "state_shard_bytes",
    "vit_tp_specs",
    "zero1_sharded_fraction",
    "zero1_state_specs",
    "zero1_sumsq_reduce",
    "zero1_update_shard_bytes",
    "zero3_param_specs",
    "zero3_state_specs",
]
