"""Per-chip collective-byte accounting parsed from HLO text.

The single source of truth for every collective-traffic claim in the
repo's benches and regression locks: SCALEBENCH's flat DDP/ZeRO-1
accounting (scripts/run_scalebench.py), COMMBENCH's hierarchical
per-link split (scripts/run_commbench.py), and the HLO-level tests in
tests/test_hierarchy.py all call these parsers — a second copy of the
byte math would let a bench and its regression lock silently diverge.

Two views:

* :func:`collective_bytes_per_chip` — the original SCALEBENCH r06
  accounting, preserved verbatim: per-op-kind bytes one chip SENDS on a
  ring, with the ring width taken as the GLOBAL device count ``n``.
* :func:`collective_bytes_by_link` — the hierarchical view: every
  instruction's ``replica_groups`` decide whether it runs inside one
  slice (ICI) or crosses slices (DCN), and the ring width is the
  GROUP size (identical to ``n`` for flat programs, where one group
  spans the world — so the two views agree on every r06 program).

Ring-send formulas (result shapes, as HLO writes them): all-gather's
result is the full gathered array — a chip sends ``(m-1)/m`` of it;
reduce-scatter's result is the scattered ``1/m`` slice — a chip sends
``(m-1)×`` the result; all-reduce's result equals its input — ``2·
(m-1)/m`` for the fused reduce-scatter + all-gather phases.

Works on OPTIMIZED HLO (``lowered.compile().as_text()`` — the compiled
program's own accounting, the default for every gate) and on
PRE-OPTIMIZATION HLO (``lowered.compiler_ir(dialect="hlo")
.as_hlo_text()``) — which COMMBENCH's bf16-DCN arm needs because this
container's CPU backend has no bf16 collective kernels: its float
normalization pass promotes every bf16 collective to f32 before the
optimized text exists, so the requested wire dtype is only observable
pre-optimization (on TPU the bf16 collective survives to the wire).
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional

_ITEMSIZE = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
             "pred": 1, "u8": 1, "s8": 1, "f64": 8, "u64": 8, "s64": 8}

_OP_RE = re.compile(
    r"=\s+(.*?)\s+"
    r"(all-gather|reduce-scatter|all-reduce)(-start|-done)?\("
)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=(\[[\d,]+\])(T\(([\d,]+)\))?"
)


def _parse_groups(line: str) -> Optional[List[List[int]]]:
    """Replica groups from one HLO instruction line, handling both the
    explicit ``{{0,1},{2,3}}`` form and the iota-tile form
    ``[G,M]<=[dims...](T(perm))?``. None when the attribute is absent
    (a groupless collective spans every participant)."""
    m = _GROUPS_RE.search(line)
    if m:
        groups = []
        for grp in re.findall(r"\{([^}]*)\}", m.group(1)):
            ids = [int(x) for x in grp.split(",") if x.strip() != ""]
            groups.append(ids)
        return groups
    m = _IOTA_RE.search(line)
    if m:
        g, per, dims_s, _t, perm_s = m.groups()
        import numpy as np

        dims = [int(d) for d in dims_s.strip("[]").split(",")]
        arr = np.arange(int(np.prod(dims))).reshape(dims)
        if perm_s:
            arr = arr.transpose([int(p) for p in perm_s.split(",")])
        return arr.reshape(int(g), int(per)).tolist()
    return None


def parse_collectives(hlo_text: str) -> List[Dict]:
    """Every gather/scatter/reduce collective instruction in ``hlo_text``
    as ``{"op", "result_bytes", "groups", "dtypes"}``.

    Result shapes may be nested tuples (combined async collectives:
    ``((f32[a], f32[b]), (f32[c], f32[d])) all-gather-start(...)``), so
    every ``dtype[dims]`` token left of the op name is collected;
    ``-done`` carries the same payload its ``-start`` already counted
    and is skipped; async ``-start`` results are (operands..., results...)
    pairs — only the result half is payload.
    """
    out = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        result_part, op, suffix = m.groups()
        if suffix == "-done":
            continue
        shapes = []
        dtypes = []
        for dt, dims in _SHAPE_RE.findall(result_part):
            size = 1
            for d in dims.split(","):
                if d:
                    size *= int(d)
            shapes.append(size * _ITEMSIZE.get(dt, 4))
            dtypes.append(dt)
        if suffix == "-start" and len(shapes) > 1:
            shapes = shapes[len(shapes) // 2:]
            dtypes = dtypes[len(dtypes) // 2:]
        out.append({
            "op": op,
            "result_bytes": sum(shapes),
            "groups": _parse_groups(line),
            "dtypes": dtypes,
        })
    return out


def _send_bytes(op: str, result_bytes: int, m: int) -> int:
    """Bytes ONE chip sends for one instruction on an m-wide ring."""
    if m <= 1:
        return 0
    if op == "all-gather":
        return int(result_bytes * (m - 1) / m)
    if op == "reduce-scatter":
        return int(result_bytes * (m - 1))
    return int(result_bytes * 2 * (m - 1) / m)  # all-reduce


def collective_bytes_per_chip(hlo_text: str, n: int) -> dict:
    """The SCALEBENCH r06 accounting: per-op-kind per-chip ring-send
    bytes with the ring width fixed at the global device count ``n``
    (every r06 program's collectives span the whole world, so this
    equals the group-aware view there — locked by
    tests/test_hierarchy.py against the analytic formulas)."""
    out = {"all-gather": 0, "reduce-scatter": 0, "all-reduce": 0,
           "instructions": 0}
    for inst in parse_collectives(hlo_text):
        out["instructions"] += 1
        out[inst["op"]] += _send_bytes(inst["op"], inst["result_bytes"], n)
    out["total"] = (out["all-gather"] + out["reduce-scatter"]
                    + out["all-reduce"])
    return out


def collective_bytes_by_link(
    hlo_text: str, slice_of: Callable[[int], int], world: int
) -> dict:
    """Per-chip send bytes split by LINK CLASS on a two-level mesh.

    ``slice_of`` maps a logical device id (the mesh-flattened position
    the HLO's replica groups reference) to its slice; ``world`` is the
    total participant count (the ring width for groupless collectives).
    An instruction whose every group stays inside one slice is ICI; any
    group spanning two slices makes the whole instruction DCN-crossing
    — for the flat baseline that is the honest statement of what a
    topology-blind all-reduce risks (its ring crosses DCN at full
    gradient width). Ring width per instruction = its group size.

    Returns per-kind dicts plus ``ici``/``dcn`` totals and instruction
    counts, e.g. ``{"dcn": {"all-reduce": B, ...,"total": B,
    "instructions": k}, "ici": {...}, "total": ...}``.
    """
    links = {
        "ici": {"all-gather": 0, "reduce-scatter": 0, "all-reduce": 0,
                "instructions": 0},
        "dcn": {"all-gather": 0, "reduce-scatter": 0, "all-reduce": 0,
                "instructions": 0},
    }
    for inst in parse_collectives(hlo_text):
        groups = inst["groups"]
        if not groups:
            groups = [list(range(world))]
        m = max(len(g) for g in groups)
        crosses = any(
            len({slice_of(d) for d in g}) > 1 for g in groups
        )
        link = links["dcn" if crosses else "ici"]
        link["instructions"] += 1
        link[inst["op"]] += _send_bytes(inst["op"], inst["result_bytes"], m)
    for link in links.values():
        link["total"] = (link["all-gather"] + link["reduce-scatter"]
                         + link["all-reduce"])
    links["total"] = links["ici"]["total"] + links["dcn"]["total"]
    return links


_INSTR_RE = re.compile(
    r"\s*(?:ROOT )?%?[\w.-]+ = (\S+?)\[([\d,]*)\][^ ]* (\w+)"
)
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\(\d+")
_F64_RE = re.compile(r"\bf64\[")


def op_census(hlo_text: str) -> dict:
    """Whole-program op-category census (the scripts/analyze_hlo.py
    analysis, folded in here so the lint's HLO gates and the copy-storm
    attribution can never diverge): per-opcode instruction counts,
    copy ops bucketed by shape, every select-and-scatter line, and the
    f64 shape-token count (the no-f64 gate — a single f64 anywhere in
    the program means an accidental double-precision promotion)."""
    import collections

    ops = collections.Counter()
    copy_shapes = collections.Counter()
    sas_lines = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m:
            dtype, shape, opname = m.groups()
            ops[opname] += 1
            if opname in ("copy", "copy-start", "copy-done"):
                copy_shapes[f"{dtype}[{shape}]"] += 1
        if "select-and-scatter" in line:
            sas_lines.append(line.strip()[:200])
    return {
        "ops": dict(ops),
        "copy_shapes": dict(copy_shapes),
        "select_and_scatter": sas_lines,
        "f64_shapes": len(_F64_RE.findall(hlo_text)),
    }


def donated_alias_count(hlo_text: str) -> int:
    """Entries in the module's ``input_output_alias`` map — the
    donation-honored gate's raw number. ``jit(..., donate_argnums=0)``
    aliases every donated state leaf to its output slot; a refactor
    that breaks donation (e.g. an output no longer shape-compatible
    with its input) silently reintroduces a full-parameter copy in the
    update, and this count is how the budget gate notices."""
    # the map lives on the HloModule header line and nests bare {} pairs
    # (empty shape indices), so scope the entry count to that line
    # rather than bracket-matching
    for line in hlo_text.splitlines():
        if "input_output_alias=" in line:
            return len(_ALIAS_ENTRY_RE.findall(
                line.split("input_output_alias=", 1)[1]
            ))
    return 0


_DOT_RE = re.compile(
    r"= (\w+)\[[\d,]*\][^ ]* (?:dot|convolution)\("
)
_S8_PARAM_RE = re.compile(r"= s8\[[\d,]*\][^ ]* parameter\(")


def dot_dtype_census(hlo_text: str) -> dict:
    """Requested dtypes of the program's matmul work — the serve-quant
    budget gate's raw numbers: per-result-dtype counts of every dot /
    convolution instruction, plus the count of ``s8`` parameters (the
    weights that actually travel int8). Run on PRE-OPTIMIZATION HLO
    (``lowered.compiler_ir(dialect="hlo").as_hlo_text()``): this
    container's CPU backend has no bf16 gemm kernels, so its float
    normalization pass rewrites every bf16 dot as convert-to-f32 +
    f32 dot before the optimized text exists — the REQUESTED compute
    dtype (what a TPU backend would execute) is only observable
    pre-optimization."""
    import collections

    dots = collections.Counter()
    for line in hlo_text.splitlines():
        m = _DOT_RE.search(line)
        if m:
            dots[m.group(1)] += 1
    return {
        "dots": dict(dots),
        "s8_params": len(_S8_PARAM_RE.findall(hlo_text)),
    }


# opcodes that represent real math in the scheduled entry computation —
# the "backward computation" the overlap evidence counts between
# reduction collectives (fusions cover almost everything post-fusion;
# convolution/dot are the unfused gemms, while the scan loop,
# custom-call the top-k kernel)
_COMPUTE_OPCODES = frozenset((
    "fusion", "convolution", "dot", "while", "reduce", "reduce-window",
    "select-and-scatter", "custom-call", "call", "scatter", "sort",
))


def _entry_opcode(line: str) -> Optional[str]:
    """Opcode of one entry-computation instruction line, handling tuple
    result types (``%t = (f32[2], f32[3]) tuple(...)``) whose parens
    defeat a naive token split."""
    eq = line.find(" = ")
    if eq < 0:
        return None
    rest = line[eq + 3:].lstrip()
    if rest.startswith("("):
        depth = 0
        for k, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    rest = rest[k + 1:].lstrip()
                    break
        else:
            return None
    else:
        parts = rest.split(None, 1)
        if len(parts) < 2:
            return None
        rest = parts[1]
    m = re.match(r"([\w-]+)\(", rest)
    return m.group(1) if m else None


def overlap_evidence(hlo_text: str, min_bytes: int = 256) -> dict:
    """Evidence that gradient reductions overlap backward compute in
    the COMPILED SCHEDULE (``compile().as_text()`` prints the scheduled
    module — instruction order IS execution order per stream).

    A "reduction" is an ``all-reduce``/``reduce-scatter`` instruction
    (sync, or the async ``-start`` half) whose result payload is at
    least ``min_bytes`` — the gradient/bucket collectives; the scalar
    metric psums and tiny BN-stat pmeans fall below the bar.  Evidence:

    * ``reductions`` — how many such instructions the entry holds
      (bucketed programs: one per bucket per hop; the monolithic fused
      form would show 1);
    * ``interleaved_gaps`` — adjacent reduction pairs with >= 1 compute
      instruction (fusion/conv/dot/while/...) scheduled between them:
      > 0 means the collectives are NOT one contiguous post-backward
      block;
    * ``compute_between`` — total compute instructions between the
      first and last reduction (the work available to hide them);
    * ``async_pairs`` / ``async_compute_between`` — on backends that
      emit ``-start``/``-done`` pairs (XLA:TPU), how many pairs exist
      and how much compute is scheduled inside each window — the
      DIRECT overlap statement.  This CPU backend emits synchronous
      collectives, so here the schedule-interleaving numbers are the
      evidence (the honesty note in PARALLELISM.md).

    The ``dptpu check`` overlap gates assert ``reductions >= 2`` and
    ``interleaved_gaps >= 1`` for the overlap budget configs.
    """
    seq: List[dict] = []
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY"):
            in_entry = True
            continue
        if not in_entry:
            continue
        if line.startswith("}"):
            break
        if not re.match(r"\s*(?:ROOT )?%?[\w.-]+ = ", line):
            continue
        entry = {"kind": "other", "name": None, "start_ref": None}
        mc = _OP_RE.search(line)
        if mc:
            result_part, op, suffix = mc.groups()
            payload = 0
            shapes = []
            for dt, dims in _SHAPE_RE.findall(result_part):
                size = 1
                for d in dims.split(","):
                    if d:
                        size *= int(d)
                shapes.append(size * _ITEMSIZE.get(dt, 4))
            if suffix == "-start" and len(shapes) > 1:
                shapes = shapes[len(shapes) // 2:]
            payload = sum(shapes)
            nm = re.match(r"\s*(?:ROOT )?%?([\w.-]+) = ", line)
            entry["name"] = nm.group(1) if nm else None
            if op in ("all-reduce", "reduce-scatter") \
                    and payload >= min_bytes:
                if suffix == "-done":
                    entry["kind"] = "red_done"
                    # the done's operand is the start instruction: the
                    # last %name closing a paren on the line (the
                    # result type's nested tuple parens carry no names)
                    ref = re.findall(r"%([\w.-]+)\)", line)
                    entry["start_ref"] = ref[-1] if ref else None
                else:
                    entry["kind"] = (
                        "red_start" if suffix == "-start" else "red"
                    )
            elif suffix == "-done":
                entry["kind"] = "coll"
            else:
                entry["kind"] = "coll"
        else:
            op = _entry_opcode(line)
            if op in _COMPUTE_OPCODES:
                entry["kind"] = "compute"
        seq.append(entry)

    red_pos = [i for i, e in enumerate(seq)
               if e["kind"] in ("red", "red_start")]
    compute_pos = [i for i, e in enumerate(seq) if e["kind"] == "compute"]
    interleaved = 0
    for a, b in zip(red_pos, red_pos[1:]):
        if any(a < c < b for c in compute_pos):
            interleaved += 1
    between = (
        sum(1 for c in compute_pos if red_pos[0] < c < red_pos[-1])
        if len(red_pos) >= 2 else 0
    )
    # async start/done windows: compute scheduled while the collective
    # is in flight (matched by the done's operand reference)
    starts = {e["name"]: i for i, e in enumerate(seq)
              if e["kind"] == "red_start"}
    async_pairs = 0
    async_between = 0
    for j, e in enumerate(seq):
        if e["kind"] == "red_done" and e["start_ref"] in starts:
            i = starts[e["start_ref"]]
            async_pairs += 1
            async_between += sum(1 for c in compute_pos if i < c < j)
    return {
        "entry_instructions": len(seq),
        "reductions": len(red_pos),
        "interleaved_gaps": interleaved,
        "compute_between": between,
        "async_pairs": async_pairs,
        "async_compute_between": async_between,
        "contiguous_tail_block": len(red_pos) >= 2 and between == 0,
        "min_bytes": min_bytes,
    }


def preopt_hlo_text(lowered) -> str:
    """Pre-optimization HLO text from a ``jax.jit(...).lower(...)``
    result — where a requested bf16 wire dtype is still visible on
    backends whose float normalization promotes bf16 collectives (this
    container's CPU; see module docstring)."""
    return lowered.compiler_ir(dialect="hlo").as_hlo_text()
