"""Unified partition-rule sharding engine: ONE ordered regex ->
``PartitionSpec`` table drives every placement in the repo.

Before this module the repo carried three parallel sharding
vocabularies: the shard_map DDP/ZeRO step bodies picked shard dims with
``zero._leaf_spec``, the GSPMD path hand-wrote a per-family spec
function (``vit_tp_specs`` et al.), and serve duplicated the TP
placement through the same hand-written functions. Each was correct in
isolation and none could see the others — adding a model family meant
三 separate spec edits. The fix is the fjformer/EasyLM idiom
(SNIPPETS.md [1]): an ordered table of ``(regex, PartitionSpec)`` rules
matched against the "/"-joined parameter path, first match wins, with a
mandatory ``.*`` fallback. Every placement consumer — ZeRO-1/ZeRO-3
state layout, the GSPMD/pjit shardings (DP, TP, hierarchical FSDP), and
serve's TP placement — resolves through ``match_partition_rules`` here;
the per-family tables themselves live next to the model registry
(``dptpu/models/registry.py FAMILY_RULES``) so a new family declares
its placement ONCE.

Grammar: rule specs name axes from the full ``{slice, data, model}``
vocabulary — ``data`` is the FSDP/ZeRO axis, ``model`` the tensor-
parallel axis (compound entries like ``("data", "model")`` shard one
dim over both). A CONSUMER then projects the table onto the axes its
mesh actually opens (``keep_axes``) and optionally clamps to
divisibility (``clamp`` — the shard_map paths need even tiles; GSPMD
tolerates uneven shards but clean tiles keep the HLO budgets exact).
One table therefore yields the pure-TP specs (project to ``model``),
the ZeRO-3/FSDP layout (project to ``data``), and the combined DPxTPx
FSDP placement (keep both) — placements cannot drift apart because
they are projections of the same declaration.

``AUTO_FSDP`` is the table-side spelling of the repo's ONE shard-dim
selection rule (``mesh.largest_divisible_dim``): "shard this leaf's
largest evenly-divisible dim over the data axis". The generic CNN table
is exactly ``((".*", AUTO_FSDP),)``, which makes the rules-driven
ZeRO-1/ZeRO-3 layout bit-identical to the historical ``_leaf_spec``
behavior for every architecture without a family table.
"""

from __future__ import annotations

import hashlib
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec as P

from dptpu.parallel.mesh import DATA_AXIS, largest_divisible_dim


class AutoFsdp:
    """Sentinel rule value: shard the leaf's largest evenly-divisible
    dim over the data axis (``mesh.largest_divisible_dim`` — the shared
    dim-selection rule ZeRO-1 has always used). Resolves to ``P()``
    when the consumer's projection drops the data axis (pure TP) or no
    dim divides (tiny biases)."""

    def __repr__(self) -> str:  # stable for rules_fingerprint
        return "AUTO_FSDP"


AUTO_FSDP = AutoFsdp()


def fsdp_auto_spec(shape, n: int) -> P:
    """``AUTO_FSDP`` resolved for one leaf: ``P(*Nones, "data")`` on
    its largest dim divisible by ``n``, ``P()`` when none divides. THE
    dim-selection rule (``mesh.largest_divisible_dim``) — ZeRO-1's
    ``_leaf_spec`` resolves through here, so the table's fallback and
    the legacy layout cannot desynchronize."""
    best = largest_divisible_dim(tuple(shape), n)
    if best < 0:
        return P()
    return P(*([None] * best), DATA_AXIS)


def _canonical(entries: Sequence) -> P:
    """Normalize a projected entry list to the repo's canonical spec
    spelling: 1-tuples collapse to the bare axis name, empty tuples to
    ``None``, and an all-``None`` spec to ``P()`` (the forms the
    locked spec-equality tests compare against — ``PartitionSpec``
    equality is strict, ``P(None) != P()``)."""
    out = []
    for e in entries:
        if isinstance(e, tuple):
            e = e[0] if len(e) == 1 else (None if not e else e)
        out.append(e)
    if all(e is None for e in out):
        return P()
    return P(*out)


def _entry_axes(entry) -> tuple:
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, tuple) else (entry,)


def project_spec(spec: P, keep_axes: Sequence[str]) -> P:
    """Keep only ``keep_axes`` names in ``spec`` (compound entries
    filter member-wise), canonicalized. The consumer-side projection:
    the table speaks the full axis vocabulary; a mesh that only opens
    ``model`` projects everything else away."""
    keep = set(keep_axes)
    out = []
    for entry in spec:
        names = tuple(a for a in _entry_axes(entry) if a in keep)
        out.append(names if names else None)
    return _canonical(out)


def clamp_spec(spec: P, shape, sizes: Dict[str, int]) -> P:
    """Drop axis names whose mesh size does not evenly divide the dim
    they shard (compound entries drop members from the END until the
    product divides), and names missing from ``sizes`` entirely. The
    shard_map consumers (ZeRO-3's explicit tiled all-gather) REQUIRE
    even tiles; an undivisible leaf degrades to replicated exactly
    like the legacy ``_leaf_spec`` remainder."""
    out = []
    for d, entry in enumerate(spec):
        if d >= len(shape):
            out.append(None)
            continue
        names = [a for a in _entry_axes(entry) if a in sizes]
        while names:
            prod = 1
            for a in names:
                prod *= int(sizes[a])
            if prod > 0 and shape[d] % prod == 0:
                break
            names.pop()
        out.append(tuple(names) if names else None)
    return _canonical(out)


def _leaf_paths(params) -> Tuple[list, list, "jax.tree_util.PyTreeDef"]:
    """(path_strings, leaves, treedef) — paths are the "/"-joined flax
    key chain the rule regexes match against."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    paths, leaves = [], []
    for path, leaf in flat:
        parts = []
        for k in path:
            parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
        paths.append("/".join(parts))
        leaves.append(leaf)
    return paths, leaves, treedef


def validate_rules(rules: Sequence[tuple]) -> None:
    """Static table checks: every pattern compiles, every value is a
    ``PartitionSpec`` or ``AUTO_FSDP``, and the LAST rule is the
    mandatory ``.*`` fallback (a table without one would leave
    unmatched leaves to a runtime surprise; the fallback makes the
    default placement an explicit, reviewable declaration)."""
    if not rules:
        raise ValueError("empty partition-rules table — at minimum the "
                         "mandatory ('.*', ...) fallback rule is required")
    for pat, val in rules:
        try:
            re.compile(pat)
        except re.error as e:
            raise ValueError(
                f"partition rule pattern {pat!r} does not compile: {e}"
            ) from e
        if not isinstance(val, (P, AutoFsdp)):
            raise ValueError(
                f"partition rule {pat!r} maps to {type(val).__name__}, "
                f"expected PartitionSpec or AUTO_FSDP"
            )
    if rules[-1][0] != ".*":
        raise ValueError(
            "partition-rules table must END with the mandatory ('.*', "
            f"...) fallback rule, got {rules[-1][0]!r} last — the "
            "default placement is part of the declaration, not an "
            "accident"
        )


def rule_match_counts(rules: Sequence[tuple], params) -> List[int]:
    """How many leaves each rule claimed under first-match-wins — the
    dead-rule census (`dptpu check` partition-rules aggregates this
    across every model of a family; a rule matching zero leaves in ALL
    of them is dead weight or a stale regex)."""
    validate_rules(rules)
    paths, _, _ = _leaf_paths(params)
    counts = [0] * len(rules)
    for path in paths:
        for i, (pat, _) in enumerate(rules):
            if re.search(pat, path):
                counts[i] += 1
                break
    return counts


def match_partition_rules(rules: Sequence[tuple], params, *,
                          keep_axes: Optional[Sequence[str]] = None,
                          clamp: Optional[Dict[str, int]] = None,
                          strict_dead: bool = False):
    """Resolve the ordered rules table over a parameter pytree.

    Returns a params-structured tree of ``PartitionSpec``. Each leaf's
    "/"-joined path is tested against the rule regexes IN ORDER
    (``re.search``) and the first match wins; the table must end with
    the mandatory ``.*`` fallback (``validate_rules``). ``keep_axes``
    projects the matched specs onto the consumer's axes (None keeps
    all); ``clamp`` maps axis name -> mesh size and drops entries that
    do not evenly divide their dim (required by the shard_map
    consumers). ``AUTO_FSDP`` values resolve through
    ``fsdp_auto_spec`` using ``clamp``'s data-axis size (and to
    ``P()`` when the projection drops the data axis).

    ``strict_dead=True`` additionally raises when any non-fallback
    rule matched zero leaves — the single-model strictness the
    matcher unit tests lock; family tables spanning model VARIANTS
    (e.g. swin v1's bias table vs v2's logit_scale) aggregate
    liveness across models via ``rule_match_counts`` instead.

    Raises on an unmatched leaf (impossible with the mandatory
    fallback, kept as defense for hand-built partial tables that
    bypass ``validate_rules``).
    """
    validate_rules(rules)
    keep = None if keep_axes is None else set(keep_axes)
    data_n = int(clamp[DATA_AXIS]) if clamp and DATA_AXIS in clamp else None
    compiled = [(re.compile(pat), val) for pat, val in rules]
    paths, leaves, treedef = _leaf_paths(params)
    counts = [0] * len(rules)
    out = []
    for path, leaf in zip(paths, leaves):
        spec = None
        for i, (rx, val) in enumerate(compiled):
            if rx.search(path):
                counts[i] += 1
                shape = tuple(getattr(leaf, "shape", ()))
                if isinstance(val, AutoFsdp):
                    use_auto = (data_n is not None
                                and (keep is None or DATA_AXIS in keep))
                    spec = fsdp_auto_spec(shape, data_n) if use_auto \
                        else P()
                else:
                    spec = val
                    if keep is not None:
                        spec = project_spec(spec, keep)
                    if clamp is not None:
                        spec = clamp_spec(spec, shape, clamp)
                break
        if spec is None:
            raise ValueError(
                f"no partition rule matched parameter {path!r} — add a "
                f"rule for it or restore the mandatory ('.*', ...) "
                f"fallback"
            )
        out.append(spec)
    if strict_dead:
        dead = [rules[i][0] for i in range(len(rules) - 1)
                if counts[i] == 0]
        if dead:
            raise ValueError(
                f"dead partition rule(s) {dead!r}: matched zero leaves "
                f"of this parameter tree — stale regex or a renamed "
                f"module; fix or remove them (the '.*' fallback is "
                f"exempt)"
            )
    return jax.tree_util.tree_unflatten(treedef, out)


def rules_fingerprint(rules: Sequence[tuple]) -> str:
    """Stable 12-hex digest of a rules table — the sharding half of the
    checkpoint geometry stamp (train/checkpoint.py): a ``--resume``
    across a CHANGED table fail-fasts naming both fingerprints instead
    of loading state whose shard layout silently moved."""
    h = hashlib.sha256()
    for pat, val in rules:
        h.update(pat.encode())
        h.update(b"\x00")
        h.update(repr(val).encode())
        h.update(b"\x01")
    return h.hexdigest()[:12]
