"""Device mesh construction and sharding helpers.

The reference's unit of parallelism is a process pinned to one GPU inside an
NCCL process group (imagenet_ddp.py:103-127). The TPU-native unit is a named
mesh axis: every chip on every host joins one global
``jax.sharding.Mesh`` and parallelism is expressed as sharding
annotations — XLA compiles the collectives onto ICI (intra-slice) and DCN
(cross-slice) links.

The default mesh is 1-D over a ``data`` axis (pure data parallelism — the
reference's only strategy, SURVEY.md §2c), but ``make_mesh`` accepts an
explicit shape so a ``model`` axis can be opened for tensor/FSDP sharding
without touching callers (the "don't hard-code a single axis name" guidance,
SURVEY.md §5).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
# Two-level data parallelism (dptpu/parallel/hierarchy.py): the OUTER
# axis of a {slice, data} mesh. Chips inside one slice talk over ICI;
# the slice axis is the DCN hop between slices, so a collective whose
# replica groups span the slice axis is the expensive one.
SLICE_AXIS = "slice"


def largest_divisible_dim(shape, n: int) -> int:
    """Largest dim of ``shape`` divisible by ``n`` (lowest index on
    ties), -1 when none divides. The ONE shard-dim selection rule:
    ZeRO-1's state layout (``zero._leaf_spec``) and the hierarchical
    reduce-scatter (``hierarchy._scatter_dim``) both resolve through
    here, which is what makes "the reduce-scatter output IS the 1/N
    update shard" hold by construction — two copies of this loop could
    silently desynchronize the gradient shard from the state shard."""
    best = -1
    for d, extent in enumerate(shape):
        if extent >= n and extent % n == 0 and (
            best < 0 or extent > shape[best]
        ):
            best = d
    return best


def _host_major_order(devices: Sequence[jax.Device]) -> list:
    """Order devices host-major (every host's chips contiguous,
    hosts by process index, chips by id) — the (DCN, ICI) factored
    layout both mesh builders depend on. Raises on unequal
    chips-per-host."""
    per_host: dict = {}
    for d in devices:
        per_host.setdefault(getattr(d, "process_index", 0), []).append(d)
    counts = {len(v) for v in per_host.values()}
    if len(counts) != 1:
        raise ValueError(
            f"hierarchical mesh needs equal chips per host, got "
            f"{ {k: len(v) for k, v in per_host.items()} }"
        )
    return [
        d
        for proc in sorted(per_host)
        for d in sorted(per_host[proc], key=lambda d: getattr(d, "id", 0))
    ]


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[dict] = None,
    hierarchical: Optional[bool] = None,
) -> Mesh:
    """Build the global device mesh.

    ``mesh_shape`` maps axis name → size, in axis order; ``-1`` means "all
    remaining devices". Default: ``{"data": -1}`` — every chip on the data
    axis, the DDP-equivalent topology.

    ``hierarchical`` (default: auto — on whenever the devices span more
    than one process) orders the device array **host-major**: every host's
    chips form a contiguous block along the outermost (first) axis, with
    any inner axes (e.g. ``model``) living entirely inside one host. This
    is the (DCN, ICI) factored layout for multi-host pods — XLA decomposes
    the data-axis all-reduce into a fast intra-host ICI reduce followed by
    a small cross-host DCN exchange, instead of ring-reducing over DCN at
    ICI granularity. The v5p-32/128 BASELINE configs (4/16 hosts) depend
    on this. Counterpart of the reference's node-major rank layout
    (``rank = node_rank * ngpus_per_node + gpu``, imagenet_ddp.py:103),
    which gives NCCL the same hierarchy.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n_procs = len({getattr(d, "process_index", 0) for d in devices})
    if hierarchical is None:
        hierarchical = n_procs > 1
    if hierarchical:
        devices = _host_major_order(devices)
    devices = np.asarray(devices)
    if mesh_shape is None:
        mesh_shape = {DATA_AXIS: -1}
    names = tuple(mesh_shape)
    sizes = list(mesh_shape.values())
    n = devices.size
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh shape {dict(zip(names, sizes))} != {n} devices")
    if hierarchical and int(np.prod(sizes[1:])) > n // n_procs:
        raise ValueError(
            f"hierarchical mesh: inner axes {dict(zip(names[1:], sizes[1:]))} "
            f"exceed one host's {n // n_procs} chips — inner-axis collectives "
            f"would cross DCN"
        )
    return Mesh(devices.reshape(sizes), names)


def make_hierarchical_mesh(
    slices: int, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the two-level ``{slice: S, data: N/S}`` data-parallel mesh
    (``--slices`` / ``DPTPU_SLICES``; dptpu/parallel/hierarchy.py).

    The slice axis is OUTER and host-major: slice ``s`` owns the
    contiguous host-major device block ``[s·N/S, (s+1)·N/S)``, so the
    inner ``data`` axis stays on intra-slice ICI links and only
    slice-axis collectives cross DCN. On a multi-host pod every slice
    must hold a whole number of hosts — a slice boundary through the
    middle of a host would put "ICI" neighbours on different DCN
    endpoints and void the two-level cost model.
    """
    if slices < 1:
        raise ValueError(f"slices={slices} must be >= 1")
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if n % slices != 0:
        raise ValueError(
            f"DPTPU_SLICES/--slices {slices} does not divide the "
            f"{n}-device world — pick a divisor so every slice gets "
            f"the same number of chips"
        )
    n_procs = len({getattr(d, "process_index", 0) for d in devices})
    if n_procs > 1:
        if n_procs % slices != 0:
            raise ValueError(
                f"DPTPU_SLICES/--slices {slices} does not divide the "
                f"{n_procs} hosts — a slice must hold whole hosts, or "
                f"its 'intra-slice' axis would cross DCN"
            )
        # host-major ordering (the make_mesh(hierarchical=True) layout),
        # then the contiguous S-way split puts each host fully inside
        # one slice
        devices = _host_major_order(devices)
    return Mesh(
        np.asarray(devices).reshape(slices, n // slices),
        (SLICE_AXIS, DATA_AXIS),
    )


def data_axis_names(mesh: Optional[Mesh]) -> tuple:
    """The mesh axes a data batch (and the gradient reduction) spans:
    ``(slice, data)`` on a hierarchical mesh, ``(data,)`` otherwise."""
    if mesh is not None and SLICE_AXIS in mesh.axis_names:
        return (SLICE_AXIS, DATA_AXIS)
    return (DATA_AXIS,)


def squeeze_axes(names: tuple):
    """Collapse a 1-tuple of axis names to the bare name. The one-name
    spelling is LOAD-BEARING on single-axis meshes: it keeps their
    compiled collectives byte-identical to the pre-hierarchy (r06)
    programs — every call site that feeds axis names to a collective or
    a PartitionSpec must route through this one helper rather than
    hand-rolling the conditional."""
    return names[0] if len(names) == 1 else names


def data_parallel_width(mesh: Optional[Mesh]) -> int:
    """Total data-parallel replicas: the product of the data axes'
    sizes (``slices × dp_in_slice`` on a hierarchical mesh)."""
    if mesh is None:
        return 1
    w = 1
    for name in data_axis_names(mesh):
        w *= int(mesh.shape[name])
    return w


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a batch: leading axis split over the data axis (or
    jointly over ``(slice, data)`` on a hierarchical mesh — slice-major,
    so replica ``r``'s rows sit on the same chip either way)."""
    return NamedSharding(mesh, P(squeeze_axes(data_axis_names(mesh))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for params/opt state: replicated on every device (DDP-style)."""
    return NamedSharding(mesh, P())


def shard_host_batch(batch, mesh: Mesh):
    """Place a host-local numpy batch onto the mesh's data axis.

    The multi-host analog of the reference's per-rank H2D copy
    (imagenet_ddp.py:258-259): each host holds only its disjoint shard (the
    DistributedSampler contract, imagenet_ddp.py:178-183), and
    ``make_array_from_process_local_data`` assembles the logical global batch
    across hosts without any cross-host data movement.
    """
    sharding = data_sharding(mesh)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        batch,
    )
