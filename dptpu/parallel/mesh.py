"""Device mesh construction and sharding helpers.

The reference's unit of parallelism is a process pinned to one GPU inside an
NCCL process group (imagenet_ddp.py:103-127). The TPU-native unit is a named
mesh axis: every chip on every host joins one global
``jax.sharding.Mesh`` and parallelism is expressed as sharding
annotations — XLA compiles the collectives onto ICI (intra-slice) and DCN
(cross-slice) links.

The default mesh is 1-D over a ``data`` axis (pure data parallelism — the
reference's only strategy, SURVEY.md §2c), but ``make_mesh`` accepts an
explicit shape so a ``model`` axis can be opened for tensor/FSDP sharding
without touching callers (the "don't hard-code a single axis name" guidance,
SURVEY.md §5).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[dict] = None,
    hierarchical: Optional[bool] = None,
) -> Mesh:
    """Build the global device mesh.

    ``mesh_shape`` maps axis name → size, in axis order; ``-1`` means "all
    remaining devices". Default: ``{"data": -1}`` — every chip on the data
    axis, the DDP-equivalent topology.

    ``hierarchical`` (default: auto — on whenever the devices span more
    than one process) orders the device array **host-major**: every host's
    chips form a contiguous block along the outermost (first) axis, with
    any inner axes (e.g. ``model``) living entirely inside one host. This
    is the (DCN, ICI) factored layout for multi-host pods — XLA decomposes
    the data-axis all-reduce into a fast intra-host ICI reduce followed by
    a small cross-host DCN exchange, instead of ring-reducing over DCN at
    ICI granularity. The v5p-32/128 BASELINE configs (4/16 hosts) depend
    on this. Counterpart of the reference's node-major rank layout
    (``rank = node_rank * ngpus_per_node + gpu``, imagenet_ddp.py:103),
    which gives NCCL the same hierarchy.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n_procs = len({getattr(d, "process_index", 0) for d in devices})
    if hierarchical is None:
        hierarchical = n_procs > 1
    if hierarchical:
        per_host: dict = {}
        for d in devices:
            per_host.setdefault(getattr(d, "process_index", 0), []).append(d)
        counts = {len(v) for v in per_host.values()}
        if len(counts) != 1:
            raise ValueError(
                f"hierarchical mesh needs equal chips per host, got "
                f"{ {k: len(v) for k, v in per_host.items()} }"
            )
        devices = [
            d
            for proc in sorted(per_host)
            for d in sorted(per_host[proc], key=lambda d: getattr(d, "id", 0))
        ]
    devices = np.asarray(devices)
    if mesh_shape is None:
        mesh_shape = {DATA_AXIS: -1}
    names = tuple(mesh_shape)
    sizes = list(mesh_shape.values())
    n = devices.size
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh shape {dict(zip(names, sizes))} != {n} devices")
    if hierarchical and int(np.prod(sizes[1:])) > n // n_procs:
        raise ValueError(
            f"hierarchical mesh: inner axes {dict(zip(names[1:], sizes[1:]))} "
            f"exceed one host's {n // n_procs} chips — inner-axis collectives "
            f"would cross DCN"
        )
    return Mesh(devices.reshape(sizes), names)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a batch: leading axis split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for params/opt state: replicated on every device (DDP-style)."""
    return NamedSharding(mesh, P())


def shard_host_batch(batch, mesh: Mesh):
    """Place a host-local numpy batch onto the mesh's data axis.

    The multi-host analog of the reference's per-rank H2D copy
    (imagenet_ddp.py:258-259): each host holds only its disjoint shard (the
    DistributedSampler contract, imagenet_ddp.py:178-183), and
    ``make_array_from_process_local_data`` assembles the logical global batch
    across hosts without any cross-host data movement.
    """
    sharding = data_sharding(mesh)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        batch,
    )
