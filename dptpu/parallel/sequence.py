"""Sequence-parallel training step: the {data, seq} mesh path.

The reference has no sequence dimension (SURVEY.md §5 "long-context:
absent by construction"); this is the trainer-level entry for dptpu's
beyond-reference sequence/context parallelism (`DPTPU_SP=N` in
``fit()``). The token axis of a ViT shards over the inner ``seq`` mesh
axis — Ulysses all-to-all or ring attention per block
(dptpu/ops/sequence_parallel.py) — while the batch shards over ``data``
as usual.

Design (why this is NOT the shared ``train_step_body``):

* the model runs with ``seq_shard_tokens=True`` — embedding replicated,
  tokens padded/sliced per sequence member, cls recovered by psum
  (dptpu/models/vit.py Encoder docstring) — so the per-member forward
  already contains cross-``seq`` collectives (all_to_all/ppermute/psum)
  whose VJPs route the cross-member cotangents;
* Ulysses' all-to-all output sharding defeats shard_map's replication
  checker, so the step runs ``check_rep=False`` — no automatic psum is
  inserted for the replicated params, and the gradient reduction is
  therefore EXPLICIT: each (data, seq) member differentiates the global
  mean loss restricted to its local graph, and one
  ``psum(grads, ("data", "seq"))`` sums the member contributions —
  over ``data`` that is the DDP gradient all-reduce, over ``seq`` it
  sums each member's token-chunk contribution (the head/embedding
  grads arrive pre-scaled by 1/n_seq from the redundant per-member
  loss, so the same psum reconstructs them exactly);
* ViT only (LayerNorm, no BatchNorm, no dropout), enforced by fit()'s
  arch gate — batch_stats pass through untouched.

Update math (SGD chain, LR application) is shared with every other
step via ``state.tx`` + ``optax.apply_updates``, identical to
dptpu/train/step.py; parity with the single-device step is locked
through the trainer in tests/test_fit.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dptpu.ops.loss import cross_entropy_loss
from dptpu.ops.metrics import topk_correct_fraction
from dptpu.parallel.mesh import DATA_AXIS

SEQ_AXIS = "seq"


def make_seq_train_step(mesh: Mesh, seq_model, compute_dtype=jnp.float32,
                        lr_schedule=None, label_smoothing: float = 0.0):
    """Build the jitted sequence-parallel train step.

    ``seq_model`` is the ViT built with ``seq_axis_name=SEQ_AXIS`` and
    ``seq_shard_tokens=True``; its param tree must equal the state's
    (the seq flags add no params — fit() creates the state from the
    plain model). Same contract as ``make_train_step``:
    ``step(state, batch) -> (state, metrics)`` with the batch sharded
    ``P(DATA_AXIS)`` (replicated over ``seq``) and replicated state.
    """
    from dptpu.train.step import (
        normalize_images,
        shard_map_nocheck,
        tpu_compiler_options,
    )

    if lr_schedule is None:
        lr_schedule = lambda count: 0.1  # noqa: E731
    n_data = int(mesh.shape[DATA_AXIS])
    n_seq = int(mesh.shape[SEQ_AXIS])

    def step(state, batch):
        images = normalize_images(batch["images"], compute_dtype)
        labels = batch["labels"]

        def loss_fn(params):
            logits = seq_model.apply(
                {"params": params}, images, train=True
            )
            local_loss = cross_entropy_loss(logits, labels,
                                            label_smoothing)
            # global mean loss restricted to this member's local graph:
            # /n_data for the data-shard mean, /n_seq because every
            # sequence member recomputes the (identical) loss — the
            # explicit two-axis psum below then sums members back to
            # exactly the global-batch-mean gradient
            return local_loss / (n_data * n_seq), (local_loss, logits)

        (_, (loss, logits)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        grads = lax.psum(grads, (DATA_AXIS, SEQ_AXIS))
        top1, top5 = topk_correct_fraction(logits, labels, (1, 5))
        # metrics are already seq-invariant (psum'd cls -> same logits);
        # average over data shards like the DDP step's reduce_tensor
        loss, top1, top5 = lax.pmean((loss, top1, top5), DATA_AXIS)
        direction, new_opt = state.tx.update(
            grads, state.opt_state, state.params
        )
        lr = lr_schedule(state.step)
        updates = jax.tree_util.tree_map(lambda u: -lr * u, direction)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=params,
            batch_stats=state.batch_stats,
            opt_state=new_opt,
        )
        metrics = {
            "loss": loss,
            "top1": top1 * 100.0,
            "top5": top5 * 100.0,
            "lr": jnp.asarray(lr, jnp.float32),
        }
        return new_state, metrics

    # Ulysses' all-to-all output sharding defeats the replication
    # checker, so this step runs with it off — via the same
    # version-portable helper every other dptpu step uses
    sharded = shard_map_nocheck(
        step,
        mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=(P(), P()),
    )
    return jax.jit(
        sharded, donate_argnums=0, compiler_options=tpu_compiler_options()
    )
