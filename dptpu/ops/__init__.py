from dptpu.ops.loss import cross_entropy_loss
from dptpu.ops.metrics import accuracy, topk_correct_fraction
from dptpu.ops.optimizers import (
    lamb,
    lars,
    scale_by_trust_ratio,
    trust_ratio_stats,
)
from dptpu.ops.schedules import (
    step_decay_lr,
    warmup_step_decay_lr,
    scale_lr_linear,
)
from dptpu.ops.sequence_parallel import (
    full_attention,
    ring_attention,
    sequence_parallel_attention,
    ulysses_attention,
)

__all__ = [
    "cross_entropy_loss",
    "accuracy",
    "topk_correct_fraction",
    "lamb",
    "lars",
    "scale_by_trust_ratio",
    "trust_ratio_stats",
    "step_decay_lr",
    "warmup_step_decay_lr",
    "scale_lr_linear",
    "full_attention",
    "ring_attention",
    "sequence_parallel_attention",
    "ulysses_attention",
]
