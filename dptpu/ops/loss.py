"""Classification loss.

TPU-native replacement for ``nn.CrossEntropyLoss()`` (reference
imagenet_ddp.py:131, default mean reduction): softmax cross-entropy with
integer labels, computed in float32 regardless of the compute dtype so that
the bf16 policy (the Apex-AMP replacement) never loses precision in the
log-sum-exp — the same role Apex's fp32 loss kept in its O1/O2 modes.

Label smoothing (``--label-smoothing``, a dptpu extension) is part of the
large-batch recipe every ImageNet-in-minutes paper ships (e.g.
arXiv:1711.04325 trains with smoothing 0.1): targets become
``(1-s)·onehot + s/K``. Training-path only — validation loss stays the
reference's unsmoothed CE so accuracy/loss numbers compare across recipes.
"""

import jax
import jax.numpy as jnp
import optax


def cross_entropy_loss(logits, labels, label_smoothing: float = 0.0):
    """Mean softmax cross-entropy, optionally label-smoothed.

    Args:
      logits: ``[batch, num_classes]`` array (any float dtype; upcast to f32).
      labels: ``[batch]`` integer class ids.
      label_smoothing: static smoothing mass ``s`` in [0, 1); 0 is the
        reference's exact hard-target CE.

    Returns:
      Scalar f32 mean loss (``nn.CrossEntropyLoss`` default reduction).
    """
    logits = logits.astype(jnp.float32)
    if label_smoothing:
        targets = optax.smooth_labels(
            jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32),
            label_smoothing,
        )
        return optax.softmax_cross_entropy(logits, targets).mean()
    per_example = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return per_example.mean()
