"""Classification loss.

TPU-native replacement for ``nn.CrossEntropyLoss()`` (reference
imagenet_ddp.py:131, default mean reduction): softmax cross-entropy with
integer labels, computed in float32 regardless of the compute dtype so that
the bf16 policy (the Apex-AMP replacement) never loses precision in the
log-sum-exp — the same role Apex's fp32 loss kept in its O1/O2 modes.
"""

import jax.numpy as jnp
import optax


def cross_entropy_loss(logits, labels):
    """Mean softmax cross-entropy.

    Args:
      logits: ``[batch, num_classes]`` array (any float dtype; upcast to f32).
      labels: ``[batch]`` integer class ids.

    Returns:
      Scalar f32 mean loss (``nn.CrossEntropyLoss`` default reduction).
    """
    logits = logits.astype(jnp.float32)
    per_example = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return per_example.mean()
