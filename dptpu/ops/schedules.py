"""Learning-rate schedules with the reference's exact math.

Two schedules exist in the reference, reimplemented here as pure functions
(the reference mutates ``optimizer.param_groups`` in place; here the value is
fed into the jitted train step each step — schedules stay host-side Python,
the update stays compiled):

* Step decay — ``lr = lr0 * 0.1**(epoch // 30)``
  (imagenet_ddp.py:374-378; nd_imagenet.py:428-432).
* Apex variant — the step decay plus an EXTRA ×0.1 at epoch ≥ 80 and a
  5-epoch linear warmup scaled by global step, applied per-step
  (imagenet_ddp_apex.py:527-543), on top of the linear-scaling rule
  ``lr0 · global_batch/256`` (imagenet_ddp_apex.py:161-162).

One schedule is a dptpu extension (no reference analog): the
large-batch recipe's linear-warmup + cosine-decay
(:func:`make_warmup_cosine_schedule`) — the shape every
ImageNet-in-minutes paper pairs with LARS/LAMB (arXiv:1711.04325 §5.1,
arXiv:1904.00962 §5): LR ramps linearly from ~0 to the scaled peak over
the warmup epochs (large-batch SGD diverges without it), then follows a
half-cosine to ``end_lr``. Selected by ``--warmup-epochs N > 0``.
"""


def step_decay_lr(base_lr, epoch):
    """``lr = base_lr * 0.1**(epoch // 30)`` (imagenet_ddp.py:376)."""
    return base_lr * (0.1 ** (epoch // 30))


def warmup_step_decay_lr(base_lr, epoch, step, len_epoch):
    """Apex schedule (imagenet_ddp_apex.py:527-543).

    ``step`` is 1-based within the epoch, exactly as the reference's train
    loop increments ``i`` before the first use (imagenet_ddp_apex.py:367-369).
    Docstring claim carried over: "should yield 76% converged accuracy with
    batch size 256".
    """
    factor = epoch // 30
    if epoch >= 80:
        factor = factor + 1
    lr = base_lr * (0.1 ** factor)
    if epoch < 5:
        lr = lr * float(1 + step + epoch * len_epoch) / (5.0 * len_epoch)
    return lr


def scale_lr_linear(base_lr, global_batch_size):
    """Linear-scaling rule: ``lr0 · global_batch/256``
    (imagenet_ddp_apex.py:161-162)."""
    return base_lr * float(global_batch_size) / 256.0


def make_step_decay_schedule(base_lr, steps_per_epoch):
    """Traced, optax-compatible form of :func:`step_decay_lr`.

    The reference mutates ``optimizer.param_groups`` once per epoch from the
    host (imagenet_ddp.py:203,374-378); here the LR is a pure function of the
    optimizer's global step count, evaluated *inside* the compiled train step
    — no host round-trip, and one compilation covers every epoch.
    """
    import jax.numpy as jnp

    def schedule(count):
        epoch = jnp.asarray(count) // steps_per_epoch
        return base_lr * jnp.power(0.1, (epoch // 30).astype(jnp.float32))

    return schedule


def make_warmup_cosine_schedule(base_lr, steps_per_epoch, total_epochs,
                                warmup_epochs, end_lr=0.0, power=1.0):
    """Traced large-batch schedule: linear warmup to ``base_lr`` over
    ``warmup_epochs``, then cosine decay to ``end_lr`` over the rest.

    Warmup is 1-based like the Apex schedule (the first step already
    takes a nonzero LR — ``base_lr / warmup_steps`` — so no step is
    wasted at exactly 0). A pure function of the global step count, so
    resume lands on the exact LR like every other dptpu schedule.

    ``power`` != 1 bends the warmup into the POLYNOMIAL ramp of the
    extreme-scale recipes (``DPTPU_WARMUP_POLY``; Mikami et al.,
    arXiv:1811.05233 warm up as ``(t/T_w)^p`` — a gentler start for the
    very large batches where even the linear ramp's first steps
    overshoot). ``power == 1.0`` keeps today's exact linear expression
    (bit-identical: the power path is never traced).
    """
    import jax.numpy as jnp

    warmup_steps = max(int(warmup_epochs * steps_per_epoch), 1)
    total_steps = max(int(total_epochs * steps_per_epoch), warmup_steps + 1)

    def schedule(count):
        count = jnp.asarray(count).astype(jnp.float32)
        if power == 1.0:
            warm = base_lr * (count + 1.0) / warmup_steps
        else:
            warm = base_lr * jnp.power(
                (count + 1.0) / warmup_steps, power
            )
        frac = jnp.clip(
            (count - warmup_steps) / (total_steps - warmup_steps), 0.0, 1.0
        )
        cos = end_lr + (base_lr - end_lr) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * frac)
        )
        return jnp.where(count < warmup_steps, warm, cos)

    return schedule


def parse_batch_ramp(spec):
    """Parse ``DPTPU_BATCH_RAMP`` — the batch-size ramp of the
    extreme-scale recipes (arXiv:1811.05233 §3.1: start small while the
    loss surface is steep, grow the batch as training stabilizes).

    Format: ``"epoch:mult[,epoch:mult...]"`` — from ``epoch`` onward the
    per-host batch is ``mult ×`` the configured ``--batch-size`` (and
    the schedule's peak LR scales ``× mult`` per the linear-scaling
    rule). Epochs must be non-negative ints, strictly increasing;
    multipliers positive ints. A leading ``(0, 1)`` phase is implied
    when the spec does not name epoch 0. Raises actionably on any
    malformed spec (the locked fail-fast knob contract).
    """
    pairs = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        epoch_s, sep, mult_s = part.partition(":")
        try:
            if not sep:
                raise ValueError
            epoch, mult = int(epoch_s), int(mult_s)
        except ValueError:
            raise ValueError(
                f"DPTPU_BATCH_RAMP entry {part!r} is not 'epoch:mult' "
                f"(expected e.g. DPTPU_BATCH_RAMP=4:2,8:4)"
            ) from None
        if epoch < 0 or mult < 1:
            raise ValueError(
                f"DPTPU_BATCH_RAMP entry {part!r}: epoch must be >= 0 "
                f"and mult >= 1"
            )
        pairs.append((epoch, mult))
    if not pairs:
        raise ValueError(
            "DPTPU_BATCH_RAMP is set but holds no 'epoch:mult' entries "
            "(expected e.g. DPTPU_BATCH_RAMP=4:2,8:4)"
        )
    epochs = [e for e, _ in pairs]
    if sorted(set(epochs)) != epochs:
        raise ValueError(
            f"DPTPU_BATCH_RAMP epochs must be strictly increasing, got "
            f"{epochs}"
        )
    if pairs[0][0] != 0:
        pairs.insert(0, (0, 1))
    return pairs


def ramp_multiplier(ramp, epoch: int) -> int:
    """The batch multiplier in force at ``epoch`` (a step function of
    the parsed ramp table — the LAST phase whose start is <= epoch)."""
    mult = 1
    for e, m in ramp:
        if epoch >= e:
            mult = m
    return mult


def ramp_phase_start(ramp, epoch: int) -> int:
    """The start epoch of the phase containing ``epoch`` (the LR
    schedule's anchor: together with the cumulative step count at that
    boundary it makes the phase schedule a pure function of the global
    step, so resume lands on the exact LR)."""
    start = 0
    for e, _m in ramp:
        if epoch >= e:
            start = e
    return start


def make_ramp_phase_schedule(peak_lr, steps_per_epoch, total_epochs,
                             warmup_epochs, epoch0, step0, end_lr=0.0,
                             power=1.0):
    """The warmup→cosine schedule for ONE batch-ramp phase, expressed
    in fractional epochs so phases with different ``steps_per_epoch``
    chain continuously: ``epoch(count) = epoch0 + (count - step0) /
    steps_per_epoch`` with ``(epoch0, step0)`` the phase-start anchor
    (both derivable from the ramp table alone, so a resumed run
    reconstructs the identical schedule). ``peak_lr`` already carries
    the phase's linear-scaling factor; ``power`` is the polynomial
    warmup exponent (1 = linear)."""
    import jax.numpy as jnp

    warmup_e = float(max(warmup_epochs, 1e-9))
    total_e = float(max(total_epochs, warmup_epochs + 1e-6))

    def schedule(count):
        count = jnp.asarray(count).astype(jnp.float32)
        # 1-based within the phase, like the non-ramp warmup
        e1 = epoch0 + (count - step0 + 1.0) / steps_per_epoch
        e = epoch0 + (count - step0) / steps_per_epoch
        warm = peak_lr * jnp.power(
            jnp.clip(e1 / warmup_e, 0.0, 1.0), power
        )
        frac = jnp.clip((e - warmup_e) / (total_e - warmup_e), 0.0, 1.0)
        cos = end_lr + (peak_lr - end_lr) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * frac)
        )
        return jnp.where(e1 < warmup_e, warm, cos)

    return schedule


def make_warmup_step_decay_schedule(base_lr, steps_per_epoch):
    """Traced form of the Apex per-step schedule (:func:`warmup_step_decay_lr`):
    step decay ×0.1/30 epochs, extra ×0.1 at epoch ≥ 80, 5-epoch linear
    warmup scaled by global step (imagenet_ddp_apex.py:527-543). The
    reference's in-epoch ``step`` is 1-based (imagenet_ddp_apex.py:367-369).
    """
    import jax.numpy as jnp

    def schedule(count):
        count = jnp.asarray(count)
        epoch = count // steps_per_epoch
        step_1based = count % steps_per_epoch + 1
        factor = epoch // 30 + jnp.where(epoch >= 80, 1, 0)
        lr = base_lr * jnp.power(0.1, factor.astype(jnp.float32))
        warm = lr * (1.0 + step_1based + epoch * steps_per_epoch) / (
            5.0 * steps_per_epoch
        )
        return jnp.where(epoch < 5, warm, lr)

    return schedule
