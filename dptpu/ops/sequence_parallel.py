"""Sequence/context-parallel attention: Ulysses all-to-all + ring attention.

The reference has no sequence dimension at all (vision CNNs,
SURVEY.md §5 "long-context: absent by construction"), but this framework
ships attention families (ViT/Swin), and on TPU the idiomatic way to
scale their sequence axis past one chip's HBM is sequence parallelism
over a named mesh axis. Two standard schemes, both expressed as pure
functions over per-device shards for use inside ``shard_map``:

* **Ulysses** (all-to-all head scatter): each device holds a sequence
  shard of q/k/v with ALL heads; one ``lax.all_to_all`` per tensor
  re-shards to all-sequence/heads-split, plain attention runs locally,
  and one reverse all-to-all restores sequence sharding. Exact — the
  result is bitwise the unsharded attention (modulo reduction order).
  Communication rides the ICI as 3+1 all-to-alls of the activation size;
  requires ``heads % axis_size == 0``.

* **Ring attention** (k/v rotation with online softmax): k/v shards hop
  around the ring via ``lax.ppermute`` inside a ``lax.fori_loop`` while
  each device accumulates its queries' attention with the
  running-max/denominator (flash-attention style) update — the full
  (s, s) score matrix never materializes. The loop is double-buffered:
  each iteration ISSUES the permute fetching block i+1 before consuming
  block i, and neither depends on the other's output, so XLA's
  latency-hiding scheduler is free to run the ICI transfer under the
  block's einsums (structural overlap; actual overlap is the
  scheduler's call and has not been measured on multi-chip hardware —
  this environment has one chip). Works for any head count; memory per
  chip is O(s_local * d), enabling sequences that cannot fit on one
  chip.

Both schemes take an optional per-shard ``kv_mask`` (local key-validity
mask) so callers that PAD the token axis to a multiple of the axis size
— e.g. ViT's ``S + 1`` cls-prepended sequence in the trainer's
``DPTPU_SP`` path — get exact softmax over the real keys only.

Scaled dot-product convention matches ``dptpu.models.vit.SelfAttention``
(scale 1/sqrt(head_dim), f32 softmax). Equivalence against single-device
attention is locked in tests/test_sequence_parallel.py on the fake
8-device CPU mesh.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp


# Masked logits are set to a finite huge-negative instead of -inf:
# exp(-1e30 - m) is exactly 0.0 in f32 for any real row max m, while a
# fully-masked (padding) query row stays NaN-free through softmax and
# the online-softmax recurrence — its garbage output is sliced away by
# the caller and contributes zero cotangent.
_MASKED = -1e30


def axis_size(axis_name: str) -> int:
    """Static mesh-axis size, portable across jax versions:
    ``lax.axis_size`` only exists in newer jax; ``psum(1, axis)`` is the
    classic spelling and constant-folds to the same static int."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:  # pragma: no cover - depends on jax version
        return jax.lax.psum(1, axis_name)


def full_attention(q, k, v, kv_mask=None):
    """Reference scaled-dot-product attention.

    q/k/v: (batch, seq, heads, head_dim) -> (batch, seq, heads, head_dim).
    ``kv_mask`` (seq,) bool marks valid KEY positions (False = padding).
    """
    hd = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(hd)
    logits = logits.astype(jnp.float32)
    if kv_mask is not None:
        logits = jnp.where(kv_mask[None, None, None, :], logits, _MASKED)
    attn = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", attn.astype(q.dtype), v)


def ulysses_attention(q, k, v, axis_name: str, kv_mask=None):
    """All-to-all sequence-parallel attention (per-shard view).

    Inputs are the LOCAL sequence shard (batch, seq/N, heads, head_dim)
    on every device of ``axis_name`` (size N, ``heads % N == 0``).
    Internally re-shards to (batch, seq, heads/N, head_dim), runs plain
    attention, and re-shards back. Call under ``shard_map`` with the
    sequence axis of q/k/v partitioned over ``axis_name``. ``kv_mask``
    (seq/N,) bool marks this shard's valid key positions.
    """
    n = axis_size(axis_name)
    heads = q.shape[2]
    if heads % n:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by axis size ({n})"
        )
    # (b, s/N, h, d) -> (b, s, h/N, d): scatter heads, gather sequence
    gather = lambda t: jax.lax.all_to_all(
        t, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    full_mask = (
        None
        if kv_mask is None
        else jax.lax.all_gather(kv_mask, axis_name, tiled=True)
    )
    out = full_attention(
        gather(q), gather(k), gather(v), kv_mask=full_mask
    )
    # (b, s, h/N, d) -> (b, s/N, h, d)
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ring_attention(q, k, v, axis_name: str, kv_mask=None):
    """Ring sequence-parallel attention with online softmax (per-shard).

    Inputs are the LOCAL sequence shard (batch, seq/N, heads, head_dim).
    k/v rotate N-1 times around the ring; the local q block folds each
    incoming k/v block into flash-style running statistics
    (row max ``m``, denominator ``l``, weighted accumulator ``o``), so
    peak memory is O(s_local^2) scores per step instead of O(s^2).

    Double-buffered: each loop iteration first ISSUES the ppermute that
    fetches block i+1, then consumes block i — the permute reads only
    the incoming buffer, never the block's outputs, so the ICI transfer
    and the einsums have no data dependence and XLA's latency-hiding
    scheduler may overlap them (whether it does is its call; single-chip
    hardware here cannot measure it). The final block is peeled out of
    the loop so exactly N-1 hops are issued.

    ``kv_mask`` (seq/N,) bool marks this shard's valid key positions;
    it rides the ring alongside its k/v block.
    """
    n = axis_size(axis_name)
    hd = q.shape[-1]
    scale = 1.0 / math.sqrt(hd)
    qf = q.astype(jnp.float32) * scale
    # the mask (when given) rides the ring inside the rotated payload; a
    # default all-ones mask would be axis-INVARIANT and mismatch the
    # varying ppermute output in the loop carry, so unmasked callers get
    # a mask-free payload instead
    has_mask = kv_mask is not None

    def block(carry, kv):
        m, l, o = carry
        if has_mask:
            kb, vb, maskb = kv
        else:
            kb, vb = kv
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32))
        if has_mask:
            s = jnp.where(maskb[None, None, None, :], s, _MASKED)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)  # rescale of prior accumulator
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32)
        )
        return (m_new, l, o)

    # accumulators derived from qf so shard_map types them as varying
    # over the ring axis (plain constants would mismatch the loop carry).
    # m0 = _MASKED (not -inf): a fully-padded block then yields
    # alpha = exp(_MASKED - _MASKED) = 1, keeping pad-row garbage finite.
    zero = (qf * 0.0).sum(axis=-1).transpose(0, 2, 1)  # (b, h, s_local)
    m0 = zero + _MASKED
    l0 = zero
    o0 = qf.transpose(0, 2, 1, 3) * 0.0

    perm = [(i, (i + 1) % n) for i in range(n)]
    payload = (k, v, kv_mask) if has_mask else (k, v)

    def step(i, carry):
        m_l_o, kv = carry
        # issue the fetch of block i+1 FIRST; consume block i while the
        # permute is (potentially) in flight — no data dependence between
        # the two, so the scheduler may run them concurrently
        kv_next = jax.lax.ppermute(kv, axis_name, perm)
        m_l_o = block(m_l_o, kv)
        return (m_l_o, kv_next)

    m_l_o, kv = jax.lax.fori_loop(0, n - 1, step, ((m0, l0, o0), payload))
    m, l, o = block(m_l_o, kv)  # last block: no hop issued
    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (b, s/N, h, d)


def sequence_parallel_attention(
    q, k, v, axis_name: Optional[str], mode: str = "ulysses", kv_mask=None
):
    """Dispatch: plain attention when unsharded, else ulysses or ring."""
    if axis_name is None:
        return full_attention(q, k, v, kv_mask=kv_mask)
    if mode == "ulysses":
        return ulysses_attention(q, k, v, axis_name, kv_mask=kv_mask)
    if mode == "ring":
        return ring_attention(q, k, v, axis_name, kv_mask=kv_mask)
    raise ValueError(f"unknown sequence-parallel mode {mode!r}")
