"""Sequence/context-parallel attention: Ulysses all-to-all + ring attention.

The reference has no sequence dimension at all (vision CNNs,
SURVEY.md §5 "long-context: absent by construction"), but this framework
ships attention families (ViT/Swin), and on TPU the idiomatic way to
scale their sequence axis past one chip's HBM is sequence parallelism
over a named mesh axis. Two standard schemes, both expressed as pure
functions over per-device shards for use inside ``shard_map``:

* **Ulysses** (all-to-all head scatter): each device holds a sequence
  shard of q/k/v with ALL heads; one ``lax.all_to_all`` per tensor
  re-shards to all-sequence/heads-split, plain attention runs locally,
  and one reverse all-to-all restores sequence sharding. Exact — the
  result is bitwise the unsharded attention (modulo reduction order).
  Communication rides the ICI as 3+1 all-to-alls of the activation size;
  requires ``heads % axis_size == 0``.

* **Ring attention** (k/v rotation with online softmax): k/v shards hop
  around the ring via ``lax.ppermute`` inside a ``lax.fori_loop`` while
  each device accumulates its queries' attention with the
  running-max/denominator (flash-attention style) update — the full
  (s, s) score matrix never materializes and each step overlaps the
  next permute with compute. Works for any head count; memory per chip
  is O(s_local * d), enabling sequences that cannot fit on one chip.

Scaled dot-product convention matches ``dptpu.models.vit.SelfAttention``
(scale 1/sqrt(head_dim), f32 softmax). Equivalence against single-device
attention is locked in tests/test_sequence_parallel.py on the fake
8-device CPU mesh.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp


def full_attention(q, k, v):
    """Reference scaled-dot-product attention.

    q/k/v: (batch, seq, heads, head_dim) -> (batch, seq, heads, head_dim).
    """
    hd = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(hd)
    attn = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", attn.astype(q.dtype), v)


def ulysses_attention(q, k, v, axis_name: str):
    """All-to-all sequence-parallel attention (per-shard view).

    Inputs are the LOCAL sequence shard (batch, seq/N, heads, head_dim)
    on every device of ``axis_name`` (size N, ``heads % N == 0``).
    Internally re-shards to (batch, seq, heads/N, head_dim), runs plain
    attention, and re-shards back. Call under ``shard_map`` with the
    sequence axis of q/k/v partitioned over ``axis_name``.
    """
    n = jax.lax.axis_size(axis_name)
    heads = q.shape[2]
    if heads % n:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by axis size ({n})"
        )
    # (b, s/N, h, d) -> (b, s, h/N, d): scatter heads, gather sequence
    gather = lambda t: jax.lax.all_to_all(
        t, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    out = full_attention(gather(q), gather(k), gather(v))
    # (b, s, h/N, d) -> (b, s/N, h, d)
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ring_attention(q, k, v, axis_name: str):
    """Ring sequence-parallel attention with online softmax (per-shard).

    Inputs are the LOCAL sequence shard (batch, seq/N, heads, head_dim).
    k/v rotate N-1 times around the ring; the local q block folds each
    incoming k/v block into flash-style running statistics
    (row max ``m``, denominator ``l``, weighted accumulator ``o``), so
    peak memory is O(s_local^2) scores per step instead of O(s^2).
    """
    n = jax.lax.axis_size(axis_name)
    hd = q.shape[-1]
    scale = 1.0 / math.sqrt(hd)
    qf = q.astype(jnp.float32) * scale

    def block(carry, kv):
        m, l, o = carry
        kb, vb = kv
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32))
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)  # rescale of prior accumulator
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32)
        )
        return (m_new, l, o)

    # accumulators derived from qf so shard_map types them as varying
    # over the ring axis (plain constants would mismatch the loop carry)
    zero = (qf * 0.0).sum(axis=-1).transpose(0, 2, 1)  # (b, h, s_local)
    m0 = zero - jnp.inf
    l0 = zero
    o0 = qf.transpose(0, 2, 1, 3) * 0.0

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        m_l_o, kb, vb = carry
        m_l_o = block(m_l_o, (kb, vb))
        # rotate AFTER consuming so the last block needs no extra hop;
        # lax.cond keeps the final-iteration permute out of the graph
        kb, vb = jax.lax.cond(
            i < n - 1,
            lambda kv: jax.lax.ppermute(kv, axis_name, perm),
            lambda kv: kv,
            (kb, vb),
        )
        return (m_l_o, kb, vb)

    (m, l, o), _, _ = jax.lax.fori_loop(0, n, step, ((m0, l0, o0), k, v))
    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (b, s/N, h, d)


def sequence_parallel_attention(
    q, k, v, axis_name: Optional[str], mode: str = "ulysses"
):
    """Dispatch: plain attention when unsharded, else ulysses or ring."""
    if axis_name is None:
        return full_attention(q, k, v)
    if mode == "ulysses":
        return ulysses_attention(q, k, v, axis_name)
    if mode == "ring":
        return ring_attention(q, k, v, axis_name)
    raise ValueError(f"unknown sequence-parallel mode {mode!r}")
