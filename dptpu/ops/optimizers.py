"""Large-batch optimizers: LARS and LAMB with layer-wise trust ratios.

The reference trains with plain momentum SGD at batch 256-1024
(imagenet_ddp.py:133-135). Every PAPERS.md system trains ImageNet in
minutes by scaling the batch to 32k-64k, and plain SGD diverges there:
the ratio ``||w_l|| / ||update_l||`` varies by orders of magnitude
across layers, so any single LR overshoots some layer. LARS (You et
al., arXiv:1708.03888 — the optimizer behind the 15-minute ResNet-50,
arXiv:1711.04325) and LAMB (You et al., arXiv:1904.00962) fix this with
a per-layer **trust ratio** ``||w_l|| / ||u_l||`` that rescales each
layer's update to the layer's own weight scale.

Both are built in this repo's optimizer convention (dptpu/train/state.py
``make_optimizer``): the transform chain emits an **lr-less direction**
and the compiled train step multiplies by ``-lr(step)`` — so the LR
schedule stays a pure function of the checkpointed global step.

Weight-update-sharding hook (arXiv:2004.13336, dptpu/parallel/zero.py):
the ONLY non-elementwise piece of either optimizer is the pair of
per-layer norms. ``scale_by_trust_ratio`` therefore routes every
per-leaf sum-of-squares through an injectable ``sumsq_reduce`` — under
ZeRO-style sharding each device computes partial sums on its local
shard and the reducer completes them with ONE small psum (a [L, 2]
stack, a few hundred floats), so the whole optimizer state and all its
math stay 1/N per device.

Skip list: following both papers (and every reference implementation),
1-D parameters — biases, BatchNorm/LayerNorm scale and shift — are
excluded from the trust ratio AND from weight decay; they take the
plain (momentum/adam) update. ``ndim >= 2`` is the membership test.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class ScaleByTrustRatioState(NamedTuple):
    """Per-update trust-ratio summary, carried in the optimizer state so
    the compiled step can surface it as ``Opt/*`` metrics without
    recomputing norms: min/mean/max over the trusted (ndim>=2) leaves.
    Scalars, so they stay replicated under every sharding rule
    (``zero1_state_specs`` finds no divisible dim)."""

    trust_min: jnp.ndarray
    trust_mean: jnp.ndarray
    trust_max: jnp.ndarray


def _trusted(leaf) -> bool:
    """Trust-ratio / weight-decay membership: matrices and conv kernels
    yes; biases and norm scale/shift (ndim<=1) no."""
    return getattr(leaf, "ndim", 0) >= 2


def trust_mask(params):
    """Pytree of bools marking the leaves that get weight decay and the
    trust ratio (the ``optax.masked`` mask for LARS/LAMB)."""
    return jax.tree_util.tree_map(_trusted, params)


def scale_by_trust_ratio(
    trust_coefficient: float = 0.001,
    eps: float = 0.0,
    sumsq_reduce: Optional[Callable] = None,
):
    """Layer-wise trust-ratio scaling: ``u_l <- r_l * u_l`` with
    ``r_l = trust_coefficient * ||w_l|| / (||u_l|| + eps)``.

    ``r_l`` falls back to 1.0 whenever either norm is zero (fresh zero
    init, dead gradient) — the LARS paper's guard, which also covers the
    skip list: ndim<2 leaves always scale by exactly 1.0.

    ``sumsq_reduce`` completes partial norms under sharding: it receives
    a params-structured pytree whose every leaf is a length-2 f32 vector
    ``[sum(w^2), sum(u^2)]`` computed over the LOCAL shard, and must
    return the tree with globally-completed sums. None (default) means
    the local values are already global (replicated params).
    """

    def init_fn(params):
        del params
        one = jnp.ones((), jnp.float32)
        return ScaleByTrustRatioState(one, one, one)

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError(
                "scale_by_trust_ratio requires params "
                "(optax update(updates, state, params))"
            )
        pairs = jax.tree_util.tree_map(
            lambda w, u: jnp.stack([
                jnp.sum(jnp.square(w.astype(jnp.float32))),
                jnp.sum(jnp.square(u.astype(jnp.float32))),
            ]),
            params,
            updates,
        )
        if sumsq_reduce is not None:
            pairs = sumsq_reduce(pairs)

        def ratio(pair):
            wn = jnp.sqrt(pair[0])
            un = jnp.sqrt(pair[1])
            r = trust_coefficient * wn / (un + eps)
            return jnp.where((wn > 0.0) & (un > 0.0), r, 1.0)

        ratios = jax.tree_util.tree_map(ratio, pairs)
        scaled = jax.tree_util.tree_map(
            lambda u, r, w: (u * r).astype(u.dtype) if _trusted(w) else u,
            updates,
            ratios,
            params,
        )
        trusted = [
            r
            for r, w in zip(
                jax.tree_util.tree_leaves(ratios),
                jax.tree_util.tree_leaves(params),
            )
            if _trusted(w)
        ]
        if trusted:
            vec = jnp.stack(trusted)
            new_state = ScaleByTrustRatioState(
                jnp.min(vec), jnp.mean(vec), jnp.max(vec)
            )
        else:  # degenerate all-1D model: every ratio is identically 1
            new_state = init_fn(None)
        return scaled, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def lars(
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    trust_coefficient: float = 0.001,
    nesterov: bool = False,
    sumsq_reduce: Optional[Callable] = None,
) -> optax.GradientTransformation:
    """LARS direction (arXiv:1708.03888), WITHOUT the learning rate.

    Paper ordering: ``g_l <- g_l + wd*w_l`` (trusted leaves only), then
    ``r_l = tc * ||w_l|| / ||g_l||`` (the denominator already carries
    the decay term, matching eq. 6), then ``buf = m*buf + r_l*g_l``; the
    train step applies ``w -= lr*buf``. Skip-list leaves get plain
    momentum SGD with no decay.
    """
    return optax.chain(
        optax.masked(optax.add_decayed_weights(weight_decay), trust_mask),
        scale_by_trust_ratio(
            trust_coefficient=trust_coefficient, sumsq_reduce=sumsq_reduce
        ),
        optax.trace(decay=momentum, nesterov=nesterov),
    )


def lamb(
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 1e-4,
    sumsq_reduce: Optional[Callable] = None,
) -> optax.GradientTransformation:
    """LAMB direction (arXiv:1904.00962), WITHOUT the learning rate:
    bias-corrected Adam moments → decoupled weight decay (trusted leaves)
    → unit trust ratio ``||w_l|| / ||u_l||``. Skip-list leaves take the
    plain Adam update with no decay and ratio 1."""
    return optax.chain(
        optax.scale_by_adam(b1=b1, b2=b2, eps=eps),
        optax.masked(optax.add_decayed_weights(weight_decay), trust_mask),
        scale_by_trust_ratio(trust_coefficient=1.0, sumsq_reduce=sumsq_reduce),
    )


def trust_ratio_stats(opt_state):
    """Extract the ``ScaleByTrustRatioState`` summary from an optimizer
    state tree, or None when the optimizer has no trust-ratio stage
    (plain SGD). Structural walk, like ``map_momentum``."""
    found = []

    def rec(node):
        if isinstance(node, ScaleByTrustRatioState):
            found.append(node)
            return
        if isinstance(node, (tuple, list)) and not hasattr(node, "shape"):
            for child in node:
                rec(child)

    rec(opt_state)
    if not found:
        return None
    s = found[0]
    return {
        "trust_min": s.trust_min,
        "trust_mean": s.trust_mean,
        "trust_max": s.trust_max,
    }
