"""Fused stem: BN-affine + ReLU + 3x3/2 max-pool as one custom-VJP region.

The reference's stem (torchvision resnet: conv7x7 -> BN -> ReLU ->
MaxPool2d(3,2,1), consumed via imagenet_ddp.py:108-114) is the single most
bandwidth-hungry non-conv piece of a ResNet train step on TPU: at batch 128
the 112x112x64 ReLU plane is 205 MB that the stock XLA program writes in
forward, re-reads for the pool, and walks twice more in backward
(``select_and_scatter`` + the BN/ReLU backward chain) — ~3 ms of a ~47 ms
step (PERF.md).

This module folds the whole post-conv stem into one custom-VJP region

    y = maxpool_3x3s2p1(relu(gamma_t * z + beta_t))

where ``gamma_t = scale * rsqrt(var + eps)`` and ``beta_t = bias -
mean * gamma_t`` are the BN affine with statistics pre-folded (batch stats
in train mode, running stats in eval). Because ReLU and the affine are
monotone per-channel maps, pooling commutes with them and the forward is a
single fusion ``z -> y``: the 112x112 ReLU plane is **never materialized**.

Backward exploits three identities:

* the pool's pre-ReLU window max ``best`` recomputed from ``z`` gives both
  the ReLU mask (``y > 0  <=>  best > 0``) and the winner;
* the winner of ``relu(affine(z))`` under first-max (select_and_scatter's
  GE tie-break) equals the winner of ``affine(z)`` whenever the window
  emits gradient (max > 0), so a 9-way first-strict-max scan yields the
  routing index ``widx``;
* each input position belongs to at most 4 windows with *statically known*
  offsets per (row, col) parity, so routing is a gather, not a scatter:
  ``dz[2u+a, 2v+b] = sum of g~ * [widx == offset]`` over <= 4 taps.

``d(gamma_t) = sum(g~ * z_win)`` and ``d(beta_t) = sum(g~)`` ride the small
56x56 grid (``z_win`` is tracked during the scan), so backward never
re-reads the input plane beyond the one scan pass.

Two implementations with identical semantics (parity-tested against
``nn.max_pool``'s select_and_scatter in tests/test_fused_stem.py):

* ``_*_xla``: pure lax ops — runs anywhere, used on CPU and as the
  reference.
* ``_*_pallas``: TPU Pallas kernels gridded over the batch, one VMEM-
  resident image per program — XLA's fusion emitter handles the 9 strided
  window views poorly (measured +4.7 ms), Mosaic does not.

The op itself picks Pallas vs XLA automatically (Pallas on TPU for even
square spatial dims, XLA elsewhere). Whether the resnet stem uses this op
at all is **opt-in**: ``DPTPU_FUSED_STEM=1`` (handled in
``dptpu.train.fit``) or ``create_model(..., fused_stem=True)`` — measured
slower than XLA's native stem lowering on v5e Mosaic (PERF.md), so the
default stem remains the unfused one.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:  # pallas is TPU-only at runtime but importable everywhere
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pl = pltpu = None


# ---------------------------------------------------------------------------
# shared XLA forward (also the Pallas fallback / reference)
# ---------------------------------------------------------------------------

def _fwd_xla(z, gamma_t, beta_t):
    # affine + pool in f32 (the Pallas kernels compute in f32 for Mosaic's
    # bf16 sublane-granularity rules; keeping the XLA path identical makes
    # winner selection — and therefore backward routing — bit-identical
    # across implementations), output cast back to the compute dtype
    a = gamma_t.astype(jnp.float32) * z.astype(jnp.float32) \
        + beta_t.astype(jnp.float32)
    pooled = lax.reduce_window(
        a, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        ((0, 0), (1, 1), (1, 1), (0, 0)),
    )
    return jnp.maximum(pooled, 0.0).astype(z.dtype)


def _bwd_xla(z, gamma_t, beta_t, g):
    """Reference backward (pure lax). Returns (dz, dgamma_t, dbeta_t)."""
    b, h, w, c = z.shape
    oh, ow = g.shape[1], g.shape[2]
    dt = z.dtype

    a = gamma_t.astype(jnp.float32) * z.astype(jnp.float32) \
        + beta_t.astype(jnp.float32)
    ap = lax.pad(a, jnp.float32(-jnp.inf),
                 ((0, 0, 0), (1, 1, 0), (1, 1, 0), (0, 0, 0)))
    zp = lax.pad(z.astype(jnp.float32), jnp.float32(0),
                 ((0, 0, 0), (1, 1, 0), (1, 1, 0), (0, 0, 0)))
    best = widx = zwin = None
    for r in range(3):
        for s in range(3):
            k = 3 * r + s
            lim = (b, r + 2 * oh - 1, s + 2 * ow - 1, c)
            ars = lax.slice(ap, (0, r, s, 0), lim, (1, 2, 2, 1))
            zrs = lax.slice(zp, (0, r, s, 0), lim, (1, 2, 2, 1))
            if best is None:
                best, widx, zwin = ars, jnp.zeros(ars.shape, jnp.uint8), zrs
            else:
                gt = ars > best  # strict: the earlier offset keeps ties
                best = jnp.maximum(ars, best)
                widx = jnp.where(gt, jnp.uint8(k), widx)
                zwin = jnp.where(gt, zrs, zwin)

    # relu mask from the recomputed pre-ReLU max (== y > 0), f32 like the
    # Pallas kernel so multi-window sums round identically
    gm = jnp.where(best > 0, g.astype(jnp.float32), 0.0)
    dgamma_t = (gm * zwin).sum(axis=(0, 1, 2))
    dbeta_t = gm.sum(axis=(0, 1, 2))

    gp = lax.pad(gm, jnp.float32(0), ((0, 0, 0), (0, 1, 0), (0, 1, 0), (0, 0, 0)))
    wp = lax.pad(widx, jnp.uint8(255), ((0, 0, 0), (0, 1, 0), (0, 1, 0), (0, 0, 0)))

    def tap(di, dj, r, s):
        gs = lax.slice(gp, (0, di, dj, 0), (b, di + oh, dj + ow, c))
        ws = lax.slice(wp, (0, di, dj, 0), (b, di + oh, dj + ow, c))
        return jnp.where(ws == np.uint8(3 * r + s), gs, jnp.float32(0))

    dx00 = tap(0, 0, 1, 1)
    dx01 = tap(0, 0, 1, 2) + tap(0, 1, 1, 0)
    dx10 = tap(0, 0, 2, 1) + tap(1, 0, 0, 1)
    dx11 = tap(0, 0, 2, 2) + tap(0, 1, 2, 0) + tap(1, 0, 0, 2) + tap(1, 1, 0, 0)
    inner0 = jnp.stack([dx00, dx01], axis=3)
    inner1 = jnp.stack([dx10, dx11], axis=3)
    dy = jnp.stack([inner0, inner1], axis=2).reshape(b, 2 * oh, 2 * ow, c)
    dz = (gamma_t.astype(jnp.float32) * dy).astype(dt)
    return dz, dgamma_t.astype(gamma_t.dtype), dbeta_t.astype(beta_t.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernels (one batch image per grid step, image VMEM-resident)
# ---------------------------------------------------------------------------

def _window_view(ext, r, s, row0, nrows, oh, c):
    """Window-offset (r, s) rows [row0, row0+nrows) of an extended
    [2*rh, 2*(oh+1), c] plane as [nrows, oh, c], via parity reshapes +
    unit-stride slices (Mosaic has no stride-2 vector slices).

    Window row w covers ext rows [2w, 2w+3); offset r contributes ext row
    2w + r, which in the (rh, 2)-split is (w + r // 2, r % 2)."""
    rh = ext.shape[0] // 2
    oh1 = oh + 1
    x = ext.reshape(rh, 2, 2 * oh1, c)
    x = lax.slice(x, (row0 + r // 2, r % 2, 0, 0),
                  (row0 + r // 2 + nrows, r % 2 + 1, 2 * oh1, c))
    x = x.reshape(nrows, 2 * oh1, c)
    x = x.reshape(nrows, oh1, 2, c)
    x = lax.slice(x, (0, s // 2, s % 2, 0),
                  (nrows, s // 2 + oh, s % 2 + 1, c)).reshape(nrows, oh, c)
    return x


def _row_chunk(oh):
    """Output-row chunk size: bounds Mosaic's VMEM stack (live vector temps
    scale with the chunk) while keeping the static loop short."""
    return 8 if oh % 8 == 0 else oh


def _fwd_kernel(z_ref, gam_ref, bet_ref, y_ref, aext):
    # compute in f32: Mosaic's bf16 vectors need 16-multiple sublane dims,
    # which the 56/57-sized window views violate; f32 also upgrades the
    # affine's precision for free (one rounding at the output)
    h = z_ref.shape[1]
    oh = y_ref.shape[1]
    c = z_ref.shape[3]
    a = gam_ref[:] * z_ref[0].astype(jnp.float32) + bet_ref[:]
    aext[:] = jnp.full(aext.shape, -jnp.inf, jnp.float32)
    aext[1:h + 1, 1:h + 1, :] = a
    ext = aext[:]
    ch = _row_chunk(oh)
    for t in range(oh // ch):
        best = None
        for r in range(3):
            for s in range(3):
                ars = _window_view(ext, r, s, t * ch, ch, oh, c)
                best = ars if best is None else jnp.maximum(best, ars)
        y_ref[0, t * ch:(t + 1) * ch, :, :] = (
            jnp.maximum(best, 0.0).astype(y_ref.dtype)
        )


def _bwd_kernel(z_ref, g_ref, gam_ref, bet_ref,
                dz_ref, dgam_ref, dbet_ref,
                aext, zext, gscr, wscr):
    h = z_ref.shape[1]
    oh = g_ref.shape[1]
    c = z_ref.shape[3]

    @pl.when(pl.program_id(0) == 0)
    def _():
        dgam_ref[:] = jnp.zeros_like(dgam_ref)
        dbet_ref[:] = jnp.zeros_like(dbet_ref)

    z = z_ref[0].astype(jnp.float32)
    a = gam_ref[:] * z + bet_ref[:]
    # rows run to 2*(oh+2) so the phantom window row w == oh (needed by the
    # +1-row taps) reads -inf and contributes nothing
    aext[:] = jnp.full(aext.shape, -jnp.inf, jnp.float32)
    aext[1:h + 1, 1:h + 1, :] = a
    # zext borders are never selected (their affine is -inf): interior only
    zext[1:h + 1, 1:h + 1, :] = z
    aext_v, zext_v = aext[:], zext[:]

    ch = _row_chunk(oh)
    gam = gam_ref[:]
    for t in range(oh // ch):
        w0 = t * ch
        nw = ch + 1           # one extra window row for the di == 1 taps
        nreal = min(nw, oh - w0)

        best = widx = zwin = None
        for r in range(3):
            for s in range(3):
                k = 3 * r + s
                ars = _window_view(aext_v, r, s, w0, nw, oh, c)
                zrs = _window_view(zext_v, r, s, w0, nw, oh, c)
                if best is None:
                    best, zwin = ars, zrs
                    widx = jnp.zeros(ars.shape, jnp.int32)
                else:
                    gt = ars > best
                    best = jnp.maximum(ars, best)
                    widx = jnp.where(gt, jnp.int32(k), widx)
                    zwin = jnp.where(gt, zrs, zwin)

        gscr[:] = jnp.zeros(gscr.shape, jnp.float32)
        gscr[:nreal, :oh, :] = g_ref[0, w0:w0 + nreal, :, :].astype(jnp.float32)
        graw = gscr[:nw, :oh, :]
        gm = jnp.where(best > 0, graw, 0.0)
        # affine grads sum over THIS chunk's ch owned window rows only —
        # the +1 overlap row (needed by the di == 1 taps below) belongs to
        # the next chunk, which sums it itself
        dgam_ref[:] = dgam_ref[:] + (gm[:ch] * zwin[:ch]).sum(axis=(0, 1))
        dbet_ref[:] = dbet_ref[:] + gm[:ch].sum(axis=(0, 1))

        # re-store the masked gradient + winner index with a zero/255 apron
        # so the four parity taps can read one row/col beyond the chunk
        gscr[:] = jnp.zeros(gscr.shape, jnp.float32)
        gscr[:nw, :oh, :] = gm
        wscr[:] = jnp.full(wscr.shape, 255, jnp.int32)
        wscr[:nw, :oh, :] = widx
        gscr_v, wscr_v = gscr[:], wscr[:]

        def tap(di, dj, r, s):
            gs = lax.slice(gscr_v, (di, dj, 0), (di + ch, dj + oh, c))
            ws = lax.slice(wscr_v, (di, dj, 0), (di + ch, dj + oh, c))
            return jnp.where(ws == 3 * r + s, gs, 0.0)

        dx00 = tap(0, 0, 1, 1)
        dx01 = tap(0, 0, 1, 2) + tap(0, 1, 1, 0)
        dx10 = tap(0, 0, 2, 1) + tap(1, 0, 0, 1)
        dx11 = (tap(0, 0, 2, 2) + tap(0, 1, 2, 0)
                + tap(1, 0, 0, 2) + tap(1, 1, 0, 0))
        inner0 = jnp.stack([dx00, dx01], axis=2)
        inner1 = jnp.stack([dx10, dx11], axis=2)
        dy = jnp.stack([inner0, inner1], axis=1).reshape(2 * ch, 2 * oh, c)
        dz_ref[0, 2 * w0:2 * (w0 + ch), :, :] = (gam * dy).astype(dz_ref.dtype)


def _pallas_ok(z):
    b, h, w, c = z.shape
    # even square spatial dims; channel dim a clean lane multiple (the
    # resnet stem's 64) — Mosaic mishandles sub-8 lane dims
    return h == w and h % 2 == 0 and h >= 4 and c % 64 == 0


def _fwd_pallas(z, gamma_t, beta_t, interpret=False):
    b, h, w, c = z.shape
    oh = h // 2
    return pl.pallas_call(
        _fwd_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, oh, oh, c), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, oh, oh, c), z.dtype),
        scratch_shapes=[pltpu.VMEM((h + 2, h + 2, c), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )(z, gamma_t.astype(jnp.float32), beta_t.astype(jnp.float32))


def _bwd_pallas(z, gamma_t, beta_t, g, interpret=False):
    b, h, w, c = z.shape
    oh = h // 2
    dz, dgam, dbet = pl.pallas_call(
        _bwd_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, oh, oh, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, w, c), z.dtype),
            jax.ShapeDtypeStruct((c,), jnp.float32),
            jax.ShapeDtypeStruct((c,), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((h + 4, h + 2, c), jnp.float32),
            pltpu.VMEM((h + 4, h + 2, c), jnp.float32),
            pltpu.VMEM((_row_chunk(oh) + 8, oh + 8, c), jnp.float32),
            pltpu.VMEM((_row_chunk(oh) + 8, oh + 8, c), jnp.int32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )(z, g, gamma_t.astype(jnp.float32), beta_t.astype(jnp.float32))
    return dz, dgam.astype(gamma_t.dtype), dbet.astype(beta_t.dtype)


# ---------------------------------------------------------------------------
# public custom-VJP op
# ---------------------------------------------------------------------------

def _use_pallas(z):
    return jax.default_backend() == "tpu" and _pallas_ok(z)


@partial(jax.custom_vjp)
def _affine_relu_pool_even(z, gamma_t, beta_t):
    if _use_pallas(z):
        return _fwd_pallas(z, gamma_t, beta_t)
    return _fwd_xla(z, gamma_t, beta_t)


def _arp_fwd(z, gamma_t, beta_t):
    y = _affine_relu_pool_even(z, gamma_t, beta_t)
    # y is NOT a residual: backward recomputes the window max (which also
    # yields the relu mask), so the pooled activation can die after use
    return y, (z, gamma_t, beta_t)


def _arp_bwd(res, g):
    z, gamma_t, beta_t = res
    if _use_pallas(z):
        return _bwd_pallas(z, gamma_t, beta_t, g)
    return _bwd_xla(z, gamma_t, beta_t, g)


_affine_relu_pool_even.defvjp(_arp_fwd, _arp_bwd)


def affine_relu_pool(z, gamma_t, beta_t):
    """maxpool_3x3s2p1(relu(gamma_t * z + beta_t)) with a fused backward.

    ``z``: NHWC; ``gamma_t``/``beta_t``: per-channel affine. Even spatial
    dims run the custom-VJP region (Pallas kernels on TPU when the shape
    qualifies, pure-XLA reference otherwise — identical semantics). Odd
    dims fall back to the plain composition, whose backward is XLA's own
    select_and_scatter: the fused backward's parity interleave only
    reconstructs 2*oh x 2*ow planes.
    """
    if z.shape[1] % 2 or z.shape[2] % 2:
        a = gamma_t.astype(jnp.float32) * z.astype(jnp.float32) \
            + beta_t.astype(jnp.float32)
        pooled = lax.reduce_window(
            a, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
            ((0, 0), (1, 1), (1, 1), (0, 0)),
        )
        return jnp.maximum(pooled, 0.0).astype(z.dtype)
    return _affine_relu_pool_even(z, gamma_t, beta_t)
