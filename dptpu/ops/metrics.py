"""Top-k accuracy.

TPU-native equivalent of the reference's ``accuracy(output, target, topk)``
(imagenet_ddp.py:381-395): top-k predictions via ``lax.top_k`` (compiles to a
single fused TPU sort/select instead of the reference's
topk→transpose→eq→expand chain), returning percentages ``×100/batch`` with
identical semantics. jit-friendly — no host sync; callers pull scalars out
once per print interval, mirroring the Apex script's advice to avoid
per-step device→host syncs (imagenet_ddp_apex.py:386-388).
"""

import jax
import jax.numpy as jnp


def topk_correct_fraction(logits, labels, topk=(1,)):
    """Fraction of examples whose label is within the top-k predictions.

    Returns a tuple of scalar f32 fractions in [0, 1], one per k.
    """
    num_classes = logits.shape[-1]
    maxk = min(max(topk), num_classes)  # tiny heads: clamp k (k ≤ classes)
    _, pred = jax.lax.top_k(logits, maxk)  # [batch, maxk]
    correct = pred == labels[:, None]  # [batch, maxk] bool
    fractions = []
    for k in topk:
        fractions.append(
            correct[:, : min(k, maxk)].any(axis=1).mean(dtype=jnp.float32)
        )
    return tuple(fractions)


def accuracy(logits, labels, topk=(1,)):
    """Percent accuracy over the k top predictions, reference semantics
    (imagenet_ddp.py:381-395): returns one value per k, scaled ×100."""
    return tuple(f * 100.0 for f in topk_correct_fraction(logits, labels, topk))
