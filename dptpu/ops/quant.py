"""Per-channel weight quantization primitives for the serve fast path.

Post-training, weight-only: matmul/conv kernels (any param leaf with
``ndim >= 2``) are stored int8 with one fp32 scale per OUTPUT channel
(last axis — Flax kernels are ``(..., in, out)``); everything rank-0/1
(biases, norm scales/offsets, layer_scale) stays fp32 — those leaves
are a rounding error of the residency bill and quantizing norms is
where PTQ accuracy actually dies. Compute dequantizes in-graph to
bf16, so the compiled forward carries ``s8`` parameters and ``bf16``
dots (asserted from HLO by the serve-quant budget config in ``dptpu
check`` — a silent fp32 fallback fails statically).

The scheme is symmetric absmax: ``scale = max|w_channel| / 127``,
``q = round(w / scale)`` — zero-point-free, so dequantization is one
multiply. Scales are computed offline by ``dptpu quantize`` and travel
in the CRC-sealed calibration artifact (dptpu/serve/quant.py), NOT
recomputed at load: the artifact is the provenance record that ties a
quantized deployment to the exact weights it was calibrated against.

Quantized trees keep the original nesting but each quantized leaf
becomes a ``{"q": int8, "scale": fp32}`` marker dict — walkable by the
same recursion everywhere (:func:`is_quantized_leaf`), and a pytree
jax can place/donate like any other.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

# Symmetric int8: the full signed range less -128 (absmax maps to +/-127
# exactly; keeping the range symmetric makes q = -q for w = -w).
QMAX = 127.0

# A channel of exact zeros gets scale EPS instead of 0 so dequantize is
# division-free and never NaNs; its q values are all 0 either way.
_SCALE_EPS = 1e-12


def quantizable(leaf) -> bool:
    """True for leaves that take per-channel int8: real matmul/conv
    kernels (``ndim >= 2``). Rank-0/1 leaves (bias/norm/scale) pass
    through fp32."""
    return hasattr(leaf, "ndim") and leaf.ndim >= 2


def channel_scales(w) -> np.ndarray:
    """fp32 absmax scale per last-axis (output) channel, shape
    ``w.shape[-1:]`` broadcast-ready against ``w``."""
    a = np.asarray(w, np.float32)
    reduce_axes = tuple(range(a.ndim - 1))
    s = np.max(np.abs(a), axis=reduce_axes) / QMAX
    return np.maximum(s, _SCALE_EPS).astype(np.float32)


def quantize_leaf(w, scale=None) -> Tuple[np.ndarray, np.ndarray]:
    """``(q_int8, scale_fp32)`` for one kernel leaf. ``scale`` from a
    calibration artifact wins; absent, it is computed from ``w``."""
    a = np.asarray(w, np.float32)
    if scale is None:
        scale = channel_scales(a)
    scale = np.asarray(scale, np.float32)
    q = np.clip(np.rint(a / scale), -QMAX, QMAX).astype(np.int8)
    return q, scale


def dequantize_leaf(q, scale, dtype=jnp.bfloat16):
    """In-graph dequantize: one convert + one broadcast multiply. Scales
    multiply in fp32 THEN cast — quantization error stays the rounding
    of q, not compounded by a bf16 scale."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def is_quantized_leaf(node) -> bool:
    """A ``{"q": ..., "scale": ...}`` marker dict produced by
    :func:`quantize_tree`."""
    return (isinstance(node, dict) and set(node) == {"q", "scale"}
            and hasattr(node["q"], "dtype"))


def quantize_tree(params: dict, scales: dict = None) -> dict:
    """Quantize a (nested-dict) param tree: quantizable leaves become
    ``{"q", "scale"}`` markers, the rest pass through as fp32 np arrays.
    ``scales`` (same nesting, leaves = per-channel scale arrays or None)
    comes from the calibration artifact; None recomputes from weights.
    """
    def walk(node, snode):
        if isinstance(node, dict):
            return {k: walk(v, None if snode is None else snode.get(k))
                    for k, v in node.items()}
        if quantizable(node):
            if snode is not None and getattr(snode, "size", 1) == 0:
                snode = None  # placeholder row: recompute (deterministic)
            q, s = quantize_leaf(node, snode)
            return {"q": q, "scale": s}
        return np.asarray(node, np.float32)

    return walk(params, scales)


def scales_tree(params: dict) -> dict:
    """The calibration payload: same nesting as ``params``, quantizable
    leaves carry their per-channel fp32 scales, others an empty fp32
    array (msgpack-serializable placeholder — ``quantize_tree`` treats
    size-0 as 'recompute', but absmax scales are deterministic so the
    placeholder never matters in practice)."""
    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if quantizable(node):
            return channel_scales(node)
        return np.zeros((0,), np.float32)

    return walk(params)


def dequantize_tree(qparams: dict, dtype=jnp.bfloat16):
    """The in-forward walk: marker leaves dequantize to ``dtype``, fp32
    passthrough leaves are left untouched (norms/bias stay fp32 — mixed
    precision exactly like the bf16 train step keeps its norm params)."""
    def walk(node):
        if is_quantized_leaf(node):
            return dequantize_leaf(node["q"], node["scale"], dtype)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(qparams)


def cast_tree(params: dict, dtype=jnp.bfloat16) -> dict:
    """The bf16 precision arm: quantizable (matmul) leaves cast to
    ``dtype`` for residency + compute, rank-0/1 leaves stay fp32."""
    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if quantizable(node):
            return np.asarray(node, dtype)
        return np.asarray(node, np.float32)

    return walk(params)


def tree_nbytes(tree) -> int:
    """Resident bytes of a (possibly quantized) variables tree — the
    HBM-residency meter SERVEBENCH's quantized arm reports."""
    total = 0

    def walk(node):
        nonlocal total
        if isinstance(node, dict):
            for v in node.values():
                walk(v)
        elif hasattr(node, "nbytes"):
            total += int(node.nbytes)

    walk(tree)
    return total
