"""Online telemetry→knob controllers (ISSUE 19 tentpole, half b).

The PR-11 ``StragglerController`` proved the shape of a live control
loop this codebase will accept: streaming estimators over telemetry the
hot path already produces, a PERSISTENCE requirement before any verdict
(one bad interval is noise), a fresh-verdict window after every
escalation, and actuation through existing seams that no-op safely.
:class:`Actuator` generalizes it:

* **bounded** — each actuator owns ONE monotonic adjustment direction
  (deepen decode-ahead, densify the serve ladder, declare a host lost)
  with an explicit action budget and a seam that returns None at its
  bound, so the loop can tighten a knob but never wander the knob
  space or oscillate (there is no reverse actuation to oscillate
  with);
* **rate-limited** — evaluation (including the telemetry read) runs at
  most once per ``interval_s``, so a controller can ride a per-step
  hook without turning the KV store or the batcher lock into a hot
  path;
* **loud** — every verdict, actuation, and disarm lands in the event
  log and the ``on_event`` callback (fit publishes them through obs);
* **individually disarmable** — ``DPTPU_TUNE_CONTROL`` names the armed
  set; an actuator also disarms ITSELF the moment its seam reports no
  headroom or its budget is spent, and a disarmed actuator never reads
  telemetry again.

Tick placement (CONCURRENCY.md): no new threads. In fit the controller
ticks on the host thread inside the existing post-step hook (after the
straggler tick); in serve it ticks on ``dptpu-serve-dispatch`` between
batches, holding no lock — each actuator's seam takes its own locks in
rank order.
"""

from __future__ import annotations

import time


class Actuator:
    """One bounded control loop: ``read()`` telemetry at most once per
    ``interval_s``; ``persist`` consecutive over-``threshold`` verdicts
    fire ``act(value)``; a None read freezes the verdict (no fresh
    evidence — the straggler controller's evidence rule); a None act
    result or an exhausted ``max_actions`` budget disarms, loudly."""

    def __init__(self, name: str, read, act, threshold: float, *,
                 persist: int = 3, interval_s: float = 10.0,
                 max_actions: int = 1, on_event=None,
                 clock=time.monotonic):
        if persist < 1:
            raise ValueError(f"{name}: persist={persist} must be >= 1")
        if interval_s <= 0:
            raise ValueError(
                f"{name}: interval_s={interval_s} must be > 0"
            )
        if max_actions < 1:
            raise ValueError(
                f"{name}: max_actions={max_actions} must be >= 1"
            )
        self.name = name
        self.read = read
        self.act = act
        self.threshold = float(threshold)
        self.persist = int(persist)
        self.interval_s = float(interval_s)
        self.max_actions = int(max_actions)
        self.on_event = on_event
        self.clock = clock
        # all mutable verdict state below is owned-by: tick-thread — exactly
        # one thread ever ticks a given actuator (the train loop in fit,
        # dptpu-serve-dispatch in serve; CONCURRENCY.md controller-tick
        # table), so no lock: the single-writer StragglerController argument
        self.armed = True
        self.disarm_reason = None
        self.actions = 0
        self.last_value = None
        self.events = []
        self._strikes = 0
        self._last_eval = None

    def _emit(self, kind: str, payload: dict):
        evt = {"kind": kind, "actuator": self.name, **payload}
        self.events.append(evt)
        if self.on_event is not None:
            try:
                self.on_event(kind, evt)
            except Exception:
                pass

    def disarm(self, reason: str):
        if self.armed:
            self.armed = False
            self.disarm_reason = reason
            self._emit("tune_disarm", {"reason": reason})

    def tick(self):
        """Returns the actuation payload when this tick actuated, else
        None. Never raises: a failing read or seam disarms loudly
        instead of taking the train/serve loop down with it."""
        if not self.armed:
            return None
        now = self.clock()
        if self._last_eval is not None \
                and now - self._last_eval < self.interval_s:
            return None
        self._last_eval = now
        try:
            value = self.read()
        except Exception as e:
            self.disarm(f"telemetry read failed: {e}")
            return None
        if value is None:
            return None  # no fresh evidence: the verdict freezes
        self.last_value = value
        if value <= self.threshold:
            self._strikes = 0
            return None
        self._strikes += 1
        self._emit("tune_verdict", {
            "value": round(float(value), 6),
            "threshold": self.threshold,
            "strikes": self._strikes,
        })
        if self._strikes < self.persist:
            return None
        self._strikes = 0  # fresh verdict window after every actuation
        try:
            result = self.act(value)
        except Exception as e:
            self.disarm(f"actuation failed: {e}")
            return None
        if result is None:
            self.disarm("no headroom at the seam")
            return None
        self.actions += 1
        self._emit("tune_actuate", {
            "value": round(float(value), 6), "result": result,
            "actions": self.actions,
        })
        if self.actions >= self.max_actions:
            self.disarm("action budget spent")
        return result

    def stats(self) -> dict:
        return {
            "name": self.name,
            "armed": self.armed,
            "disarm_reason": self.disarm_reason,
            "actions": self.actions,
            "last_value": self.last_value,
            "events": list(self.events),
        }


class Controller:
    """A named set of actuators sharing one tick source."""

    def __init__(self, actuators=()):
        self.actuators = list(actuators)

    def add(self, actuator: Actuator):
        self.actuators.append(actuator)

    def tick(self):
        for a in self.actuators:
            a.tick()

    def stats(self) -> dict:
        return {a.name: a.stats() for a in self.actuators}


# -- the three ISSUE 19 actuators, built on existing seams ---------------


def host_lost_actuator(coord, on_lost, *, deadline_s: float,
                       interval_s: float = 10.0, persist: int = 2,
                       on_event=None, clock=time.monotonic) -> Actuator:
    """Auto-arm the heartbeat-driven host-lost verdict (PR 11 follow-on
    (b)): poll ``QuorumCoordinator.missing_hosts`` — fed by every
    host's dedicated beat thread — and once hosts stay silent past the
    deadline for ``persist`` evaluations, fire ``on_lost(missing)``
    exactly once (fit's ``_host_lost``: finish the epoch, sync save,
    exit for the elastic restart). One action, then disarmed: declaring
    the pod smaller twice has no meaning."""

    def read():
        return float(len(coord.missing_hosts(deadline_s)))

    def act(_value):
        missing = coord.missing_hosts(deadline_s)
        if not missing:
            return None  # the host came back between verdict and act
        on_lost(missing)
        return {"missing_hosts": list(missing)}

    return Actuator("host_lost", read, act, threshold=0.0,
                    persist=persist, interval_s=interval_s,
                    max_actions=1, on_event=on_event, clock=clock)


def decode_ahead_actuator(loader, *, interval_s: float = 10.0,
                          persist: int = 3, io_fraction: float = 0.25,
                          max_ahead: int = 16, max_actions: int = 4,
                          on_event=None,
                          clock=time.monotonic) -> Actuator:
    """Deepen the feed's issue window while the parent spends more than
    ``io_fraction`` of its wall time blocked on spans: reads the
    CUMULATIVE ring io-wait (never the obs interval — that belongs to
    feed_stats), differentiates it over its own evaluation interval,
    and steps ``DataLoader.grow_decode_ahead`` — one batch per
    actuation, capped by the ring and ``max_ahead``, effective at the
    next epoch's pipeline build. Monotonic: the window only deepens, so
    the loop cannot oscillate; the seam's None (bound reached / thread
    mode) disarms it.

    ``loader`` may be a zero-arg callable returning the CURRENT loader:
    the DPTPU_BATCH_RAMP phase switch rebuilds the pool, and the
    actuator must follow the rebuild rather than keep a handle to a
    closed loader. A rebuild resets the cumulative counter, which shows
    up here as a negative interval — below any threshold, so the strike
    window naturally re-baselines."""

    get = loader if callable(loader) else (lambda: loader)
    state = {"wait": None, "t": None}

    def read():
        wait, t = get().io_wait_total_s(), clock()
        prev_wait, prev_t = state["wait"], state["t"]
        state["wait"], state["t"] = wait, t
        if prev_t is None or t <= prev_t:
            return None  # first evaluation: baseline only
        return (wait - prev_wait) / (t - prev_t)

    def act(_value):
        new = get().grow_decode_ahead(max_ahead=max_ahead)
        if new is None:
            return None
        return {"decode_ahead": new}

    return Actuator("decode_ahead", read, act, threshold=io_fraction,
                    persist=persist, interval_s=interval_s,
                    max_actions=max_actions, on_event=on_event,
                    clock=clock)


def serve_ladder_actuator(engine, batcher, *, interval_s: float = 10.0,
                          persist: int = 3, waste: float = 0.25,
                          max_actions: int = 4, on_event=None,
                          clock=time.monotonic) -> Actuator:
    """Densify the serve bucket ladder under sustained padding waste:
    reads the batcher's cumulative pad/exec row counters (interval
    ratio over its own evaluation window), and inserts the midpoint of
    the ladder's widest multiplicative gap via
    ``ServeEngine.add_bucket`` — compiled before publication, never
    past ``max_bucket`` (admission never moves). Monotonic densify-only
    with a hard action budget; a gapless ladder disarms it."""

    state = {"pad": None, "exec": None}

    def read():
        pad, ex = batcher.padding_counts()
        prev_pad, prev_ex = state["pad"], state["exec"]
        state["pad"], state["exec"] = pad, ex
        if prev_ex is None or ex <= prev_ex:
            return None  # no batches this interval: verdict freezes
        return (pad - prev_pad) / (ex - prev_ex)

    def act(_value):
        ladder = engine.buckets
        best, best_ratio = None, 1.0
        for lo, hi in zip(ladder, ladder[1:]):
            mid = (lo + hi) // 2
            if mid <= lo or mid >= hi:
                continue
            ratio = hi / lo
            if ratio > best_ratio:
                best, best_ratio = mid, ratio
        if best is None:
            return None  # gapless ladder: nothing left to densify
        added = engine.add_bucket(best)
        if added is None:
            return None
        return {"bucket": added, "ladder": list(engine.buckets)}

    return Actuator("serve_ladder", read, act, threshold=waste,
                    persist=persist, interval_s=interval_s,
                    max_actions=max_actions, on_event=on_event,
                    clock=clock)


__all__ = [
    "Actuator",
    "Controller",
    "decode_ahead_actuator",
    "host_lost_actuator",
    "serve_ladder_actuator",
]
