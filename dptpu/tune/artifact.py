"""TUNING.json — the committed, sealed output of ``dptpu tune``.

An artifact is a plain-JSON record (committed, diffable, precommit-
validated) of the knob values the offline search picked on a given
host, sealed with the same never-silent discipline as the quantization
calibration artifact (dptpu/serve/quant.py): a CRC over the canonical
payload so bit rot or a hand-edit fails the load by name, a
``host`` provenance stamp so a future reader can tell which hardware
produced the numbers, and the objective scores the winner beat.

Precedence (the ISSUE 19 contract, locked in tests/test_tune.py):
**explicit knobs always win.** ``apply_tuning`` env-injects a tuned
value ONLY when its env twin is unset/empty and its CLI twin was not
explicitly given (callers pass the names their CLI already bound);
every applied value and every explicit override is named in one loud
banner — a run never silently trains under tuned knobs.

Stdlib-only: fit()/serve() load the artifact pre-jax, and the
precommit hook validates it with no heavyweight imports.
"""

from __future__ import annotations

import json
import os
import zlib

from dptpu.envknob import env_float, env_str

TUNING_SCHEMA = "dptpu-tuning-v1"

# the knob space `dptpu tune` searches — an artifact may carry any
# subset of these; anything else fails the load (a registry drift or a
# hand-edit, either way not a tuner output)
TUNABLE_KNOBS = (
    "DPTPU_BUCKET_MB",
    "DPTPU_RING_DEPTH",
    "DPTPU_DECODE_AHEAD",
    "DPTPU_CACHE_SCOPE",
    "DPTPU_CACHE_BYTES",
    "DPTPU_SERVE_BUCKETS",
    "DPTPU_ACCUM",
)

DEFAULT_TUNE_INTERVAL_S = 10.0
ACTUATOR_NAMES = ("host_lost", "decode_ahead", "serve_ladder")


class TuningError(ValueError):
    """A tuning artifact that cannot be trusted — every message names
    the re-tune command."""


def _retune_cmd(path: str) -> str:
    return f"dptpu tune --out {path}"


def _payload_crc(payload: dict) -> str:
    canon = json.dumps(payload, sort_keys=True,
                       separators=(",", ":")).encode()
    return f"{zlib.crc32(canon) & 0xFFFFFFFF:08x}"


def save_tuning(path: str, knobs: dict, objective: dict,
                probes: dict, host: dict) -> dict:
    """Seal + write a tuning artifact; returns the full record."""
    bad = sorted(k for k in knobs if k not in TUNABLE_KNOBS)
    if bad:
        raise TuningError(
            f"tuning artifact refuses non-tunable knob(s) "
            f"{', '.join(bad)} — the searchable space is "
            f"{', '.join(TUNABLE_KNOBS)}"
        )
    payload = {
        "schema": TUNING_SCHEMA,
        "knobs": {k: str(v) for k, v in sorted(knobs.items())},
        "objective": objective,
        "probes": probes,
        "host": host,
    }
    record = dict(payload)
    record["crc32"] = _payload_crc(payload)
    with open(path, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    return record


def load_tuning(path: str) -> dict:
    """Load + verify a tuning artifact; every failure is a
    :class:`TuningError` naming the re-tune command.

    Checks, in order: file present and parseable → schema known → CRC
    seal present AND matching the canonical payload → every knob name
    tunable with a string value."""
    cmd = _retune_cmd(path)
    if not os.path.exists(path):
        raise TuningError(
            f"tuning artifact {path} does not exist — run: {cmd}"
        )
    try:
        with open(path) as f:
            record = json.load(f)
    except Exception as e:
        raise TuningError(
            f"tuning artifact {path} is not JSON ({e}) — re-tune "
            f"with: {cmd}"
        ) from e
    if not isinstance(record, dict) \
            or record.get("schema") != TUNING_SCHEMA:
        raise TuningError(
            f"tuning artifact {path}: schema "
            f"{record.get('schema') if isinstance(record, dict) else None!r}"
            f" != {TUNING_SCHEMA!r} — not a dptpu tune output (or from "
            f"an incompatible version); re-tune with: {cmd}"
        )
    crc = record.get("crc32")
    payload = {k: v for k, v in record.items() if k != "crc32"}
    if not crc:
        raise TuningError(
            f"tuning artifact {path} has no crc32 seal — truncated or "
            f"hand-built; re-tune with: {cmd}"
        )
    want = _payload_crc(payload)
    if crc != want:
        raise TuningError(
            f"tuning artifact {path} fails its CRC seal (stamped {crc}, "
            f"payload {want}) — bit rot or a hand-edit; re-tune with: "
            f"{cmd}"
        )
    knobs = record.get("knobs")
    if not isinstance(knobs, dict) or not knobs:
        raise TuningError(
            f"tuning artifact {path} carries no tuned knobs — re-tune "
            f"with: {cmd}"
        )
    for k, v in knobs.items():
        if k not in TUNABLE_KNOBS:
            raise TuningError(
                f"tuning artifact {path} names {k}, which is not in "
                f"the tunable set ({', '.join(TUNABLE_KNOBS)}) — "
                f"artifact/registry drift; re-tune with: {cmd}"
            )
        if not isinstance(v, str):
            raise TuningError(
                f"tuning artifact {path}: {k}={v!r} must be a string "
                f"(env-injection value); re-tune with: {cmd}"
            )
    return record


def apply_tuning(path: str, *, cli_set=(), environ=None,
                 log=print) -> dict:
    """Env-inject the artifact's knobs under the explicit-wins rule.

    ``cli_set`` is the set of knob names whose CLI twin the caller saw
    explicitly (e.g. ``--accum-steps`` → ``DPTPU_ACCUM``); those and
    any knob whose env twin is already set are SKIPPED — the tuned
    value never beats an operator's hand. Returns
    ``{"applied": {...}, "overridden": {...}}`` and prints ONE banner
    naming every decision (never a silent knob change)."""
    env = environ if environ is not None else os.environ
    record = load_tuning(path)
    applied, overridden = {}, {}
    cli_set = set(cli_set)
    for name, value in sorted(record["knobs"].items()):
        if env.get(name):
            overridden[name] = f"env {name}={env[name]}"
        elif name in cli_set:
            overridden[name] = "explicit CLI flag"
        else:
            env[name] = value
            applied[name] = value
    host = record.get("host") or {}
    lines = [f"TUNING: artifact {path} "
             f"(tuned on {host.get('platform', 'unknown host')}, "
             f"crc {record['crc32']})"]
    for k, v in applied.items():
        lines.append(f"TUNING:   applied {k}={v}")
    for k, why in overridden.items():
        lines.append(f"TUNING:   kept explicit {k} ({why})")
    if log is not None:
        log("\n".join(lines))
    return {"applied": applied, "overridden": overridden,
            "artifact": path, "crc32": record["crc32"]}


def tune_knobs(environ=None) -> dict:
    """The ``DPTPU_TUNE_*`` env knobs, under the locked fail-fast
    contract:

    * ``DPTPU_TUNE_ARTIFACT`` — path to a ``dptpu tune`` output;
      fit()/serve() load + apply it (explicit knobs win). Empty =
      no artifact (the default);
    * ``DPTPU_TUNE_CONTROL`` — arm the online controllers: ``all``,
      ``off`` (default), or a comma list from
      ``host_lost``/``decode_ahead``/``serve_ladder`` — each actuator
      individually disarmable;
    * ``DPTPU_TUNE_INTERVAL_S`` — minimum seconds between any two
      actuations of one controller (> 0, default 10): the rate limit
      that keeps the loop from oscillating faster than its telemetry
      settles.
    """
    raw_art = env_str("DPTPU_TUNE_ARTIFACT", "", environ)
    raw_ctl = env_str("DPTPU_TUNE_CONTROL", "", environ).strip()
    if not raw_ctl or raw_ctl == "off":
        control = ()
    elif raw_ctl == "all":
        control = ACTUATOR_NAMES
    else:
        names = tuple(p.strip() for p in raw_ctl.split(",") if p.strip())
        bad = sorted(set(names) - set(ACTUATOR_NAMES))
        if bad:
            raise ValueError(
                f"DPTPU_TUNE_CONTROL={raw_ctl!r} names unknown "
                f"actuator(s) {', '.join(bad)} — pick from "
                f"{', '.join(ACTUATOR_NAMES)}, or 'all'/'off'"
            )
        control = names
    interval = env_float("DPTPU_TUNE_INTERVAL_S",
                         DEFAULT_TUNE_INTERVAL_S, environ)
    if interval <= 0:
        raise ValueError(
            f"DPTPU_TUNE_INTERVAL_S={interval} must be > 0 seconds "
            f"(the per-controller actuation rate limit)"
        )
    return {
        "artifact": raw_art,
        "control": control,
        "interval_s": float(interval),
    }


__all__ = [
    "ACTUATOR_NAMES",
    "TUNABLE_KNOBS",
    "TUNING_SCHEMA",
    "TuningError",
    "apply_tuning",
    "load_tuning",
    "save_tuning",
    "tune_knobs",
]
