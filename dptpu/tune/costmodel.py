"""The RACEBENCH simulated-pod wall-clock model, as a library.

Extracted verbatim from scripts/run_racebench.py (ISSUE 19) so the
offline autotuner can score bucket-size candidates against the SAME
model the committed RACEBENCH.json rows came from — and so a tier-1
test can lock the extraction as behavior-preserving by recomputing the
committed rows (tests/test_tune_costmodel.py). run_racebench.py now
imports from here; the bench's numbers and gates are unchanged.

Pure stdlib: callers bring their own bucket-size lists (the jax-side
``partition_buckets``/``bucket_sizes_bytes`` stay in
dptpu/parallel/overlap.py — this module must be importable by the CLI
pre-jax).
"""

from __future__ import annotations


def simulate_pod(bucket_bytes_list, compute_s, dcn_gbps, latency_s,
                 slices, inner):
    """The wall-clock model for ONE partition of the gradients.

    ``bucket_bytes_list`` is in ISSUE order (bucket 0 = last layers =
    first gradients backward produces). Returns serial/overlapped wall
    seconds plus the per-bucket event trace."""
    total = sum(bucket_bytes_list) or 1
    bw = dcn_gbps * 1e9
    ring = 2.0 * (slices - 1) / slices

    def comm_s(nbytes):
        return latency_s + ring * (nbytes / inner) / bw

    # backward produces bucket k's gradients after its proportional
    # compute segment (recorded assumption: FLOPs track bytes)
    ready, acc = [], 0.0
    for b in bucket_bytes_list:
        acc += compute_s * (b / total)
        ready.append(acc)
    # overlapped: FIFO DCN channel, a bucket issues when ready
    t_chan = 0.0
    events = []
    for b, r in zip(bucket_bytes_list, ready):
        start = max(r, t_chan)
        t_chan = start + comm_s(b)
        events.append({"bytes": b, "grads_ready_s": round(r, 6),
                       "comm_start_s": round(start, 6),
                       "comm_end_s": round(t_chan, 6)})
    overlapped = max(compute_s, t_chan)
    serial = compute_s + sum(comm_s(b) for b in bucket_bytes_list)
    return {"serial_s": serial, "overlapped_s": overlapped,
            "exposed_comm_s": max(0.0, overlapped - compute_s),
            "events": events}


def model_row(anchor, t_compute, bucket_mb, sizes, perleaf_sizes,
              dcn_gbps, latency_s, slices, inner):
    """One RACEBENCH ``simulated_pod`` row: the overlapped/serial/
    per-leaf walls for one (compute anchor, bucket size, bandwidth)
    point, with the rounding the committed artifact carries."""
    sim = simulate_pod(sizes, t_compute, dcn_gbps, latency_s,
                       slices, inner)
    perleaf = simulate_pod(perleaf_sizes, t_compute, dcn_gbps,
                           latency_s, slices, inner)
    comm_s = sim["serial_s"] - t_compute
    return {
        "compute_anchor": anchor,
        "compute_ms": round(t_compute * 1e3, 3),
        "bucket_mb": bucket_mb,
        "buckets": len(sizes),
        "dcn_gbps": dcn_gbps,
        "serial_ms": round(sim["serial_s"] * 1e3, 3),
        "overlapped_ms": round(sim["overlapped_s"] * 1e3, 3),
        "exposed_comm_ms": round(sim["exposed_comm_s"] * 1e3, 3),
        # the REAL overlap statement: what fraction of the
        # communication disappears under backward (a lost win shows
        # here even though overlapped < serial holds trivially for any
        # >= 2-bucket partition)
        "hidden_comm_fraction": round(
            1.0 - sim["exposed_comm_s"] / max(comm_s, 1e-12), 4),
        "speedup": round(
            sim["serial_s"] / max(sim["overlapped_s"], 1e-12), 3),
        "perleaf_serial_ms": round(perleaf["serial_s"] * 1e3, 3),
        "perleaf_overlapped_ms": round(perleaf["overlapped_s"] * 1e3, 3),
    }


def greedy_bucket_sizes(leaf_bytes, bucket_bytes):
    """The engine's greedy partition over a leaf-byte list, payload
    bytes only (dptpu/parallel/overlap.py ``partition_buckets`` without
    the pytree or the dtype splits): a bucket closes when adding the
    next leaf would exceed ``bucket_bytes`` (an over-sized leaf still
    gets its own bucket). ``leaf_bytes`` must already be in issue order
    (reverse flatten order). Lets the tuner sweep candidate bucket
    sizes from a recorded leaf-byte profile without building params."""
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes={bucket_bytes} must be > 0")
    sizes, acc = [], 0
    for b in leaf_bytes:
        nb = int(b)
        if acc and acc + nb > bucket_bytes:
            sizes.append(acc)
            acc = 0
        acc += nb
    if acc:
        sizes.append(acc)
    return sizes or [0]


__all__ = ["greedy_bucket_sizes", "model_row", "simulate_pod"]
