"""``dptpu tune``: the offline autotuner → committed TUNING.json.

The artifact is the only way tuned knobs enter a run, and it enters at
the LOWEST precedence: ``fit()``/``dptpu serve`` load it via
``DPTPU_TUNE_ARTIFACT`` and env-inject only knobs nothing else set
(:func:`dptpu.tune.artifact.apply_tuning` — explicit env/CLI always
wins, and a loud banner names every tuned value actually applied).

Search strategy (dptpu/tune/search.py):

* ``DPTPU_BUCKET_MB`` — full candidate sweep against the RACEBENCH
  simulated-pod cost model for the target geometry/DCN (analytic:
  microseconds per candidate).
* ``DPTPU_SERVE_BUCKETS`` — candidate ladders scored analytically
  against a request-size mix; ``--serve-probe`` re-checks the winner
  through a real ``ServeEngine`` + ``DynamicBatcher`` replay.
* ``DPTPU_DECODE_AHEAD`` / ``DPTPU_RING_DEPTH`` / ``DPTPU_CACHE_SCOPE``
  / ``DPTPU_ACCUM`` — measured A/B probes through real ``fit()`` runs
  on synthetic data, interleaved default/candidate pairs in ABBA order;
  a candidate is adopted only when its median paired gain clears the
  default arm's own noise floor (``--probe none`` skips these).

Usage::

    dptpu tune --out TUNING.json [--arch resnet18] [--smoke]
               [--slices 2 --chips-per-slice 2 --dcn-gbps 12.5]
               [--probe quick|none|full] [--serve-probe]

Then: ``DPTPU_TUNE_ARTIFACT=TUNING.json python imagenet_apex.py ...``
"""

from __future__ import annotations

import argparse
import json
import sys


def build_tune_parser():
    p = argparse.ArgumentParser(
        prog="dptpu tune",
        description="offline knob autotuner: cost-model sweep + short "
                    "measured probes -> CRC-sealed TUNING.json "
                    "(loaded via DPTPU_TUNE_ARTIFACT; explicit "
                    "env/CLI knobs always win)",
    )
    p.add_argument("-o", "--out", default="TUNING.json", metavar="PATH",
                   help="artifact output path (default TUNING.json)")
    p.add_argument("-a", "--arch", default="resnet18",
                   help="architecture whose gradient layout the bucket "
                        "sweep scores (default resnet18)")
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--num-classes", type=int, default=16)
    p.add_argument("--slices", type=int, default=2,
                   help="modeled pod slices (cost model)")
    p.add_argument("--chips-per-slice", type=int, default=2)
    p.add_argument("--per-chip-batch", type=int, default=8)
    p.add_argument("--dcn-gbps", type=float, default=12.5,
                   help="modeled per-chip DCN bandwidth (GB/s)")
    p.add_argument("--dcn-latency-us", type=float, default=15.0)
    p.add_argument("--chip-img-per-s", type=float, default=2734.0,
                   help="chip-equivalent compute anchor (BENCH_r04)")
    p.add_argument("--probe", choices=("none", "quick", "full"),
                   default="quick",
                   help="measured fit() probes: none = cost model "
                        "only; quick = decode-ahead + accum; full = "
                        "adds ring depth + cache scope")
    p.add_argument("--probe-images", type=int, default=None)
    p.add_argument("--probe-batch", type=int, default=32)
    p.add_argument("--probe-epochs", type=int, default=None)
    p.add_argument("--probe-reps", type=int, default=2,
                   help="interleaved default/candidate pairs per knob")
    p.add_argument("--serve-probe", action="store_true",
                   help="re-check the chosen serve ladder through a "
                        "real ServeEngine replay (one AOT compile per "
                        "bucket — the expensive probe)")
    p.add_argument("--max-bucket", type=int, default=64,
                   help="serve ladder admission bound to tune within")
    p.add_argument("--smoke", action="store_true",
                   help="CI preset: cost-model + analytic ladder only, "
                        "one tiny measured probe, no serve compile")
    return p


def main_tune(argv=None):
    from dptpu.tune.artifact import save_tuning
    from dptpu.tune.search import (
        default_request_mix,
        model_leaf_sizes,
        probe_knob_paired,
        probe_serve_ladder,
        search_bucket_mb,
        search_serve_buckets,
    )

    args = build_tune_parser().parse_args(argv)
    if args.smoke:
        args.probe = "quick" if args.probe != "none" else "none"
        args.serve_probe = False
    probe_images = args.probe_images or (128 if args.smoke else 512)
    probe_epochs = args.probe_epochs or (1 if args.smoke else 2)

    knobs = {}
    probes = {}

    # 1. DPTPU_BUCKET_MB: analytic sweep over the cost model ----------
    print(f"=> tune: scoring DPTPU_BUCKET_MB candidates against the "
          f"simulated pod ({args.slices}x{args.chips_per_slice}, "
          f"{args.dcn_gbps} GB/s DCN, {args.arch} gradient layout)")
    perleaf = model_leaf_sizes(
        args.arch, image_size=args.image_size,
        num_classes=args.num_classes,
    )
    t_chip = args.per_chip_batch / args.chip_img_per_s
    bucket = search_bucket_mb(
        perleaf, t_chip,
        dcn_gbps=args.dcn_gbps,
        latency_s=args.dcn_latency_us * 1e-6,
        slices=args.slices, inner=args.chips_per_slice,
    )
    knobs["DPTPU_BUCKET_MB"] = f"{bucket['best_bucket_mb']:g}"
    probes["bucket_mb"] = {
        "kind": "cost_model",
        "grad_bytes": sum(perleaf),
        "best": bucket["best_row"],
        "rows": bucket["rows"],
    }
    print(f"   best DPTPU_BUCKET_MB={knobs['DPTPU_BUCKET_MB']} "
          f"(overlapped {bucket['best_row']['overlapped_ms']} ms, "
          f"speedup {bucket['best_row']['speedup']}x over serial)")

    # 2. DPTPU_SERVE_BUCKETS: analytic ladder search ------------------
    mix = default_request_mix(args.max_bucket)
    ladder = search_serve_buckets(mix)
    default_waste = next(
        r["waste"] for r in ladder["rows"]
        if r["ladder"] == [1, 4, 16, 64]
    )
    probes["serve_buckets"] = {
        "kind": "analytic_padding",
        "request_mix_len": len(mix),
        "default_waste": default_waste,
        "best": {"ladder": ladder["best_ladder"],
                 "waste": ladder["best_waste"]},
        "rows": ladder["rows"],
    }
    if ladder["best_ladder"] != [1, 4, 16, 64]:
        knobs["DPTPU_SERVE_BUCKETS"] = ",".join(
            str(b) for b in ladder["best_ladder"]
        )
        print(f"   best DPTPU_SERVE_BUCKETS="
              f"{knobs['DPTPU_SERVE_BUCKETS']} (padding waste "
              f"{ladder['best_waste']:.1%} vs default "
              f"{default_waste:.1%})")
    else:
        print(f"   serve ladder: default [1,4,16,64] already best "
              f"({default_waste:.1%} waste) — not emitting")
    if args.serve_probe:
        probes["serve_buckets"]["measured"] = probe_serve_ladder(
            ladder["best_ladder"], mix[:64], arch=args.arch,
            image_size=args.image_size, num_classes=args.num_classes,
        )
        print(f"   measured ladder waste "
              f"{probes['serve_buckets']['measured']['measured_waste']:.1%}")

    # 3. measured fit() probes ----------------------------------------
    if args.probe != "none":
        plan = [("DPTPU_DECODE_AHEAD", "8",
                 {"DPTPU_WORKERS_MODE": "process"}),
                ("DPTPU_ACCUM", "2", {})]
        if args.probe == "full":
            plan += [("DPTPU_RING_DEPTH", "12",
                      {"DPTPU_WORKERS_MODE": "process"}),
                     ("DPTPU_CACHE_SCOPE", "sharded",
                      {"DPTPU_CACHE_BYTES": str(256 << 20),
                       "DPTPU_WORKERS_MODE": "process"})]
        if args.smoke:
            plan = plan[:1]
        for knob, candidate, base_env in plan:
            print(f"=> tune: measured probe {knob}={candidate} "
                  f"({args.probe_reps} ABBA pairs, {probe_images} "
                  f"synthetic images)")
            verdict = probe_knob_paired(
                knob, candidate, base_env,
                reps=args.probe_reps, arch=args.arch,
                images=probe_images, batch=args.probe_batch,
                epochs=probe_epochs, image_size=args.image_size,
            )
            probes[knob.lower()] = {"kind": "measured_fit", **verdict}
            if verdict["adopt"]:
                knobs[knob] = candidate
                for k, v in base_env.items():
                    # a knob that only wins inside its enabling
                    # context carries that context (tunable ones only)
                    from dptpu.tune.artifact import TUNABLE_KNOBS

                    if k in TUNABLE_KNOBS:
                        knobs.setdefault(k, v)
                print(f"   ADOPT {knob}={candidate} "
                      f"(+{verdict['gain_pct']:.1f}% median, noise "
                      f"{verdict['noise_pct']:.1f}%)")
            else:
                print(f"   keep default for {knob} "
                      f"({verdict['gain_pct']:+.1f}% median does not "
                      f"clear noise {verdict['noise_pct']:.1f}%)")

    objective = {
        "cost_model": {
            "slices": args.slices,
            "chips_per_slice": args.chips_per_slice,
            "per_chip_batch": args.per_chip_batch,
            "dcn_gbps": args.dcn_gbps,
            "dcn_latency_us": args.dcn_latency_us,
            "chip_img_per_s": args.chip_img_per_s,
            "arch": args.arch,
        },
        "probe_preset": args.probe,
        "smoke": bool(args.smoke),
    }
    from dptpu.utils.provenance import host_provenance

    host = host_provenance()
    payload = save_tuning(args.out, knobs, objective, probes, host=host)
    print(json.dumps({"out": args.out, "knobs": knobs,
                      "crc32": payload["crc32"]}))
    print(f"wrote {args.out} — load with "
          f"DPTPU_TUNE_ARTIFACT={args.out} (explicit env/CLI knobs "
          f"always win)")
    return 0


if __name__ == "__main__":
    sys.exit(main_tune())
