"""Offline knob search for ``dptpu tune`` (ISSUE 19 tentpole, half a).

Two kinds of evidence, deliberately separated:

* **Analytic** — the RACEBENCH simulated-pod cost model
  (:mod:`dptpu.tune.costmodel`) scores every ``DPTPU_BUCKET_MB``
  candidate for a given arch/geometry/DCN in microseconds of arithmetic,
  and a padding-waste model scores serve bucket ladders against a
  request-size mix. Cheap enough to sweep the whole candidate grid.
* **Measured** — short REAL runs: ``fit()`` on synthetic data probes
  the host-feed knobs the model cannot see (decode-ahead, ring depth,
  cache scope, accumulation), and a real ``ServeEngine`` +
  ``DynamicBatcher`` pass can re-check the chosen ladder end to end.
  Probes are paired against the default (the candidate must BEAT the
  measured default plus the host's own noise floor, or the knob is left
  alone) — a tuner that emits knobs it cannot defend is worse than no
  tuner.

The search never writes env: every probe saves/restores the knobs it
touches, and the output is a plain dict for
:func:`dptpu.tune.artifact.save_tuning` to seal.
"""

from __future__ import annotations

import os
import tempfile
import time

# DPTPU_BUCKET_MB candidates: geometric sweep around the shipped 25 MB
# default — small enough to amortize latency, large enough to overlap
CANDIDATE_BUCKET_MB = (0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 25.0)

# serve ladder candidates, all within the default admission bound of 64
CANDIDATE_LADDERS = (
    (1, 4, 16, 64),            # shipped default
    (1, 2, 4, 8, 16, 32, 64),  # dense powers of two
    (1, 4, 8, 16, 32, 64),
    (1, 8, 64),                # sparse (wins only on bimodal mixes)
)


def model_leaf_sizes(arch: str, image_size: int = 224,
                     num_classes: int = 1000):
    """Per-leaf gradient bytes in REVERSE flatten order — the overlap
    engine's issue order — via ``jax.eval_shape`` (no real init, no
    device memory: shapes only)."""
    import jax
    import jax.numpy as jnp

    from dptpu.models import create_model

    model = create_model(arch, num_classes=num_classes)
    variables = jax.eval_shape(
        lambda rng: model.init(
            rng, jnp.zeros((1, image_size, image_size, 3), jnp.float32),
            train=False,
        ),
        jax.random.PRNGKey(0),
    )
    leaves = jax.tree_util.tree_leaves(variables["params"])
    sizes = [int(_prod(l.shape)) * 4 if l.shape else 4 for l in leaves]
    return list(reversed(sizes))


def _prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


def search_bucket_mb(perleaf_sizes, compute_s: float, *,
                     dcn_gbps: float, latency_s: float, slices: int,
                     inner: int, candidates=CANDIDATE_BUCKET_MB) -> dict:
    """Sweep ``DPTPU_BUCKET_MB`` candidates against the simulated-pod
    model; returns the winner (min overlapped step; ties break toward
    the LARGER bucket — fewer collectives for the same wall clock) and
    the full scored table for the artifact's provenance."""
    from dptpu.tune.costmodel import greedy_bucket_sizes, model_row

    rows = []
    for mb in sorted(candidates):
        sizes = greedy_bucket_sizes(perleaf_sizes, int(mb * 1e6))
        rows.append(model_row(
            "chip_equivalent", compute_s, mb, sizes, perleaf_sizes,
            dcn_gbps, latency_s, slices, inner,
        ))
    best = min(rows, key=lambda r: (r["overlapped_ms"], -r["bucket_mb"]))
    return {"best_bucket_mb": best["bucket_mb"], "best_row": best,
            "rows": rows}


def ladder_waste(ladder, request_sizes) -> float:
    """Padding-waste fraction of a bucket ladder over a request mix:
    padded rows / executed rows, each request routed to the smallest
    bucket that holds it (``ServeEngine.bucket_for``), oversize
    requests split greedily from the top (the batcher's chunking)."""
    ladder = sorted(ladder)
    pad = ex = 0
    for n in request_sizes:
        n = int(n)
        while n > 0:
            for b in ladder:
                if b >= n:
                    break
            take = min(n, b)
            pad += b - take
            ex += b
            n -= take
    return pad / max(ex, 1)


def search_serve_buckets(request_sizes, *,
                         candidates=CANDIDATE_LADDERS) -> dict:
    """Score candidate ladders against the expected request-size mix
    (analytic: no compile). Denser ladders pay more AOT compiles, so
    ties break toward FEWER buckets."""
    rows = []
    for ladder in candidates:
        rows.append({
            "ladder": list(ladder),
            "waste": round(ladder_waste(ladder, request_sizes), 4),
        })
    best = min(rows, key=lambda r: (r["waste"], len(r["ladder"])))
    return {
        "best_ladder": best["ladder"],
        "best_waste": best["waste"],
        "rows": rows,
    }


def default_request_mix(max_size: int = 64, seed: int = 0):
    """The mix the analytic ladder search scores against when the
    operator gives no trace: geometric-ish small-heavy sizes (most
    serving traffic is singles and small bursts) plus occasional
    near-max batches."""
    import random

    rng = random.Random(seed)
    mix = []
    for _ in range(512):
        r = rng.random()
        if r < 0.5:
            mix.append(rng.randint(1, 4))
        elif r < 0.85:
            mix.append(rng.randint(5, 24))
        else:
            mix.append(rng.randint(25, max_size))
    return mix


class _env_patch:
    """Save/restore the env knobs a probe touches — the search must
    never leak a candidate into the caller's environment."""

    def __init__(self, overrides: dict):
        self.overrides = dict(overrides)
        self._saved = {}

    def __enter__(self):
        for k, v in self.overrides.items():
            self._saved[k] = os.environ.get(k)
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        return self

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return False


def probe_fit(overrides: dict, *, arch: str = "resnet18",
              images: int = 256, batch: int = 32, epochs: int = 1,
              image_size: int = 32, workers: int = 2,
              seed: int = 0) -> float:
    """One short REAL ``fit()`` on synthetic data under the candidate
    env; returns steady-state images/sec. Checkpoints and TB runs land
    in a scratch dir, never the repo (the obsbench discipline)."""
    from dptpu.config import Config
    from dptpu.train import fit

    cfg = Config(
        data=f"synthetic:{images}",
        variant="apex",
        arch=arch,
        epochs=epochs,
        batch_size=batch,
        lr=0.05,
        workers=workers,
        print_freq=10_000,
        seed=seed,
        opt_level="O0",
    )
    # process-mode data workers re-import dptpu in the spawn child with
    # the parent's sys.path; a relative '' entry stops resolving once we
    # chdir into the scratch dir, so pin the absolute package root
    import sys

    import dptpu

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(dptpu.__file__)))
    if pkg_root not in sys.path:
        sys.path.insert(0, pkg_root)
    cwd = os.getcwd()
    rundir = tempfile.mkdtemp(prefix="dptpu_tune_probe_")
    with _env_patch(overrides):
        os.chdir(rundir)
        try:
            result = fit(cfg, image_size=image_size, verbose=False)
        finally:
            os.chdir(cwd)
    hist = result["history"]
    steady = hist[1:] if len(hist) > 1 else hist
    bt = sum(h["train_batch_time"] for h in steady) / len(steady)
    return batch / max(bt, 1e-9)


def probe_knob_paired(knob: str, candidate: str, base_env: dict,
                      *, reps: int = 2, log=print, **fit_kw) -> dict:
    """Measured A/B for one knob: interleaved default/candidate pairs
    in ABBA order (the obsbench drift-cancelling recipe), decided on
    the MEDIAN of per-pair relative deltas. The candidate must beat
    the default by more than the default arm's own spread — otherwise
    the verdict is "keep the default" and no knob is emitted."""
    from statistics import median

    rates = {"default": [], "candidate": []}
    for rep in range(reps):
        arms = (("default", None), ("candidate", candidate))
        if rep % 2:
            arms = arms[::-1]
        for arm, value in arms:
            env = dict(base_env)
            if value is not None:
                env[knob] = value
            rate = probe_fit(env, **fit_kw)
            rates[arm].append(round(rate, 1))
            log(f"  probe {knob}={value if value is not None else '<default>'}"
                f" rep {rep}: {rate:.1f} img/s")
    paired = [
        (c - d) / d * 100.0
        for d, c in zip(rates["default"], rates["candidate"])
    ]
    gain_pct = median(paired)
    noise_pct = (max(rates["default"]) - min(rates["default"])) \
        / max(rates["default"]) * 100.0
    return {
        "knob": knob,
        "candidate": candidate,
        "default_img_s": rates["default"],
        "candidate_img_s": rates["candidate"],
        "paired_deltas_pct": [round(p, 3) for p in paired],
        "gain_pct": round(gain_pct, 3),
        "noise_pct": round(noise_pct, 3),
        "adopt": bool(gain_pct > max(noise_pct, 0.5)),
    }


def probe_serve_ladder(ladder, request_sizes, *, arch: str = "resnet18",
                       image_size: int = 32,
                       num_classes: int = 16) -> dict:
    """Measured end-to-end check of a ladder through a REAL
    ``ServeEngine`` + ``DynamicBatcher``: replay the request mix,
    report the batcher's own padding counters. Costs one AOT compile
    per bucket — the expensive probe, gated behind ``--serve-probe``."""
    import numpy as np

    from dptpu.serve.batcher import DynamicBatcher
    from dptpu.serve.engine import ServeEngine

    engine = ServeEngine(
        arch, buckets=tuple(sorted(ladder)), num_classes=num_classes,
        image_size=image_size, verbose=False,
    )
    batcher = DynamicBatcher(engine, max_delay_ms=0.5)
    try:
        rng = np.random.RandomState(0)
        img = rng.randint(
            0, 256, (image_size, image_size, 3)
        ).astype(np.uint8)
        for n in request_sizes:
            # one burst per mix entry, drained before the next so the
            # coalescer sees the intended batch-size distribution
            futs = [batcher.submit_array(img) for _ in range(int(n))]
            for f in futs:
                f.result(timeout=300.0)
        pad, ex = batcher.padding_counts()
        return {
            "ladder": list(sorted(ladder)),
            "pad_rows": int(pad),
            "exec_rows": int(ex),
            "measured_waste": round(pad / max(ex, 1), 4),
        }
    finally:
        batcher.close()


__all__ = [
    "CANDIDATE_BUCKET_MB",
    "CANDIDATE_LADDERS",
    "default_request_mix",
    "ladder_waste",
    "model_leaf_sizes",
    "probe_fit",
    "probe_knob_paired",
    "probe_serve_ladder",
    "search_bucket_mb",
    "search_serve_buckets",
]
