"""Self-tuning control plane (ISSUE 19).

Two halves close ROADMAP open item 5 — "encode the hand-tuning":

* **offline** — ``dptpu tune`` searches the knob space against the
  RACEBENCH simulated-pod cost model (``costmodel.py``, extracted from
  scripts/run_racebench.py) plus short measured probes through the real
  ``fit()``/``ServeEngine`` paths, and seals the winning knobs into a
  provenance-stamped ``TUNING.json`` (``artifact.py``) that fit/serve
  load via ``DPTPU_TUNE_ARTIFACT`` — explicit env/CLI knobs always win;
* **online** — ``controller.py`` generalizes the PR-11 straggler
  controller idiom (streaming estimators, persistence, probation) into
  bounded, rate-limited, individually-disarmable actuators that ride
  fit's post-step hook and the serve batcher's telemetry.

Everything here is lazy-importing and stdlib/numpy on the hot paths:
knob parsing and artifact loading must never drag JAX into a CLI that
only wants to validate a file.
"""

from __future__ import annotations

__all__ = [
    "apply_tuning",
    "load_tuning",
    "save_tuning",
    "simulate_pod",
    "tune_knobs",
]


def __getattr__(name):
    if name in ("apply_tuning", "load_tuning", "save_tuning",
                "tune_knobs"):
        from dptpu.tune import artifact

        return getattr(artifact, name)
    if name == "simulate_pod":
        from dptpu.tune.costmodel import simulate_pod

        return simulate_pod
    raise AttributeError(name)
