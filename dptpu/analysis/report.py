"""ANALYSIS.json — the machine-readable ``dptpu check`` report.

Host-provenance-stamped like every other committed artifact
(dptpu/utils/provenance.py), with the full suppression census: a waiver
is never silent — every live ``# dptpu: allow-<rule>(<reason>)`` lands here
with its file:line and reason, so the inventory of exceptions is
reviewable in one place. The committed copy at the repo root is the
baseline tier-1 asserts against (tests/test_analysis_repo.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from dptpu.analysis.knobs import knob_census
from dptpu.analysis.lint import iter_rules, lint_repo

REPORT_FILENAME = "ANALYSIS.json"


def build_report(root: str, run_hlo: bool = True,
                 budgets: Optional[dict] = None,
                 computed: Optional[dict] = None) -> dict:
    """Run the full check and assemble the report. ``report["ok"]`` is
    the exit-code contract's single bit: True iff zero unsuppressed
    lint findings AND (when run) zero HLO budget violations AND zero
    partition-rules violations.
    ``computed`` passes a fresh compile through to the budget gates
    (``--update-hlo-budgets`` reuses its own compile instead of paying
    four more)."""
    findings, suppressions, n_files = lint_repo(root)
    report = {
        "version": 1,
        "lint": {
            "files_scanned": n_files,
            "rules": {r.name: r.doc for r in iter_rules()},
            "findings": [f.format() for f in findings],
            "suppressions": sorted(
                (dataclasses.asdict(s) for s in suppressions),
                key=lambda s: (s["path"], s["line"], s["rule"]),
            ),
        },
        "knobs": knob_census(),
    }
    ok = not findings
    if run_hlo:
        from dptpu.analysis.hlo_budget import (
            budget_summary,
            check_hlo_budgets,
        )
        from dptpu.analysis.partition import (
            check_partition_rules,
            partition_summary,
        )

        violations, computed = check_hlo_budgets(
            root, budgets=budgets, computed=computed
        )
        report["hlo"] = budget_summary(violations, computed)
        ok = ok and not violations
        # partition-rules rides the jax half: it needs eval_shape over
        # the family representatives, so the --no-hlo stdlib-only run
        # skips it the same way it skips the budget gates
        p_violations = check_partition_rules()
        report["partition_rules"] = partition_summary(p_violations)
        ok = ok and not p_violations
    else:
        report["hlo"] = {"ok": None,
                         "note": "skipped (--no-hlo lint-only run)"}
        report["partition_rules"] = {
            "ok": None, "note": "skipped (--no-hlo lint-only run)"}
    report["ok"] = ok
    # stamped LAST so a full run records the jax the HLO gates actually
    # loaded (and a lint-only run honestly records None — provenance
    # reads sys.modules, it never imports jax itself)
    from dptpu.utils.provenance import host_provenance

    report["provenance"] = host_provenance()
    return report


def write_report(report: dict, path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def load_report(root: str) -> Optional[dict]:
    path = os.path.join(root, REPORT_FILENAME)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)
