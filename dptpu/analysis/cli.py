"""``dptpu check`` — the static-analysis CLI.

Exit-code contract (LOCKED by tests/test_analysis_repo.py):

* ``0`` — clean: zero unsuppressed lint findings, every suppression
  carries a reason, and (unless ``--no-hlo``) every HLO budget gate
  holds;
* ``1`` — findings: at least one unsuppressed finding or budget
  violation (each printed with the locked actionable message);
* ``2`` — usage/internal error (argparse's own convention).

``--no-hlo`` keeps the run stdlib-only (no jax import) — safe inside
spawned data workers and jax-free CI shards. ``python -m
dptpu.analysis`` is the same entry without loading the trainer CLI.
"""

from __future__ import annotations

import argparse
import os


def build_check_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dptpu check",
        description="repo-invariant static analysis: AST lints "
                    "(knob-contract / determinism / host-sync / "
                    "shm-hygiene / shard-map) + HLO budget gates + "
                    "partition-rules table checks (dptpu/analysis)",
    )
    p.add_argument("--root", default=".", metavar="DIR",
                   help="repo root to check (default: .)")
    p.add_argument("--no-hlo", action="store_true",
                   help="lint only — skip the HLO budget gates (and "
                        "with them any jax import; worker-safe)")
    p.add_argument("--update-hlo-budgets", action="store_true",
                   help="recompile the representative configs and "
                        "re-commit HLO_BUDGETS.json (for INTENDED "
                        "comms/sharding changes)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="also write the machine-readable report "
                        "(ANALYSIS.json format) to PATH")
    p.add_argument("--changed-only", action="store_true",
                   help="lint only files changed vs git (diff + staged "
                        "+ untracked) — the seconds-fast pre-commit "
                        "mode (scripts/precommit.sh); implies lint-only "
                        "semantics for file selection, HLO gates still "
                        "run unless --no-hlo")
    p.add_argument("--files", nargs="*", default=None, metavar="PATH",
                   help="explicit repo-relative file list to lint "
                        "instead of the git diff (use with "
                        "--changed-only)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="print findings only, no summary")
    return p


def _changed_python_files(root: str) -> list:
    """Repo-relative .py files changed vs git: unstaged + staged +
    untracked, restricted to the scan roots. Raises RuntimeError when
    git is unusable (the caller turns that into exit 2 — a broken diff
    must never report 'clean over zero files')."""
    import subprocess

    from dptpu.analysis.lint import DEFAULT_SCAN_ROOTS

    def run(*args):
        try:
            proc = subprocess.run(
                ["git", *args], cwd=root, capture_output=True, text=True,
                timeout=30,
            )
        except subprocess.SubprocessError as e:
            # TimeoutExpired etc. — normalize so the caller's exit-2
            # path handles a hung git like a failed one
            raise RuntimeError(f"git {' '.join(args)}: {e}") from e
        if proc.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(args)} failed: "
                f"{proc.stderr.strip() or proc.returncode}"
            )
        return [ln.strip() for ln in proc.stdout.splitlines()
                if ln.strip()]

    names = set(run("diff", "--name-only", "HEAD"))
    names |= set(run("ls-files", "--others", "--exclude-standard"))
    return sorted(
        n for n in names
        if n.endswith(".py") and n.startswith(
            tuple(f"{d}/" for d in DEFAULT_SCAN_ROOTS))
    )


def main_check(argv=None) -> int:
    import sys

    from dptpu.analysis.lint import DEFAULT_SCAN_ROOTS

    parser = build_check_parser()
    args = parser.parse_args(argv)
    if args.update_hlo_budgets and args.no_hlo:
        # committing a table the gates never validated would exit 0
        # "clean" over an unchecked budget — refuse the combination
        parser.error(
            "--update-hlo-budgets needs the HLO gates it re-commits — "
            "drop --no-hlo"
        )
    if args.files is not None and not args.changed_only:
        parser.error("--files only makes sense with --changed-only")
    if args.changed_only and (args.update_hlo_budgets or args.json):
        # the committed ANALYSIS.json baseline and the budget table are
        # whole-repo artifacts; a partial scan must never overwrite them
        parser.error(
            "--changed-only is the partial pre-commit mode — "
            "--json/--update-hlo-budgets need the full scan"
        )
    root = args.root
    if not any(os.path.isdir(os.path.join(root, d))
               for d in DEFAULT_SCAN_ROOTS):
        # a mis-set CI root must not scan zero files and report "clean"
        print(
            f"dptpu check: none of {'/'.join(DEFAULT_SCAN_ROOTS)} "
            f"exists under --root {root!r} — wrong directory? "
            f"(a clean exit over zero files would hide every finding)",
            file=sys.stderr,
        )
        return 2
    if args.changed_only:
        from dptpu.analysis.lint import lint_paths

        if args.files is not None:
            if not args.files:
                # an empty explicit list (e.g. a shell expansion that
                # matched nothing) must not report "clean over zero
                # files" — same contract as the wrong-root guard
                print(
                    "dptpu check: --files got an empty list — pass the "
                    "paths to lint (or drop --files to diff against "
                    "git)", file=sys.stderr,
                )
                return 2
            files = sorted(args.files)
            missing = [f for f in files
                       if not os.path.isfile(os.path.join(root, f))]
            if missing:
                print(
                    f"dptpu check: --files names missing paths: "
                    f"{', '.join(missing)}", file=sys.stderr,
                )
                return 2
        else:
            try:
                files = _changed_python_files(root)
            except (RuntimeError, OSError) as e:
                print(f"dptpu check: cannot diff against git ({e}) — "
                      f"run the full check instead", file=sys.stderr)
                return 2
            files = [f for f in files
                     if os.path.isfile(os.path.join(root, f))]
        findings, suppressions = lint_paths(root, files)
        for f in findings:
            print(f.format())
        ok = not findings
        if not args.no_hlo:
            from dptpu.analysis.hlo_budget import check_hlo_budgets

            violations, _ = check_hlo_budgets(root)
            for v in violations:
                print(v.format())
            ok = ok and not violations
        if not args.quiet:
            print(
                f"=> dptpu check --changed-only: {len(files)} changed "
                f"file(s), {len(findings)} finding(s), "
                f"{len(suppressions)} reasoned suppression(s) — "
                f"{'clean' if ok else 'NOT CLEAN'}"
            )
        return 0 if ok else 1
    computed = None
    if args.update_hlo_budgets:
        from dptpu.analysis.hlo_budget import (
            compute_budgets,
            write_budgets,
        )

        computed = compute_budgets()
        path = write_budgets(root, computed)
        if not args.quiet:
            print(f"=> wrote {path}")
    from dptpu.analysis.report import build_report, write_report

    report = build_report(root, run_hlo=not args.no_hlo,
                          computed=computed)
    for line in report["lint"]["findings"]:
        print(line)
    for line in report.get("hlo", {}).get("violations", ()):
        print(line)
    for line in report.get("partition_rules", {}).get("violations", ()):
        print(line)
    if args.json:
        write_report(report, args.json)
    if not args.quiet:
        lint = report["lint"]
        hlo = report["hlo"]
        hlo_note = (
            "skipped" if hlo["ok"] is None
            else ("ok" if hlo["ok"] else "FAILED")
        )
        rules = report["partition_rules"]
        rules_note = (
            "skipped" if rules["ok"] is None
            else ("ok" if rules["ok"] else "FAILED")
        )
        print(
            f"=> dptpu check: {lint['files_scanned']} files, "
            f"{len(lint['findings'])} finding(s), "
            f"{len(lint['suppressions'])} reasoned suppression(s), "
            f"HLO budgets {hlo_note}, partition rules {rules_note} — "
            f"{'clean' if report['ok'] else 'NOT CLEAN'}"
        )
    return 0 if report["ok"] else 1


def console_check(argv=None) -> int:
    return main_check(argv)


if __name__ == "__main__":  # pragma: no cover - exercised as a module
    raise SystemExit(main_check())
