"""The AST lint engine behind ``dptpu check`` (stdlib-only, worker-safe).

Mechanics, shared by every rule in :mod:`dptpu.analysis.rules`:

* a rule is registered with :func:`register` and receives a
  :class:`FileContext` (source, ``ast`` tree, repo-level context);
  it yields ``(line, message)`` pairs;
* findings are suppressible per line with the pragma
  ``# dptpu: allow-<rule>(<reason>)`` — the reason is MANDATORY
  (an empty reason, an unknown rule name, a malformed pragma, or a
  pragma that suppresses nothing is itself a finding of the ``pragma``
  meta-rule, which is deliberately not suppressible);
* every finding formats to the locked actionable-message contract:
  rule name, ``file:line``, the message, and the exact pragma syntax
  that would suppress it (tests/test_analysis.py locks this).

Import discipline: this module (and rules.py) must import NOTHING
beyond the stdlib — the lint half of ``dptpu check`` runs inside
spawned data workers and jax-free CI shards.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Tuple

PRAGMA_SYNTAX = "# dptpu: allow-<rule>(<reason>)"
_PRAGMA_RE = re.compile(
    r"#\s*dptpu:\s*allow-([A-Za-z0-9][A-Za-z0-9_-]*)\(([^()]*)\)"
)
# anything that says "dptpu:" in a comment but is not a well-formed
# allow-pragma is flagged: a typo'd pragma silently suppressing nothing
# is exactly the failure mode pragmas exist to avoid
_PRAGMA_INTENT_RE = re.compile(r"#\s*dptpu:")

# file sets scanned by lint_repo, relative to the repo root
DEFAULT_SCAN_ROOTS = ("dptpu", "scripts")


# meta-rules whose findings are deliberately NOT suppressible (a
# pragma silencing pragma hygiene would be a hole in the hole-checker);
# their messages must not advertise a pragma that cannot work
UNSUPPRESSIBLE_RULES = ("pragma", "parse")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding. ``format()`` is the locked message contract."""

    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        head = f"{self.rule}: {self.path}:{self.line}: {self.message}"
        if self.rule in UNSUPPRESSIBLE_RULES:
            return f"{head} [not suppressible — fix the line itself]"
        return (
            f"{head} [suppress with a mandatory reason: "
            f"# dptpu: allow-{self.rule}(<reason>)]"
        )


@dataclasses.dataclass(frozen=True)
class Suppression:
    """A finding silenced by a reasoned pragma (censused, never lost)."""

    rule: str
    path: str
    line: int
    reason: str


@dataclasses.dataclass
class RepoContext:
    """Repo-level facts rules may consult. ``readme_text=None`` (snippet
    lints in unit tests) disables the README cross-checks."""

    root: Optional[str] = None
    readme_text: Optional[str] = None
    knobs: Optional[dict] = None

    @classmethod
    def for_root(cls, root: str) -> "RepoContext":
        from dptpu.analysis.knobs import KNOB_REGISTRY

        readme = os.path.join(root, "README.md")
        text = None
        if os.path.exists(readme):
            with open(readme, encoding="utf-8") as f:
                text = f.read()
        return cls(root=root, readme_text=text, knobs=KNOB_REGISTRY)


@dataclasses.dataclass
class FileContext:
    relpath: str
    source: str
    tree: ast.AST
    repo: RepoContext

    _func_stack: Optional[Dict[int, Tuple[str, ...]]] = None
    _module_consts: Optional[Dict[str, str]] = None

    def enclosing_functions(self, node: ast.AST) -> Tuple[str, ...]:
        """Names of the def/class scopes enclosing ``node`` (outermost
        first) — how rules scope themselves to step bodies / the blessed
        segment constructor / a specific class."""
        if self._func_stack is None:
            stack_of: Dict[int, Tuple[str, ...]] = {}

            def visit(node, stack):
                stack_of[id(node)] = stack
                child_stack = stack
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    child_stack = stack + (node.name,)
                for child in ast.iter_child_nodes(node):
                    visit(child, child_stack)

            visit(self.tree, ())
            self._func_stack = stack_of
        return self._func_stack.get(id(node), ())

    def resolve_str(self, node: ast.AST) -> Optional[str]:
        """Static best-effort string value: a literal, or a Name bound
        to a module-level string constant (``SEGMENT_PREFIX``-style)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            if self._module_consts is None:
                consts: Dict[str, str] = {}
                for stmt in getattr(self.tree, "body", []):
                    if (isinstance(stmt, ast.Assign)
                            and isinstance(stmt.value, ast.Constant)
                            and isinstance(stmt.value.value, str)):
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                consts[tgt.id] = stmt.value.value
                self._module_consts = consts
            return self._module_consts.get(node.id)
        return None


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    fn: Callable[[FileContext], Iterable[Tuple[int, str]]]
    scope: Callable[[str], bool]
    doc: str


_RULES: Dict[str, Rule] = {}


def register(name: str, scope: Callable[[str], bool], doc: str):
    def deco(fn):
        _RULES[name] = Rule(name, fn, scope, doc)
        return fn

    return deco


def iter_rules() -> List[Rule]:
    _load_rules()
    return [_RULES[n] for n in sorted(_RULES)]


def _load_rules():
    # rules self-register on import; deferred so lint.py has no import
    # cycle with rules.py / concurrency.py
    from dptpu.analysis import concurrency, rules  # noqa: F401


def _parse_pragmas(relpath: str, source: str):
    """Per-line pragma table + the pragma meta-rule's own findings."""
    pragmas: Dict[int, List[dict]] = {}
    findings: List[Finding] = []
    for lineno, text in enumerate(source.splitlines(), start=1):
        matches = list(_PRAGMA_RE.finditer(text))
        for m in matches:
            rule_name, reason = m.group(1), m.group(2).strip()
            entry = {"rule": rule_name, "reason": reason, "used": False}
            if rule_name not in _RULES:
                findings.append(Finding(
                    "pragma", relpath, lineno,
                    f"pragma names unknown rule {rule_name!r} (known: "
                    f"{', '.join(sorted(_RULES))})",
                ))
                continue
            if not reason:
                findings.append(Finding(
                    "pragma", relpath, lineno,
                    f"pragma allow-{rule_name} has no reason — a reason "
                    f"is mandatory: {PRAGMA_SYNTAX}",
                ))
                continue
            pragmas.setdefault(lineno, []).append(entry)
        if (_PRAGMA_INTENT_RE.search(text) and not matches
                and "allow-<" not in text and "(<reason>)" not in text):
            # lines quoting the SYNTAX itself (docstrings, the format
            # string above) keep their placeholders; a real typo'd
            # pragma has concrete text and still lands here
            findings.append(Finding(
                "pragma", relpath, lineno,
                f"malformed dptpu pragma (would silently suppress "
                f"nothing) — the syntax is {PRAGMA_SYNTAX}",
            ))
    return pragmas, findings


def lint_source(
    relpath: str,
    source: str,
    repo: Optional[RepoContext] = None,
    only_rules: Optional[Iterable[str]] = None,
) -> Tuple[List[Finding], List[Suppression]]:
    """Lint one file's source. Returns (findings, suppressions)."""
    _load_rules()
    repo = repo if repo is not None else RepoContext()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(
            "parse", relpath, e.lineno or 1,
            f"file does not parse: {e.msg}",
        )], []
    pragmas, findings = _parse_pragmas(relpath, source)
    ctx = FileContext(relpath=relpath, source=source, tree=tree, repo=repo)
    names = set(only_rules) if only_rules is not None else None
    suppressions: List[Suppression] = []
    for rule in iter_rules():
        if names is not None and rule.name not in names:
            continue
        if not rule.scope(relpath):
            continue
        for line, message in rule.fn(ctx):
            hit = next(
                (p for p in pragmas.get(line, ())
                 if p["rule"] == rule.name),
                None,
            )
            if hit is not None:
                hit["used"] = True
                suppressions.append(
                    Suppression(rule.name, relpath, line, hit["reason"])
                )
            else:
                findings.append(Finding(rule.name, relpath, line, message))
    for lineno, entries in pragmas.items():
        for p in entries:
            if not p["used"] and (names is None or p["rule"] in names):
                findings.append(Finding(
                    "pragma", relpath, lineno,
                    f"unused pragma allow-{p['rule']} — nothing on this "
                    f"line triggers that rule; remove the pragma",
                ))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, suppressions


def lint_paths(
    root: str, relpaths: Iterable[str], repo: Optional[RepoContext] = None
) -> Tuple[List[Finding], List[Suppression]]:
    repo = repo if repo is not None else RepoContext.for_root(root)
    findings: List[Finding] = []
    suppressions: List[Suppression] = []
    for rel in relpaths:
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            source = f.read()
        got, sup = lint_source(rel, source, repo)
        findings.extend(got)
        suppressions.extend(sup)
    return findings, suppressions


def repo_python_files(root: str,
                      scan_roots=DEFAULT_SCAN_ROOTS) -> List[str]:
    """The repo's own lintable files: every ``.py`` under the scan
    roots, repo-relative, sorted (deterministic reports)."""
    out = []
    for base in scan_roots:
        basedir = os.path.join(root, base)
        for dirpath, dirnames, filenames in os.walk(basedir):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, name), root
                    ))
    return sorted(out)


def lint_repo(root: str) -> Tuple[List[Finding], List[Suppression], int]:
    """Lint the whole repo. Returns (findings, suppressions, n_files)."""
    files = repo_python_files(root)
    findings, suppressions = lint_paths(root, files)
    return findings, suppressions, len(files)
