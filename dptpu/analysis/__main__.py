"""``python -m dptpu.analysis`` — ``dptpu check`` without loading the
trainer CLI (dptpu/cli.py imports the full train stack at module
scope; this entry keeps lint-only runs stdlib-light)."""

from dptpu.analysis.cli import main_check

if __name__ == "__main__":
    raise SystemExit(main_check())
