"""The declared ``DPTPU_*`` knob registry — the knob-contract rule's
source of truth.

Every ``DPTPU_*`` name the code reads MUST have an entry here, and every
non-internal entry MUST appear in README's knob docs (the knob-contract
lint enforces both directions, so a knob can no longer ship undocumented
the way DPTPU_SERVE_SLOTS / DPTPU_FUSED_STEM / DPTPU_NO_LHS / DPTPU_S2D
did before ISSUE 12). ``kind`` names the envknob helper that parses the
value — the fail-fast contract (dptpu/envknob.py) is what makes a typo'd
knob raise instead of silently falling back.

``internal=True`` marks child-process sentinels the bench drivers set
for their own subprocesses (never user-facing, so README documentation
would be noise); the registry entry still declares them so the lint can
tell a sentinel from a typo'd knob.
"""

from __future__ import annotations


def _k(kind: str, area: str, internal: bool = False) -> dict:
    return {"kind": kind, "area": area, "internal": internal}


# name -> {"kind": envknob parser, "area": owning subsystem, "internal"}
KNOB_REGISTRY = {
    # train / optimizer recipe
    "DPTPU_OPT": _k("choice", "train"),
    "DPTPU_ACCUM": _k("int", "train"),
    "DPTPU_WARMUP_EPOCHS": _k("int", "train"),
    "DPTPU_WARMUP_POLY": _k("float", "train"),
    "DPTPU_BATCH_RAMP": _k("str", "train"),
    "DPTPU_DIST_EVAL": _k("bool", "train"),
    "DPTPU_LABEL_SMOOTH": _k("float", "train"),
    "DPTPU_FUSED_STEM": _k("bool", "train"),
    "DPTPU_S2D": _k("bool", "train"),
    "DPTPU_NO_LHS": _k("bool", "train"),
    "DPTPU_PROFILE": _k("str", "train"),
    "DPTPU_ASYNC_CKPT": _k("bool", "train"),
    "DPTPU_PRETRAINED_DIR": _k("str", "models"),
    # parallelism
    "DPTPU_TP": _k("int", "parallel"),
    "DPTPU_SP": _k("int", "parallel"),
    "DPTPU_SP_MODE": _k("choice", "parallel"),
    "DPTPU_ZERO1": _k("bool", "parallel"),
    "DPTPU_ZERO": _k("int", "parallel"),
    "DPTPU_FSDP": _k("bool", "parallel"),
    "DPTPU_RULES": _k("choice", "parallel"),
    "DPTPU_GSPMD": _k("bool", "parallel"),
    "DPTPU_SLICES": _k("int", "parallel"),
    "DPTPU_DCN_DTYPE": _k("choice", "parallel"),
    "DPTPU_OVERLAP": _k("bool", "parallel"),
    "DPTPU_BUCKET_MB": _k("float", "parallel"),
    "DPTPU_RENDEZVOUS_TIMEOUT": _k("int", "parallel"),
    # data plane
    "DPTPU_WORKERS_MODE": _k("choice", "data"),
    "DPTPU_CACHE_BYTES": _k("int", "data"),
    "DPTPU_CACHE_SCOPE": _k("choice", "data"),
    "DPTPU_LEASE": _k("bool", "data"),
    "DPTPU_LEASE_DEPTH": _k("int", "data"),
    "DPTPU_RING_DEPTH": _k("int", "data"),
    "DPTPU_DECODE_AHEAD": _k("int", "data"),
    "DPTPU_SPECULATE": _k("bool", "data"),
    "DPTPU_READAHEAD": _k("bool", "data"),
    "DPTPU_SPAN_AFFINITY": _k("bool", "data"),
    "DPTPU_SPAN_RETRIES": _k("int", "data"),
    "DPTPU_POOL_RESTARTS": _k("int", "data"),
    "DPTPU_WORKER_TIMEOUT_S": _k("float", "data"),
    "DPTPU_SHARD_LOCALITY": _k("bool", "data"),
    "DPTPU_SHARD_CACHE_BYTES": _k("int", "data"),
    "DPTPU_ODIRECT": _k("bool", "data"),
    "DPTPU_STORE_FETCH": _k("choice", "data"),
    "DPTPU_STORE_RETRIES": _k("int", "data"),
    "DPTPU_STORE_BACKOFF_S": _k("float", "data"),
    # resilience
    "DPTPU_FAULT": _k("str", "resilience"),
    "DPTPU_FAULT_SEED": _k("int", "resilience"),
    "DPTPU_ELASTIC": _k("bool", "resilience"),
    "DPTPU_QUORUM_DIR": _k("str", "resilience"),
    "DPTPU_QUORUM_DEADLINE_S": _k("float", "resilience"),
    "DPTPU_STRAGGLER_FACTOR": _k("float", "resilience"),
    "DPTPU_STRAGGLER_PERSIST": _k("int", "resilience"),
    # observability
    "DPTPU_OBS": _k("bool", "obs"),
    "DPTPU_OBS_RING": _k("int", "obs"),
    "DPTPU_OBS_DIR": _k("str", "obs"),
    "DPTPU_OBS_TRACE_STEPS": _k("int", "obs"),
    "DPTPU_OBS_TRIGGER": _k("str", "obs"),
    "DPTPU_OBS_ANOMALY": _k("float", "obs"),
    # serving
    "DPTPU_SERVE_BUCKETS": _k("str", "serve"),
    "DPTPU_SERVE_MAX_DELAY_MS": _k("float", "serve"),
    "DPTPU_SERVE_PLACEMENT": _k("choice", "serve"),
    "DPTPU_SERVE_SLOTS": _k("int", "serve"),
    "DPTPU_SERVE_QUEUE_DEPTH": _k("int", "serve"),
    "DPTPU_SERVE_PRIORITIES": _k("str", "serve"),
    "DPTPU_SERVE_DEADLINE_MS": _k("float", "serve"),
    "DPTPU_SERVE_CANARY_FRACTION": _k("float", "serve"),
    "DPTPU_SERVE_CANARY_DRIFT": _k("float", "serve"),
    "DPTPU_SERVE_CANARY_LAT_FACTOR": _k("float", "serve"),
    # quantized serving
    "DPTPU_QUANT_PRECISION": _k("choice", "serve"),
    "DPTPU_QUANT_CALIB": _k("str", "serve"),
    "DPTPU_QUANT_DRIFT": _k("float", "serve"),
    "DPTPU_QUANT_TOP1_MIN": _k("float", "serve"),
    # serve fleet
    "DPTPU_FLEET_DIR": _k("str", "serve"),
    "DPTPU_FLEET_HEARTBEAT_S": _k("float", "serve"),
    "DPTPU_FLEET_DEADLINE_S": _k("float", "serve"),
    "DPTPU_FLEET_RETRIES": _k("int", "serve"),
    # self-tuning control plane (dptpu/tune)
    "DPTPU_TUNE_ARTIFACT": _k("str", "tune"),
    "DPTPU_TUNE_CONTROL": _k("str", "tune"),
    "DPTPU_TUNE_INTERVAL_S": _k("float", "tune"),
    # analysis / sanitizers
    "DPTPU_SYNC_CHECK": _k("bool", "analysis"),
    # bench-driver child sentinels (subprocess re-entry guards)
    "DPTPU_NUMERICS_CHILD": _k("str", "bench", internal=True),
    "DPTPU_SCALEBENCH_CHILD": _k("str", "bench", internal=True),
    "DPTPU_COMMBENCH_CHILD": _k("str", "bench", internal=True),
    "DPTPU_RACEBENCH_CHILD": _k("str", "bench", internal=True),
}


def knob_census() -> dict:
    """Registry summary for ANALYSIS.json."""
    internal = sorted(k for k, v in KNOB_REGISTRY.items() if v["internal"])
    return {
        "declared": len(KNOB_REGISTRY),
        "internal": internal,
        "by_area": {
            area: sorted(
                k for k, v in KNOB_REGISTRY.items() if v["area"] == area
            )
            for area in sorted({v["area"] for v in KNOB_REGISTRY.values()})
        },
    }
