"""Concurrency lint rules: ``guarded-by`` / ``lock-order`` / ``thread-hygiene``.

The host side of dptpu is a hand-rolled concurrent system — the serve
dispatcher, the async checkpoint writer, the shard-extent prefetcher,
the seqlock'd pooled cache, signal handlers — and until ISSUE 14 the
only thing standing between it and a silent data race was test luck.
These rules machine-check the lock discipline the same way TSan /
Guava's ``@GuardedBy`` checkers do in mature training stacks:

* ``guarded-by`` — a class that spawns threads (or hands callbacks to
  them: ``Thread(target=...)``, executor ``submit``, ``atexit`` /
  ``signal`` registration) or that owns a lock must ANNOTATE its shared
  mutable attributes::

      self._completed = 0      # guarded-by: _lock
      self.requested = False   # owned-by: signal-handler

  The rule builds per-class attribute read/write maps from the AST,
  computes which methods run on a spawned thread (reachability from the
  thread-entry points) vs. the calling thread, and reports: shared
  mutable attributes with no annotation, ``guarded-by`` attributes
  touched anywhere without the named lock held (``with``-statement
  scope tracking; methods suffixed ``_locked`` are held-by-contract,
  and calls to them must themselves be made under a lock), annotations
  naming nonexistent locks, and ``owned-by`` state written from both
  sides (single-writer is the whole point of the annotation).
  ``__init__``/``__del__``/pickling dunders are exempt (pre-publication
  and teardown are single-threaded by construction).

* ``lock-order`` — a whole-file lock acquisition graph: nested ``with
  lock:`` scopes, plus call edges (a method called while holding A
  contributes every lock it acquires as A -> B). Any cycle is a
  potential ABBA deadlock and a finding; so is re-acquiring a
  non-reentrant lock on a path that already holds it, and an edge that
  inverts the declared :data:`dptpu.utils.sync.LOCK_RANKS` ranks.
  ``OrderedLock("name")`` literals must name a declared rank.

* ``thread-hygiene`` — non-daemon threads must have a reachable
  ``join()`` on a teardown path (and dptpu-package threads must carry a
  ``dptpu``-prefixed name so the conftest thread census can attribute a
  leak); ``Condition.wait`` must sit in a predicate re-check loop; no
  blocking ``join()`` while holding a lock.

Static analysis is conservative where Python is dynamic: cross-CLASS
lock nesting (object A holding its lock while calling into object B) is
invisible here and is covered by the RUNTIME half instead —
``DPTPU_SYNC_CHECK=1`` makes every ``OrderedLock`` assert the declared
rank order on real executions (dptpu/utils/sync.py; tier-1 runs the
whole suite under it). Stdlib-only, like the engine (dptpu.utils.sync
is itself stdlib-only and safe to import here).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from dptpu.analysis.lint import FileContext, register
from dptpu.utils.sync import LOCK_RANKS

# lock primitives a `with` statement can hold
_HOLDABLE_CTORS = {"Lock", "RLock", "OrderedLock", "OrderedRLock",
                   "ordered_mp_lock"}
# anything whose presence declares "this class is concurrent"
_MARKER_CTORS = _HOLDABLE_CTORS | {"Condition", "Event", "Semaphore",
                                   "BoundedSemaphore", "Barrier"}
_ORDERED_CTORS = {"OrderedLock", "OrderedRLock", "ordered_mp_lock"}
# single-threaded-by-construction methods: pre-publication init,
# interpreter-teardown del, spawn-boundary pickling
_EXEMPT_METHODS = {"__init__", "__del__", "__getstate__", "__setstate__",
                   "__reduce__"}

_ANNOT_RE = re.compile(r"#\s*(guarded-by|owned-by):\s*([A-Za-z_][\w-]*)")


def _qualname(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _qualname(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _last(q: Optional[str]) -> str:
    return (q or "").rsplit(".", 1)[-1]


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' for a ``self.X`` attribute node."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_ctor_kind(value: ast.AST) -> Optional[str]:
    """Classify an assigned value: 'lock' / 'rlock' / 'cond' /
    'collection' (a list/comprehension of locks) / 'marker' / None."""
    if isinstance(value, ast.Call):
        name = _last(_qualname(value.func))
        if name in ("Lock", "OrderedLock", "ordered_mp_lock"):
            return "lock"
        if name in ("RLock", "OrderedRLock"):
            return "rlock"
        if name == "Condition":
            return "cond"
        if name in _MARKER_CTORS:
            return "marker"
        return None
    if isinstance(value, (ast.List, ast.Tuple, ast.ListComp)):
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call) \
                    and _last(_qualname(sub.func)) in _HOLDABLE_CTORS:
                return "collection"
    return None


class _ClassConc:
    """Everything the three rules need to know about one class."""

    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.AST] = {
            m.name: m for m in node.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.lock_attrs: Dict[str, str] = {}   # attr -> kind
        self.alias: Dict[str, str] = {}        # cond attr -> lock attr
        self.ordered_names: Dict[str, str] = {}  # attr -> LOCK_RANKS name
        self.markers = False
        self.entries: Set[str] = set()         # entry regions
        self.entry_lines: Dict[str, int] = {}
        # (attr, 'load'|'store', held, region, line)
        self.accesses: List[Tuple[str, str, frozenset, str, int]] = []
        # (callee, held, region, line) — self.<callee>() calls
        self.calls: List[Tuple[str, frozenset, str, int]] = []
        # (held-lock, acquired-lock, line) lexical nesting edges
        self.nest_edges: List[Tuple[str, str, int]] = []
        # region -> locks lexically acquired in it
        self.acquired_in: Dict[str, Set[str]] = {}
        # (lockname, region, line) same-lock nested acquisition
        self.reacquisitions: List[Tuple[str, str, int]] = []
        # (held-locks, line) for every *.join(...) call
        self.join_calls: List[Tuple[frozenset, int]] = []
        # (region, line, loop_depth) for every <cond>.wait(...) call
        self.cond_waits: List[Tuple[str, int, int]] = []
        self._nested_thread_defs: Dict[int, str] = {}
        # attr -> (kind, value, line), filled by _analyze (with
        # same-file base-class inheritance)
        self.annotations: Dict[str, Tuple[str, str, int]] = {}
        self.annotation_conflicts: List[Tuple[int, str]] = []

    # -- pass 1: locks, markers, thread entries --------------------------

    def scan_decls(self):
        for stmt in ast.walk(self.node):
            if isinstance(stmt, ast.Assign):
                kind = _lock_ctor_kind(stmt.value)
                if kind is None:
                    continue
                for tgt in stmt.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    self.markers = True
                    if kind == "marker":
                        continue
                    self.lock_attrs[attr] = kind
                    if kind == "cond" and isinstance(stmt.value, ast.Call) \
                            and stmt.value.args:
                        under = _self_attr(stmt.value.args[0])
                        if under is not None:
                            self.alias[attr] = under
                    if kind in ("lock", "rlock") \
                            and isinstance(stmt.value, ast.Call):
                        ctor = _last(_qualname(stmt.value.func))
                        if ctor in _ORDERED_CTORS and stmt.value.args:
                            arg = stmt.value.args[0]
                            if isinstance(arg, ast.Constant) \
                                    and isinstance(arg.value, str):
                                self.ordered_names[attr] = arg.value
        for mname, mnode in self.methods.items():
            nested = {
                n.name: n for n in ast.walk(mnode)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not mnode
            }
            for call in ast.walk(mnode):
                if not isinstance(call, ast.Call):
                    continue
                target = _thread_callback(call)
                if target is None:
                    continue
                attr = _self_attr(target)
                if attr is not None:
                    self.entries.add(attr)
                    self.entry_lines.setdefault(attr, call.lineno)
                elif isinstance(target, ast.Name) \
                        and target.id in nested:
                    region = f"{mname}:{target.id}"
                    self.entries.add(region)
                    self.entry_lines.setdefault(region, call.lineno)
                    self._nested_thread_defs[id(nested[target.id])] = region

    def canon(self, lock: str) -> str:
        return self.alias.get(lock, lock)

    def holdable(self, attr: str) -> bool:
        kind = self.lock_attrs.get(attr)
        return kind in ("lock", "rlock", "cond")

    # -- pass 2: accesses / calls / edges under with-scope tracking ------

    def scan_bodies(self):
        for mname, mnode in self.methods.items():
            self._visit(mnode, frozenset(), mname, loop_depth=0,
                        top=True)

    def _visit(self, node, held: frozenset, region: str, loop_depth: int,
               top: bool = False):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and not top:
            # a nested def's body runs LATER, on whatever thread calls
            # it: the lexical locks are not held there
            region = self._nested_thread_defs.get(id(node), region)
            held = frozenset()
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new = set(held)
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and self.holdable(attr):
                    lock = self.canon(attr)
                    if lock in new and \
                            self.lock_attrs.get(lock) != "rlock":
                        self.reacquisitions.append(
                            (lock, region, node.lineno)
                        )
                    for h in new:
                        if h != lock:
                            self.nest_edges.append((h, lock, node.lineno))
                    new.add(lock)
                    self.acquired_in.setdefault(region, set()).add(lock)
            for item in node.items:
                self._visit(item.context_expr, held, region, loop_depth)
            for child in node.body:
                self._visit(child, frozenset(new), region, loop_depth)
            return
        if isinstance(node, ast.While):
            loop_depth += 1
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None:
                kind = "store" if isinstance(
                    node.ctx, (ast.Store, ast.Del)) else "load"
                self.accesses.append(
                    (attr, kind, held, region, node.lineno)
                )
        if isinstance(node, (ast.Subscript,)) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            # container mutation through a self attribute
            # (self.X[k] = v / del self.X[k]) is a WRITE to X
            base = node.value
            while isinstance(base, ast.Subscript):
                base = base.value
            attr = _self_attr(base)
            if attr is not None:
                self.accesses.append(
                    (attr, "store", held, region, node.lineno)
                )
        if isinstance(node, ast.Call):
            attr = _self_attr(node.func)
            if attr is not None and attr in self.methods:
                self.calls.append((attr, held, region, node.lineno))
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                self.join_calls.append((held, node.lineno))
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "wait":
                cattr = _self_attr(node.func.value)
                if cattr is not None \
                        and self.lock_attrs.get(cattr) == "cond":
                    self.cond_waits.append(
                        (region, node.lineno, loop_depth)
                    )
        for child in ast.iter_child_nodes(node):
            self._visit(child, held, region, loop_depth)

    # -- sides -----------------------------------------------------------

    def sides(self) -> Tuple[Set[str], Set[str]]:
        """(thread-side regions, caller-side regions)."""
        callee_edges: Dict[str, Set[str]] = {}
        in_edges: Set[str] = set()
        for callee, _held, region, _line in self.calls:
            callee_edges.setdefault(region, set()).add(callee)
            in_edges.add(callee)

        def closure(roots):
            seen = set(roots)
            todo = list(roots)
            while todo:
                r = todo.pop()
                for c in callee_edges.get(r, ()):
                    if c not in seen:
                        seen.add(c)
                        todo.append(c)
            return seen

        tr = closure(self.entries)
        roots = {
            m for m in self.methods
            if m not in self.entries and m not in in_edges
        }
        cr = closure(roots)
        # a method reachable from nothing we can see is still a public
        # entry point in waiting: presume caller-side
        for m in self.methods:
            if m not in tr and m not in cr:
                cr.add(m)
        return tr, cr


def _thread_callback(call: ast.Call) -> Optional[ast.AST]:
    """The callable handed to another thread by this call, if any:
    Thread(target=X) / Timer(t, X) / <executor>.submit(X, ...) /
    atexit.register(X) / signal.signal(sig, X)."""
    q = _qualname(call.func)
    name = _last(q)
    if name == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
        return None
    if name == "Timer":
        for kw in call.keywords:
            if kw.arg == "function":
                return kw.value
        if len(call.args) >= 2:
            return call.args[1]
        return None
    if isinstance(call.func, ast.Attribute) and call.func.attr == "submit" \
            and call.args:
        return call.args[0]
    if q == "atexit.register" and call.args:
        return call.args[0]
    if q and q.endswith("signal.signal") and len(call.args) >= 2:
        return call.args[1]
    return None


def _class_annotations(ctx: FileContext, cls: _ClassConc
                       ) -> Dict[int, Tuple[str, str]]:
    """line -> (kind, value) for guarded-by/owned-by comments inside the
    class body."""
    end = getattr(cls.node, "end_lineno", None) or cls.node.lineno
    out: Dict[int, Tuple[str, str]] = {}
    lines = ctx.source.splitlines()
    for lineno in range(cls.node.lineno, min(end, len(lines)) + 1):
        m = _ANNOT_RE.search(lines[lineno - 1])
        if m:
            out[lineno] = (m.group(1), m.group(2))
    return out


def _analyze(ctx: FileContext) -> List[_ClassConc]:
    cached = getattr(ctx, "_concurrency_classes", None)
    if cached is not None:
        return cached
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            cls = _ClassConc(node)
            cls.scan_decls()
            out.append(cls)
    # same-file inheritance: a subclass holds (and is concurrent via)
    # its base's locks and inherits its attribute annotations —
    # HTTPStore riding Store._lock and Store's guarded-by declarations
    # must see both. Iterate to convergence for base chains.
    by_name = {c.name: c for c in out}
    for cls in out:
        cls.annotations = _bind_annotations(ctx, cls)
    changed = True
    while changed:
        changed = False
        for cls in out:
            for base in cls.node.bases:
                bname = _last(_qualname(base))
                parent = by_name.get(bname)
                if parent is None or parent is cls:
                    continue
                for attr, kind in parent.lock_attrs.items():
                    if attr not in cls.lock_attrs:
                        cls.lock_attrs[attr] = kind
                        changed = True
                for cattr, under in parent.alias.items():
                    if cattr not in cls.alias:
                        cls.alias[cattr] = under
                        changed = True
                for attr, name in parent.ordered_names.items():
                    if attr not in cls.ordered_names:
                        cls.ordered_names[attr] = name
                        changed = True
                if parent.markers and not cls.markers:
                    cls.markers = True
                    changed = True
                for attr, entry in parent.annotations.items():
                    if attr not in cls.annotations:
                        cls.annotations[attr] = entry
                        changed = True
    for cls in out:
        cls.scan_bodies()
    ctx._concurrency_classes = out
    return out


def _bind_annotations(ctx: FileContext, cls: _ClassConc
                      ) -> Dict[str, Tuple[str, str, int]]:
    """attr -> (kind, value, line): the guarded-by/owned-by comments
    bound to this class's own attribute stores. Needs a quick store
    scan of its own because it runs BEFORE scan_bodies (inheritance
    merging wants annotations early)."""
    annot_lines = _class_annotations(ctx, cls)
    if not annot_lines:
        return {}
    out: Dict[str, Tuple[str, str, int]] = {}
    conflicts: List[Tuple[int, str]] = []
    for node in ast.walk(cls.node):
        attr = None
        if isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node)
        if attr is None or node.lineno not in annot_lines:
            continue
        akind, aval = annot_lines[node.lineno]
        prev = out.get(attr)
        if prev is not None and (prev[0], prev[1]) != (akind, aval):
            conflicts.append((node.lineno, (
                f"attribute '{attr}' carries conflicting annotations "
                f"('{prev[0]}: {prev[1]}' at line {prev[2]} vs "
                f"'{akind}: {aval}') — keep exactly one"
            )))
            continue
        out[attr] = (akind, aval, node.lineno)
    cls.annotation_conflicts = conflicts
    return out


def _in_package(relpath: str) -> bool:
    return relpath.startswith(("dptpu/", "scripts/"))


# -------------------------------------------------------------- guarded-by


@register(
    "guarded-by", _in_package,
    "classes that spawn threads (or hand callbacks to them) or own "
    "locks must annotate shared mutable attributes with "
    "'# guarded-by: <lock-attr>' (every access lock-held, "
    "with-statement scope tracking, *_locked held-by-contract) or "
    "'# owned-by: <thread-role>' (single-writer handoff state); stale "
    "annotations naming nonexistent locks are findings too",
)
def guarded_by(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    for cls in _analyze(ctx):
        concurrent = bool(cls.entries or cls.lock_attrs or cls.markers)
        annotations = cls.annotations
        yield from cls.annotation_conflicts
        # stale guarded-by: the named lock must exist (checked even in
        # classes this rule otherwise skips — a stale name is never ok)
        for attr, (akind, aval, line) in sorted(annotations.items()):
            if akind == "guarded-by" and not cls.holdable(aval):
                yield line, (
                    f"attribute '{attr}' is declared guarded-by "
                    f"'{aval}' but class {cls.name} has no such lock "
                    f"attribute (known locks: "
                    f"{', '.join(sorted(cls.lock_attrs)) or 'none'}) — "
                    f"stale annotation?"
                )
        if not concurrent:
            continue
        tr, cr = cls.sides()

        def side_of(region: str) -> Tuple[bool, bool]:
            return (region in tr, region in cr)

        writes_outside: Dict[str, int] = {}
        first_init_store: Dict[str, int] = {}
        touched_tr: Set[str] = set()
        touched_cr: Set[str] = set()
        writes_tr: Dict[str, int] = {}
        writes_cr: Dict[str, int] = {}
        for attr, kind, _held, region, line in cls.accesses:
            if attr in cls.lock_attrs:
                continue
            method = region.split(":", 1)[0]
            if method in _EXEMPT_METHODS:
                if kind == "store" and method == "__init__" \
                        and attr not in first_init_store:
                    first_init_store[attr] = line
                continue
            is_tr, is_cr = side_of(region)
            if is_tr:
                touched_tr.add(attr)
            if is_cr:
                touched_cr.add(attr)
            if kind == "store":
                if attr not in writes_outside:
                    writes_outside[attr] = line
                if is_tr and attr not in writes_tr:
                    writes_tr[attr] = line
                if is_cr and attr not in writes_cr:
                    writes_cr[attr] = line
        if cls.entries:
            shared = {
                a for a in writes_outside
                if a in touched_tr and a in touched_cr
            }
        else:
            # no visible spawn point, but the class declared itself
            # concurrent by owning a lock: every mutated attribute is
            # presumed reachable from multiple threads
            shared = set(writes_outside)
        for attr in sorted(shared):
            if attr in annotations:
                continue
            line = first_init_store.get(attr, writes_outside[attr])
            if cls.entries:
                detail = ("is touched from both a spawned thread and "
                          "the caller thread")
            else:
                detail = (f"is mutated in lock-owning class {cls.name}")
            yield line, (
                f"shared mutable attribute '{attr}' {detail} with no "
                f"concurrency annotation — declare "
                f"'# guarded-by: <lock-attr>' on an assignment of it "
                f"(or '# owned-by: <thread-role>' for single-writer "
                f"handoff state); see CONCURRENCY.md"
            )
        # guarded-by enforcement: EVERY non-exempt access lock-held
        for attr, (akind, aval, _line) in sorted(annotations.items()):
            if akind == "guarded-by" and cls.holdable(aval):
                want = cls.canon(aval)
                for a, kind, held, region, line in cls.accesses:
                    if a != attr:
                        continue
                    method = region.split(":", 1)[0]
                    if method in _EXEMPT_METHODS:
                        continue
                    if method.endswith("_locked"):
                        continue
                    if want in held:
                        continue
                    yield line, (
                        f"'{attr}' is declared guarded-by '{aval}' but "
                        f"{method}() touches it without the lock held — "
                        f"wrap the access in 'with self.{aval}:' or "
                        f"move it into a *_locked helper that is only "
                        f"called under the lock"
                    )
            elif akind == "owned-by" and cls.entries:
                if attr in writes_tr and attr in writes_cr:
                    yield writes_outside[attr], (
                        f"'{attr}' is declared owned-by '{aval}' but is "
                        f"written from BOTH a spawned thread (line "
                        f"{writes_tr[attr]}) and the caller thread "
                        f"(line {writes_cr[attr]}) — single-writer "
                        f"handoff state has exactly one writing side; "
                        f"guard it with a lock instead"
                    )
        # the *_locked contract: such helpers may elide the with-block
        # only because every CALL to them already holds a lock
        for callee, held, region, line in cls.calls:
            if not callee.endswith("_locked"):
                continue
            method = region.split(":", 1)[0]
            if held or method.endswith("_locked") \
                    or method in _EXEMPT_METHODS:
                continue
            yield line, (
                f"call to {callee}() from {method}() with no lock held "
                f"— the *_locked suffix means 'caller holds the lock'; "
                f"acquire it first or rename the helper"
            )


# -------------------------------------------------------------- lock-order


@register(
    "lock-order", _in_package,
    "whole-file lock acquisition graph (nested with-scopes + call "
    "edges): acquisition cycles are potential ABBA deadlocks, "
    "re-acquiring a non-reentrant lock on a holding path self-"
    "deadlocks, edges must respect dptpu.utils.sync.LOCK_RANKS, and "
    "OrderedLock names must be declared there",
)
def lock_order(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    # OrderedLock("name") literals must be declared ranks (repo-wide
    # check, classes or not)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _last(_qualname(node.func)) not in _ORDERED_CTORS:
            continue
        name_node = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "name"), None
        )
        name = ctx.resolve_str(name_node) if name_node is not None else None
        if name is None:
            yield node.lineno, (
                "OrderedLock name is not statically resolvable — pass a "
                "string literal so the analyzer (and CONCURRENCY.md) "
                "can place it in the global order"
            )
        elif name not in LOCK_RANKS:
            yield node.lineno, (
                f"OrderedLock name {name!r} is not declared in "
                f"dptpu/utils/sync.py LOCK_RANKS — declare its rank "
                f"there and document it in CONCURRENCY.md (known: "
                f"{', '.join(sorted(LOCK_RANKS))})"
            )
    for cls in _analyze(ctx):
        # self-deadlock: same non-reentrant lock nested lexically
        for lock, region, line in cls.reacquisitions:
            yield line, (
                f"{cls.name}.{region}() acquires '{lock}' while already "
                f"holding it — a non-reentrant lock self-deadlocks here; "
                f"restructure (or use OrderedRLock if re-entry is truly "
                f"intended)"
            )
        # edges: lexical nesting + call edges
        edges: List[Tuple[str, str, int]] = list(cls.nest_edges)
        callee_edges: Dict[str, Set[str]] = {}
        for callee, _held, region, _line in cls.calls:
            callee_edges.setdefault(region, set()).add(callee)

        def acquires_closure(method: str) -> Set[str]:
            seen: Set[str] = set()
            todo = [method]
            visited = set()
            while todo:
                m = todo.pop()
                if m in visited:
                    continue
                visited.add(m)
                seen |= cls.acquired_in.get(m, set())
                for c in callee_edges.get(m, ()):
                    todo.append(c)
            return seen

        for callee, held, _region, line in cls.calls:
            if not held:
                continue
            for lock in sorted(acquires_closure(callee)):
                for h in held:
                    if h == lock:
                        if cls.lock_attrs.get(lock) != "rlock":
                            yield line, (
                                f"{cls.name}: calling {callee}() while "
                                f"holding '{lock}', which {callee}() "
                                f"re-acquires — a non-reentrant lock "
                                f"self-deadlocks on this path"
                            )
                    else:
                        edges.append((h, lock, line))
        # cycle detection over the merged edge set
        graph: Dict[str, Set[str]] = {}
        edge_line: Dict[Tuple[str, str], int] = {}
        for a, b, line in edges:
            graph.setdefault(a, set()).add(b)
            edge_line.setdefault((a, b), line)

        def reachable(src: str, dst: str) -> bool:
            seen, todo = set(), [src]
            while todo:
                n = todo.pop()
                if n == dst:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                todo.extend(graph.get(n, ()))
            return False

        reported = set()
        for (a, b), line in sorted(edge_line.items(),
                                   key=lambda kv: kv[1]):
            if (a, b) in reported:
                continue
            if reachable(b, a):
                reported.add((a, b))
                reported.add((b, a))
                yield line, (
                    f"{cls.name}: potential ABBA deadlock — '{b}' is "
                    f"acquired here while holding '{a}', but another "
                    f"path acquires '{a}' while holding '{b}' (line "
                    f"{edge_line.get((b, a), '?')}); pick ONE global "
                    f"order (dptpu/utils/sync.py LOCK_RANKS, "
                    f"CONCURRENCY.md) and restructure the inverted side"
                )
        # declared-rank consistency on the visible edges
        for (a, b), line in sorted(edge_line.items(),
                                   key=lambda kv: kv[1]):
            ra = cls.ordered_names.get(a)
            rb = cls.ordered_names.get(b)
            if ra in LOCK_RANKS and rb in LOCK_RANKS \
                    and LOCK_RANKS[ra] >= LOCK_RANKS[rb]:
                yield line, (
                    f"{cls.name}: acquiring '{b}' (rank "
                    f"{LOCK_RANKS[rb]}, {rb!r}) while holding '{a}' "
                    f"(rank {LOCK_RANKS[ra]}, {ra!r}) inverts the "
                    f"declared LOCK_RANKS order — swap the nesting or "
                    f"re-rank in dptpu/utils/sync.py"
                )


# ---------------------------------------------------------- thread-hygiene


def _scope_has_join(scope: ast.AST, recv: Optional[str]) -> bool:
    """Does ``scope`` contain a ``<recv>.join(...)`` call (any receiver
    when ``recv`` is None — threads stored into containers)?"""
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            if recv is None:
                return True
            got = _qualname(node.func.value)
            if got == recv:
                return True
    return False


@register(
    "thread-hygiene", _in_package,
    "non-daemon threads need a reachable join() on a teardown path "
    "(and dptpu-package threads a dptpu-prefixed name for the conftest "
    "thread census); Condition.wait sits in a predicate re-check "
    "loop; no blocking join() while holding a lock",
)
def thread_hygiene(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    tree = ctx.tree
    # parent scopes for every node: nearest enclosing function + class
    scope_of: Dict[int, Tuple[Optional[ast.AST], Optional[ast.AST]]] = {}

    def map_scopes(node, func, cls):
        scope_of[id(node)] = (func, cls)
        nfunc, ncls = func, cls
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            nfunc = node
        elif isinstance(node, ast.ClassDef):
            ncls = node
            nfunc = None
        for child in ast.iter_child_nodes(node):
            map_scopes(child, nfunc, ncls)

    map_scopes(tree, None, None)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _last(_qualname(node.func)) != "Thread":
            continue
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        # census attribution: dptpu-package threads carry a dptpu name
        if ctx.relpath.startswith("dptpu/"):
            name = ctx.resolve_str(kwargs["name"]) \
                if "name" in kwargs else None
            if name is None or not name.startswith("dptpu"):
                yield node.lineno, (
                    "thread without a 'dptpu'-prefixed name= — the "
                    "conftest thread census attributes leaks by name; "
                    "pass name='dptpu-<role>'"
                )
        daemon = kwargs.get("daemon")
        is_daemon = (isinstance(daemon, ast.Constant)
                     and daemon.value is True)
        if is_daemon:
            continue
        func, cls = scope_of.get(id(node), (None, None))
        # where did the Thread object land? self-attr / local / nowhere
        recv = None
        search: Optional[ast.AST] = func or cls or tree
        parentage = _assignment_target(tree, node)
        if parentage is not None:
            attr = _self_attr(parentage)
            if attr is not None and cls is not None:
                recv = f"self.{attr}"
                search = cls
            elif isinstance(parentage, ast.Name):
                recv = parentage.id
                search = func or cls or tree
            else:
                recv = None  # container (list of threads): any join ok
        if search is None or not _scope_has_join(search, recv):
            yield node.lineno, (
                "non-daemon thread with no reachable join() in its "
                "owning scope — join it on a teardown path (close()/"
                "finally) or pass daemon=True; a leaked non-daemon "
                "thread hangs interpreter exit and fails the conftest "
                "thread census"
            )
    # Condition.wait predicate loops + join-under-lock, via the class
    # analysis machinery
    for cls in _analyze(ctx):
        for region, line, loop_depth in cls.cond_waits:
            if loop_depth < 1:
                yield line, (
                    f"{cls.name}.{region}(): Condition.wait() outside a "
                    f"predicate re-check loop — spurious/stolen wakeups "
                    f"make the condition a hint, not a fact; wrap it in "
                    f"'while not <predicate>:'"
                )
        for held, line in cls.join_calls:
            if held:
                locks = ", ".join(sorted(held))
                yield line, (
                    f"{cls.name}: blocking join() while holding "
                    f"'{locks}' — a thread that needs that lock to "
                    f"finish can never finish (deadlock); release "
                    f"before joining"
                )


def _assignment_target(tree: ast.AST, call: ast.Call) -> Optional[ast.AST]:
    """The Assign target that receives ``call``'s value, if the call is
    the direct RHS (or sits inside a comprehension/list RHS — returns a
    sentinel Attribute-free node so callers treat it as a container)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            if node.value is call:
                return node.targets[0]
            for sub in ast.walk(node.value):
                if sub is call and node.value is not call:
                    # stored via a container expression
                    return node.value
    return None
